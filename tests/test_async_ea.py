"""AsyncEA protocol tests.

The reference has NO tests for its async path (SURVEY.md §4: "no tests for
AsyncEA at all"); these cover the protocol over the real transport on
localhost — threads as processes, like the reference's own ``ipc.map``
threading trick for the sync suites (test/test_AllReduceSGD.lua:26-35).
"""

import threading

import numpy as np
import pytest

from distlearn_tpu.parallel.async_ea import (AsyncEAClient, AsyncEAServer,
                                             AsyncEATester)
from distlearn_tpu.utils.logging import set_verbose

set_verbose(False)

from tests.net_util import reserve_port_window


def _ports(n: int = 8) -> int:
    """Reserve a fresh ephemeral base-port window per test (server occupies
    port..port+numNodes+1)."""
    return reserve_port_window(n)


def _params():
    return {"w": np.zeros((4, 3), np.float32), "b": np.zeros((3,), np.float32)}


def test_init_broadcast_delivers_center():
    port = _ports()
    server_params = {"w": np.full((4, 3), 7.0, np.float32),
                     "b": np.full((3,), -1.0, np.float32)}
    got = {}

    def client_fn(node):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=10, alpha=0.5)
        got[node] = c.init_client(_params())
        c.close()

    threads = [threading.Thread(target=client_fn, args=(i,)) for i in (1, 2)]
    for t in threads:
        t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2)
    srv.init_server(server_params)
    for t in threads:
        t.join(timeout=30)
    srv.close()
    for node in (1, 2):
        np.testing.assert_array_equal(got[node]["w"], server_params["w"])
        np.testing.assert_array_equal(got[node]["b"], server_params["b"])


def test_sync_round_easgd_math():
    """One client, one sync: delta=(p-c)*alpha, p-=delta, center+=delta
    (lua/AsyncEA.lua:109-119,212-216)."""
    port = _ports()
    alpha = 0.5
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=2, alpha=alpha)
        p = c.init_client(_params())
        p = {"w": p["w"] + 2.0, "b": p["b"] + 4.0}  # local training drift
        p, synced = c.sync_client(p)      # step 1: no sync
        assert not synced
        p, synced = c.sync_client(p)      # step 2: tau boundary -> sync
        assert synced
        out["p"] = p
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server(_params())            # center = zeros
    new_params = srv.sync_server(_params())
    th.join(timeout=30)
    srv.close()
    # delta_w = (2 - 0) * 0.5 = 1 -> client w: 2-1=1; center_w: 0+1=1
    # delta_b = (4 - 0) * 0.5 = 2 -> client b: 4-2=2; center_b: 0+2=2
    np.testing.assert_allclose(out["p"]["w"], 1.0)
    np.testing.assert_allclose(out["p"]["b"], 2.0)
    np.testing.assert_allclose(new_params["w"], 1.0)  # params := center
    np.testing.assert_allclose(new_params["b"], 2.0)


def test_concurrent_clients_serialized_and_consistent():
    """Two clients hammer the server concurrently; the Enter?/Enter critical
    section must serialize them (lua :163-177) and every delta must land on
    the center exactly once."""
    port = _ports()
    alpha, tau, rounds = 0.5, 1, 8
    rng = np.random.RandomState(0)
    drifts = {1: rng.randn(rounds).astype(np.float32),
              2: rng.randn(rounds).astype(np.float32)}
    sent_deltas = []
    lock = threading.Lock()

    def client_fn(node):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=tau, alpha=alpha)
        p = c.init_client({"w": np.zeros((2, 2), np.float32)})
        for r in range(rounds):
            p = {"w": p["w"] + drifts[node][r]}
            before = p["w"].copy()
            p, synced = c.sync_client(p)
            assert synced
            with lock:
                sent_deltas.append(before - p["w"])  # = delta sent
        c.close()

    threads = [threading.Thread(target=client_fn, args=(i,)) for i in (1, 2)]
    for t in threads:
        t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2)
    srv.init_server({"w": np.zeros((2, 2), np.float32)})
    for _ in range(2 * rounds):
        srv.sync_server({"w": np.zeros((2, 2), np.float32)})
    for t in threads:
        t.join(timeout=60)
    # center must equal the sum of every delta the clients applied locally
    total = np.sum(sent_deltas, axis=0)
    np.testing.assert_allclose(srv.center[0], total, rtol=1e-5, atol=1e-5)
    srv.close()


def test_tester_receives_center_push():
    port = _ports()
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client(_params())
        p, _ = c.sync_client({"w": p["w"] + 1.0, "b": p["b"]})
        c.close()

    def tester_fn():
        t = AsyncEATester("127.0.0.1", port, num_nodes=1)
        p = t.start_test(_params())
        out["center"] = p
        t.finish_test()
        t.close()

    tc = threading.Thread(target=client_fn)
    tt = threading.Thread(target=tester_fn)
    tc.start()
    tt.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, with_tester=True)
    srv.init_server(_params())
    srv.sync_server(_params())
    srv.test_net()
    tc.join(timeout=30)
    tt.join(timeout=30)
    srv.close()
    np.testing.assert_allclose(out["center"]["w"], 0.5)  # (1-0)*0.5 applied


def test_client_requires_one_based_node():
    with pytest.raises(ValueError):
        AsyncEAClient("127.0.0.1", _ports(), node=0, tau=1, alpha=0.5)


def _live_client_fn(port, out, delay=0.0):
    import time
    c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
    p = c.init_client(_params())
    if delay:
        time.sleep(delay)
    p, synced = c.sync_client({"w": p["w"] + 1.0, "b": p["b"]})
    out["p"] = p
    out["synced"] = synced
    c.close()


def test_dead_client_evicted_server_keeps_serving():
    """Client #2 is admitted to the critical section then dies (sockets
    closed mid-handshake).  The server must evict it — not wedge
    (lua/AsyncEA.lua:163-228 has no such recovery; VERDICT r1 weak #6) —
    and complete the round with the surviving client #1."""
    from distlearn_tpu.comm.transport import connect

    port = _ports()
    out = {}

    def zombie_fn():
        b = connect("127.0.0.1", port)
        d = connect("127.0.0.1", port + 2)
        for _ in range(2):                # receive the initial center (w, b)
            b.recv_tensor()
        b.send_msg({"q": "Enter?", "clientID": 2})
        b.close()     # dies right after requesting the critical section
        d.close()

    tz = threading.Thread(target=zombie_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.5))
    tz.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2, handshake_timeout=5.0)
    srv.init_server(_params())            # center = zeros
    new_params = srv.sync_server(_params())
    tz.join(timeout=30)
    tl.join(timeout=30)
    srv.close()
    assert 2 in srv.evicted
    assert srv.live_clients == 1
    assert out["synced"]
    # client 1's round landed in full: delta_w = (1-0)*0.5
    np.testing.assert_allclose(new_params["w"], 0.5)
    np.testing.assert_allclose(out["p"]["w"], 0.5)


def test_hung_client_evicted_by_timeout():
    """Client #2 enters the critical section and goes silent (socket open,
    no protocol progress).  The per-handshake timeout must evict it and the
    server must then serve client #1."""
    import time

    from distlearn_tpu.comm.transport import connect

    port = _ports()
    out = {}
    release = threading.Event()

    def hung_fn():
        b = connect("127.0.0.1", port)
        d = connect("127.0.0.1", port + 2)
        b.send_msg({"q": "Enter?", "clientID": 2})
        release.wait(timeout=60)          # never answers the handshake
        b.close()
        d.close()

    th = threading.Thread(target=hung_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.5))
    th.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2,
                        handshake_timeout=0.5)
    srv.init_server(_params())
    t0 = time.monotonic()
    new_params = srv.sync_server(_params())
    assert time.monotonic() - t0 < 20     # did not wedge on the hung client
    release.set()
    th.join(timeout=30)
    tl.join(timeout=30)
    srv.close()
    assert 2 in srv.evicted
    assert out["synced"]
    np.testing.assert_allclose(new_params["w"], 0.5)


def test_evicted_client_rejoins_and_syncs_serial():
    """Completing the elastic story the reference lacks entirely
    (lua/AsyncEA.lua wedges; SURVEY §5 failure row): client #2 hangs
    mid-handshake and is evicted, then REJOINS — fresh channels, Rejoin?
    announce, current center down — and syncs.  The center math must stay
    exact across the whole eviction/rejoin cycle (VERDICT r4 next #8)."""
    port = _ports()
    alpha = 0.5
    out = {}
    evicted_ev = threading.Event()

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=2, tau=1, alpha=alpha)
        c.init_client(_params())
        # request entry then go silent mid-handshake -> eviction
        c.broadcast.send_msg({"q": "Enter?", "clientID": 2})
        evicted_ev.wait(timeout=60)
        p = c.rejoin(_params())           # params := CURRENT center
        out["after_rejoin"] = {k: v.copy() for k, v in p.items()}
        p = {"w": p["w"] + 2.0, "b": p["b"] + 2.0}   # local drift
        p, synced = c.sync_client(p)
        out["synced2"] = synced
        out["p2"] = p
        c.close()

    tf = threading.Thread(target=flaky_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.5))
    tf.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2,
                        handshake_timeout=0.5)
    srv.init_server(_params())            # center = zeros
    srv.sync_server(_params())            # evicts #2, serves #1
    assert 2 in srv.evicted
    evicted_ev.set()
    # re-admits #2, serves its sync.  Client #1 may have closed before the
    # rejoiner dials in, leaving ZERO open conns — sync_server raises
    # RuntimeError then (documented); waiting out an outage is the
    # documented catch-and-retry pattern.
    import time
    deadline = time.monotonic() + 30
    while True:
        try:
            new_params = srv.sync_server(_params(), timeout=5.0)
            break
        except (RuntimeError, TimeoutError):
            assert time.monotonic() < deadline, "rejoin never served"
            time.sleep(0.05)
    tf.join(timeout=30)
    tl.join(timeout=30)
    srv.close()
    assert 2 not in srv.evicted and srv.live_clients == 2
    assert out["synced2"]
    # client 1's sync: center 0 -> 0.5.  Rejoiner takes center 0.5, drifts
    # +2 -> 2.5, delta = (2.5-0.5)*0.5 = 1.0: center -> 1.5, params -> 1.5.
    np.testing.assert_allclose(out["after_rejoin"]["w"], 0.5)
    np.testing.assert_allclose(out["p2"]["w"], 1.5)
    np.testing.assert_allclose(new_params["w"], 1.5)


def test_evicted_client_rejoins_concurrent_server():
    """Same elastic cycle against the concurrent server: the worker that
    evicted has returned; rejoin must respawn one and the accumulation
    stays exact."""
    from distlearn_tpu.parallel.async_ea import AsyncEAServerConcurrent

    port = _ports()
    params0 = {"w": np.zeros(8, np.float32)}
    evicted_ev = threading.Event()
    out = {}

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=2, tau=1, alpha=0.5)
        c.init_client({"w": params0["w"].copy()})
        c.broadcast.send_msg({"q": "Enter?", "clientID": 2})
        c.conn.recv_msg()                 # ENTER, then silence -> eviction
        evicted_ev.wait(timeout=60)
        p = c.rejoin({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        p, synced = c.sync_client(p)
        out["synced"] = synced
        out["p"] = p
        c.close()

    def good_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        c.sync_client(p)                  # center 0 -> 1.0
        c.close()

    tf = threading.Thread(target=flaky_fn, daemon=True)
    tg = threading.Thread(target=good_fn, daemon=True)
    tf.start()
    tg.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=2,
                                  handshake_timeout=0.5,
                                  rejoin_grace=30.0)
    srv.init_server({"w": params0["w"].copy()})
    srv.start()
    import time
    t0 = time.time()
    while 2 not in srv.evicted or srv.syncs_completed < 1:
        assert time.time() - t0 < 30, (srv.evicted, srv.syncs_completed)
        time.sleep(0.02)
    evicted_ev.set()
    while srv.syncs_completed < 2:        # the rejoiner's sync lands
        assert time.time() - t0 < 60, srv.syncs_completed
        time.sleep(0.02)
    tf.join(timeout=30)
    tg.join(timeout=30)
    assert out["synced"]
    assert 2 not in srv.evicted
    # center after good sync: 1.0.  Rejoiner: params=1.0, drift -> 3.0,
    # delta=(3.0-1.0)*0.5=1.0 -> center 2.0, client params 2.0.
    np.testing.assert_allclose(out["p"]["w"], 2.0)
    np.testing.assert_allclose(srv.current_center(params0)["w"], 2.0)
    srv.stop()
    srv.close()


def test_partial_frame_client_cannot_wedge_server():
    """Client #2 sends HALF a frame header on the broadcast channel and
    stalls with the socket open.  select() reports the conn readable, but
    the frame never completes — without a frame-read deadline this wedges
    recv_any (and with it the serial server and the concurrent dispatcher
    alike; VERDICT r4 weak #4).  The bounded frame read must drop the
    peer and the server must then serve client #1."""
    import struct
    import time

    from distlearn_tpu.comm.transport import connect

    port = _ports()
    out = {}
    release = threading.Event()

    def partial_fn():
        b = connect("127.0.0.1", port)
        d = connect("127.0.0.1", port + 2)
        # 5 of the 9 header bytes (kind + u64 length), then silence
        b.sock.sendall(struct.pack("<BQ", ord("J"), 64)[:5])
        release.wait(timeout=60)
        b.close()
        d.close()

    tp = threading.Thread(target=partial_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.5))
    tp.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2,
                        handshake_timeout=0.5)
    srv.init_server(_params())
    t0 = time.monotonic()
    new_params = srv.sync_server(_params())
    assert time.monotonic() - t0 < 20     # did not wedge on the stalled peer
    release.set()
    tp.join(timeout=30)
    tl.join(timeout=30)
    srv.close()
    assert out["synced"]
    np.testing.assert_allclose(new_params["w"], 0.5)


def test_admitted_client_frame_stall_becomes_eviction_then_rejoins():
    """An ADMITTED client whose broadcast stream stalls mid-frame is cut
    by recv_any's frame timeout — that cut must be recorded as a real
    EVICTION (dedicated channel closed too, rejoin possible), not a
    silent transport drop that leaves the client unrecoverable and
    live_clients over-counting (r5 review finding)."""
    import struct
    import time

    port = _ports()
    out = {}
    stalled_ev = threading.Event()

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=2, tau=1, alpha=0.5)
        p = c.init_client(_params())
        p = {"w": p["w"] + 2.0, "b": p["b"]}
        p, synced = c.sync_client(p)     # one clean sync: cid 2 is mapped
        assert synced
        # then HALF an Enter? frame and silence -> frame-timeout cut
        c.broadcast.sock.sendall(struct.pack("<BQ", ord("J"), 64)[:5])
        stalled_ev.wait(timeout=60)
        p = c.rejoin(_params())          # must be possible: it was EVICTED
        p = {"w": p["w"] + 2.0, "b": p["b"]}
        p, synced = c.sync_client(p)
        out["synced2"] = synced
        out["p2"] = p
        c.close()

    tf = threading.Thread(target=flaky_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 1.0))
    tf.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2,
                        handshake_timeout=0.5)
    srv.init_server(_params())
    srv.sync_server(_params())           # client 2's clean sync
    # serve until the mid-frame stall is cut (client 1's sync may be
    # served first depending on select ordering)
    deadline = time.monotonic() + 30
    while 2 not in srv.evicted:
        assert time.monotonic() < deadline, "stall never evicted"
        try:
            srv.sync_server(_params(), timeout=2.0)
        except (RuntimeError, TimeoutError):
            time.sleep(0.05)
    stalled_ev.set()
    deadline = time.monotonic() + 30
    while True:
        try:
            new_params = srv.sync_server(_params(), timeout=5.0)
            break
        except (RuntimeError, TimeoutError):
            assert time.monotonic() < deadline, "rejoin never served"
            time.sleep(0.05)
    tf.join(timeout=30)
    tl.join(timeout=30)
    srv.close()
    assert 2 not in srv.evicted
    assert out["synced2"]
    # deltas: c2 +1.0 (first sync), c1 +0.5... exact values depend on
    # ordering; the invariant that matters here is the cycle completed
    # with finite, consistent math
    assert np.isfinite(new_params["w"]).all()


def test_concurrent_dispatcher_evict_then_rejoin_serves_fresh_conn():
    """Dispatcher-side eviction (frame stall on the broadcast conn) never
    unparks the client's worker; after the rejoin the SAME parked worker
    must serve the FRESH dedicated conn (it re-reads it per token) — a
    stale captured conn here evicted the just-readmitted client on its
    first sync (r5 review finding)."""
    import struct
    import time

    from distlearn_tpu.parallel.async_ea import AsyncEAServerConcurrent

    port = _ports()
    params0 = {"w": np.zeros(8, np.float32)}
    evicted_ev = threading.Event()
    out = {}

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        p, synced = c.sync_client(p)      # one clean sync: cid mapped
        assert synced
        c.broadcast.sock.sendall(struct.pack("<BQ", ord("J"), 64)[:5])
        evicted_ev.wait(timeout=60)
        p = c.rejoin({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        p, synced = c.sync_client(p)      # served by the PARKED worker
        out["synced"] = synced
        out["p"] = p
        c.close()

    tf = threading.Thread(target=flaky_fn, daemon=True)
    tf.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=1,
                                  handshake_timeout=0.5,
                                  rejoin_grace=30.0)
    srv.init_server({"w": params0["w"].copy()})
    srv.start()
    t0 = time.time()
    while 1 not in srv.evicted:
        assert time.time() - t0 < 30, srv.evicted
        time.sleep(0.02)
    evicted_ev.set()
    while srv.syncs_completed < 2:
        assert time.time() - t0 < 60, srv.syncs_completed
        time.sleep(0.02)
    tf.join(timeout=30)
    assert out["synced"]
    assert 1 not in srv.evicted
    # center: 0 +1.0 (first sync) then rejoiner takes 1.0, drifts +2,
    # delta (3-1)*0.5=1 -> center 2.0
    np.testing.assert_allclose(srv.current_center(params0)["w"], 2.0)
    srv.stop()
    srv.close()


def test_silent_rejoiner_conn_swept_after_deadline():
    """A rejoiner that dials the broadcast port but never speaks (the
    same hang that got it evicted) must be closed once its speak-by
    deadline passes — a silent socket may not keep the serve/dispatch
    loop (and `drained`) alive forever (r5 review finding)."""
    import socket as _socket
    import time

    from distlearn_tpu.comm.transport import connect

    port = _ports()
    out = {}
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.3))

    def hung_fn():
        b = connect("127.0.0.1", port)
        d = connect("127.0.0.1", port + 2)
        b.send_msg({"q": "Enter?", "clientID": 2})
        time.sleep(30)
        b.close()
        d.close()

    th = threading.Thread(target=hung_fn, daemon=True)
    th.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=2,
                        handshake_timeout=0.4)
    srv.init_server(_params())
    srv.sync_server(_params())           # evicts #2, serves #1
    assert 2 in srv.evicted
    # a silent re-dial: accepted as a rejoin candidate, never speaks
    s = _socket.create_connection(("127.0.0.1", port))
    srv._accept_rejoiners()
    assert len(srv._rejoin_pending) == 1
    time.sleep(0.5)                      # past the speak-by deadline
    srv._accept_rejoiners()
    assert srv._rejoin_pending == []     # swept: closed, no longer watched
    s.close()
    tl.join(timeout=30)
    srv.close()
    assert out["synced"]


def test_dead_tester_dropped_server_continues():
    """A tester that dies mid-push must be dropped (test_net returns False)
    without stalling the serve loop."""
    from distlearn_tpu.comm.transport import connect

    port = _ports()
    out = {}

    def tester_fn():
        t = connect("127.0.0.1", port + 2)   # test channel: port+numNodes+1
        t.close()                            # dies immediately

    tt = threading.Thread(target=tester_fn)
    tl = threading.Thread(target=_live_client_fn, args=(port, out, 0.2))
    tt.start()
    tl.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, with_tester=True,
                        handshake_timeout=0.5)
    srv.init_server(_params())
    srv.sync_server(_params())
    assert srv.test_net() is False           # dropped, not wedged
    assert srv.test_conn is None
    assert srv.test_net() is False           # later calls no-op
    tt.join(timeout=10)
    tl.join(timeout=30)
    srv.close()
    assert out["synced"]


def test_many_clients_with_abrupt_disconnects():
    """4 clients sync concurrently with uneven round counts; two disconnect
    ABRUPTLY (raw socket close, no protocol goodbye) after their rounds.
    The server must keep serving through the dirty EOFs (recv_any drops
    them) and the center must equal the sum of every delivered delta —
    mid-HANDSHAKE deaths are covered by test_dead_client_evicted_* above."""
    port = _ports(12)
    alpha, tau = 0.5, 1
    rounds = {1: 6, 2: 2, 3: 6, 4: 3}     # clients 2 and 4 stop early
    dies = {2, 4}
    sent = []
    lock = threading.Lock()

    def client_fn(node):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=tau, alpha=alpha)
        p = c.init_client({"w": np.zeros((2, 2), np.float32)})
        for r in range(rounds[node]):
            p = {"w": p["w"] + node * 0.1}
            before = p["w"].copy()
            p, synced = c.sync_client(p)
            assert synced
            with lock:
                sent.append(before - p["w"])
        if node in dies:
            c.conn.sock.close()           # dies abruptly (no clean close)
            c.broadcast.sock.close()
        else:
            c.close()

    threads = [threading.Thread(target=client_fn, args=(i,))
               for i in rounds]
    for t in threads:
        t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=4,
                        handshake_timeout=2.0)
    srv.init_server({"w": np.zeros((2, 2), np.float32)})
    total_syncs = sum(rounds.values())
    for _ in range(total_syncs):
        srv.sync_server({"w": np.zeros((2, 2), np.float32)})
    for t in threads:
        t.join(timeout=60)
    # every delta that a client saw complete must be on the center exactly once
    np.testing.assert_allclose(srv.center[0], np.sum(sent, axis=0),
                               rtol=1e-5, atol=1e-5)
    srv.close()


def _run_concurrent_accumulation(pin_device=None, n_clients=3, rounds=4):
    """Shared driver: N clients sync concurrently through per-client worker
    threads; the center must end at init + the sum of every pushed delta,
    and every client must complete all its rounds.  Exactness rationale:
    with alpha=0.5 and small integer drifts, every value is a small dyadic
    rational (denominator up to 2^rounds) — exactly representable in f32,
    so float addition is associative here and the sum is order-independent
    regardless of how the concurrent applies interleave."""
    from distlearn_tpu.parallel.async_ea import AsyncEAServerConcurrent

    port = _ports()
    tau, alpha = 1, 0.5
    params0 = {"w": np.zeros(64, np.float32)}
    deltas_pushed = []
    lock = threading.Lock()

    def client(node):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=tau, alpha=alpha)
        p = c.init_client({"w": params0["w"].copy()})
        rng = np.random.RandomState(node)
        for _ in range(rounds):
            # integer-valued params make (p - c) * 0.5 exact in f32 and the
            # center sum order-independent
            p = {"w": p["w"] + rng.randint(-4, 5, p["w"].shape) * 2.0}
            before = p["w"].copy()
            p, synced = c.sync_client(p)
            assert synced
            with lock:
                deltas_pushed.append((before - np.asarray(c.center[0]))
                                     * alpha)
        c.close()

    # start clients FIRST: the server constructor blocks in accept, and the
    # client connect() retries until the listener binds
    threads = [threading.Thread(target=client, args=(i + 1,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=n_clients,
                                  accept_timeout=60.0,
                                  pin_device=pin_device)
    srv.init_server({"w": params0["w"].copy()})
    srv.start()
    deadline = 60.0
    import time
    t0 = time.time()
    while srv.syncs_completed < n_clients * rounds:
        if time.time() - t0 > deadline:
            raise AssertionError(
                f"only {srv.syncs_completed}/{n_clients * rounds} syncs")
        time.sleep(0.02)
    for t in threads:
        t.join(timeout=20.0)
    if pin_device is not None:
        assert srv._dev_center is not None      # really device-resident
    got = srv.current_center(params0)["w"]
    want = params0["w"] + np.sum(deltas_pushed, axis=0)
    np.testing.assert_array_equal(got, want)
    srv.stop()
    srv.close()


def test_concurrent_server_overlapped_syncs_accumulate_exactly():
    _run_concurrent_accumulation()


def test_concurrent_server_device_pinned_center():
    """pin_device: the center lives on a jax device with a jitted donated
    apply; snapshots and accumulation must match the host path exactly."""
    import jax
    _run_concurrent_accumulation(pin_device=jax.devices()[0],
                                 n_clients=2, rounds=3)


def test_concurrent_server_evicts_dead_client_others_continue():
    """A client that dies mid-handshake is evicted by ITS worker; the other
    clients' workers keep serving."""
    from distlearn_tpu.parallel.async_ea import AsyncEAServerConcurrent

    port = _ports()
    params0 = {"w": np.zeros(32, np.float32)}

    def good_client(node, rounds):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=1, alpha=0.5)
        p = c.init_client({"w": params0["w"].copy()})
        for _ in range(rounds):
            p = {"w": p["w"] + 2.0}
            p, _ = c.sync_client(p)
        c.close()

    def dying_client(node):
        c = AsyncEAClient("127.0.0.1", port, node=node, tau=1, alpha=0.5)
        c.init_client({"w": params0["w"].copy()})
        # request entry, get admitted, then vanish mid-handshake
        c.broadcast.send_msg({"q": "Enter?", "clientID": node})
        c.conn.recv_msg()               # ENTER
        c.close()                       # die before Center?

    t1 = threading.Thread(target=good_client, args=(1, 3), daemon=True)
    t2 = threading.Thread(target=dying_client, args=(2,), daemon=True)
    t1.start(); t2.start()
    # the dying client's eviction comes from its CLOSED socket
    # (ConnectionError is immediate), not this timeout — keep it generous
    # so a loaded 1-core host cannot evict the slow-but-alive good client
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=2,
                                  accept_timeout=60.0,
                                  handshake_timeout=20.0)
    srv.init_server({"w": params0["w"].copy()})
    srv.start()
    import time
    t0 = time.time()
    while srv.syncs_completed < 3:
        # generous: observed flaking at 30s when the full suite saturates
        # the 1-core host; solo it completes in well under a second
        assert time.time() - t0 < 90.0, (
            f"syncs={srv.syncs_completed} inflight={srv._inflight} "
            f"evicted={srv.evicted} "
            f"dispatch_closed={srv._dispatch_closed.is_set()} "
            f"queues={[q.qsize() for q in srv._queues]} "
            f"threads={[th.is_alive() for th in srv._threads]}")
        time.sleep(0.02)
    t1.join(timeout=20.0)
    t2.join(timeout=20.0)
    assert 2 in srv.evicted
    assert srv.syncs_completed == 3
    srv.stop()
    srv.close()


def test_server_evicts_config_skewed_client_before_apply():
    """A client whose model config differs (wrong-shaped delta) must be
    EVICTED with the center untouched — not crash the serve loop or
    (concurrent path) silently kill a worker."""
    port = _ports()
    init = {"w": np.ones(16, np.float32)}

    def skewed_client():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        # receive the 16-elem center into a 16-elem buffer, then push a
        # WRONG-SHAPED delta by faking the handshake manually
        c.center = [c.broadcast.recv_tensor()]
        c.broadcast.send_msg({"q": "Enter?", "clientID": 1})
        c.conn.recv_msg()                    # ENTER
        c.conn.send_msg("Center?")
        c.conn.recv_tensor()
        c.conn.send_msg("delta?")
        c.conn.recv_msg()                    # delta
        c.conn.send_tensor(np.ones(8, np.float32))   # wrong shape
        c.close()

    t = threading.Thread(target=skewed_client, daemon=True)
    t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1,
                        accept_timeout=60.0, handshake_timeout=5.0)
    srv.init_server({"w": init["w"].copy()})
    import pytest
    with pytest.raises((TimeoutError, RuntimeError)):
        # the skewed client is evicted; with no clients left the next
        # admission wait times out / runs out of connections
        srv.sync_server({"w": init["w"]}, timeout=5.0)
    t.join(timeout=10.0)
    assert 1 in srv.evicted
    np.testing.assert_array_equal(srv.center[0], init["w"])  # untouched
    srv.close()


def test_server_evicts_dtype_skewed_client_before_apply():
    """A right-shaped but wrong-DTYPE delta (e.g. f64 from a config-skewed
    client) is config skew too: eviction, center untouched — never a
    silent astype into the center (ADVICE r3)."""
    port = _ports()
    init = {"w": np.ones(16, np.float32)}

    def skewed_client():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        c.center = [c.broadcast.recv_tensor()]
        c.broadcast.send_msg({"q": "Enter?", "clientID": 1})
        c.conn.recv_msg()                    # ENTER
        c.conn.send_msg("Center?")
        c.conn.recv_tensor()
        c.conn.send_msg("delta?")
        c.conn.recv_msg()                    # delta
        c.conn.send_tensor(np.ones(16, np.float64))  # right shape, wrong dtype
        c.close()

    t = threading.Thread(target=skewed_client, daemon=True)
    t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1,
                        accept_timeout=60.0, handshake_timeout=5.0)
    srv.init_server({"w": init["w"].copy()})
    with pytest.raises((TimeoutError, RuntimeError)):
        srv.sync_server({"w": init["w"]}, timeout=5.0)
    t.join(timeout=10.0)
    assert 1 in srv.evicted
    np.testing.assert_array_equal(srv.center[0], init["w"])  # untouched
    srv.close()


def test_client_wide_dtype_params_interop():
    """A client whose local params drifted to f64 still syncs: deltas go
    over the wire in the CENTER's dtype (f32), so the strict server-side
    dtype check passes and the elastic math stays consistent."""
    port = _ports()
    out = {}

    def client():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": np.zeros(8, np.float32)})
        p = {"w": p["w"].astype(np.float64) + 2.0}   # f64 drift
        p, synced = c.sync_client(p)
        out["synced"] = synced
        out["p"] = p
        c.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, accept_timeout=60.0)
    srv.init_server({"w": np.zeros(8, np.float32)})
    srv.sync_server({"w": np.zeros(8, np.float32)})
    t.join(timeout=10.0)
    assert out["synced"]
    assert srv.center[0].dtype == np.float32
    np.testing.assert_allclose(srv.center[0], 1.0)   # (2-0)*0.5 applied
    np.testing.assert_allclose(out["p"]["w"], 1.0)   # p -= delta


def test_concurrent_server_serial_api_still_works():
    """The concurrent server's center is immutable-published (read-only
    leaves); the inherited serial sync_server() must route its apply
    through the same publish path instead of mutating frozen arrays."""
    from distlearn_tpu.parallel.async_ea import AsyncEAServerConcurrent
    port = _ports()
    out = {}

    def client():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": np.zeros(8, np.float32)})
        p = {"w": p["w"] + np.float32(2.0)}
        p, synced = c.sync_client(p)
        out["synced"] = synced
        c.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=1,
                                  accept_timeout=60.0)
    srv.init_server({"w": np.zeros(8, np.float32)})
    # serial API on the concurrent class — no start()/worker threads
    got = srv.sync_server({"w": np.zeros(8, np.float32)})
    t.join(timeout=10.0)
    assert out["synced"]
    np.testing.assert_allclose(got["w"], 1.0)
    assert srv.syncs_completed == 1
    srv.close()
