"""Model zoo tests: shapes, determinism, gradient flow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import random

from distlearn_tpu.models import cifar_convnet, loss_fn, mnist_cnn, param_count


@pytest.mark.parametrize("factory,in_shape", [
    (mnist_cnn, (32, 32, 1)),
    (cifar_convnet, (32, 32, 3)),
])
def test_forward_shapes_and_logprobs(factory, in_shape):
    model = factory()
    params, state = model.init(random.PRNGKey(0))
    x = random.normal(random.PRNGKey(1), (4,) + in_shape, jnp.float32)
    log_probs, _ = model.apply(params, state, x, train=False)
    assert log_probs.shape == (4, 10)
    # log-softmax rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(np.asarray(log_probs)).sum(-1), 1.0,
                               atol=1e-5)


def test_mnist_param_count_matches_reference_arch():
    # conv5x5(1->16)+b, conv5x5(16->16)+b, linear(400->10)+b
    # (ref architecture examples/mnist.lua:53-67)
    expected = (5 * 5 * 1 * 16 + 16) + (5 * 5 * 16 * 16 + 16) + (400 * 10 + 10)
    params, _ = mnist_cnn().init(random.PRNGKey(0))
    assert param_count(params) == expected


def test_init_deterministic():
    m = mnist_cnn()
    p1, _ = m.init(random.PRNGKey(0))
    p2, _ = m.init(random.PRNGKey(0))
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gradients_nonzero_everywhere():
    model = cifar_convnet()
    params, state = model.init(random.PRNGKey(0))
    x = random.normal(random.PRNGKey(1), (8, 32, 32, 3), jnp.float32)
    y = jnp.arange(8) % 10

    def f(p):
        return loss_fn(model, p, state, x, y, train=True,
                       rng=random.PRNGKey(2))[0]

    grads = jax.grad(f)(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert float(jnp.abs(leaf).max()) > 0


def test_batchnorm_state_updates_in_train_only():
    model = cifar_convnet()
    params, state = model.init(random.PRNGKey(0))
    x = random.normal(random.PRNGKey(1), (8, 32, 32, 3), jnp.float32)
    _, st_train = model.apply(params, state, x, train=True)
    _, st_eval = model.apply(params, state, x, train=False)
    m0 = np.asarray(state["bn1"]["mean"])
    assert not np.allclose(np.asarray(st_train["bn1"]["mean"]), m0)
    np.testing.assert_array_equal(np.asarray(st_eval["bn1"]["mean"]), m0)
