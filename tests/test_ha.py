"""Center HA (docs/HA.md): checkpoint round-trips restore bitwise, a
warm standby promotes into the next epoch, the epoch fence refuses
zombies loudly, connect() backs off with full jitter, start/stop cycles
leak nothing, SIGTERM flushes a final checkpoint, and diststat derives
the failover table from the obs trail."""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from distlearn_tpu.comm import ProtocolError
from distlearn_tpu.comm import transport as transport_mod
from distlearn_tpu.obs import core
from distlearn_tpu.parallel import ha
from distlearn_tpu.parallel.async_ea import (AsyncEAServerConcurrent,
                                             StaleCenterError, _leaves)
from distlearn_tpu.utils.checkpoint import latest_step

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import chaos  # noqa: E402
import diststat  # noqa: E402

HOST = "127.0.0.1"


@pytest.fixture()
def obs_on():
    core.configure(True)
    core.REGISTRY.reset()
    yield
    core.REGISTRY.reset()
    core.configure(None)


def _bitwise(a: list[np.ndarray], b: list[np.ndarray]) -> bool:
    return (len(a) == len(b)
            and all(x.dtype == y.dtype and np.array_equal(x, y)
                    for x, y in zip(a, b)))


# ----------------------------------------------- checkpoint round-trips

@pytest.mark.shard
@pytest.mark.parametrize("codec", ["int8", "fp16"])
@pytest.mark.parametrize("shards", [1, 4])
def test_checkpoint_roundtrip_bitwise(tmp_path, obs_on, codec, shards):
    """Sync under a quantized codec, checkpoint, restore into a FRESH
    standby: every center leaf comes back bitwise identical, and the
    promoted epoch fences out the old one."""
    base = chaos._params()
    port = chaos._reserve_window(8)
    srv, (cl,), (p,) = chaos._spawn_fleet(HOST, port, 1, shards,
                                          [codec], False, None, base)
    standby = None
    try:
        srv.enable_checkpoint(str(tmp_path), every=1)
        for r in range(3):
            p = chaos._drift(p, r)
            p, _ = cl.sync_client(p)
        chaos._settle_fleet([cl], srv)
        srv.checkpoint_now(wait=True)
        want = chaos._leaves_of(srv)

        restored, meta = ha.restore_center(str(tmp_path), base)
        assert _bitwise(want, _leaves(restored))
        assert meta["epoch"] == srv.epoch == 0
        assert meta["shards"] == shards

        win2 = chaos._reserve_window(8)
        standby = AsyncEAServerConcurrent(HOST, win2, num_nodes=1,
                                          shards=shards, standby=True)
        ha.promote(standby, str(tmp_path), base)
        assert standby.epoch == srv.epoch + 1
        assert _bitwise(want, chaos._leaves_of(standby))
        # the restored ledger covers every stripe of the only client
        assert len(standby._applied_seq[1]) == len(standby.stripes)
    finally:
        if standby is not None:
            standby.close()
        chaos._teardown([cl], srv)


@pytest.mark.shard
def test_mixed_fleet_readmits_after_promotion(tmp_path, obs_on):
    """Legacy-free mixed fleet (raw + int8 + fp16 packed clients) against
    a striped center: kill + promote, every client fails over and keeps
    syncing — no client restart, one promotion, zero stale refusals."""
    base = chaos._params()
    wins = [chaos._reserve_window(10), chaos._reserve_window(10)]
    srv, clients, ps = chaos._spawn_fleet(
        HOST, wins[0], 3, 4, ["raw", "int8", "fp16"], False,
        [(HOST, wins[1])], base)
    try:
        srv.enable_checkpoint(str(tmp_path), every=1)
        for r in range(2):
            for i, cl in enumerate(clients):
                ps[i] = chaos._drift(ps[i], r)
                ps[i], _ = cl.sync_client(ps[i])
        chaos._settle_fleet(clients, srv)
        srv = chaos._kill_and_promote(srv, HOST, wins[1], base,
                                      str(tmp_path), 4, 1,
                                      flush_first=True)
        for i, cl in enumerate(clients):
            ps[i] = chaos._drift(ps[i], 2)
            ps[i] = chaos._sync_with_failover(cl, ps[i])
        chaos._settle_fleet(clients, srv)
        totals = chaos._totals(core.REGISTRY.snapshot())
        assert totals["async_ea_failover_promotions_total"] == 1
        assert totals.get("async_ea_failover_stale_refusals_total", 0) == 0
        assert srv.syncs_completed >= 3
    finally:
        chaos._teardown(clients, srv)


# ------------------------------------------------------- the epoch fence

def test_stale_center_refused_and_dropped_from_dial_list(obs_on):
    """A center older than what the client has synced with must refuse
    the handshake loudly (StaleCenterError is-a ProtocolError), and the
    failover walk must evict it from the dial list rather than retry."""
    base = chaos._params()
    port = chaos._reserve_window(4)
    srv, (cl,), (p,) = chaos._spawn_fleet(HOST, port, 1, 1, ["raw"],
                                          False, None, base)
    try:
        p, _ = cl.sync_client(chaos._drift(p, 0))
        assert cl._seen_epoch == 0
        cl._seen_epoch = 5   # as if a promoted center was seen elsewhere
        with pytest.raises(StaleCenterError):
            cl.sync_client(chaos._drift(p, 1))
        assert issubclass(StaleCenterError, ProtocolError)
        deadline = time.monotonic() + 5.0   # eviction lands dispatcher-side
        while 1 not in srv.evicted and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 1 in srv.evicted   # the zombie also dropped the client
        # only stale centers remain on the dial list -> loud failure,
        # not an infinite re-dial loop
        with pytest.raises(ConnectionError):
            cl.failover(p, retries=3, retry_interval=0.01,
                        handshake_timeout=2.0)
        assert cl._centers == []
        totals = chaos._totals(core.REGISTRY.snapshot())
        # both sides count refusals into the family and the test runs
        # them in one process: server Enter fence + server rejoin fence
        # + the client's dial-walk eviction
        assert totals["async_ea_failover_stale_refusals_total"] == 3
    finally:
        chaos._teardown([cl], srv)


# -------------------------------------------------- connect() backoff

def test_connect_backoff_exponential_with_cap(obs_on, monkeypatch):
    sleeps: list[float] = []
    monkeypatch.setattr(transport_mod.time, "sleep",
                        lambda s: sleeps.append(s))
    monkeypatch.setattr(transport_mod.random, "uniform", lambda a, b: b)
    port = chaos._reserve_window(1)   # reserved then released: refuses
    with pytest.raises(ConnectionError):
        transport_mod.connect(HOST, port, retries=5, retry_interval=0.1,
                              max_interval=0.8)
    # full-jitter cap doubles from retry_interval, clamped at max_interval
    assert sleeps[:4] == [pytest.approx(0.1), pytest.approx(0.2),
                          pytest.approx(0.4), pytest.approx(0.8)]
    assert all(s <= 0.8 + 1e-9 for s in sleeps)
    labeled = chaos._labeled(core.REGISTRY.snapshot(),
                             "transport_connect_retries_total")
    assert labeled.get('{"reason": "refused"}', 0) == 5


def test_connect_jitter_samples_below_cap(monkeypatch):
    draws: list[tuple[float, float]] = []
    monkeypatch.setattr(transport_mod.time, "sleep", lambda s: None)
    monkeypatch.setattr(transport_mod.random, "uniform",
                        lambda a, b: draws.append((a, b)) or a)
    port = chaos._reserve_window(1)
    with pytest.raises(ConnectionError):
        transport_mod.connect(HOST, port, retries=3, retry_interval=0.25,
                              max_interval=5.0)
    assert [d[0] for d in draws] == [0.0, 0.0, 0.0]
    assert [d[1] for d in draws] == [pytest.approx(0.25),
                                     pytest.approx(0.5),
                                     pytest.approx(1.0)]


# ------------------------------------------- shutdown hygiene (no leaks)

def test_start_stop_cycles_leak_nothing(obs_on):
    """Repeated fleet up/sync/down cycles (the chaos soak's inner loop)
    must not accumulate fds or threads; the thread gauge returns to 0."""
    base = chaos._params()
    readings = []
    for cycle in range(4):
        port = chaos._reserve_window(6)
        srv, (cl,), (p,) = chaos._spawn_fleet(HOST, port, 1, 2, ["raw"],
                                              False, None, base)
        p, _ = cl.sync_client(chaos._drift(p, cycle))
        chaos._settle_fleet([cl], srv)
        chaos._teardown([cl], srv)
        deadline = time.monotonic() + 5.0
        while (threading.active_count() > 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        readings.append((chaos._fd_count(), threading.active_count()))
    fds = [f for f, _ in readings]
    ths = [t for _, t in readings]
    assert max(fds[1:]) <= fds[0], readings
    assert max(ths[1:]) <= ths[0], readings
    totals = chaos._totals(core.REGISTRY.snapshot())
    assert totals.get("async_ea_server_threads", 0) == 0
    assert totals.get("async_ea_inflight", 0) == 0


# --------------------------------------------------- SIGTERM final flush

def test_install_signal_flush_checkpoints_and_chains(tmp_path, obs_on):
    base = chaos._params()
    port = chaos._reserve_window(4)
    srv, (cl,), (p,) = chaos._spawn_fleet(HOST, port, 1, 1, ["raw"],
                                          False, None, base)
    hits: list[int] = []
    prev = signal.signal(signal.SIGUSR1, lambda n, f: hits.append(n))
    try:
        srv.enable_checkpoint(str(tmp_path), every=10 ** 9)
        p, _ = cl.sync_client(chaos._drift(p, 0))
        chaos._settle_fleet([cl], srv)
        assert latest_step(str(tmp_path)) is None   # cadence never hit
        ha.install_signal_flush(srv, signums=(signal.SIGUSR1,))
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        while not hits and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hits == [signal.SIGUSR1]   # prior handler chained
        assert latest_step(str(tmp_path)) is not None
    finally:
        signal.signal(signal.SIGUSR1, prev)
        chaos._teardown([cl], srv)


# --------------------------------------------- diststat failover table

def _counter(name, value, labels=None, labelnames=()):
    return {"name": name, "kind": "counter", "help": "",
            "labelnames": list(labelnames),
            "samples": [{"labels": labels or {}, "value": value}]}


def test_diststat_failover_table(tmp_path):
    recs = [
        {"type": "span", "name": "async_ea.promote", "ts": 1.0,
         "dur": 0.2},
        {"type": "span", "name": "async_ea.failover", "ts": 1.1,
         "dur": 0.5},
        {"type": "span", "name": "async_ea.failover", "ts": 1.2,
         "dur": 0.1},
        {"type": "snapshot", "ts": 2.0, "metrics": [
            _counter("async_ea_evictions_total", 3),
            _counter("async_ea_rejoins_total", 3),
            _counter("async_ea_failover_redials_total", 4),
            _counter("async_ea_failover_promotions_total", 1),
            _counter("center_ckpt_saves_total", 7),
            {"name": "async_ea_failover_replays_total", "kind": "counter",
             "help": "", "labelnames": ["outcome"],
             "samples": [{"labels": {"outcome": "replayed"}, "value": 2},
                         {"labels": {"outcome": "clean"}, "value": 1}]},
        ]},
    ]
    log = tmp_path / "run.jsonl"
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    tab = diststat.summarize_run([str(log)])["failover"]
    assert tab["evictions"] == 3 and tab["rejoins"] == 3
    assert tab["redials"] == 4 and tab["promotions"] == 1
    assert tab["ckpt_saves"] == 7
    assert tab["replays"] == {"clean": 1, "replayed": 2}
    assert tab["latency"]["async_ea.promote"]["count"] == 1
    assert tab["latency"]["async_ea.failover"]["p50"] == pytest.approx(0.1)


def test_diststat_failover_table_empty_without_activity(tmp_path):
    log = tmp_path / "run.jsonl"
    log.write_text(json.dumps(
        {"type": "snapshot", "ts": 1.0, "metrics": [
            _counter("async_ea_syncs_total", 5)]}) + "\n")
    assert diststat.summarize_run([str(log)])["failover"] == {}


# ------------------------------------------------ diststat codec table

def _histogram(name, rows, labelnames=("shard",)):
    return {"name": name, "kind": "histogram", "help": "",
            "labelnames": list(labelnames),
            "samples": [{"labels": lb, "sum": s, "count": c}
                        for lb, s, c in rows]}


def test_diststat_codec_table(tmp_path):
    recs = [
        {"type": "snapshot", "ts": 2.0, "metrics": [
            _histogram("wire_encode_seconds",
                       [({"shard": "0"}, 0.4, 4),
                        ({"shard": "1"}, 0.2, 2),
                        ({"shard": "all"}, 0.9, 3)]),
            _histogram("center_apply_seconds",
                       [({"shard": "0"}, 0.08, 4),
                        ({"shard": "all"}, 0.3, 3)]),
            {"name": "wire_zero_copy_total", "kind": "counter",
             "help": "", "labelnames": ["result"],
             "samples": [{"labels": {"result": "hit"}, "value": 9},
                         {"labels": {"result": "miss"}, "value": 1}]},
        ]},
    ]
    log = tmp_path / "run.jsonl"
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    tab = diststat.summarize_run([str(log)])["codec"]
    st = tab["stripes"]
    assert list(st) == ["0", "1", "all"]
    assert st["0"]["encodes"] == 4
    assert st["0"]["encode_mean"] == pytest.approx(0.1)
    assert st["0"]["applies"] == 4
    assert st["0"]["apply_mean"] == pytest.approx(0.02)
    assert st["1"]["encodes"] == 2 and st["1"]["applies"] == 0
    assert math.isnan(st["1"]["apply_mean"])
    assert st["all"]["encode_mean"] == pytest.approx(0.3)
    assert tab["zero_copy"] == {"hit": 9, "miss": 1, "hit_ratio": 0.9}


def test_diststat_codec_table_empty_without_fused_activity(tmp_path):
    log = tmp_path / "run.jsonl"
    log.write_text(json.dumps(
        {"type": "snapshot", "ts": 1.0, "metrics": [
            _counter("async_ea_syncs_total", 5)]}) + "\n")
    assert diststat.summarize_run([str(log)])["codec"] == {}
