"""The fused (Pallas + bucketed psum) trainer path must produce the SAME
training trajectory as the per-leaf tree_map path — VERDICT r1 #5: the
kernels are a component only if the production steps run through them.

Runs on the 8-device CPU mesh (Pallas interpret mode) so the identical code
path compiles on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from distlearn_tpu.data import synthetic_cifar10
from distlearn_tpu.models import mnist_cnn
from distlearn_tpu.ops import flatten as flatten_lib
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.train import (build_ea_steps, build_sgd_step,
                                 build_sync_step, init_ea_state,
                                 init_train_state)


def _data(tree, batch=16):
    x = np.random.RandomState(0).randn(batch, 32, 32, 1).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, (batch,)).astype(np.int32)
    sh = NamedSharding(tree.mesh, P(tree.axis_name))
    return jax.device_put(x, sh), jax.device_put(y, sh)


def _model():
    return mnist_cnn()


def _leaves_equal(a, b, exact=True):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, z in zip(la, lb):
        x, z = np.asarray(x), np.asarray(z)
        if exact:
            np.testing.assert_array_equal(x, z)
        else:
            np.testing.assert_allclose(x, z, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("max_bucket_bytes", [None, 64 * 1024])
def test_fused_sgd_step_matches_treemap(max_bucket_bytes):
    tree = MeshTree(num_nodes=8)
    model = _model()
    bx, by = _data(tree)
    ts_a = init_train_state(model, tree, random.PRNGKey(0), 10)
    ts_b = init_train_state(model, tree, random.PRNGKey(0), 10)
    step_ref = build_sgd_step(model, tree, lr=0.1, fused=False)
    step_fused = build_sgd_step(model, tree, lr=0.1, fused=True,
                                max_bucket_bytes=max_bucket_bytes)
    for _ in range(3):
        ts_a, loss_a = step_ref(ts_a, bx, by)
        ts_b, loss_b = step_fused(ts_b, bx, by)
    _leaves_equal(ts_a.params, ts_b.params, exact=False)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


def test_fused_sgd_step_with_contrib_matches():
    tree = MeshTree(num_nodes=8)
    model = _model()
    bx, by = _data(tree)
    contrib = jax.device_put(
        np.array([1, 1, 0, 1, 0, 1, 1, 1], np.float32),
        NamedSharding(tree.mesh, P(tree.axis_name)))
    ts_a = init_train_state(model, tree, random.PRNGKey(0), 10)
    ts_b = init_train_state(model, tree, random.PRNGKey(0), 10)
    step_ref = build_sgd_step(model, tree, lr=0.1, with_contrib=True,
                              fused=False)
    step_fused = build_sgd_step(model, tree, lr=0.1, with_contrib=True,
                                fused=True)
    ts_a, _ = step_ref(ts_a, bx, by, contrib)
    ts_b, _ = step_fused(ts_b, bx, by, contrib)
    _leaves_equal(ts_a.params, ts_b.params, exact=False)
    np.testing.assert_array_equal(np.asarray(ts_a.sync.my_steps),
                                  np.asarray(ts_b.sync.my_steps))
    # Winner-takes-all sync must leave params bitwise identical across the
    # device shards (params are replicated, spec P()).
    sync = build_sync_step(tree)
    ts_b = sync(ts_b)
    for leaf in jax.tree_util.tree_leaves(ts_b.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_fused_ea_round_matches_treemap():
    tree = MeshTree(num_nodes=8)
    model = _model()
    bx, by = _data(tree)
    ts_a = init_ea_state(model, tree, random.PRNGKey(0), 10)
    ts_b = init_ea_state(model, tree, random.PRNGKey(0), 10)
    local_a, round_a = build_ea_steps(model, tree, lr=0.05, alpha=0.25,
                                      fused=False)
    local_b, round_b = build_ea_steps(model, tree, lr=0.05, alpha=0.25,
                                      fused=True)
    for _ in range(2):
        ts_a, _ = local_a(ts_a, bx, by)
        ts_b, _ = local_b(ts_b, bx, by)
        ts_a = round_a(ts_a)
        ts_b = round_b(ts_b)
    _leaves_equal(ts_a.params, ts_b.params, exact=False)
    _leaves_equal(ts_a.center, ts_b.center, exact=False)


def test_bucket_spec_roundtrip_mixed_dtypes():
    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": jnp.ones((5,), jnp.float64),
            "c": jnp.full((3, 3), 2.0, jnp.float32),
            "d": jnp.asarray(7.0, jnp.float64)}
    spec = flatten_lib.make_bucket_spec(tree)
    assert len(spec.buckets) == 2  # one per dtype, no casting
    flats = flatten_lib.pack_buckets(spec, tree)
    for b, f in zip(spec.buckets, flats):
        assert f.dtype == b.dtype and f.shape == (b.padded,)
    back = flatten_lib.unpack_buckets(spec, flats)
    _leaves_equal(tree, back)


def test_bucket_spec_respects_max_bytes():
    tree = [jnp.zeros((1000,), jnp.float32) for _ in range(10)]
    spec = flatten_lib.make_bucket_spec(tree, max_bucket_bytes=3000 * 4)
    assert len(spec.buckets) >= 4          # <=3 leaves of 1000 f32 per bucket
    assert all(sum(b.sizes) <= 3000 for b in spec.buckets)
    flats = flatten_lib.pack_buckets(spec, tree)
    back = flatten_lib.unpack_buckets(spec, flats)
    _leaves_equal(tree, back)


def test_fused_lm_step_matches_unfused():
    """The LM step's Pallas packed-bucket SGD update must reproduce the
    per-leaf tree_map update (same mesh, same batch) — the wiring that
    removes the ~21%-of-step-time per-leaf f32 update the dim-4096
    profile exposed."""
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import (param_specs,
                                                  transformer_lm)
    from distlearn_tpu.train.lm import build_lm_step

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                ("data", "seq", "model"))
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16)
    params, _ = lm.init(random.PRNGKey(0))
    toks = jax.device_put(
        np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))
    sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                param_specs(params, tp_axis="model"))
    outs = {}
    for fused in (False, True):
        step = build_lm_step(lm, mesh, params, lr=0.1, fused=fused,
                             donate=False)
        p = jax.device_put(params, sh)
        for _ in range(3):
            p, loss = step(p, toks)
        outs[fused] = (float(loss), jax.tree_util.tree_leaves(
            jax.device_get(p)))
    np.testing.assert_allclose(outs[False][0], outs[True][0], rtol=1e-6)
    for a, b in zip(outs[False][1], outs[True][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
