"""MeshTree collective semantics (the torch-ipc ``tree`` contract, SURVEY §1 L1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distlearn_tpu.parallel.mesh import MeshTree


@pytest.mark.parametrize("num_nodes", [2, 4, 8])
def test_all_reduce_sums_across_nodes(num_nodes):
    tree = MeshTree(num_nodes=num_nodes)
    vals = tree.put_per_node(
        {"w": np.arange(num_nodes * 3, dtype=np.float32).reshape(num_nodes, 3)})
    reduced, n = tree.all_reduce(vals)
    assert n == num_nodes
    expected = np.arange(num_nodes * 3, dtype=np.float32).reshape(num_nodes, 3).sum(0)
    for i in range(num_nodes):
        np.testing.assert_array_equal(tree.node_slice(reduced, i)["w"], expected)


def test_all_reduce_contrib_mask_counts_contributors():
    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    vals = tree.put_per_node(np.ones((num_nodes, 2), np.float32))
    contrib = np.array([1, 0, 1, 0], np.int32)
    reduced, n = tree.all_reduce(vals, contrib=contrib)
    assert n == 2
    for i in range(num_nodes):
        np.testing.assert_array_equal(tree.node_slice(reduced, i), np.full(2, 2.0, np.float32))


@pytest.mark.parametrize("src", [0, 2])
def test_scatter_broadcasts_src_row(src):
    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    data = np.stack([np.full(3, i, np.float32) for i in range(num_nodes)])
    out = tree.scatter(tree.put_per_node(data), src=src)
    for i in range(num_nodes):
        np.testing.assert_array_equal(tree.node_slice(out, i), np.full(3, src, np.float32))


def test_replicate_and_pytree_walk():
    tree = MeshTree(num_nodes=4)
    params = {"a": np.ones(3, np.float32), "b": {"c": np.zeros((2, 2), np.float32)}}
    rep = tree.replicate(params)
    assert rep["a"].shape == (4, 3)
    walked = tree.walk(rep, lambda x: x + 1)
    np.testing.assert_array_equal(tree.node_slice(walked, 2)["b"]["c"], np.ones((2, 2)))


def test_spmd_step_with_in_step_collectives():
    """Composing in-step all_reduce inside a shard_map'd fn over the mesh."""
    from distlearn_tpu.parallel import mesh as m
    tree = MeshTree(num_nodes=8)
    from jax.sharding import PartitionSpec as P

    def step(x):
        x = jnp.squeeze(x, 0)
        red, n = m.all_reduce(x, tree.axis_name)
        return (red / n)[None]

    fn = tree.spmd(step, in_specs=(P(tree.axis_name),), out_specs=P(tree.axis_name))
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = fn(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), x.mean()), rtol=1e-6)
