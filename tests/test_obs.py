"""Runtime telemetry subsystem (distlearn_tpu/obs): registry semantics,
kill-switch behavior (including the no-allocation disabled path), span
ring/spill, the /metrics + /healthz endpoint, and the end-to-end
acceptance run — a concurrent AsyncEA server with an injected
eviction/rejoin whose JSONL trail diststat must reconstruct."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from distlearn_tpu import obs
from distlearn_tpu.obs import core, export, trace

from tests.net_util import reserve_port_window

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import diststat  # noqa: E402

pytestmark = pytest.mark.obs


@pytest.fixture()
def clean_obs():
    """Force-enable obs with a fresh registry/ring, restore after.  The
    registry is process-global: handles other tests' objects already hold
    go stale on reset, which telemetry tolerates."""
    core.configure(True)
    core.REGISTRY.reset()
    trace.clear()
    trace.set_spill(None)
    export.set_health_source(None)
    yield
    trace.set_spill(None)
    trace.clear()
    export.set_health_source(None)
    core.REGISTRY.reset()
    core.configure(None)


# -- core registry -----------------------------------------------------------

def test_counter_gauge_histogram(clean_obs):
    c = obs.counter("t_total", "help text")
    c.inc()
    c.inc(41)
    assert c.value == 42
    g = obs.gauge("t_gauge")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    h = obs.histogram("t_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    s = core.REGISTRY._families["t_seconds"].sample()[0]
    assert s["count"] == 3 and s["inf"] == 1
    assert s["buckets"] == {"0.1": 1, "1.0": 1}
    assert abs(s["sum"] - 5.55) < 1e-9


def test_labels_and_overflow(clean_obs):
    fam = obs.counter("t_lbl_total", labels=("conn",), max_children=2)
    fam.labels(conn="a").inc(1)
    fam.labels(conn="b").inc(2)
    fam.labels(conn="c").inc(4)      # over the bound -> __overflow__
    fam.labels(conn="d").inc(8)      # same overflow child
    by = {s["labels"]["conn"]: s["value"] for s in fam.sample()}
    assert by == {"a": 1, "b": 2, core._OVERFLOW: 12}
    # same label set resolves the same child, no growth
    assert fam.labels(conn="a") is fam.labels(conn="a")


def test_re_registration_mismatch_raises(clean_obs):
    obs.counter("t_kind")
    with pytest.raises(ValueError):
        obs.gauge("t_kind")
    obs.counter("t_lbls", labels=("x",))
    with pytest.raises(ValueError):
        obs.counter("t_lbls", labels=("y",))


def test_prometheus_rendering(clean_obs):
    obs.counter("t_c_total", "counts things").inc(7)
    obs.histogram("t_h_seconds", buckets=(0.5,)).observe(0.1)
    text = core.REGISTRY.render_prometheus()
    assert "# HELP t_c_total counts things" in text
    assert "# TYPE t_c_total counter" in text
    assert "t_c_total 7" in text
    assert 't_h_seconds_bucket{le="0.5"} 1' in text
    assert 't_h_seconds_bucket{le="+Inf"} 1' in text
    assert "t_h_seconds_count 1" in text


# -- kill switch -------------------------------------------------------------

def test_kill_switch_factories_return_null(tmp_path):
    core.configure(False)
    try:
        assert obs.counter("t_off") is obs.NULL
        assert obs.gauge("t_off") is obs.NULL
        assert obs.histogram("t_off") is obs.NULL
        assert obs.span("t_off") is trace.NULL_SPAN
        path = tmp_path / "off.jsonl"
        trace.set_spill(str(path))         # no-op while disabled
        with obs.span("t_off", x=1):
            pass
        assert obs.write_snapshot(str(path)) is None
        assert obs.start_http_server() is None
        assert not path.exists()
        assert trace.spans() == []
    finally:
        core.configure(None)
        trace.set_spill(None)


def test_disabled_increment_allocates_nothing():
    """The tier-1 overhead bar: with the kill switch off, an
    instrumentation site's counter increment leaves no trace — no
    retained allocation at all (timing asserts flake in CI; allocation
    is the deterministic proxy)."""
    core.configure(False)
    try:
        c = obs.counter("t_alloc_total")
        assert c is obs.NULL

        def run(sink, n):
            inc = sink.inc
            labels = sink.labels
            for _ in range(n):
                inc(5)
                labels(conn="x").inc(3)

        run(c, 10)                     # warm code paths / caches
        import tracemalloc
        tracemalloc.start()
        before = tracemalloc.get_traced_memory()[0]
        run(c, 1000)
        after = tracemalloc.get_traced_memory()[0]
        tracemalloc.stop()
        assert after - before == 0
    finally:
        core.configure(None)


def test_kill_switch_env_subprocess(tmp_path):
    """DISTLEARN_OBS=0 end to end in a fresh process: instrumented
    transport runs, yet the registry stays empty and no spill file is
    created — the run emits nothing."""
    code = """
import sys
import numpy as np
from distlearn_tpu import obs
from distlearn_tpu.comm import transport

assert not obs.enabled()
assert obs.counter("x_total") is obs.NULL
obs.set_spill(sys.argv[1])
srv = transport.Server()
cli = transport.connect(srv.host, srv.port)
(sc,) = srv.accept(1)
cli.send_msg({"q": "hi"})
assert sc.recv_msg() == {"q": "hi"}
cli.send_tensor(np.arange(8, dtype=np.float32))
assert sc.recv_tensor().sum() == 28.0
with obs.span("x"):
    pass
assert cli.bytes_sent > 0               # the attribute still counts
assert obs.REGISTRY.snapshot() == []    # ...but nothing registered
assert obs.write_snapshot(sys.argv[1]) is None
assert obs.start_http_server() is None
"""
    spill = tmp_path / "off.jsonl"
    env = dict(os.environ, DISTLEARN_OBS="0", JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code, str(spill)],
                   check=True, env=env, timeout=120)
    assert not spill.exists()


# -- spans -------------------------------------------------------------------

def test_span_ring_labels_and_err(clean_obs):
    with obs.span("ok", cid=3):
        pass
    with pytest.raises(RuntimeError):
        with obs.span("boom"):
            raise RuntimeError("x")
    recs = obs.spans()
    assert [r["name"] for r in recs] == ["ok", "boom"]
    assert recs[0]["labels"] == {"cid": 3}
    assert recs[0]["dur"] >= 0 and "err" not in recs[0]
    assert recs[1]["err"] == "RuntimeError"


def test_span_spill_jsonl(clean_obs, tmp_path):
    path = tmp_path / "spans.jsonl"
    trace.set_spill(str(path))
    with obs.span("a"):
        pass
    with obs.span("b", k="v"):
        pass
    trace.set_spill(None)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r["name"] for r in lines] == ["a", "b"]
    assert all(r["type"] == "span" for r in lines)
    assert lines[1]["labels"] == {"k": "v"}


def test_traced_decorator(clean_obs):
    @obs.traced()
    def work(x):
        return x + 1

    assert work(1) == 2
    assert obs.spans()[-1]["name"].endswith("work")


def test_ring_is_bounded(clean_obs):
    trace.set_ring_size(4)
    try:
        for i in range(10):
            with obs.span(f"s{i}"):
                pass
        assert [r["name"] for r in obs.spans()] == ["s6", "s7", "s8", "s9"]
    finally:
        trace.set_ring_size(4096)


# -- export ------------------------------------------------------------------

def _get(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_metrics_and_healthz(clean_obs):
    obs.counter("t_http_total").inc(5)
    srv = obs.start_http_server(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(base + "/metrics")
        assert code == 200 and b"t_http_total 5" in body
        code, body = _get(base + "/healthz")
        doc = json.loads(body)
        assert code == 200 and doc["ok"] is True
        obs.set_health_source(
            lambda: {"live_clients": 2, "inflight": 1, "drained": False})
        doc = json.loads(_get(base + "/healthz")[1])
        assert doc["live_clients"] == 2 and doc["inflight"] == 1
        obs.set_health_source(lambda: 1 / 0)   # a dying source -> 503
        code, body = _get(base + "/healthz")
        assert code == 503 and json.loads(body)["ok"] is False
        assert _get(base + "/nope")[0] == 404
    finally:
        srv.close()


def test_write_snapshot_appends(clean_obs, tmp_path):
    obs.counter("t_snap_total").inc(3)
    path = tmp_path / "run.jsonl"
    rec = obs.write_snapshot(str(path))
    assert rec["type"] == "snapshot"
    obs.counter("t_snap_total").inc(1)
    obs.write_snapshot(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 2
    vals = [m["samples"][0]["value"] for ln in lines
            for m in ln["metrics"] if m["name"] == "t_snap_total"]
    assert vals == [3, 4]


# -- instrumented transport --------------------------------------------------

def test_transport_counters_mirror_byte_attributes(clean_obs):
    from distlearn_tpu.comm import transport

    srv = transport.Server()
    cli = transport.connect(srv.host, srv.port)
    (sc,) = srv.accept(1)
    try:
        cli.send_msg({"q": "Enter?", "clientID": 1})
        sc.recv_msg()
        cli.send_tensor(np.ones((4, 4), np.float32))
        sc.recv_tensor(deadline=time.monotonic() + 5.0)
        doc = {m["name"]: m for m in core.REGISTRY.snapshot()}
        sent = {s["labels"]["conn"]: s["value"]
                for s in doc["transport_bytes_sent_total"]["samples"]}
        recv = {s["labels"]["conn"]: s["value"]
                for s in doc["transport_bytes_received_total"]["samples"]}
        assert sent[cli.conn_id] == cli.bytes_sent > 0
        assert recv[sc.conn_id] == sc.bytes_received == cli.bytes_sent
        lat = {s["labels"]["kind"]: s
               for s in doc["transport_frame_recv_seconds"]["samples"]}
        assert lat["control"]["count"] == 1
        assert lat["tensor"]["count"] == 1
    finally:
        cli.close()
        srv.close()


def test_recv_tensor_deadline_kills_trickler(clean_obs):
    """Satellite: the tensor path honors deadline= like recv_msg — a peer
    that sends half a tensor frame and stalls trips TimeoutError instead
    of wedging the read forever."""
    from distlearn_tpu.comm import transport

    srv = transport.Server()
    cli = transport.connect(srv.host, srv.port)
    (sc,) = srv.accept(1)
    try:
        # half a tensor frame: header promises more bytes than arrive
        header = json.dumps({"dtype": "float32", "shape": [1024]}).encode()
        meta = transport._THDR.pack(len(header)) + header
        total = len(meta) + 4096
        cli.sock.sendall(transport._HDR.pack(ord("T"), total))
        cli.sock.sendall(meta)
        cli.sock.sendall(b"\x00" * 16)   # 16 of 4096 payload bytes, stall
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            sc.recv_tensor(deadline=time.monotonic() + 0.5)
        assert time.monotonic() - t0 < 5.0
        doc = {m["name"]: m for m in core.REGISTRY.snapshot()}
        ops = {s["labels"]["op"]: s["value"]
               for s in doc["transport_timeouts_total"]["samples"]}
        assert ops.get("recv_deadline", 0) >= 1
    finally:
        cli.close()
        srv.close()


def test_connect_failure_closes_socket_and_counts(clean_obs):
    """Satellite: each failed dial closes its socket (no fd leak across
    the retry sleep) and bumps the retry counter."""
    import resource
    import socket as socket_mod

    from distlearn_tpu.comm import transport

    # a port with nothing listening: bind-then-close reserves a loser
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    def count_fds() -> int:
        return len(os.listdir("/proc/self/fd")) \
            if os.path.isdir("/proc/self/fd") else -1

    before = count_fds()
    with pytest.raises(ConnectionError):
        transport.connect("127.0.0.1", port, retries=5, retry_interval=0.01)
    after = count_fds()
    if before >= 0:
        assert after <= before    # all 5 failed dials' sockets closed
    soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    assert soft > 0               # sanity: the rlimit read itself works
    doc = {m["name"]: m for m in core.REGISTRY.snapshot()}
    assert doc["transport_connect_retries_total"]["samples"][0]["value"] >= 5


# -- end-to-end acceptance run ----------------------------------------------

def test_e2e_concurrent_run_jsonl_trail(clean_obs, tmp_path):
    """The ISSUE acceptance run: concurrent AsyncEA server, two clients,
    one injected eviction + rejoin, spans spilled live and one final
    registry snapshot — then diststat reconstructs syncs, exactly one
    eviction and one rejoin, a finite handshake p95, and per-conn wire
    bytes that match each Conn's ``bytes_sent`` attribute exactly."""
    from distlearn_tpu.parallel.async_ea import (AsyncEAClient,
                                                 AsyncEAServerConcurrent)

    log = str(tmp_path / "run.jsonl")
    trace.set_spill(log)
    port = reserve_port_window(4)
    params0 = {"w": np.zeros(8, np.float32)}
    evicted_ev = threading.Event()
    out = {}
    conns: list = []

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=2, tau=1, alpha=0.5)
        c.init_client({"w": params0["w"].copy()})
        c.broadcast.send_msg({"q": "Enter?", "clientID": 2})
        c.conn.recv_msg()             # ENTER, then silence -> eviction
        evicted_ev.wait(timeout=60)
        p = c.rejoin({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        p, out["synced"] = c.sync_client(p)
        conns.extend([c.broadcast, c.conn])   # post-rejoin conns
        c.close()

    def good_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": params0["w"].copy()})
        p = {"w": p["w"] + 2.0}
        c.sync_client(p)
        conns.extend([c.broadcast, c.conn])
        c.close()

    tf = threading.Thread(target=flaky_fn, daemon=True)
    tg = threading.Thread(target=good_fn, daemon=True)
    tf.start()
    tg.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=2,
                                  handshake_timeout=0.5, rejoin_grace=30.0)
    srv.init_server({"w": params0["w"].copy()})
    srv.start()
    t0 = time.time()
    while 2 not in srv.evicted or srv.syncs_completed < 1:
        assert time.time() - t0 < 30, (srv.evicted, srv.syncs_completed)
        time.sleep(0.02)
    evicted_ev.set()
    while srv.syncs_completed < 2:
        assert time.time() - t0 < 60, srv.syncs_completed
        time.sleep(0.02)
    tf.join(timeout=30)
    tg.join(timeout=30)
    assert out["synced"] and 2 not in srv.evicted
    conns.extend(c for c in srv.dedicated.values() if c is not None)
    conns.extend(srv.broadcast.conns)
    srv.stop()
    srv.close()

    obs.write_snapshot(log)
    trace.set_spill(None)

    doc = diststat.summarize_run([log])
    # protocol counters: 2 syncs, exactly one eviction, one rejoin
    assert doc["counter_totals"]["async_ea_syncs_total"] == 2
    assert doc["counter_totals"]["async_ea_evictions_total"] == 1
    assert doc["counter_totals"]["async_ea_rejoins_total"] == 1
    # handshake spans: >=2 completed + 1 errored (the evicted one);
    # p95 is a real number computed from the span durations
    hs = doc["spans"]["async_ea.handshake"]
    assert hs["count"] >= 3 and hs["errors"] >= 1
    assert hs["p95"] == hs["p95"] and hs["p95"] > 0    # finite, not NaN
    assert doc["spans"]["async_ea.rejoin"]["count"] == 1
    # per-conn wire bytes in the snapshot == the Conn attributes, exactly
    # (single IO thread per conn; docs/PERF.md's traffic evidence is now
    # exported, not recomputed by hand)
    checked = 0
    for c in conns:
        key = f'transport_bytes_sent_total{{conn="{c.conn_id}"}}'
        if c.bytes_sent or key in doc["counters"]:
            assert doc["counters"][key] == c.bytes_sent
            checked += 1
    assert checked >= 4
    # the inflight gauge settled back to zero
    assert doc["gauges"]["async_ea_inflight"] == 0


# -- fleet aggregation satellites --------------------------------------------

def _hist_sample(observations, bounds):
    """Histogram sample dict for ``observations`` under ``bounds`` —
    built through a real registry histogram so the test exercises the
    same sampling path agg.py consumes."""
    from distlearn_tpu.obs import agg  # noqa: F401  (import guard)
    reg = core.Registry()
    h = reg.histogram("t_merge_seconds", buckets=bounds)
    for v in observations:
        h.observe(v)
    return reg._families["t_merge_seconds"].sample()[0]


def test_histogram_merge_identical_bounds_is_exact(clean_obs):
    """Property (ISSUE satellite): for identical bucket bounds,
    merge(sample(A), sample(B)) == sample(A + B) — bucket counts, count,
    inf and sum all add exactly, over randomized observation sets."""
    from distlearn_tpu.obs import agg

    bounds = (0.001, 0.01, 0.1, 1.0)
    rng = np.random.default_rng(20260806)
    for _trial in range(20):
        na, nb = int(rng.integers(0, 40)), int(rng.integers(0, 40))
        a = [float(x) for x in rng.lognormal(-3, 2, size=na)]
        b = [float(x) for x in rng.lognormal(-3, 2, size=nb)]
        merged = agg.merge_histograms(_hist_sample(a, bounds),
                                      _hist_sample(b, bounds))
        whole = _hist_sample(a + b, bounds)
        assert merged["count"] == whole["count"] == na + nb
        assert merged["inf"] == whole["inf"]
        assert merged["buckets"] == whole["buckets"]
        assert abs(merged["sum"] - whole["sum"]) < 1e-9 * max(
            1.0, abs(whole["sum"]))


def test_histogram_merge_mismatched_bounds_raise(clean_obs):
    """Mismatched bucket bounds refuse to merge (MergeError), both via
    the free function and through FleetRegistry.merged()."""
    from distlearn_tpu.obs import agg

    a = _hist_sample([0.05], (0.01, 0.1))
    b = _hist_sample([0.05], (0.01, 1.0))
    with pytest.raises(agg.MergeError):
        agg.merge_histograms(a, b)

    fleet = agg.FleetRegistry()
    for src, bounds in (("p0", (0.01, 0.1)), ("p1", (0.01, 1.0))):
        reg = core.Registry()
        reg.histogram("t_skew_seconds", buckets=bounds).observe(0.05)
        fleet.ingest({"type": "snapshot", "ts": 1.0,
                      "metrics": reg.snapshot()}, source=src)
    with pytest.raises(agg.MergeError):
        fleet.merged()
    # kind skew between sources is the same class of config error
    fleet2 = agg.FleetRegistry()
    reg_c = core.Registry()
    reg_c.counter("t_kind_skew").inc()
    reg_g = core.Registry()
    reg_g.gauge("t_kind_skew").set(1)
    fleet2.ingest({"type": "snapshot", "ts": 1.0,
                   "metrics": reg_c.snapshot()}, source="p0")
    fleet2.ingest({"type": "snapshot", "ts": 1.0,
                   "metrics": reg_g.snapshot()}, source="p1")
    with pytest.raises(agg.MergeError):
        fleet2.merged()


def test_estimate_quantile_interpolation(clean_obs):
    from distlearn_tpu.obs import agg

    # 100 observations uniform in (0, 1) binned at 0.25/0.5/0.75/1.0:
    # the p50 sits at the 0.5 bound, p95 interpolates inside (0.75, 1].
    s = _hist_sample([(i + 0.5) / 100 for i in range(100)],
                     (0.25, 0.5, 0.75, 1.0))
    assert abs(agg.estimate_quantile(s, 0.50) - 0.50) < 0.02
    assert abs(agg.estimate_quantile(s, 0.95) - 0.95) < 0.02
    assert agg.estimate_quantile({"count": 0, "buckets": {}}, 0.5) != \
        agg.estimate_quantile({"count": 0, "buckets": {}}, 0.5)  # NaN
    # everything past the last bound clamps to the highest finite bound
    hot = _hist_sample([5.0, 6.0, 7.0], (0.25, 0.5, 0.75, 1.0))
    assert agg.estimate_quantile(hot, 0.99) == 1.0


def _parse_prometheus(text: str) -> dict:
    """Minimal Prometheus text-format parser: name{labels} -> float,
    plus the # TYPE lines.  Understands escaped label values."""
    types, values = {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        values[key] = float(val)
    return {"types": types, "values": values}


def test_prometheus_scrape_and_parse_roundtrip(clean_obs):
    """Exposition audit (ISSUE satellite): scrape /metrics over HTTP and
    parse it back — names sanitized, label values with quotes/newlines
    escaped so the line still parses, histograms typed and cumulative."""
    obs.counter("t_rt_total", "round trip").inc(3)
    fam = obs.counter("t-rt.bad name_total", labels=("q",))
    fam.labels(q='he said "hi"\nand \\ left').inc(5)
    h = obs.histogram("t_rt_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    srv = obs.start_http_server(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            text = r.read().decode()
    finally:
        srv.close()

    doc = _parse_prometheus(text)
    assert doc["types"]["t_rt_total"] == "counter"
    assert doc["types"]["t_rt_seconds"] == "histogram"
    assert doc["values"]["t_rt_total"] == 3
    # the dotted/hyphenated name was sanitized into one valid metric name
    assert doc["values"][
        't_rt_bad_name_total{q="he said \\"hi\\"\\nand \\\\ left"}'] == 5
    # histogram buckets render cumulative with a closing +Inf == count
    assert doc["values"]['t_rt_seconds_bucket{le="0.1"}'] == 1
    assert doc["values"]['t_rt_seconds_bucket{le="1.0"}'] == 2
    assert doc["values"]['t_rt_seconds_bucket{le="+Inf"}'] == 3
    assert doc["values"]["t_rt_seconds_count"] == 3
    assert abs(doc["values"]["t_rt_seconds_sum"] - 5.55) < 1e-9
    # every sample line's metric name is a valid Prometheus identifier
    import re
    for key in doc["values"]:
        name = key.split("{", 1)[0]
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), key


def test_spans_dropped_surfaced_in_diststat(clean_obs, tmp_path, capsys):
    """Ring overflow increments obs_spans_dropped_total, which survives
    into the snapshot and makes ``diststat`` lead with a WARNING."""
    trace.set_ring_size(4)
    try:
        for i in range(10):
            trace.record_span("t.noise", 0.001, i=i)
    finally:
        trace.set_ring_size(4096)
    log = str(tmp_path / "trail.jsonl")
    obs.write_snapshot(log)
    doc = diststat.summarize_run([log])
    assert doc["counter_totals"]["obs_spans_dropped_total"] == 6
    diststat._print_summary(doc)
    out = capsys.readouterr().out
    assert "WARNING" in out and "dropped 6" in out
