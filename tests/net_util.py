"""Ephemeral port allocation for socket tests.

The reference's tests bind an OS-assigned ephemeral port
(test_AllReduceSGD.lua:26); fixed port windows collide with whatever else
runs on the host (flaky-CI seed — VERDICT r1).  The tree/AsyncEA topologies
derive a *fan* of ports from one base (port+i, port+numNodes+1 —
examples/EASGD_server.lua:67-77), so a single ephemeral socket isn't enough:
this reserves a contiguous window by probing OS-assigned bases.
"""

from __future__ import annotations

import socket
from contextlib import closing


def reserve_port_window(n: int, host: str = "127.0.0.1") -> int:
    """Return a base port ``p`` such that ``p .. p+n-1`` were all bindable a
    moment ago.  The OS picks the base from the ephemeral range, so freshly
    reserved windows don't collide with long-lived services; the tiny
    close-to-rebind race is the same one the reference's handoff has."""
    for _ in range(256):
        with closing(socket.socket()) as probe:
            probe.bind((host, 0))
            base = probe.getsockname()[1]
        if base + n >= 65535:
            continue
        socks = []
        try:
            try:
                for i in range(n):
                    s = socket.socket()
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((host, base + i))
                    socks.append(s)
            except OSError:
                continue
            return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"could not reserve a window of {n} free ports")
