"""distserve tests: KV-cache invariants, engine/greedy parity under
continuous batching, scheduler policy, the 'G'/'R' wire frames, the e2e
loopback service, and a chaos-style churn soak (zero leaked fds/threads,
drained gauges).

The load-bearing invariant is PARITY: continuous-batched, slot-addressed,
paged decode — with requests admitted/finished at different times and
slots/pages heavily reused — must be token-identical to N independent
``greedy_generate`` runs.  Everything else (paging, trash-page routing,
eviction) only has to preserve that.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serve

VOCAB, DIM, DEPTH, HEADS, MAX_LEN = 61, 32, 2, 4, 64


@pytest.fixture(scope="module")
def lm_params():
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    model = transformer_lm(vocab=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                           max_len=MAX_LEN)
    params, _ = model.init(jax.random.PRNGKey(0))
    return params


def _greedy_ref(params, prompt, steps):
    from distlearn_tpu.models.transformer import greedy_generate
    out = greedy_generate(params, np.asarray(prompt, np.int32)[None], steps)
    return np.asarray(out)[0].tolist()


def _prompts(n, lo=3, hi=9, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, VOCAB, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


# -- kv cache -----------------------------------------------------------------

def test_kv_cache_accounting_and_trash_page():
    from distlearn_tpu.serve.kv_cache import CacheFull, PagedKVCache
    c = PagedKVCache(num_slots=2, page=4, max_len=16)
    assert c.num_pages == 2 * 4 + 1
    assert 0 not in c._free                # page 0 reserved (trash)
    s0 = c.admit(10)                       # 3 pages
    s1 = c.admit(16)                       # 4 pages
    assert (c.block_table[[s0, s1]] > 0).sum() == 7
    c.check()
    with pytest.raises(CacheFull):
        c.admit(4)                         # no free slot
    c.release(s0)
    assert (c.block_table[s0] == 0).all()  # row reset to trash
    c.check()
    with pytest.raises(ValueError):
        c.release(s0)                      # double release
    # pages, not just slots, gate admission
    assert c.free_slots() == 1
    assert not c.can_admit(8 * 4)          # > free pages even with a slot
    c.release(s1)
    c.check()
    assert c.free_pages() == c.num_pages - 1


def test_kv_cache_rejects_overlong():
    from distlearn_tpu.serve.kv_cache import PagedKVCache
    c = PagedKVCache(num_slots=2, page=4, max_len=16)
    assert not c.can_admit(17)
    with pytest.raises(ValueError):
        c.admit(17)


# -- engine parity (the acceptance invariant) ---------------------------------

def test_engine_continuous_batching_parity(lm_params):
    """Requests admitted at different ticks, finishing at different
    ticks, with slots and pages reused across waves — every request's
    stream must equal its isolated greedy_generate run."""
    from distlearn_tpu.serve.engine import DecodeEngine
    prompts = _prompts(6)
    max_new = 7
    refs = [_greedy_ref(lm_params, p, max_new) for p in prompts]
    eng = DecodeEngine(lm_params, num_slots=3, max_len=MAX_LEN, page=8)

    pending = list(range(len(prompts)))
    live: dict[int, dict] = {}             # slot -> {i, toks}
    got: dict[int, list] = {}
    admitted = 0
    while pending or live:
        # admit up to one request per loop turn (staggered arrival)
        if pending and eng.has_capacity(len(prompts[pending[0]]), max_new):
            i = pending.pop(0)
            slot, first = eng.admit(prompts[i], max_new)
            live[slot] = {"i": i, "toks": [first]}
            admitted += 1
        for slot, tok in eng.tick().items():
            live[slot]["toks"].append(tok)
        for slot in [s for s, st in live.items()
                     if len(st["toks"]) >= max_new]:
            st = live.pop(slot)
            eng.finish(slot)
            got[st["i"]] = st["toks"]
    assert admitted == len(prompts)
    for i, ref in enumerate(refs):
        assert got[i] == ref, f"request {i} diverged"
    eng.cache.check()
    assert eng.cache.free_pages() == eng.cache.num_pages - 1


def test_engine_slot_reuse_never_leaks_stale_kv(lm_params):
    """A slot that decoded request A, then is released and re-admitted
    with request B, must produce B's exact isolated stream — recycled
    (un-zeroed) pages must never be observable."""
    from distlearn_tpu.serve.engine import DecodeEngine
    a, b = _prompts(2, seed=7)
    max_new = 6
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    for prompt in (a, b, a):               # same slot, three generations
        slot, first = eng.admit(prompt, max_new)
        toks = [first]
        while len(toks) < max_new:
            toks.append(eng.tick()[slot])
        eng.finish(slot)
        assert toks == _greedy_ref(lm_params, prompt, max_new)


def test_engine_parity_tp_sharded(lm_params):
    """The mesh-wrapped (jit/shard_map) decode programs over tp-sharded
    weights emit the same tokens as the unsharded single-replica run."""
    import jax
    from jax.sharding import Mesh
    from distlearn_tpu.serve.engine import DecodeEngine
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    prompts = _prompts(2, seed=3)
    max_new = 5
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8,
                       mesh=mesh, tp_axis="model")
    slots = {}
    for i, p in enumerate(prompts):
        slot, first = eng.admit(p, max_new)
        slots[slot] = {"i": i, "toks": [first]}
    for _ in range(max_new - 1):
        for slot, tok in eng.tick().items():
            slots[slot]["toks"].append(tok)
    for slot, st in slots.items():
        eng.finish(slot)
        assert st["toks"] == _greedy_ref(lm_params, prompts[st["i"]],
                                         max_new)


def test_engine_validation(lm_params):
    from distlearn_tpu.serve.engine import DecodeEngine
    eng = DecodeEngine(lm_params, num_slots=1, max_len=16, page=8)
    with pytest.raises(ValueError, match="max_len"):
        eng.admit(np.ones(10, np.int32), 10)
    with pytest.raises(ValueError):
        eng.admit(np.ones(0, np.int32), 4)
    with pytest.raises(ValueError, match="max_len"):
        DecodeEngine(lm_params, max_len=MAX_LEN + 1)


# -- scheduler ----------------------------------------------------------------

def test_scheduler_queue_overflow_rejection(lm_params):
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import QueueFull, Scheduler
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    sched = Scheduler(eng, max_queue=2)
    p = _prompts(1)[0]
    sched.submit(p, 4)
    sched.submit(p, 4)
    with pytest.raises(QueueFull):
        sched.submit(p, 4)
    # never-runnable requests are rejected at submit, not queued
    with pytest.raises(ValueError, match="max_len"):
        Scheduler(eng, max_queue=8).submit(np.ones(60, np.int32), 60)


def test_scheduler_deadline_eviction(lm_params):
    """Deadlines evict BOTH queued and decoding requests; the evicted
    slot frees and the queue drains into it."""
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import Scheduler
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    now = [0.0]
    sched = Scheduler(eng, max_queue=4, clock=lambda: now[0])
    p = _prompts(1)[0]
    slow = sched.submit(p, 20, deadline_s=5.0)     # will be admitted
    queued = sched.submit(p, 4, deadline_s=1.0)    # expires in queue
    ok = sched.submit(p, 4)                        # no deadline
    events = sched.step()                          # admits slow, ticks
    assert any(e.kind == "token" and e.rid == slow for e in events)
    now[0] = 2.0
    events = sched.step()
    assert any(e.kind == "finish" and e.rid == queued
               and e.reason == "deadline" for e in events)
    now[0] = 6.0                                   # slow passes deadline
    events = sched.step()
    assert any(e.kind == "finish" and e.rid == slow
               and e.reason == "deadline" for e in events)
    # the freed slot admits the remaining request in the same round
    assert any(e.kind == "token" and e.rid == ok and e.first
               for e in events)
    while sched.active_count():
        events = sched.step()
    assert any(e.kind == "finish" and e.rid == ok
               and e.reason == "complete" for e in events)
    eng.cache.check()


def test_scheduler_parity_and_eos(lm_params):
    """Scheduler-driven continuous batching stays token-identical, and
    an eos hit finishes early with reason 'eos'."""
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import Scheduler
    prompts = _prompts(5, seed=11)
    max_new = 6
    refs = [_greedy_ref(lm_params, p, max_new) for p in prompts]
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)
    sched = Scheduler(eng, max_queue=8)
    rids = [sched.submit(p, max_new) for p in prompts]
    got = {r: [] for r in rids}
    while not sched.idle():
        for ev in sched.step():
            if ev.kind == "token":
                got[ev.rid].append(ev.token)
    for rid, ref in zip(rids, refs):
        assert got[rid] == ref
    # eos: pick a ref token and stop there
    eos = refs[0][2]
    rid = sched.submit(prompts[0], max_new, eos=eos)
    done = []
    while not sched.idle():
        done += [e for e in sched.step() if e.kind == "finish"]
    assert done and done[-1].rid == rid and done[-1].reason == "eos"
    idx = refs[0].index(eos)
    # stream stops at (and includes) the eos token
    # note: tokens before eos still match the reference prefix
    # (the engine state is unaffected by the early finish)
    eng.cache.check()


def test_scheduler_cancel(lm_params):
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import Scheduler
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    sched = Scheduler(eng, max_queue=4)
    p = _prompts(1)[0]
    r1 = sched.submit(p, 8)
    r2 = sched.submit(p, 8)
    sched.step()                           # r1 admitted
    assert sched.cancel(r1)                # running
    assert sched.cancel(r2)                # queued
    assert not sched.cancel(r1)            # unknown now
    assert sched.idle()
    eng.cache.check()
    assert eng.cache.free_pages() == eng.cache.num_pages - 1


def test_scheduler_duplicate_rid_rejected(lm_params):
    """A rid colliding with a QUEUED or RUNNING request is rejected at
    submit — the bookkeeping is rid-keyed, so a second live request
    under the same id would overwrite the first's entry, cross the two
    streams, and KeyError the scheduler when the survivor finishes.  A
    finished rid is reusable, and auto-assigned ids skip numerals a
    client squatted on."""
    import distlearn_tpu.serve.scheduler as sched_mod
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import Scheduler
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    sched = Scheduler(eng, max_queue=8)
    p = _prompts(1)[0]
    sched.submit(p, 4, rid="dup")
    with pytest.raises(ValueError, match="duplicate"):
        sched.submit(p, 4, rid="dup")      # collides while queued
    sched.step()                           # admitted -> running
    with pytest.raises(ValueError, match="duplicate"):
        sched.submit(p, 4, rid="dup")      # collides while running
    squat = str(next(sched_mod._RIDS) + 1)
    sched.submit(p, 4, rid=squat)
    assert sched.submit(p, 4) != squat     # auto id skips the squat
    while not sched.idle():
        sched.step()
    assert sched.submit(p, 4, rid="dup") == "dup"   # finished: reusable
    while not sched.idle():
        sched.step()
    eng.cache.check()


def test_scheduler_deadline_zero_expires_immediately(lm_params):
    """deadline_s=0 is an already-expired deadline, not 'no deadline' —
    a falsy zero must not disable the deadline the client asked for."""
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.scheduler import Scheduler
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    now = [100.0]
    sched = Scheduler(eng, max_queue=4, clock=lambda: now[0])
    p = _prompts(1)[0]
    rid = sched.submit(p, 4, deadline_s=0.0)
    assert any(e.kind == "finish" and e.rid == rid
               and e.reason == "deadline" for e in sched.step())
    assert sched.idle()


# -- wire frames --------------------------------------------------------------

def test_transport_serve_frames():
    from distlearn_tpu.comm import transport
    srv = transport.Server()
    cl = transport.connect(srv.host, srv.port)
    (sc,) = srv.accept(1)
    try:
        cl.send_gen({"prompt": [1, 2, 3], "max_new": 4, "rid": "a"})
        kind, msg = sc.recv_serve(deadline=time.monotonic() + 5)
        assert kind == "G" and msg["prompt"] == [1, 2, 3]
        sc.send_stream({"rid": "a", "tokens": [9], "done": False})
        kind, msg = cl.recv_serve(deadline=time.monotonic() + 5)
        assert kind == "R" and msg["tokens"] == [9]
        cl.send_msg({"q": "stats"})        # 'J' stays legal on the port
        kind, msg = sc.recv_serve(deadline=time.monotonic() + 5)
        assert kind == "J" and msg["q"] == "stats"
        # tensor frames are a desync for a serve endpoint
        cl.send_tensor(np.zeros(4, np.float32))
        with pytest.raises(transport.ProtocolError):
            sc.recv_serve(deadline=time.monotonic() + 5)
    finally:
        cl.close()
        srv.close()


# -- e2e over loopback --------------------------------------------------------

def _gauge_value(name: str) -> float:
    from distlearn_tpu import obs
    for fam in obs.snapshot_record()["metrics"]:
        if fam["name"] == name:
            return sum(s["value"] for s in fam["samples"])
    return 0.0


def _serve_server(lm_params, **kw):
    from distlearn_tpu.serve import DecodeEngine, ServeServer
    eng = DecodeEngine(lm_params, num_slots=kw.pop("num_slots", 2),
                       max_len=MAX_LEN, page=8)
    return ServeServer(eng, idle_wait=0.01, **kw).start()


def test_e2e_loopback_parity(lm_params):
    from distlearn_tpu.serve import ServeClient
    prompts = _prompts(4, seed=5)
    max_new = 6
    refs = [_greedy_ref(lm_params, p, max_new) for p in prompts]
    srv = _serve_server(lm_params, max_queue=8)
    try:
        results = {}

        def run(i):
            with ServeClient(srv.host, srv.port) as c:
                results[i] = c.generate(prompts[i], max_new,
                                        rid=f"r{i}")["tokens"]

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(not t.is_alive() for t in threads)
        for i, ref in enumerate(refs):
            assert results[i] == ref
        with ServeClient(srv.host, srv.port) as c:
            st = c.ping()
            assert st["ok"] and st["active"] == 0
    finally:
        srv.checkpoint_now(wait=True)
        srv.stop()
    assert _gauge_value("serve_queue_depth") == 0
    assert _gauge_value("serve_active_slots") == 0


def test_e2e_rejection_paths(lm_params):
    from distlearn_tpu.serve import ServeClient, ServeError
    srv = _serve_server(lm_params, max_queue=1)
    try:
        with ServeClient(srv.host, srv.port) as c:
            with pytest.raises(ServeError, match="max_len"):
                c.generate(np.ones(60, np.int32), 60)
    finally:
        srv.stop()


def test_e2e_sigterm_drain_contract(lm_params):
    """checkpoint_now(wait=True) — the hook ha.install_signal_flush
    calls on SIGTERM — finishes in-flight requests before stopping."""
    from distlearn_tpu.serve import ServeClient
    p = _prompts(1, seed=9)[0]
    max_new = 20
    ref = _greedy_ref(lm_params, p, max_new)
    srv = _serve_server(lm_params)
    try:
        out = {}

        def run():
            with ServeClient(srv.host, srv.port) as c:
                out["r"] = c.generate(p, max_new)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.2)                    # request in flight
        srv.checkpoint_now(wait=True)      # what the SIGTERM handler runs
        t.join(30)
        assert not t.is_alive()
        assert out["r"]["tokens"] == ref   # drained, not cut off
        assert out["r"]["reason"] == "complete"
    finally:
        srv.stop()


# -- hostile/broken clients must not hurt anyone else -------------------------

def _pump(srv):
    """One serve_forever round, driven synchronously by the test."""
    srv._poll_io()
    srv._dispatch(srv.sched.step())


def test_e2e_duplicate_rid_rejected(lm_params):
    """A client-chosen rid colliding with a LIVE request is rejected
    with an error chunk; the victim's stream completes token-exact and
    the loop survives (a remote client must not be able to corrupt
    rid-keyed routing or crash the service).  Driven synchronously so
    the collision window is deterministic."""
    import select
    from distlearn_tpu.comm import transport
    from distlearn_tpu.serve import DecodeEngine, ServeServer
    p = _prompts(1, seed=21)[0]
    max_new = 6
    ref = _greedy_ref(lm_params, p, max_new)
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)
    srv = ServeServer(eng, idle_wait=0.01)     # not started: test pumps
    try:
        c1 = transport.connect(srv.host, srv.port)
        c2 = transport.connect(srv.host, srv.port)
        gen = {"prompt": p.tolist(), "max_new": max_new, "rid": "same"}
        c1.send_gen(gen)
        deadline = time.monotonic() + 30
        while not any(r.rid == "same" for r in srv.sched.requests()):
            assert time.monotonic() < deadline
            _pump(srv)
        c2.send_gen(gen)                       # collides while live
        # io-only rounds: "same" cannot finish before the collision lands
        while not select.select([c2.sock], [], [], 0.0)[0]:
            assert time.monotonic() < deadline
            srv._poll_io()
        kind, chunk = c2.recv_serve(deadline=time.monotonic() + 5)
        assert kind == "R" and chunk["done"]
        assert "duplicate" in chunk["error"]
        while not srv.sched.idle():            # victim decodes to the end
            assert time.monotonic() < deadline
            _pump(srv)
        toks, reason = [], None
        while reason is None:
            kind, chunk = c1.recv_serve(deadline=time.monotonic() + 5)
            assert kind == "R" and not chunk.get("error")
            toks += chunk.get("tokens") or []
            if chunk.get("done"):
                reason = chunk["reason"]
        assert toks == ref and reason == "complete"
        c1.close()
        c2.close()
    finally:
        srv.stop()


def test_partial_frame_no_head_of_line_blocking(lm_params):
    """A peer that half-sends a frame and stalls must not stall anyone
    else: the old blocking whole-frame read wedged the single-threaded
    loop for frame_timeout per readiness event.  With buffered
    reassembly the other client's request completes immediately — and
    the stalled frame still decodes once its remaining bytes arrive."""
    import json
    import struct
    from distlearn_tpu.comm import transport
    from distlearn_tpu.serve import ServeClient
    p_slow, p_fast = _prompts(2, seed=17)
    max_new = 4
    ref_slow = _greedy_ref(lm_params, p_slow, max_new)
    ref_fast = _greedy_ref(lm_params, p_fast, max_new)
    srv = _serve_server(lm_params, frame_timeout=60.0)
    try:
        payload = json.dumps({"prompt": p_slow.tolist(),
                              "max_new": max_new, "rid": "slow"}).encode()
        frame = struct.pack("<BQ", ord("G"), len(payload)) + payload
        half = transport.connect(srv.host, srv.port)
        half.sock.sendall(frame[:5])           # half a header, then stall
        time.sleep(0.1)                        # server has seen the bytes
        with ServeClient(srv.host, srv.port) as c:
            # frame_timeout (60s) > client timeout (20s): with the old
            # blocking read this request could never finish in time
            r = c.generate(p_fast, max_new, rid="fast", timeout=20)
        assert r["tokens"] == ref_fast
        half.sock.sendall(frame[5:])           # complete the stalled frame
        toks = []
        while True:
            kind, chunk = half.recv_serve(deadline=time.monotonic() + 30)
            assert kind == "R" and not chunk.get("error")
            toks += chunk.get("tokens") or []
            if chunk.get("done"):
                break
        assert toks == ref_slow                # reassembled and served
        half.close()
    finally:
        srv.stop()


def test_trickler_dropped_after_frame_timeout(lm_params):
    """A partial frame older than frame_timeout gets its connection
    dropped — the trickler wedge is bounded without ever blocking."""
    from distlearn_tpu.comm import transport
    srv = _serve_server(lm_params, frame_timeout=0.3)
    try:
        trick = transport.connect(srv.host, srv.port)
        trick.sock.sendall(b"G\x10")           # 2 bytes of a 9-byte header
        with pytest.raises(ConnectionError):
            trick.recv_serve(deadline=time.monotonic() + 10)
        trick.close()
    finally:
        srv.stop()


def test_serve_loop_failure_observable(lm_params):
    """An unexpected scheduler/engine error must not kill the loop
    thread silently: health() flips to serving=False and records the
    failure, so probes see the death instead of serving=True forever."""
    srv = _serve_server(lm_params)
    try:
        assert srv.health()["serving"] and srv.health()["failed"] is None

        def boom():
            raise RuntimeError("boom")

        srv.sched.step = boom
        srv._thread.join(10)
        assert not srv._thread.is_alive()
        h = srv.health()
        assert not h["serving"] and "boom" in h["failed"]
    finally:
        srv.stop()


# -- churn soak (chaos style) -------------------------------------------------

def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def test_serve_soak_churny_arrival(lm_params):
    """Waves of concurrent clients with mixed fates — completions,
    mid-stream disconnects, deadline evictions — cycling admit/finish/
    evict through a 2-slot cache.  Exit criteria (tests/test_chaos.py
    style): every completed stream token-exact, zero leaked fds/threads,
    gauges drained, page accounting exact."""
    from distlearn_tpu.serve import ServeClient
    prompts = _prompts(4, seed=13)
    max_new = 8
    refs = [_greedy_ref(lm_params, p, max_new) for p in prompts]
    fd_base, th_base = _fd_count(), threading.active_count()
    srv = _serve_server(lm_params, max_queue=8)
    try:
        for wave in range(3):
            results, fails = {}, []

            def full(i):
                try:
                    with ServeClient(srv.host, srv.port) as c:
                        results[i] = c.generate(
                            prompts[i], max_new, rid=f"w{wave}r{i}")
                except Exception as e:  # noqa: BLE001
                    fails.append(e)

            def disconnector(i):
                # send a request, read one chunk, vanish mid-stream
                c = ServeClient(srv.host, srv.port)
                c.conn.send_gen({"prompt": prompts[i].tolist(),
                                 "max_new": max_new, "rid": f"w{wave}d{i}"})
                c.conn.recv_serve(deadline=time.monotonic() + 30)
                c.close()

            def doomed(i):
                # deadline too tight to ever finish -> evicted
                try:
                    with ServeClient(srv.host, srv.port) as c:
                        c.generate(prompts[i], 40, rid=f"w{wave}x{i}",
                                   deadline_s=0.0001, timeout=30)
                except Exception:  # noqa: BLE001 — eviction IS the point
                    pass

            threads = [threading.Thread(target=full, args=(i,))
                       for i in range(len(prompts))]
            threads += [threading.Thread(target=disconnector, args=(i,))
                        for i in range(2)]
            threads += [threading.Thread(target=doomed, args=(i,))
                        for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert all(not t.is_alive() for t in threads), "client wedged"
            assert not fails, fails
            for i, ref in enumerate(refs):
                assert results[i]["tokens"] == ref, \
                    f"wave {wave} request {i} diverged under churn"
        srv.checkpoint_now(wait=True)
    finally:
        srv.stop()
    srv.engine.cache.check()
    assert srv.engine.cache.free_pages() == srv.engine.cache.num_pages - 1
    # leak check: sockets closed, serve loop thread gone
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            _fd_count() > fd_base or threading.active_count() > th_base):
        time.sleep(0.1)
    assert _fd_count() <= fd_base, "leaked fds"
    assert threading.active_count() <= th_base, "leaked threads"
    assert _gauge_value("serve_queue_depth") == 0
    assert _gauge_value("serve_active_slots") == 0


# -- client failure classification (serve.client) -----------------------------

def test_client_dial_deadline_raises_replicadead():
    """Nothing listening: the dial exhausts its deadline and surfaces
    the typed death, not a raw ConnectionError after 60 retries."""
    import socket
    from distlearn_tpu.serve import ReplicaDead, ServeClient
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    t0 = time.monotonic()
    with pytest.raises(ReplicaDead):
        ServeClient("127.0.0.1", port, retries=1000, deadline_s=0.3)
    assert time.monotonic() - t0 < 10.0


def test_client_stream_timeout_when_server_never_answers(lm_params):
    """The request loop never runs (server constructed, not started):
    the stream read must give up at the caller's timeout, not hang."""
    from distlearn_tpu.serve import DecodeEngine, ServeClient, ServeServer
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)
    srv = ServeServer(eng, idle_wait=0.01)     # no loop: TCP backlog only
    try:
        with ServeClient(srv.host, srv.port) as c:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                c.generate([1, 2, 3], 4, timeout=0.3)
            assert time.monotonic() - t0 < 10.0
    finally:
        srv.stop()


def test_client_half_sent_chunk_is_replica_death():
    """A 'R' frame whose payload is cut by a FIN is a torn frame, not a
    clean goodbye — classified ReplicaDead so the router retries it."""
    import struct
    from distlearn_tpu.comm import transport
    from distlearn_tpu.serve import ReplicaDead, ServeClient
    lst = transport.Server()
    try:
        c = ServeClient(lst.host, lst.port)
        (sc,) = lst.accept(1, timeout=5.0)

        def feed():
            kind, _msg = sc.recv_serve(deadline=time.monotonic() + 10)
            assert kind == "G"
            sc.sock.sendall(struct.pack("<BQ", ord("R"), 64)
                            + b'{"rid": "x"')  # 11 of 64 payload bytes
            sc.sock.close()

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        with pytest.raises(ReplicaDead, match="mid-stream"):
            c.generate([1, 2, 3], 4, rid="x", timeout=10.0)
        t.join(10)
        c.close()
    finally:
        lst.close()


def test_client_server_death_mid_stream_is_replica_death(lm_params):
    """The server dies after tokens flowed: the typed death tells the
    caller how much output it already holds (and the router knows NOT
    to resubmit)."""
    from distlearn_tpu.serve import ReplicaDead, ServeClient
    srv = _serve_server(lm_params)
    try:
        with ServeClient(srv.host, srv.port) as c:
            with pytest.raises(ReplicaDead, match="mid-stream"):
                c.generate(_prompts(1, seed=23)[0], 30, rid="die",
                           on_chunk=lambda toks: srv.stop(), timeout=30)
    finally:
        srv.stop()


def test_client_sees_drain_and_unretryable_rejection(lm_params):
    """While checkpoint_now drains in-flight work: health says draining,
    and a new submission is refused with queue_depth but NO retry_after
    — 'don't retry here, dial another replica' (what the router does)."""
    from distlearn_tpu.serve import ServeClient, ServeError
    p = _prompts(1, seed=29)[0]
    srv = _serve_server(lm_params)
    orig_tick = srv.engine.tick
    srv.engine.tick = lambda *a, **kw: (time.sleep(0.02), orig_tick())[1]
    out = {}
    try:
        def run():
            with ServeClient(srv.host, srv.port) as c:
                out["r"] = c.generate(p, 50, rid="long", timeout=60)

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 30
        while srv.sched.active_count() == 0:
            assert time.monotonic() < deadline, "request never prefilled"
            time.sleep(0.005)
        drainer = threading.Thread(
            target=lambda: srv.checkpoint_now(wait=True))
        drainer.start()
        deadline = time.monotonic() + 10
        while not srv._draining:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        with ServeClient(srv.host, srv.port) as probe:
            assert probe.ping()["draining"]
            with pytest.raises(ServeError, match="draining") as ei:
                probe.generate(p, 4, rid="late", timeout=10)
            assert ei.value.retry_after is None
            assert ei.value.queue_depth is not None
        t.join(60)
        drainer.join(60)
        assert not t.is_alive() and not drainer.is_alive()
        assert out["r"]["reason"] == "complete"   # drained, not cut
        assert len(out["r"]["tokens"]) == 50
    finally:
        srv.stop()


def test_queue_full_rejection_carries_depth_and_hint(lm_params):
    """The overflow rejection chunk tells the client how loaded the
    replica is (queue_depth) and when to come back (retry_after) —
    driven synchronously so the overflow window is deterministic."""
    from distlearn_tpu.comm import transport
    from distlearn_tpu.serve import DecodeEngine, ServeServer
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    srv = ServeServer(eng, idle_wait=0.01, max_queue=1)  # test pumps
    conns = []
    try:
        p = _prompts(1, seed=31)[0]
        for i in range(3):
            c = transport.connect(srv.host, srv.port)
            conns.append(c)
            c.send_gen({"prompt": p.tolist(), "max_new": 4,
                        "rid": f"q{i}"})
        # io-only rounds (no sched.step): one request queues, the other
        # two overflow the depth-1 queue and get rejection chunks back
        deadline = time.monotonic() + 30
        rejects = []
        while len(rejects) < 2:
            assert time.monotonic() < deadline, "rejections never arrived"
            srv._poll_io()
            for c in conns:
                for kind, chunk in c.recv_serve_nowait():
                    rejects.append((kind, chunk))
        assert srv.sched.queue_depth() == 1
        for kind, chunk in rejects:
            assert kind == "R" and chunk["done"]
            assert "capacity" in chunk["error"]
            assert chunk["queue_depth"] == 1
            assert chunk["retry_after"] > 0
            assert "epoch" in chunk
    finally:
        for c in conns:
            c.close()
        srv.stop()


def test_client_shed_retry_honors_hint():
    """generate() backs off on a retry_after rejection and retries the
    same connection; the transient never surfaces.  With retries
    disabled the shed surfaces typed, hint attached."""
    from distlearn_tpu.comm import transport
    from distlearn_tpu.serve import ReplicaDead, ServeClient, ServeError
    lst = transport.Server()
    seen = []

    def script():
        from distlearn_tpu.comm.errors import PeerClosed
        (sc,) = lst.accept(1, timeout=10.0)
        for _ in range(2):
            try:
                kind, msg = sc.recv_serve(deadline=time.monotonic() + 10)
            except PeerClosed:
                return          # client gave up after the shed (retries=0)
            assert kind == "G"
            seen.append(time.monotonic())
            if len(seen) == 1:
                sc.send_stream({"rid": msg["rid"], "done": True,
                                "error": "admission queue at capacity",
                                "queue_depth": 2, "retry_after": 0.05,
                                "epoch": 7})
            else:
                sc.send_stream({"rid": msg["rid"], "tokens": [4, 2],
                                "done": True, "reason": "complete",
                                "epoch": 7})

    t = threading.Thread(target=script, daemon=True)
    t.start()
    try:
        with ServeClient(lst.host, lst.port) as c:
            r = c.generate([1, 2, 3], 2, rid="s", shed_retries=3)
        assert r["tokens"] == [4, 2] and r["epoch"] == 7
        assert len(seen) == 2              # shed once, retried once
        t.join(10)
        # retries disabled: the shed surfaces with its hint
        seen.clear()
        t2 = threading.Thread(target=script, daemon=True)
        t2.start()
        with ServeClient(lst.host, lst.port) as c:
            with pytest.raises(ServeError) as ei:
                c.generate([1, 2, 3], 2, rid="s", shed_retries=0)
            assert not isinstance(ei.value, ReplicaDead)
            assert ei.value.retry_after == pytest.approx(0.05)
            assert ei.value.queue_depth == 2
    finally:
        lst.close()


# -- hot weight swap (engine.swap_params + WeightTailer) ----------------------

def test_engine_swap_params_parity_and_validation(lm_params):
    """A valid swap re-binds the SAME compiled programs to new leaves:
    decode after the swap is token-identical to greedy_generate under
    the new params.  Layout drift (depth or leaf shape) is refused."""
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.serve import DecodeEngine
    eng = DecodeEngine(lm_params, num_slots=1, max_len=MAX_LEN, page=8)
    p = _prompts(1, seed=33)[0]
    new_params = jax.tree_util.tree_map(lambda a: a + 0.01, lm_params)
    ref_new = _greedy_ref(new_params, p, 5)
    eng.swap_params(new_params)
    slot, first = eng.admit(p, 5)
    toks = [first]
    while len(toks) < 5:
        got = eng.tick()
        if slot in got:
            toks.append(got[slot])
    eng.finish(slot)
    assert toks == ref_new
    shallow_model = transformer_lm(vocab=VOCAB, dim=DIM, depth=1,
                                   heads=HEADS, max_len=MAX_LEN)
    shallow, _ = shallow_model.init(jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="depth"):
        eng.swap_params(shallow)
    thin_model = transformer_lm(vocab=VOCAB, dim=16, depth=DEPTH,
                                heads=4, max_len=MAX_LEN)
    thin, _ = thin_model.init(jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="mismatch|structure"):
        eng.swap_params(thin)


def test_hot_swap_epoch_fenced_e2e(lm_params, tmp_path):
    """A checkpoint landing in the tailed directory swaps between ticks:
    pre-swap streams echo epoch 1, post-swap streams echo epoch 2 with
    token parity against the NEW weights, and health reports the new
    epoch/step."""
    import jax
    from distlearn_tpu.serve import ServeClient
    from distlearn_tpu.utils.checkpoint import save_checkpoint
    new_params = jax.tree_util.tree_map(lambda a: a + 0.01, lm_params)
    srv = _serve_server(lm_params, ckpt_dir=str(tmp_path), ckpt_poll=0.01,
                        epoch=1)
    p = _prompts(1, seed=37)[0]
    try:
        with ServeClient(srv.host, srv.port) as c:
            r1 = c.generate(p, 5, rid="pre")
        assert r1["epoch"] == 1
        assert r1["tokens"] == _greedy_ref(lm_params, p, 5)
        save_checkpoint(str(tmp_path), 7, new_params,
                        metadata={"epoch": 2})
        deadline = time.monotonic() + 30
        while srv.epoch != 2:
            assert time.monotonic() < deadline, "swap never landed"
            time.sleep(0.01)
        assert srv.ckpt_step == 7
        h = srv.health()
        assert h["epoch"] == 2 and not h["swap_pending"]
        with ServeClient(srv.host, srv.port) as c:
            r2 = c.generate(p, 5, rid="post")
        assert r2["epoch"] == 2
        assert r2["tokens"] == _greedy_ref(new_params, p, 5)
    finally:
        srv.stop()


def test_hot_swap_skips_foreign_checkpoint_and_keeps_serving(lm_params,
                                                            tmp_path):
    """A checkpoint that doesn't restore against the serving layout is
    skipped with a warning — availability over freshness: the old
    weights and epoch keep serving."""
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.serve import ServeClient
    from distlearn_tpu.utils.checkpoint import save_checkpoint
    thin_model = transformer_lm(vocab=VOCAB, dim=16, depth=DEPTH,
                                heads=4, max_len=MAX_LEN)
    thin, _ = thin_model.init(jax.random.PRNGKey(1))
    srv = _serve_server(lm_params, ckpt_dir=str(tmp_path), ckpt_poll=0.01,
                        epoch=1)
    p = _prompts(1, seed=41)[0]
    try:
        save_checkpoint(str(tmp_path), 1, thin, metadata={"epoch": 9})
        deadline = time.monotonic() + 10
        while srv._tailer._warned_step != 1:   # tailer saw and skipped it
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert srv.epoch == 1                  # refused, not adopted
        with ServeClient(srv.host, srv.port) as c:
            r = c.generate(p, 5, rid="still")
        assert r["epoch"] == 1
        assert r["tokens"] == _greedy_ref(lm_params, p, 5)
    finally:
        srv.stop()
