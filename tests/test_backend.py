"""Topology-aware collective backend tests (comm/backend.py).

The load-bearing invariant is TRAJECTORY PARITY: the same EASGD run —
same nodes, same per-node steps — must produce BITWISE-identical
parameters whether the collective is the device-mesh psum
(``MeshBackend``), the reference's flat TCP tree (``HostBackend``), or
the hierarchical in-mesh-reduce-scatter / one-TCP-leg-per-host /
in-mesh-all-gather pipeline (``HybridBackend``).  Dyadic-exact values
(integer f64 grads, alpha=0.5, non-expanding recursion) make float
addition associative, so ANY reduction-order difference would show as
an exact mismatch.

Everything else supports that: the protocol surface, the value
conventions (plain vs stacked-slice pytrees, node_offset), chunk
planning and D2H staging, rider/contrib semantics across value
conventions, scatter from an arbitrary (cross-host) source, the
degenerate 1-host/1-device topologies, and — the satellite regression —
that op_timeout + FaultPlan semantics survive the backend adapter: a
partition mid-collective surfaces the SAME typed error through the
HybridBackend host leg as through a raw Tree.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from distlearn_tpu.comm.backend import (CollectiveBackend, HostBackend,
                                        HybridBackend, MeshBackend,
                                        plan_chunks)
from distlearn_tpu.comm.tree import LocalhostTree, tree_map_spawn

from tests.net_util import reserve_port_window


def _port() -> int:
    return reserve_port_window(1)


# ------------------------------------------------------------ chunk planning

def test_plan_chunks_even_and_padded():
    padded, spans = plan_chunks(16, 4)
    assert padded == 16
    assert spans == [(0, 4), (4, 8), (8, 12), (12, 16)]
    padded, spans = plan_chunks(10, 4)      # pads 10 -> 12
    assert padded == 12
    assert spans == [(0, 3), (3, 6), (6, 9), (9, 12)]
    assert spans[-1][1] == padded


def test_plan_chunks_degenerate():
    padded, spans = plan_chunks(3, 8)       # fewer elements than parts
    assert padded == 8
    assert len(spans) == 8 and all(hi - lo == 1 for lo, hi in spans)
    padded, spans = plan_chunks(5, 1)       # single part: no padding
    assert (padded, spans) == (5, [(0, 5)])


def test_stage_into_roundtrip_mixed_dtypes():
    from distlearn_tpu.comm.wire import FrameBuffer
    from distlearn_tpu.ops.staging import stage_into

    fb = FrameBuffer()
    a = np.arange(7, dtype=np.float32)
    b = np.arange(5, dtype=np.int64) * -3
    views = stage_into(fb, [a, b], [a.dtype, b.dtype])
    for v, src in zip(views, (a, b)):
        assert v.dtype == src.dtype
        np.testing.assert_array_equal(v, src)
    # windows are 16B-aligned within the frame: a is 28 bytes, so b's
    # window starts at offset 32, not 28
    assert views[1].ctypes.data - views[0].ctypes.data == 32
    # views alias fb.buf: staging a second time reuses the allocation
    views2 = stage_into(fb, [a * 2, b * 2], [a.dtype, b.dtype])
    np.testing.assert_array_equal(views2[0], a * 2)
    np.testing.assert_array_equal(views2[1], b * 2)


# ------------------------------------------------------------ protocol

def test_all_backends_satisfy_protocol():
    mesh = MeshBackend(num_nodes=4)
    hybrid = HybridBackend(0, 1, num_devices=4)
    assert isinstance(mesh, CollectiveBackend)
    assert isinstance(hybrid, CollectiveBackend)
    assert mesh.stacked_nodes == 4 and mesh.node_offset == 0
    assert hybrid.stacked_nodes == 4 and hybrid.node_offset == 0
    port = _port()

    def node(rank):
        b = HostBackend(LocalhostTree(rank, 2, port))
        ok = isinstance(b, CollectiveBackend)
        off = b.node_offset
        b.close()
        return ok, off, b.stacked_nodes
    for rank, (ok, off, stacked) in enumerate(tree_map_spawn(node, 2)):
        assert ok and off == rank and stacked is None


# ------------------------------------------------------------ host adapter

def test_host_backend_matches_raw_tree_and_scatter_src():
    """The adapter is behavior-preserving: sum/rider/contrib identical
    to the raw handle; scatter(src != 0) — the one derived op — selects
    the source's values bitwise on every rank."""
    n, port = 4, _port()
    vals = [np.arange(6, dtype=np.float64).reshape(2, 3) * (r + 1)
            for r in range(n)]

    def node(rank):
        b = HostBackend(LocalhostTree(rank, n, port))
        red, m, rid = b.all_reduce_ex({"v": vals[rank]}, rider=rank)
        masked, m2 = b.all_reduce({"v": vals[rank]}, contrib=(rank != 1))
        sc = b.scatter({"v": vals[rank]}, src=2)
        b.barrier()
        b.close()
        return red["v"], m, rid, masked["v"], m2, sc["v"]

    expect = np.sum(vals, axis=0)
    expect_masked = expect - vals[1]
    for red, m, rid, masked, m2, sc in tree_map_spawn(node, n):
        np.testing.assert_array_equal(red, expect)
        assert (m, rid) == (n, sum(range(n)))
        np.testing.assert_array_equal(masked, expect_masked)
        assert m2 == n - 1
        np.testing.assert_array_equal(sc, vals[2])


# ------------------------------------------------------------ mesh backend

def test_mesh_backend_stacked_allreduce_rider_and_contrib():
    n = 8
    b = MeshBackend(num_nodes=n)
    rows = np.arange(n * 5, dtype=np.float64).reshape(n, 5)
    red, m, rid = b.all_reduce_ex({"w": rows}, rider=3)
    assert m == n
    assert rid == 3 * n          # rider is summed per logical node
    got = b.node_slice(red, 0)["w"]
    np.testing.assert_array_equal(got, rows.sum(axis=0))
    # per-row contrib vector: row 2 excluded from the sum AND the count
    cvec = np.ones(n, bool)
    cvec[2] = False
    red, m = b.all_reduce({"w": rows}, contrib=cvec)
    assert m == n - 1
    np.testing.assert_array_equal(b.node_slice(red, 5)["w"],
                                  rows.sum(axis=0) - rows[2])
    with pytest.raises(NotImplementedError):
        b.all_reduce({"w": rows}, op="max")


# ------------------------------------------------------------ hybrid: 1 host

def test_hybrid_single_host_matches_mesh_bitwise():
    """H=1 skips the TCP leg but keeps reduce-scatter/all-gather; the
    result must be bitwise the mesh psum's (dyadic-exact values)."""
    n = 8
    mesh = MeshBackend(num_nodes=n)
    hyb = HybridBackend(0, 1, num_devices=n)
    assert hyb.num_nodes == n and hyb.host_leg is None
    val = {"w": np.arange(n * 16, dtype=np.float64).reshape(n, 16) * 0.5,
           "b": (np.arange(n * 3) % 5).astype(np.float64).reshape(n, 3)}
    m_red, m_n = mesh.all_reduce(val)
    h_red, h_n = hyb.all_reduce(val)
    assert m_n == h_n == n
    for k in val:
        np.testing.assert_array_equal(np.asarray(mesh.node_slice(m_red, 0)[k]),
                                      np.asarray(hyb.node_slice(h_red, 0)[k]))
    # rider sums per logical node; contrib row-mask drops row sums
    _, m, rid = hyb.all_reduce_ex(val, rider=2)
    assert (m, rid) == (n, 2 * n)
    cvec = np.ones(n, bool)
    cvec[3] = False
    red, m = hyb.all_reduce(val, contrib=cvec)
    assert m == n - 1
    np.testing.assert_array_equal(
        np.asarray(hyb.node_slice(red, 0)["w"]),
        val["w"].sum(axis=0) - val["w"][3])


def test_hybrid_single_device_degenerate():
    """L=1: reduce-scatter/all-gather over one device are identities;
    the backend still honors the stacked [1, ...] convention."""
    hyb = HybridBackend(0, 1, num_devices=1)
    assert hyb.num_nodes == 1 and hyb.stacked_nodes == 1
    val = {"w": np.arange(4, dtype=np.float64)[None]}
    red, m = hyb.all_reduce(val)
    assert m == 1
    np.testing.assert_array_equal(np.asarray(hyb.node_slice(red, 0)["w"]),
                                  val["w"][0])


# ------------------------------------------------------------ hybrid: 2 hosts

def _disjoint_devices(local):
    import jax
    devs = jax.devices()
    return [devs[h * local:(h + 1) * local] for h in range(2)]


def test_hybrid_two_hosts_allreduce_rider_scatter():
    """Full pipeline across a real TCP leg: mixed-dtype leaves reduce
    exactly; contributor count and rider cover all H*L logical nodes;
    scatter from a row owned by the OTHER host replicates bitwise."""
    hosts, local = 2, 2
    n = hosts * local
    port = _port()
    slices = _disjoint_devices(local)
    rows_w = np.arange(n * 8, dtype=np.float64).reshape(n, 8) * 0.25
    rows_i = (np.arange(n * 4) % 9).astype(np.int64).reshape(n, 4)

    def node(rank):
        b = HybridBackend(rank, hosts, "127.0.0.1", port,
                          devices=slices[rank])
        lo = b.node_offset
        val = {"w": rows_w[lo:lo + local], "i": rows_i[lo:lo + local]}
        red, m, rid = b.all_reduce_ex(val, rider=lo + 1)
        out_w = np.asarray(b.node_slice(red, 0)["w"])
        out_i = np.asarray(b.node_slice(red, 1)["i"])
        sc = b.scatter(val, src=3)          # host 1's second row
        sc_w = np.asarray(b.node_slice(sc, 0)["w"])
        bytes_leg = b.host_leg.nic_bytes()
        b.barrier()
        b.close()
        return out_w, out_i, m, rid, sc_w, bytes_leg

    res = tree_map_spawn(node, hosts, timeout=120)
    for out_w, out_i, m, rid, sc_w, bytes_leg in res:
        np.testing.assert_array_equal(out_w, rows_w.sum(axis=0))
        np.testing.assert_array_equal(out_i, rows_i.sum(axis=0))
        assert m == n
        # rider is per LOGICAL node: host h contributes rider_h * L
        assert rid == (0 + 1) * local + (local + 1) * local
        np.testing.assert_array_equal(sc_w, rows_w[3])
        assert bytes_leg > 0                  # the TCP leg really ran
    # both hosts bitwise identical
    np.testing.assert_array_equal(res[0][0], res[1][0])


# ------------------------------------------------------------ EASGD parity

_N, _ROUNDS, _ALPHA, _DIM = 4, 24, 0.5, 24


def _grad(rank: int, r: int) -> np.ndarray:
    """Integer-valued deterministic per-node 'gradient' (dyadic-exact:
    with alpha=0.5 and N*alpha=2 the recursion never outgrows f64)."""
    return (np.arange(_DIM, dtype=np.float64) % 5 + 3 * rank + r) * 1.0


def _easgd_trajectory(backend, local: int) -> np.ndarray:
    """Run the shared EASGD schedule over one backend handle; returns
    [rounds, dim] of this handle's row-0 params after each round."""
    from distlearn_tpu.parallel.allreduce_ea import AllReduceEA
    ea = AllReduceEA(backend, tau=1, alpha=_ALPHA)
    lo = backend.node_offset
    traj = []
    if getattr(backend, "stacked_nodes", None) is None:
        params = np.zeros(_DIM, np.float64)
        for r in range(_ROUNDS):
            params = params - _grad(lo, r)
            params = ea.average_parameters(params)
            traj.append(np.asarray(params, np.float64).copy())
    else:
        params = np.zeros((local, _DIM), np.float64)
        for r in range(_ROUNDS):
            params = np.stack([params[i] - _grad(lo + i, r)
                               for i in range(local)])
            params = ea.average_parameters(params)
            traj.append(np.asarray(params, np.float64)[0].copy())
    return np.stack(traj)


def test_easgd_trajectory_bitwise_identical_across_backends():
    """THE acceptance invariant: the same EASGD run over MeshBackend,
    HostBackend (4 TCP tree ranks) and HybridBackend (2 hosts x 2
    devices) produces bitwise-identical trajectories at S=1 over
    >= 20 rounds."""
    mesh_traj = _easgd_trajectory(MeshBackend(num_nodes=_N), _N)

    port = _port()

    def host_node(rank):
        b = HostBackend(LocalhostTree(rank, _N, port))
        traj = _easgd_trajectory(b, 1)
        b.close()
        return traj
    host_trajs = tree_map_spawn(host_node, _N, timeout=120)

    port2 = _port()
    slices = _disjoint_devices(2)

    def hybrid_node(rank):
        b = HybridBackend(rank, 2, "127.0.0.1", port2,
                          devices=slices[rank])
        traj = _easgd_trajectory(b, 2)
        b.close()
        return traj
    hybrid_trajs = tree_map_spawn(hybrid_node, 2, timeout=120)

    # rank 0's row-0 trajectory must match EXACTLY everywhere
    np.testing.assert_array_equal(mesh_traj, host_trajs[0])
    np.testing.assert_array_equal(mesh_traj, hybrid_trajs[0])
    # and the collective leaves every handle's view identical
    assert not np.array_equal(mesh_traj[0], np.zeros(_DIM))


def test_allreduce_sgd_winner_scatter_across_hosts():
    """synchronize_parameters picks the GLOBAL most-stepped node (the
    reference's last-max winner) even when the per-handle step counts
    live on different hosts of a hybrid slice — exercising the partial-
    view stacked `_global_steps` allreduce AND the cross-host scatter."""
    from distlearn_tpu.parallel.allreduce_sgd import AllReduceSGD
    hosts, local = 2, 2
    port = _port()
    slices = _disjoint_devices(local)

    def node(rank):
        b = HybridBackend(rank, hosts, "127.0.0.1", port,
                          devices=slices[rank])
        sgd = AllReduceSGD(b)
        params = {"w": np.full((local, 4), float(b.node_offset),
                               np.float64)}
        sgd._bump(True)                     # every node steps once
        if rank == 1:
            sgd._bump(np.array([0, 1]))     # logical node 3 pulls ahead
        out = sgd.synchronize_parameters(params)
        w = np.asarray(b.node_slice(out, 0)["w"])
        b.close()
        return w

    res = tree_map_spawn(node, hosts, timeout=120)
    # steps [1, 1, 1, 2] -> winner = logical node 3 -> host 1's fill
    # value (node_offset == 2.0) replicated onto every row of every host
    for w in res:
        np.testing.assert_array_equal(w, np.full(4, 2.0))


# ------------------------------------------------------------ faults parity

def _partition_error(run):
    """Run ``run(rank) -> None`` on 2 ranks; collect the exception type
    each rank surfaces (the collective must fail, not hang)."""
    errs = [None, None]

    def node(rank):
        try:
            run(rank)
        except Exception as e:  # noqa: BLE001 — the type IS the assertion
            errs[rank] = type(e)
            return
        errs[rank] = None
    tree_map_spawn(node, 2, timeout=120)
    return errs


def test_fault_partition_surfaces_same_error_raw_tree_vs_hybrid():
    """ISSUE 20 satellite: a FaultPlan partition during the HybridBackend
    host leg surfaces the SAME typed error (TimeoutError, via op_timeout)
    as the identical partition on a raw Tree collective."""
    from distlearn_tpu.comm.faults import FaultPlan

    plan_tree = FaultPlan(seed=0)
    plan_tree.partition("tree")
    port = _port()

    def raw_tree(rank):
        t = LocalhostTree(rank, 2, port, op_timeout=1.0,
                          fault_plan=plan_tree)
        try:
            t.all_reduce(np.ones(4, np.float64))
        finally:
            t.close()
    tree_errs = _partition_error(raw_tree)

    plan_hyb = FaultPlan(seed=0)
    plan_hyb.partition("hybrid")
    port2 = _port()
    slices = _disjoint_devices(1)

    def hybrid(rank):
        b = HybridBackend(rank, 2, "127.0.0.1", port2,
                          devices=slices[rank], op_timeout=1.0,
                          fault_plan=plan_hyb)
        try:
            b.all_reduce({"w": np.ones((1, 4), np.float64)})
        finally:
            b.close()
    hyb_errs = _partition_error(hybrid)

    assert TimeoutError in tree_errs     # the partition bit the raw tree
    assert TimeoutError in hyb_errs      # ... and the adapter's host leg
    # parity: the hybrid path surfaces nothing the raw path would not
    assert {e for e in hyb_errs if e} <= {e for e in tree_errs if e}


# ------------------------------------------------------------ AsyncEA slice

def test_async_ea_slice_client_one_leg_for_l_rows():
    """A slice client (slice_backend=MeshBackend) pushes ONE wire delta
    for its L device rows; the server center moves by the SUM of the
    per-row deltas and every row keeps its own elastic pull."""
    from distlearn_tpu.parallel.async_ea import AsyncEAClient, AsyncEAServer
    L, alpha = 4, 0.5
    port = reserve_port_window(8)
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=alpha,
                          slice_backend=MeshBackend(num_nodes=L))
        p = c.init_client({"w": np.zeros(3, np.float32)})
        assert p["w"].shape == (L, 3)      # stacked [L, *shape] rows
        drift = (np.arange(1, L + 1, dtype=np.float32)[:, None]
                 * np.ones(3, np.float32))
        p = {"w": p["w"] + drift}          # rows drift by 1, 2, 3, 4
        p, synced = c.sync_client(p)
        assert synced
        out["p"] = p
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server({"w": np.zeros(3, np.float32)})
    new_params = srv.sync_server({"w": np.zeros(3, np.float32)})
    th.join(timeout=60)
    srv.close()
    assert "p" in out, "slice client never finished its sync"
    # per-row pull: row i keeps (i+1) - (i+1)*alpha
    np.testing.assert_allclose(
        out["p"]["w"],
        (np.arange(1, L + 1, dtype=np.float32) * alpha)[:, None]
        * np.ones(3, np.float32))
    # center moved by the SUM of row deltas: (1+2+3+4) * 0.5 = 5.0
    np.testing.assert_allclose(new_params["w"], 5.0)


# ------------------------------------------------------------ compile cache

def test_compile_cache_env_gate(tmp_path, monkeypatch):
    """DISTLEARN_TPU_COMPILE_CACHE points jax's persistent compile cache
    at a directory — even when enabled AFTER earlier compiles latched
    the cache off (the DecodeEngine-ctor ordering)."""
    import jax
    import jax.numpy as jnp

    from distlearn_tpu.utils import compile_cache as cc

    monkeypatch.delenv(cc.ENV_VAR, raising=False)
    monkeypatch.setattr(cc, "_enabled", None)
    assert cc.enable_compile_cache() is None     # unset -> off

    cache_dir = tmp_path / "xla"
    monkeypatch.setenv(cc.ENV_VAR, str(cache_dir))
    try:
        assert cc.enable_compile_cache() == str(cache_dir)
        # idempotent re-enable is a no-op, not a cache reset
        assert cc.enable_compile_cache() == str(cache_dir)
        jax.jit(lambda x: x * 2.0 + 1.0)(jnp.ones((32, 32)))
        assert cache_dir.is_dir() and any(cache_dir.iterdir())
    finally:
        # un-latch: later tests must not write into the deleted tmp dir
        from jax.experimental.compilation_cache import (
            compilation_cache as jcc)
        monkeypatch.setattr(cc, "_enabled", None)
        jax.config.update("jax_compilation_cache_dir", None)
        jcc.reset_cache()


# ------------------------------------------------------------ lint hooks

def test_distlint_sync_family_is_clean():
    """The committed lint/budgets/sync.json lockfile matches the lowered
    mesh-allreduce and hybrid reduce-scatter/all-gather programs."""
    from distlearn_tpu.lint.registry import run_family
    results = run_family("sync")
    assert results, "sync family registered no units"
    for r in results:
        assert r.findings == [], (
            f"{r.name}: " + "; ".join(map(str, r.findings)))
