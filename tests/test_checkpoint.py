"""Checkpoint/resume tests — atomic npz + sidecar meta
(distlearn_tpu/utils/checkpoint.py; the reference only sketches this,
examples/EASGD_server.lua:37-48)."""

import numpy as np
import pytest

from distlearn_tpu.utils import checkpoint as ckpt


def _tree(dtype=np.float32):
    return {"layer": {"w": np.arange(6, dtype=dtype).reshape(2, 3),
                      "b": np.ones(3, dtype)},
            "step_scale": np.asarray(2.0, dtype)}


def test_save_restore_roundtrip(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 5, _tree(), metadata={"epoch": 1})
    like = {"layer": {"w": np.zeros((2, 3), np.float32),
                      "b": np.zeros(3, np.float32)},
            "step_scale": np.zeros((), np.float32)}
    tree, meta = ckpt.restore_checkpoint(d, like)
    np.testing.assert_array_equal(tree["layer"]["w"], _tree()["layer"]["w"])
    assert meta["step"] == 5 and meta["epoch"] == 1


def test_restore_latest_and_prune(tmp_path):
    d = str(tmp_path)
    for s in (1, 2, 3, 4, 5):
        ckpt.save_checkpoint(d, s, _tree(), keep=3)
    assert ckpt.latest_step(d) == 5
    assert sorted(ckpt._list_steps(d)) == [3, 4, 5]


def test_shape_mismatch_raises(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 1, _tree())
    bad = _tree()
    bad["layer"]["w"] = np.zeros((3, 2), np.float32)
    with pytest.raises(ValueError, match="shape"):
        ckpt.restore_checkpoint(d, bad)


def test_dtype_mismatch_raises(tmp_path):
    """ADVICE r1: restoring into a different dtype must fail loudly, not
    silently cast (precision loss)."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 1, _tree(np.float64))
    with pytest.raises(ValueError, match="dtype"):
        ckpt.restore_checkpoint(d, _tree(np.float32))


def test_missing_leaf_raises(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 1, {"a": np.zeros(2, np.float32)})
    with pytest.raises(KeyError):
        ckpt.restore_checkpoint(d, {"a": np.zeros(2, np.float32),
                                    "b": np.zeros(2, np.float32)})


def test_async_checkpointer_roundtrip(tmp_path):
    """AsyncCheckpointer: saves land durably, wait() surfaces completion,
    and a snapshot taken at save() time is immune to later mutation."""
    d = str(tmp_path)
    t = _tree()
    with ckpt.AsyncCheckpointer(d, keep=2) as acp:
        acp.save(1, t, metadata={"epoch": 1})
        # mutate AFTER save: the written checkpoint must hold the snapshot
        t["layer"]["w"] += 100.0
        acp.save(2, t)
    assert sorted(ckpt._list_steps(d)) == [1, 2]
    r1, m1 = ckpt.restore_checkpoint(d, _tree(), step=1)
    assert m1["epoch"] == 1
    np.testing.assert_array_equal(r1["layer"]["w"], _tree()["layer"]["w"])
    r2, _ = ckpt.restore_checkpoint(d, _tree(), step=2)
    np.testing.assert_array_equal(r2["layer"]["w"],
                                  _tree()["layer"]["w"] + 100.0)


def test_async_checkpointer_error_surfaces(tmp_path):
    acp = ckpt.AsyncCheckpointer(str(tmp_path / "sub" / "x"))
    # unwritable parent: make the write fail by pointing at a file path
    p = tmp_path / "f"
    p.write_text("x")
    acp.directory = str(p / "nope")   # a file cannot be a directory
    acp.save(1, _tree())
    with pytest.raises(OSError):
        acp.wait()


def test_sharded_checkpoint_roundtrip_mesh(tmp_path):
    """Sharded save/restore on the 8-device mesh: data-axis-sharded and
    replicated leaves both reassemble to the exact global arrays."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.parallel.mesh import MeshTree

    tree = MeshTree(num_nodes=8)
    sharded = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
        NamedSharding(tree.mesh, P("data")))
    replicated = jax.device_put(jnp.arange(5, dtype=jnp.float64) * 1.5,
                                NamedSharding(tree.mesh, P()))
    state = {"opt": {"m": sharded}, "w": replicated,
             "host": np.arange(3, dtype=np.int64)}
    d = str(tmp_path)
    ckpt.save_sharded_checkpoint(d, 7, state, metadata={"note": "x"},
                                 process_index=0)
    restored, meta = ckpt.restore_sharded_checkpoint(d, state)
    assert meta["step"] == 7 and meta["note"] == "x"
    np.testing.assert_array_equal(restored["opt"]["m"],
                                  np.arange(64, dtype=np.float32).reshape(8, 8))
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(5, dtype=np.float64) * 1.5)
    np.testing.assert_array_equal(restored["host"], np.arange(3))


def test_sharded_checkpoint_zero1_state(tmp_path):
    """ZeRO-1 sharded optimizer state (the state no single host holds on a
    pod) round-trips through the sharded checkpoint."""
    import jax
    import optax
    from jax import random

    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import init_zero_state

    tree = MeshTree(num_nodes=8)
    model = mnist_cnn()
    zs = init_zero_state(model, tree, optax.adam(1e-3),
                         random.PRNGKey(0), 10)
    d = str(tmp_path)
    ckpt.save_sharded_checkpoint(d, 1, zs.opt_state, process_index=0)
    restored, _ = ckpt.restore_sharded_checkpoint(d, zs.opt_state)
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(jax.device_get(zs.opt_state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_checkpoint_missing_shard_file_raises(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.parallel.mesh import MeshTree

    tree = MeshTree(num_nodes=8)
    sharded = jax.device_put(jnp.arange(16, dtype=jnp.float32),
                             NamedSharding(tree.mesh, P("data")))
    d = str(tmp_path)
    ckpt.save_sharded_checkpoint(d, 1, {"a": sharded}, process_index=0)
    # simulate a pod where process 1's file holds the other half: rewrite
    # proc-0's file to cover only half the leaf
    import json as _json
    path = d + "/ckpt_1.shard0.npz"
    with np.load(path, allow_pickle=False) as z:
        meta = _json.loads(str(z["__meta__"]))
    half_meta = {"step": 1, "process": 0,
                 "shards": {"a#0": {"leaf": "a", "index": [[0, 8]]},
                            "a!": meta["shards"]["a!"]}}
    with open(path, "wb") as fh:
        np.savez(fh, __meta__=_json.dumps(half_meta),
                 **{"a#0": np.arange(8, dtype=np.float32)})
    with pytest.raises(ValueError, match="cover"):
        ckpt.restore_sharded_checkpoint(d, {"a": np.zeros(16, np.float32)})


def test_mixed_lm_state_checkpoint_resume():
    """The mixed-precision LM train state (bf16 working params + f32
    masters) round-trips through the generic checkpoint path and resumes
    to the EXACT trajectory: save mid-training, restore into a fresh
    state, and the continued losses match the uninterrupted run
    bitwise (the master is the source of truth; the bf16 copy must
    survive as bf16, not get silently widened)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import (LMMixedState,
                                        build_lm_mixed_step,
                                        init_lm_mixed_state)

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1, 1),
                ("data", "seq", "model"))
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=1, heads=2, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_mixed_step(model, mesh, params, lr=0.1, donate=False)
    toks = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (4, L)).astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))

    st = init_lm_mixed_state(params)
    for _ in range(3):
        st, _ = step(st, toks)

    with tempfile.TemporaryDirectory() as d:
        ckpt.save_checkpoint(d, 3, st._asdict())
        like = jax.tree_util.tree_map(np.zeros_like, st._asdict())
        got, meta = ckpt.restore_checkpoint(d, like)
        assert meta["step"] == 3
    resumed = LMMixedState(**got)
    for p in jax.tree_util.tree_leaves(resumed.params):
        assert p.dtype == jnp.bfloat16        # not silently widened

    ref, res = st, resumed
    for _ in range(3):
        ref, l_ref = step(ref, toks)
        res, l_res = step(res, toks)
        np.testing.assert_array_equal(np.asarray(jax.device_get(l_ref)),
                                      np.asarray(jax.device_get(l_res)))
    for a, b in zip(jax.tree_util.tree_leaves(ref.master),
                    jax.tree_util.tree_leaves(res.master)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_checkpoint_bf16_leaf_roundtrip(tmp_path):
    """bf16 leaves through the SHARDED path: the per-shard arrays load
    back as raw void and must be viewed to the recorded global dtype
    before assembly — bitwise round-trip, dtype preserved."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.parallel.mesh import MeshTree

    tree = MeshTree(num_nodes=8)
    vals = (np.arange(64, dtype=np.float32) / 7.0).reshape(8, 8)
    sharded = jax.device_put(jnp.asarray(vals, jnp.bfloat16),
                             NamedSharding(tree.mesh, P("data")))
    state = {"wp": sharded}
    d = str(tmp_path)
    ckpt.save_sharded_checkpoint(d, 2, state, process_index=0)
    restored, meta = ckpt.restore_sharded_checkpoint(d, state)
    assert meta["step"] == 2
    assert restored["wp"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["wp"]).view(np.uint16),
        np.asarray(jax.device_get(sharded)).view(np.uint16))


def test_structured_dtype_leaf_still_roundtrips():
    """Structured (record) dtypes are also numpy kind 'V' but round-trip
    npz natively — the extension-dtype record must not claim them (a
    'void64' name crashes np.dtype at restore; r5 review)."""
    import tempfile

    rec = np.zeros(3, np.dtype([("a", np.float32), ("b", np.int32)]))
    rec["a"] = [1.5, 2.5, 3.5]
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_checkpoint(d, 1, {"rec": rec})
        got, meta = ckpt.restore_checkpoint(d, {"rec": np.zeros_like(rec)})
    assert meta.get("vdtypes") == {}
    np.testing.assert_array_equal(got["rec"]["a"], rec["a"])


def test_metadata_cannot_clobber_reserved_keys():
    """User metadata carrying 'step'/'vdtypes' keys must not overwrite
    the computed entries restore correctness depends on."""
    import tempfile

    import jax.numpy as jnp

    tree = {"w": jnp.asarray(np.arange(4, dtype=np.float32) / 3,
                             jnp.bfloat16)}
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_checkpoint(d, 7, tree,
                             metadata={"step": 999, "vdtypes": "junk"})
        got, meta = ckpt.restore_checkpoint(
            d, {"w": np.zeros(4, np.dtype("bfloat16"))}, step=7)
    assert meta["step"] == 7                 # computed value won
    assert got["w"].dtype == np.dtype("bfloat16")
