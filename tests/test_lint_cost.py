"""DL2xx fixtures: every cost/budget rule has a known-bad step that fires
and a known-good step that stays quiet, on the 8-device CPU mesh.

The firing fixtures are the real failure modes the rules exist for: a
mis-sharded matmul whose operand GSPMD must rematerialize with a
replication all-gather (DL201), a sharded in-spec that compiles to a
replicated parameter (DL202), stale budget lockfiles (DL203-DL205), and
the serve-path rules — a donation the compiled program can't use / a
pool left undonated (DL206), an unbudgeted extra lowering or a
dtype-drift retrace (DL207), an entry-parameter relayout over budget
(DL208), and host-side tensor math in the per-tick loop (DL209).
"""

import copy
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.lint import budget as budget_mod
from distlearn_tpu.lint import cost as cost_mod
from distlearn_tpu.utils.compat import shard_map

pytestmark = pytest.mark.lint

BIG = (1024, 1024)            # f32: 4 MiB, comfortably over the 1 MiB bar


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _big_args():
    return (jax.ShapeDtypeStruct(BIG, "float32"),
            jax.ShapeDtypeStruct((8, BIG[0]), "float32"))


# ---------------------------------------------------------------- DL201 --

def test_dl201_fires_on_replication_gather(devices):
    """A replication constraint on a sharded 4 MiB operand forces GSPMD to
    insert an all-gather the jaxpr never asked for."""
    mesh = _mesh()
    repl = NamedSharding(mesh, P())

    def f(w, x):
        return x @ jax.lax.with_sharding_constraint(w, repl)

    fn = jax.jit(f, in_shardings=(NamedSharding(mesh, P("data", None)), repl))
    report, findings = cost_mod.analyze_step(fn, _big_args(), mesh=mesh,
                                             name="bad_gather")
    assert any(f.rule == "DL201" for f in findings), findings
    assert report.bytes_by_kind.get("all-gather", 0) >= 1 << 22
    assert report.bytes_by_axis.get("all-gather@data", 0) >= 1 << 22


def test_dl201_quiet_below_threshold(devices):
    """The same replication pattern on a small operand is GSPMD doing its
    job, not a hot-path regression."""
    mesh = _mesh()
    repl = NamedSharding(mesh, P())

    def f(w, x):
        return x @ jax.lax.with_sharding_constraint(w, repl)

    fn = jax.jit(f, in_shardings=(NamedSharding(mesh, P("data", None)), repl))
    args = (jax.ShapeDtypeStruct((64, 64), "float32"),
            jax.ShapeDtypeStruct((8, 64), "float32"))
    _, findings = cost_mod.analyze_step(fn, args, mesh=mesh,
                                        name="small_gather")
    assert not [f for f in findings if f.rule == "DL201"]


def test_dl201_quiet_for_explicit_gather(devices):
    """An all-gather the author wrote (jaxpr-level ``all_gather``) is
    budgeted traffic, not an inserted one — even far over the threshold."""
    mesh = _mesh()

    def f(w):
        return jax.lax.all_gather(w, "data", axis=0, tiled=True)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("data", None),),
                           out_specs=P(), check_vma=False))
    w = jax.ShapeDtypeStruct(BIG, "float32")
    report, findings = cost_mod.analyze_step(fn, (w,), mesh=mesh,
                                             name="explicit_gather")
    assert report.bytes_by_kind.get("all-gather", 0) >= 1 << 22
    assert not [f for f in findings if f.rule == "DL201"]


# ---------------------------------------------------------------- DL202 --

def test_dl202_fires_when_sharding_lost(devices):
    """jit without in_shardings + a replicated output constraint: sharding
    propagation replicates the 4 MiB parameter the in-spec declared
    sharded."""
    mesh = _mesh()
    repl = NamedSharding(mesh, P())

    def g(w, x):
        return jax.lax.with_sharding_constraint(x @ w, repl)

    _, findings = cost_mod.analyze_step(
        jax.jit(g), _big_args(), mesh=mesh, name="lost_sharding",
        in_specs=(P("data", None), P()))
    assert any(f.rule == "DL202" for f in findings), findings


def test_dl202_quiet_when_sharding_honored(devices):
    """Pinning the same spec through jit in_shardings keeps the parameter
    sharded (contraction-dim partial matmul + all-reduce) — quiet."""
    mesh = _mesh()
    repl = NamedSharding(mesh, P())

    def g(w, x):
        return jax.lax.with_sharding_constraint(x @ w, repl)

    fn = jax.jit(g, in_shardings=(NamedSharding(mesh, P("data", None)), repl))
    report, findings = cost_mod.analyze_step(
        fn, _big_args(), mesh=mesh, name="kept_sharding",
        in_specs=(P("data", None), P()))
    assert not [f for f in findings if f.rule == "DL202"]
    # the sharded matmul reduces partial products instead of gathering
    assert report.bytes_by_kind.get("all-reduce", 0) > 0


# ----------------------------------------------------- DL203/DL204/DL205 --

@pytest.fixture(scope="module")
def step_report():
    """One real psum step compiled once, reused by every budget fixture."""
    mesh = _mesh()

    def f(p, g):
        return p - 0.1 * jax.lax.psum(g, "data")

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=P(), check_vma=False))
    args = (jax.ShapeDtypeStruct((1, 256), "float32"),
            jax.ShapeDtypeStruct((8, 256), "float32"))
    report, findings = cost_mod.analyze_step(fn, args, mesh=mesh,
                                             name="psum_step")
    assert not findings
    assert report.bytes_by_kind.get("all-reduce", 0) > 0
    return report


def test_budget_roundtrip_quiet(step_report, tmp_path):
    """Fresh lockfile -> reload -> compare: in budget, no findings."""
    reports = {"psum_step": step_report}
    budget_mod.save_budget("fx", reports, budget_dir=str(tmp_path))
    assert budget_mod.check_family("fx", reports,
                                   budget_dir=str(tmp_path)) == []


def test_dl203_fires_without_lockfile(step_report, tmp_path):
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget_dir=str(tmp_path))
    assert [f.rule for f in findings] == ["DL203"]
    assert "no committed budget lockfile" in findings[0].message


def test_dl203_fires_on_stale_bytes(step_report):
    stale = {"tolerance": dict(budget_mod.DEFAULT_TOLERANCE),
             "units": {"psum_step": {
                 "collective_bytes": {"all-reduce": 1},
                 "collective_ops": dict(step_report.ops_by_kind),
                 "peak_bytes": step_report.peak_bytes}}}
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget=stale)
    assert [f.rule for f in findings] == ["DL203"]
    assert "exceeds the committed" in findings[0].message


def test_dl203_fires_on_new_collective_kind(step_report):
    stale = {"units": {"psum_step": {
        "collective_bytes": {},       # lockfile predates any traffic
        "collective_ops": dict(step_report.ops_by_kind),
        "peak_bytes": step_report.peak_bytes}}}
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget=stale)
    assert [f.rule for f in findings] == ["DL203"]
    assert "appeared" in findings[0].message


def test_dl203_fires_on_unknown_unit(step_report):
    findings = budget_mod.check_family("fx", {"renamed": step_report},
                                       budget={"units": {}})
    assert [f.rule for f in findings] == ["DL203"]
    assert "not in the committed budget lockfile" in findings[0].message


def test_dl204_fires_on_peak_regression(step_report):
    assert step_report.peak_bytes, "CPU backend stopped reporting memory"
    stale = {"units": {"psum_step": {
        "collective_bytes": dict(step_report.bytes_by_kind),
        "collective_ops": dict(step_report.ops_by_kind),
        "peak_bytes": 1}}}
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget=stale)
    assert [f.rule for f in findings] == ["DL204"]


def test_dl205_fires_on_op_count_regression(step_report):
    stale = {"units": {"psum_step": {
        "collective_bytes": dict(step_report.bytes_by_kind),
        "collective_ops": {},          # fusion used to leave zero ops
        "peak_bytes": step_report.peak_bytes}}}
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget=stale)
    assert [f.rule for f in findings] == ["DL205"]


def test_budgets_quiet_on_growth_within_tolerance(step_report):
    """Numbers inside the committed tolerance band do not fire."""
    entry = {"collective_bytes": {
        k: int(v / 1.1) for k, v in step_report.bytes_by_kind.items()},
        "collective_ops": dict(step_report.ops_by_kind),
        "peak_bytes": int(step_report.peak_bytes / 1.1)}
    budget = {"tolerance": dict(budget_mod.DEFAULT_TOLERANCE),
              "units": {"psum_step": copy.deepcopy(entry)}}
    assert budget_mod.check_family("fx", {"psum_step": step_report},
                                   budget=budget) == []


# ------------------------------------------------------------ HLO parser --

def test_parse_collectives_tuple_iota_and_pairs():
    """Tuple shapes, iota-form replica groups, and permute pairs all parse
    and attribute to the right mesh axes."""
    hlo = """
  %ar = (f32[16]{0}, f32[8]{0}) all-reduce(%a, %b), replica_groups=[2,4]<=[8], to_apply=%add
  %ag = bf16[32,4]{1,0} all-gather(bf16[4,4]{1,0} %p), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %x), source_target_pairs={{0,1},{1,2},{2,3}}
  %done = f32[4]{0} all-reduce-done(f32[4]{0} %h)
"""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("a", "b"))
    ops = cost_mod.parse_collectives(hlo, mesh)
    assert [op.kind for op in ops] == ["all-reduce", "all-gather",
                                      "collective-permute"]
    ar, ag, cp = ops
    assert ar.bytes == (16 + 8) * 4
    assert ar.axes == ("b",)          # [2,4]<=[8]: rows of 4 along axis b
    assert ag.bytes == 32 * 4 * 2
    assert ag.axes == ("b",)
    assert cp.bytes == 16
    assert cp.axes == ("b",)


def test_parse_collectives_async_start_counts_once():
    hlo = """
  %s = f32[64]{0} all-gather-start(f32[8]{0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %d = f32[64]{0} all-gather-done(f32[64]{0} %s)
"""
    mesh = _mesh()
    ops = cost_mod.parse_collectives(hlo, mesh)
    assert len(ops) == 1
    assert ops[0].kind == "all-gather"
    assert ops[0].axes == ("data",)


# ---------------------------------------------------------------- DL206 --

BIG_POOL = (256, 256)         # f32: 256 KiB, over DONATION_BYTES_THRESHOLD


def test_dl206_fires_on_wasted_donation(devices):
    """Donating a buffer the program's outputs can't absorb (no
    shape/dtype match) invalidates the caller's copy for nothing."""
    fn = jax.jit(lambda y: jax.numpy.zeros((64,), "float32"),
                 donate_argnums=(0,))
    args = (jax.ShapeDtypeStruct(BIG_POOL, "float32"),)
    with warnings.catch_warnings():
        # jax itself warns 'Some donated buffers were not usable' — that
        # warning is exactly the condition DL206 turns into a gate
        warnings.simplefilter("ignore")
        _, findings = cost_mod.analyze_step(fn, args, name="wasted",
                                            donation=True)
    dl = [f for f in findings if f.rule == "DL206"]
    assert len(dl) == 1, findings
    assert "declared donated" in dl[0].message


def test_dl206_fires_on_missing_donation(devices):
    """A 256 KiB in-place update without donation holds input AND output
    buffers live — the KV-pool footprint doubler."""
    fn = jax.jit(lambda s: s + 1.0)
    args = (jax.ShapeDtypeStruct(BIG_POOL, "float32"),)
    _, findings = cost_mod.analyze_step(fn, args, name="undonated",
                                        donation=True)
    dl = [f for f in findings if f.rule == "DL206"]
    assert len(dl) == 1, findings
    assert "not donated" in dl[0].message


def test_dl206_quiet_when_donation_aliases(devices):
    fn = jax.jit(lambda s: s + 1.0, donate_argnums=(0,))
    args = (jax.ShapeDtypeStruct(BIG_POOL, "float32"),)
    _, findings = cost_mod.analyze_step(fn, args, name="donated",
                                        donation=True)
    assert not [f for f in findings if f.rule == "DL206"], findings


def test_dl206_quiet_below_threshold(devices):
    """Small bookkeeping buffers (lens, cursors) shape-matching an output
    are not worth a donation — the missing arm has a size floor."""
    fn = jax.jit(lambda s: s + 1)
    args = (jax.ShapeDtypeStruct((4,), "int32"),)
    _, findings = cost_mod.analyze_step(fn, args, name="lens",
                                        donation=True)
    assert not [f for f in findings if f.rule == "DL206"], findings


def test_dl206_needs_opt_in(devices):
    """Training-family callers never asked for the donation audit —
    the default analyze_step stays DL206-silent."""
    fn = jax.jit(lambda s: s + 1.0)
    args = (jax.ShapeDtypeStruct(BIG_POOL, "float32"),)
    _, findings = cost_mod.analyze_step(fn, args, name="train_step")
    assert not [f for f in findings if f.rule == "DL206"], findings


# ---------------------------------------------------------------- DL207 --

def _rep(name, sig):
    return cost_mod.CostReport(name=name, signature=sig, compile_s=0.25)


def test_audit_compiles_counts_distinct_lowerings():
    reports = {
        "prefill[8]": _rep("prefill[8]", (("float32", False, "(8,)"),)),
        "prefill[16]": _rep("prefill[16]", (("float32", False, "(16,)"),)),
        "tick": _rep("tick", (("float32", False, "(4,)"),)),
    }
    findings, summary = cost_mod.audit_compiles("decode", reports)
    assert findings == []
    assert summary["count"] == 3
    assert summary["warmup_s_estimate"] == pytest.approx(0.75)


def test_dl207_fires_on_signature_drift():
    """Two buckets lowering the same shapes under different dtypes is one
    logical program paying two compiles."""
    reports = {
        "prefill[8]": _rep("prefill[8]", (("float32", False, "(8,)"),)),
        "prefill[8]x": _rep("prefill[8]x", (("bfloat16", False, "(8,)"),)),
    }
    findings, summary = cost_mod.audit_compiles("decode", reports)
    assert [f.rule for f in findings] == ["DL207"]
    assert "dtype/weak-type" in findings[0].message
    assert summary["count"] == 2


def test_dl207_fires_on_unbudgeted_compile_count(step_report):
    """An extra lowering beyond the committed compile count fails the
    gate — the new-prefill-bucket acceptance case."""
    budget = {"units": {"psum_step": step_report.to_json()},
              "compiles": {"count": 0}}
    findings = budget_mod.check_family("fx", {"psum_step": step_report},
                                       budget=budget)
    assert [f.rule for f in findings] == ["DL207"]
    assert "distinct programs" in findings[0].message


def test_dl207_quiet_at_committed_count_and_without_key(step_report):
    budget = {"units": {"psum_step": step_report.to_json()},
              "compiles": {"count": 1}}
    assert budget_mod.check_family("fx", {"psum_step": step_report},
                                   budget=budget) == []
    # pre-DL207 lockfiles have no 'compiles' key: the gate must skip,
    # not fire, so old trees keep linting while they re-baseline
    legacy = {"units": {"psum_step": step_report.to_json()}}
    assert budget_mod.check_family("fx", {"psum_step": step_report},
                                   budget=legacy) == []


def test_save_budget_commits_compile_count(step_report, tmp_path):
    budget_mod.save_budget("fx", {"psum_step": step_report},
                           budget_dir=str(tmp_path))
    committed = budget_mod.load_budget("fx", budget_dir=str(tmp_path))
    assert committed["compiles"] == {"count": 1}


# ---------------------------------------------------------------- DL208 --

_RELAYOUT_HLO = """
%fused_computation {
  %param_0 = f32[8,4]{1,0} parameter(0)
  %t.1 = f32[4,8]{1,0} transpose(f32[8,4]{1,0} %param_0), dimensions={1,0}
  ROOT %r = f32[4,8]{1,0} negate(f32[4,8]{1,0} %t.1)
}

ENTRY %main.1 (p0: f32[8,4], p1: f32[16]) -> f32[4,8] {
  %p0 = f32[8,4]{1,0} parameter(0)
  %p1 = f32[16]{0} parameter(1)
  %copy.2 = f32[8,4]{0,1} copy(f32[8,4]{1,0} %p0)
  %other = f32[16]{0} negate(f32[16]{0} %p1)
  %t.9 = f32[4,8]{1,0} transpose(f32[8,4]{0,1} %copy.2), dimensions={1,0}
  ROOT %out = f32[4,8]{1,0} fusion(f32[4,8]{1,0} %t.9), kind=kLoop, calls=%fused_computation
}
"""


def test_count_entry_relayouts_scans_entry_only():
    """The entry param's copy counts; the fusion region's transpose of
    its OWN parameter(0) does not — region params say nothing about the
    entry layout contract."""
    assert cost_mod.count_entry_relayouts(_RELAYOUT_HLO) == 1
    assert cost_mod.count_entry_relayouts("no entry here") == 0


def test_dl208_fires_over_committed_relayouts(step_report):
    entry = step_report.to_json()
    assert entry["relayout_ops"] == step_report.relayout_ops
    drifted = copy.deepcopy(step_report)
    drifted.relayout_ops = (step_report.relayout_ops or 0) + 2
    findings = budget_mod.check_family(
        "fx", {"psum_step": drifted},
        budget={"units": {"psum_step": entry}})
    assert [f.rule for f in findings] == ["DL208"]
    assert "relayout" in findings[0].message


def test_dl208_quiet_at_committed_count(step_report):
    budget = {"units": {"psum_step": step_report.to_json()}}
    assert budget_mod.check_family("fx", {"psum_step": step_report},
                                   budget=budget) == []


# ---------------------------------------------------------------- DL209 --

_HOT_LOOP_SRC = '''
class Scheduler:
    def tick(self):
        probs = np.exp(self.logits)          # host softmax: flagged
        score = self.a @ self.b              # host matmul: flagged
        idx = np.flatnonzero(self.free)      # bookkeeping: exempt
        fn = lambda v: np.exp(v)             # not executed per tick
        def prefill(p, x):                   # staged program body: exempt
            return jnp.softmax(x @ p)
        return idx

    def helper(self):
        return np.exp(self.x)                # not a hot method: exempt
'''


def test_dl209_fires_on_host_tensor_math():
    findings = cost_mod.lint_tick_loop([(_HOT_LOOP_SRC, "fx.sched")])
    assert [f.rule for f in findings] == ["DL209", "DL209"]
    assert "np.exp" in findings[0].message
    assert "matrix multiply" in findings[1].message
    assert findings[0].where.startswith("fx.sched.Scheduler.tick:")


def test_dl209_quiet_on_real_serve_loop():
    """The shipped engine/scheduler tick paths are bookkeeping-only —
    the default-target pass returns nothing."""
    assert cost_mod.lint_tick_loop() == []
