"""AllReduceEA invariants, mirroring test/test_AllReduceEA.lua.

Reference oracle: over 2/4/8 nodes with tau=3, alpha=0.4, each node's params do
a random walk with geometrically shrinking noise (``params += randn/slowit``,
``slowit *= 2`` — lua :15-17) for a random 45..53 steps per epoch; after
``synchronizeCenter`` at each epoch end, the max abs param gap across nodes
must be < 1e-6 (lua :38-39).  Uneven per-node step counts are expressed with
participation masks.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distlearn_tpu.parallel.allreduce_ea import AllReduceEA
from distlearn_tpu.parallel.mesh import MeshTree

TAU, ALPHA = 3, 0.4


@pytest.mark.parametrize("trial", range(5))
def test_nodes_converge_after_synchronize_center(trial):
    """Random walk with geometrically shrinking noise; like the reference, the
    <1e-6 oracle is checked once after all epochs (lua :36-40), in float64
    (torch's default DoubleTensor)."""
    rng = np.random.default_rng(trial)
    num_nodes = int(rng.choice([2, 4, 8]))
    tree = MeshTree(num_nodes=num_nodes)
    ea = AllReduceEA(tree, tau=TAU, alpha=ALPHA)

    # Different initial params per node; synchronizeParameters makes them equal
    # (ref lua :10 does this right after construction).
    params = [tree.put_per_node(
        rng.standard_normal((num_nodes, 7)))]
    params = ea.synchronize_parameters(params)

    slowit = 1.0
    for _epoch in range(5):
        steps_per_node = rng.integers(45, 54, size=num_nodes)
        max_steps = int(steps_per_node.max())
        for s in range(max_steps):
            contrib = (s < steps_per_node).astype(np.int64)
            # random walk with shrinking noise, only on stepping nodes
            noise = rng.standard_normal((num_nodes, 7)) / slowit
            noise *= contrib[:, None]
            params = [params[0] + noise]
            params = ea.average_parameters(params, contrib=contrib)
            slowit = min(slowit * 2.0, 2.0 ** 60)
        params = ea.synchronize_center(params)
    rows = [tree.node_slice(params, i)[0] for i in range(num_nodes)]
    for i in range(1, num_nodes):
        gap = np.abs(rows[0] - rows[i]).max()
        assert gap < 1e-6, f"nodes should be really close together: {gap}"


def test_center_replicas_identical_after_sync():
    """Center replicas must be bitwise identical after synchronizeCenter
    (the scatter drift-repair, lua :74-84)."""
    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    ea = AllReduceEA(tree, tau=2, alpha=0.5)
    rng = np.random.default_rng(0)
    params = [tree.put_per_node(rng.standard_normal((num_nodes, 5)).astype(np.float32))]
    params = ea.synchronize_parameters(params)
    for s in range(6):
        params = [params[0] + tree.put_per_node(
            rng.standard_normal((num_nodes, 5)).astype(np.float32))]
        params = ea.average_parameters(params)
    params = ea.synchronize_center(params)
    centers = [tree.node_slice(ea._center, i)[0] for i in range(num_nodes)]
    for i in range(1, num_nodes):
        assert np.array_equal(centers[0], centers[i])


def test_tau_gates_communication():
    """tau-1 of every tau calls must leave params unchanged (comm-free steps,
    lua :31 — the whole point of EASGD)."""
    num_nodes = 2
    tree = MeshTree(num_nodes=num_nodes)
    ea = AllReduceEA(tree, tau=5, alpha=0.4)
    params = [tree.replicate(np.ones(3, np.float32))]
    params = ea.synchronize_parameters(params)
    before = tree.node_slice(params, 0)[0].copy()
    for s in range(4):  # steps 1..4: no averaging
        params = ea.average_parameters(params)
        np.testing.assert_array_equal(tree.node_slice(params, 0)[0], before)
    params = ea.average_parameters(params)  # step 5: average fires
    # params identical across nodes (they started equal) but center moved:
    # delta = 0 since params == center -> unchanged. Perturb to observe motion.
    noise = np.stack([np.full(3, i + 1.0, np.float32) for i in range(num_nodes)])
    params = [params[0] + tree.put_per_node(noise)]
    for s in range(5):
        params = ea.average_parameters(params)
    row0 = tree.node_slice(params, 0)[0]
    assert not np.array_equal(row0, before + 1.0), "elastic move should have fired"


def test_in_step_average_parameters_matches_math():
    """Fused in-step elastic round reproduces the md :12-24 math exactly."""
    import jax
    from jax.sharding import PartitionSpec as P
    from distlearn_tpu.parallel import allreduce_ea as ea_lib

    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    axis = tree.axis_name
    alpha = 0.25

    def step(p, c):
        p = jnp.squeeze(p, 0)
        c = jnp.squeeze(c, 0)
        st = ea_lib.EAState(center=c, step=jnp.zeros((), jnp.int32))
        new_p, new_st = ea_lib.elastic_round(p, st, alpha, axis_name=axis)
        return new_p[None], new_st.center[None]

    fn = tree.spmd(step, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis)))
    rng = np.random.default_rng(7)
    p = rng.standard_normal((num_nodes, 6)).astype(np.float32)
    c = np.broadcast_to(rng.standard_normal(6).astype(np.float32), (num_nodes, 6)).copy()

    new_p, new_c = fn(p, c)
    delta = (p - c) * alpha
    np.testing.assert_allclose(np.asarray(new_p), p - delta, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(new_c), c + delta.sum(0, keepdims=True), rtol=1e-5)
