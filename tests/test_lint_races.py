"""Static lockset race detection (DL111/DL112): the repo's threaded
modules audit clean; stripping a real lock from the real source fires
DL111; synthetic classes pin the verdict semantics (write-write race,
torn read, init-write exclusion, single-thread silence)."""

import ast
import inspect

import pytest

from distlearn_tpu.lint.races import (BENIGN_FIELDS, analyze_source,
                                      core_targets, fleet_targets,
                                      lint_races)

pytestmark = pytest.mark.model


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- real tree

def test_repo_threaded_modules_audit_clean():
    assert lint_races() == []


def test_core_and_fleet_scopes_audit_clean():
    """The two registry units (lockset = PR-1..12 modules, router = the
    fleet-era modules) each audit clean on their own."""
    assert lint_races(core_targets()) == []
    assert lint_races(fleet_targets()) == []


def test_benign_list_entries_all_suppress_something():
    """Every allowlist entry must still be load-bearing: removing it has
    to produce a finding, otherwise the entry is stale documentation."""
    import distlearn_tpu.lint.races as races_mod
    saved = dict(BENIGN_FIELDS)
    try:
        BENIGN_FIELDS.clear()
        raw = {(f.where.rsplit(".", 2)[-2], f.where.rsplit(".", 2)[-1])
               for f in lint_races()}
    finally:
        BENIGN_FIELDS.update(saved)
    assert raw == set(saved), (
        f"stale benign entries: {sorted(set(saved) - raw)}; "
        f"unsuppressed findings: {sorted(raw - set(saved))}")
    assert races_mod.lint_races() == []


# -------------------------------------------------- seeded lock stripping

def test_dl111_stripping_count_sync_lock_fires():
    """The acceptance-criteria mutation: remove ``with self._lock:`` from
    ``_count_sync`` in the REAL async_ea source — the sync counter write
    loses its guard against the lock-holding readers and DL111 names the
    field with evidence."""
    from distlearn_tpu.parallel import async_ea

    class Strip(ast.NodeTransformer):
        def visit_FunctionDef(self, node):
            self.generic_visit(node)
            if node.name == "_count_sync":
                body = []
                for st in node.body:
                    if isinstance(st, ast.With):
                        body.extend(st.body)
                    else:
                        body.append(st)
                node.body = body
            return node

    src = inspect.getsource(async_ea)
    mutated = ast.unparse(Strip().visit(ast.parse(src)))
    assert mutated != src
    fs = analyze_source(mutated, "mutated")
    assert "DL111" in _rules(fs)
    hit = [f for f in fs if "_sync_count" in f.where]
    assert hit, [str(f) for f in fs]
    assert "holds no lock" in hit[0].message


def test_dl111_stripping_collector_lock_fires():
    """Same mutation against the fleet-era scope: drop the membership
    lock from ``Collector.add_endpoint`` in the REAL obs/agg source and
    the endpoints-list append races poll()'s guarded snapshot."""
    from distlearn_tpu.obs import agg

    class Strip(ast.NodeTransformer):
        def visit_FunctionDef(self, node):
            self.generic_visit(node)
            if node.name == "add_endpoint":
                body = []
                for st in node.body:
                    if isinstance(st, ast.With):
                        body.extend(st.body)
                    else:
                        body.append(st)
                node.body = body
            return node

    src = inspect.getsource(agg)
    mutated = ast.unparse(Strip().visit(ast.parse(src)))
    assert mutated != src
    fs = analyze_source(mutated, "mutated")
    hit = [f for f in fs
           if f.rule == "DL111" and "Collector.endpoints" in f.where]
    assert hit, [str(f) for f in fs]


# ----------------------------------------------------- verdict semantics

_RACY = """
import threading
class W:
    def __init__(self):
        self._n = 0                     # init write: excluded
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        self._n += 1                    # unguarded write
    def read(self):
        return self._n                  # cross-thread read
"""

_TORN = """
import threading
class W:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        with self._lock:
            self._n += 1                # guarded write...
    def read(self):
        return self._n                  # ...lock-free read elsewhere
"""

_CLEAN = """
import threading
class W:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        with self._lock:
            self._n += 1
    def read(self):
        with self._lock:
            return self._n
"""

_SINGLE = """
class W:
    def step(self):
        self._n += 1                    # no second thread entry: quiet
    def read(self):
        return self._n
"""


def _with_api(src, api):
    """Run analyze_source with a temporary THREAD_API entry for W."""
    from distlearn_tpu.lint.races import THREAD_API
    THREAD_API["W"] = api
    try:
        return analyze_source(src, "synthetic")
    finally:
        del THREAD_API["W"]


def test_dl111_unguarded_cross_thread_write():
    fs = _with_api(_RACY, {"read"})
    assert _rules(fs) == ["DL111"]
    assert fs[0].severity == "error" and "_n" in fs[0].where


def test_dl112_guarded_write_unguarded_read_is_warning():
    fs = _with_api(_TORN, {"read"})
    assert _rules(fs) == ["DL112"]
    assert fs[0].severity == "warning"
    assert "torn-read" in fs[0].message


def test_consistent_locking_is_clean():
    assert _with_api(_CLEAN, {"read"}) == []


def test_init_writes_do_not_count_as_races():
    # _RACY minus the _loop write: only __init__ writes _n -> clean
    src = _RACY.replace("self._n += 1                    # unguarded write",
                        "pass")
    assert _with_api(src, {"read"}) == []


def test_single_threaded_class_is_quiet():
    assert analyze_source(_SINGLE, "synthetic") == []


def test_nested_closures_drop_lexical_locks():
    """A closure handed to a thread does NOT hold the lock its spawn
    site held (the _fanout leg pattern) — writes inside it race with the
    guarded readers."""
    src = """
class W:
    def spawn(self):
        with self._lock:
            def leg():
                self._n += 1            # lock NOT held when leg runs
            return leg
    def read(self):
        with self._lock:
            return self._n
"""
    fs = _with_api(src, {"read", "spawn"})
    assert _rules(fs) == ["DL111"]


def test_call_graph_propagates_held_locks():
    """A write in a helper only reached under the lock is guarded."""
    src = """
import threading
class W:
    def __init__(self):
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        with self._lock:
            self._bump()
    def _bump(self):
        self._n += 1                    # guarded via the caller
    def read(self):
        with self._lock:
            return self._n
"""
    assert _with_api(src, {"read"}) == []


def test_try_finally_release_counts_as_held():
    src = """
import threading
class W:
    def __init__(self):
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        if not self._lock.acquire(blocking=False):
            return
        try:
            self._n += 1
        finally:
            self._lock.release()
    def read(self):
        with self._lock:
            return self._n
"""
    assert _with_api(src, {"read"}) == []


def test_container_mutators_count_as_writes():
    src = """
import threading
class W:
    def __init__(self):
        self._t = threading.Thread(target=self._loop)
    def _loop(self):
        self._items.append(1)           # unguarded container mutation
    def read(self):
        with self._lock:
            return len(self._items)
"""
    fs = _with_api(src, {"read"})
    assert _rules(fs) == ["DL111"]
    assert "_items" in fs[0].where
