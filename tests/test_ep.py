"""Expert parallelism: the all-to-all routed MoE must match a dense
reference (every expert computed for every token, top-1 selected), forward
and backward, when capacity is not binding; capacity drops must zero the
dropped tokens' outputs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distlearn_tpu.parallel.ep import moe_ffn, route_top1, route_topk

E, N, D = 4, 12, 8      # 4 experts/devices, 12 tokens per device


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "experts": jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.5),
        "router": jnp.asarray(rng.randn(D, E).astype(np.float32)),
    }


def _expert(p, h):
    return jnp.tanh(h @ p)


def _dense_reference(params, x_all):
    """x_all: [E, N, D] (per-device token blocks).  Dense top-1 MoE."""
    out = []
    for dev in range(E):
        x = x_all[dev]
        gates = jax.nn.softmax(x @ params["router"], axis=-1)     # [N, E]
        pick = jnp.argmax(gates, axis=-1)                         # [N]
        ys = jnp.stack([_expert(params["experts"][e], x)
                        for e in range(E)], axis=1)               # [N, E, D]
        y = jnp.take_along_axis(ys, pick[:, None, None], 1)[:, 0]
        out.append(y * jnp.max(gates, -1, keepdims=True))
    return jnp.stack(out)


def _moe(mesh, capacity_factor):
    def fn(params, x_all):
        ep = jnp.squeeze(params["experts"], 0)        # this device's expert
        x = jnp.squeeze(x_all, 0)
        y = moe_ffn(lambda p, h: _expert(p, h), ep, params["router"], x,
                    capacity_factor=capacity_factor, axis_name="expert")
        return y[None]
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=({"experts": P("expert"), "router": P()}, P("expert")),
        out_specs=P("expert"), check_vma=False))


def test_moe_matches_dense_reference():
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
    params = _params()
    x_all = jnp.asarray(np.random.RandomState(1).randn(E, N, D)
                        .astype(np.float32))
    # capacity E*N covers any routing: no drops possible
    out = _moe(mesh, capacity_factor=float(E))(params, x_all)
    ref = _dense_reference(params, x_all)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_gradients_match_dense():
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
    params = _params(2)
    x_all = jnp.asarray(np.random.RandomState(3).randn(E, N, D)
                        .astype(np.float32))
    moe = _moe(mesh, capacity_factor=float(E))
    g_moe = jax.grad(lambda p: jnp.sum(moe(p, x_all) ** 2))(params)
    g_ref = jax.grad(lambda p: jnp.sum(_dense_reference(p, x_all) ** 2))(params)
    for k in ("experts", "router"):
        np.testing.assert_allclose(np.asarray(g_moe[k]), np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=2e-5)


def test_capacity_drops_zero_out_tokens():
    """With capacity 1 per expert, at most E tokens per device survive; all
    other rows must be exactly zero (Switch fallback-to-residual)."""
    logits = jnp.asarray(np.random.RandomState(0).randn(N, E), jnp.float32)
    dispatch, combine = route_top1(logits, capacity=1)
    assert dispatch.sum() <= E
    kept = np.asarray(dispatch.any(axis=(1, 2)))
    assert (np.asarray(combine).sum(axis=(1, 2))[~kept] == 0).all()
    # each (expert, slot) holds at most one token
    assert np.asarray(dispatch.sum(axis=0)).max() <= 1


def test_route_top1_positions_unique():
    logits = jnp.asarray(np.random.RandomState(4).randn(64, E), jnp.float32)
    dispatch, _ = route_top1(logits, capacity=16)
    per_slot = np.asarray(dispatch.sum(axis=0))       # [E, C]
    assert per_slot.max() <= 1                        # no slot collisions
    # every token whose expert had room is dispatched exactly once
    assert np.asarray(dispatch.sum(axis=(1, 2))).max() <= 1


def _dense_top2_reference(params, x_all):
    """Dense GShard top-2: both chosen experts run, gates renormalized
    over the two picks."""
    out = []
    for dev in range(E):
        x = x_all[dev]
        gates = jax.nn.softmax(x @ params["router"], axis=-1)     # [N, E]
        topv, topi = jax.lax.top_k(gates, 2)                      # [N, 2]
        w = topv / topv.sum(-1, keepdims=True)
        ys = jnp.stack([_expert(params["experts"][e], x)
                        for e in range(E)], axis=1)               # [N, E, D]
        y = sum(jnp.take_along_axis(ys, topi[:, j][:, None, None], 1)[:, 0]
                * w[:, j][:, None] for j in range(2))
        out.append(y)
    return jnp.stack(out)


def test_moe_top2_matches_dense_reference():
    """The distributed top-2 (GShard) path with non-binding capacity must
    equal the dense run-both-experts reference, forward and backward."""
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
    params = _params(5)
    x_all = jnp.asarray(np.random.RandomState(6).randn(E, N, D)
                        .astype(np.float32))

    def fn(p, xx):
        ep = jnp.squeeze(p["experts"], 0)
        y = moe_ffn(_expert, ep, p["router"], jnp.squeeze(xx, 0),
                    capacity_factor=float(E), axis_name="expert", top_k=2)
        return y[None]

    moe2 = jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=({"experts": P("expert"), "router": P()}, P("expert")),
        out_specs=P("expert"), check_vma=False))
    out = moe2(params, x_all)
    ref = _dense_top2_reference(params, x_all)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    g = jax.grad(lambda p: jnp.sum(moe2(p, x_all) ** 2))(params)
    g_ref = jax.grad(lambda p: jnp.sum(_dense_top2_reference(p, x_all) ** 2)
                     )(params)
    for k in ("experts", "router"):
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=2e-5)


def test_route_topk_aux_terms():
    """balance_loss is 1.0 for a perfectly uniform router and > 1 when
    skewed; dropped_frac counts capacity-dropped assignments exactly."""
    # uniform: every expert equally probable AND equally chosen
    N2 = 4 * E
    logits = jnp.zeros((N2, E), jnp.float32)
    # argmax ties break to expert 0 — build an exactly-cycling assignment
    # with small biases; P_e stays exactly 1/E by symmetry (each expert is
    # boosted in the same fraction of tokens)
    bias = 1e-3 * jax.nn.one_hot(jnp.arange(N2) % E, E)
    _, _, aux = route_topk(logits + bias, capacity=N2, k=1)
    np.testing.assert_allclose(float(aux["balance_loss"]), 1.0, rtol=1e-4)
    assert float(aux["dropped_frac"]) == 0.0
    # fully collapsed: all tokens pick expert 0 with prob ~1 -> loss ~ E
    big = jnp.zeros((N2, E), jnp.float32).at[:, 0].set(20.0)
    _, _, aux = route_topk(big, capacity=N2, k=1)
    np.testing.assert_allclose(float(aux["balance_loss"]), float(E),
                               rtol=1e-3)
    # capacity 1: E tokens kept of N2 assignments
    d3, _, aux = route_topk(big, capacity=1, k=1)
    assert float(aux["dropped_frac"]) == (N2 - 1) / N2


def test_route_top2_slots_unique_and_rank_priority():
    logits = jnp.asarray(np.random.RandomState(7).randn(64, E), jnp.float32)
    dispatch, combine, _ = route_topk(logits, capacity=16, k=2)
    per_slot = np.asarray(dispatch.sum(axis=0))       # [E, C]
    assert per_slot.max() <= 1                        # no slot collisions
    # each token dispatched at most twice (its two experts)
    assert np.asarray(dispatch.sum(axis=(1, 2))).max() <= 2
    # combine weights of kept assignments sum to at most 1 per token
    assert float(np.asarray(combine).sum(axis=(1, 2)).max()) <= 1.0 + 1e-5


def test_moe_rejects_wrong_router_shape():
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
    params = _params()
    bad = {"experts": params["experts"],
           "router": jnp.zeros((D, 2 * E), jnp.float32)}
    x_all = jnp.zeros((E, N, D), jnp.float32)
    with pytest.raises(ValueError, match="router_w must be"):
        _moe(mesh, capacity_factor=float(E))(bad, x_all)
