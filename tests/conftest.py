"""Test harness: multi-node without a cluster.

The reference tests spawn worker threads with fresh Lua states connected over
real localhost TCP (``ipc.map`` — test/test_AllReduceSGD.lua:26-35).  The
TPU-native analogue is a virtual multi-device CPU mesh: force 8 host-platform
devices so every collective runs through the real shard_map/psum code path
(SURVEY.md §4 "implication for the TPU build").  Must be set before jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)

# Force CPU even when the session env pins a TPU platform (the attached TPU is
# a single chip; tests need 8 virtual devices).  The env var alone is not
# enough here: a sitecustomize pre-imports jax at interpreter startup, so the
# config knob is the reliable override.
jax.config.update("jax_platforms", "cpu")

# The reference's tensors are torch DoubleTensors by default; the EA invariant
# test needs float64 to reproduce its <1e-6 oracle (test_AllReduceEA.lua:38).
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# Old jax pins (< 0.7) have no ``jax.shard_map``; tests written against the
# modern spelling go through the compat shim (utils/compat.py).
from distlearn_tpu.utils import compat  # noqa: E402

compat.install()


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
