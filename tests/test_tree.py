"""TCP tree backend tests — the reference's own randomized multi-node
invariant suites (test/test_AllReduceSGD.lua, test/test_AllReduceEA.lua)
re-run against the host-side tree, plus transport-level collective checks.
Threads connected over real localhost TCP stand in for processes, exactly
like the reference's ``ipc.map`` fixture (test/test_AllReduceSGD.lua:26-35).
"""

import numpy as np
import pytest

from distlearn_tpu.comm.tree import LocalhostTree, tree_map_spawn
from distlearn_tpu.parallel.host_algorithms import (TreeAllReduceEA,
                                                    TreeAllReduceSGD)

from tests.net_util import reserve_port_window


def _port() -> int:
    """OS-assigned ephemeral coordinator port (ref test_AllReduceSGD.lua:26;
    fixed windows were a flaky-CI seed — VERDICT r1)."""
    return reserve_port_window(1)


@pytest.mark.parametrize("n,base", [(2, 2), (4, 2), (8, 2), (5, 3), (8, 4)])
def test_allreduce_sum_and_scatter(n, base):
    port = _port()
    rng = np.random.RandomState(0)
    values = [rng.randn(3, 4).astype(np.float32) for _ in range(n)]

    def node(rank):
        t = LocalhostTree(rank, n, port, base=base)
        red, m = t.all_reduce({"v": values[rank]})
        sc = t.scatter({"v": np.full((2, 2), float(rank), np.float32)})
        t.close()
        return red["v"], m, sc["v"]

    results = tree_map_spawn(node, n)
    expected = np.sum(values, axis=0)
    for red, m, sc in results:
        np.testing.assert_allclose(red, expected, rtol=1e-5)
        assert m == n
        np.testing.assert_array_equal(sc, 0.0)  # root's value everywhere


def test_allreduce_max_with_flush_identity():
    """op='max' with a non-contributor: the flushing rank's slot must be the
    op identity (-inf), not zero — all-negative values survive."""
    n, port = 3, _port()

    def node(rank):
        t = LocalhostTree(rank, n, port)
        red, m = t.all_reduce(np.array([-5.0 - rank]), op="max",
                              contrib=(rank != 1))
        t.close()
        return red, m

    for red, m in tree_map_spawn(node, n):
        np.testing.assert_array_equal(red, -5.0)
        assert m == 2


def test_allreduce_zero_contribution_flush():
    n, port = 4, _port()

    def node(rank):
        t = LocalhostTree(rank, n, port)
        contrib = rank < 2   # ranks 2,3 flush (ref lua/AllReduceSGD.lua:37)
        red, m = t.all_reduce(np.ones(5, np.float64), contrib=contrib)
        t.close()
        return red, m

    for red, m in tree_map_spawn(node, n):
        np.testing.assert_array_equal(red, 2.0)
        assert m == 2


def test_tree_sgd_reference_invariant():
    """Port of test/test_AllReduceSGD.lua: each node runs its OWN random
    4-13 steps per epoch (uneven — stragglers are served by the flush
    protocol inside synchronizeParameters), then after sync all nodes'
    params are BITWISE identical (the reference oracle, lua :38)."""
    rng = np.random.RandomState(7)
    for trial in range(3):
        n = int(rng.choice([2, 4, 8]))
        port = _port()

        def node(rank):
            t = LocalhostTree(rank, n, port)
            sgd = TreeAllReduceSGD(t)
            r = np.random.RandomState(100 * trial + rank)
            params = {"w": np.zeros((4, 3), np.float64)}
            for ep in range(3):
                for _ in range(int(r.randint(4, 14))):  # own count only
                    g, m = sgd.sum_and_normalize_gradients(
                        {"w": r.randn(4, 3)})
                    params = {"w": params["w"] - 0.01 * g["w"]}
                params = sgd.synchronize_parameters(params)
            t.close()
            return params["w"]

        results = tree_map_spawn(node, n)
        for w in results[1:]:
            np.testing.assert_array_equal(results[0], w)  # bitwise oracle


def test_tree_ea_reference_invariant():
    """Port of test/test_AllReduceEA.lua: tau=3 alpha=0.4, each node walks
    randn/slowit with slowit doubling per step (noise -> 0 geometrically),
    own random 45-53 steps per epoch, synchronizeCenter at each epoch end;
    final inter-node params gap < 1e-6 (the reference oracle, lua :38-39)."""
    rng = np.random.RandomState(3)
    n = int(rng.choice([2, 4, 8]))
    port = _port()
    tau, alpha, epochs = 3, 0.4, 3

    def node(rank):
        t = LocalhostTree(rank, n, port)
        ea = TreeAllReduceEA(t, tau, alpha)
        r = np.random.RandomState(200 + rank)
        params = {"w": r.randn(7)}
        params = ea.synchronize_parameters(params)
        slowit = 1.0
        for ep in range(epochs):
            for _ in range(int(r.randint(45, 54))):  # own count only
                params = {"w": params["w"] + r.randn(7) / slowit}
                slowit *= 2.0
                params = ea.average_parameters(params)
            params = ea.synchronize_center(params)
        t.close()
        return params["w"]

    results = tree_map_spawn(node, n)
    params = np.stack(results)
    gap = np.abs(params - params[0]).max()
    assert gap < 1e-6, gap


def test_allreduce_rejects_dtype_skew():
    """One framework, one policy: a child contributing f64 against the
    tree's f32 accumulator is a rank config mismatch and must be REJECTED
    (matching the AsyncEA server's _check_delta eviction policy), not
    silently astype'd into the sum (VERDICT r4 weak #5)."""
    n, port = 2, _port()

    def node(rank):
        t = LocalhostTree(rank, n, port)
        dt = np.float64 if rank == 1 else np.float32
        try:
            t.all_reduce({"v": np.ones((3,), dt)})
            return "no-error"
        except (ValueError, ConnectionError, TimeoutError) as e:
            return type(e).__name__
        finally:
            t.close()

    results = tree_map_spawn(node, n, timeout=30)
    # the accumulating rank must raise ValueError; its peer may see the
    # connection drop as the raising rank tears down
    assert "ValueError" in results, results


def test_barrier_and_ranks():
    n, port = 4, _port()

    def node(rank):
        t = LocalhostTree(rank, n, port)
        t.barrier()
        idx = t.node_index
        t.close()
        return idx

    assert tree_map_spawn(node, n) == [0, 1, 2, 3]


def test_op_timeout_detects_dead_rank():
    """Failure detection: with op_timeout set, a collective waiting on a
    dead/absent rank raises TimeoutError instead of hanging forever (the
    reference wedges here — SURVEY.md §5)."""
    import time
    port = _port()

    def node(rank):
        t = LocalhostTree(rank, 2, port, base=2)
        if rank == 1:
            t.close()             # dies before participating
            return None
        t.set_op_timeout(0.5)
        t0 = time.monotonic()
        try:
            t.all_reduce({"v": np.ones((4,), np.float32)})
            return ("no-error", time.monotonic() - t0)
        except (TimeoutError, ConnectionError) as e:
            return (type(e).__name__, time.monotonic() - t0)
        finally:
            t.close()

    results = tree_map_spawn(node, 2, timeout=30)
    kind, dt = results[0]
    # PeerClosed is the clean-FIN ConnectionError subclass: a dead peer
    # may be seen either mid-frame (reset/timeout) or between frames
    assert kind in ("TimeoutError", "ConnectionError", "PeerClosed"), kind
    assert dt < 10.0
