"""Fused train-step tests: replicated-params invariant, convergence, EA
divergence/contraction — the trainer-level analogue of the reference's
invariant suites (test/test_AllReduceSGD.lua, test/test_AllReduceEA.lua)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from distlearn_tpu.data import (PermutationSampler, batch_iterator,
                                make_dataset, synthetic_mnist)
from distlearn_tpu.models import mnist_cnn
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.train import (build_ea_cycle, build_ea_steps,
                                 build_eval_step, build_sgd_scan_step,
                                 build_sgd_step, build_sync_step,
                                 init_ea_state, init_train_state,
                                 reduce_confusion)
from distlearn_tpu.utils import metrics as M


def _data_stream(tree, n=512, batch=32, seed=0):
    x, y, nc = synthetic_mnist(n, seed=seed)
    ds = make_dataset(x, y, nc)
    samp = PermutationSampler(ds.size, seed=seed)
    sh = NamedSharding(tree.mesh, P("data"))
    for bx, by in batch_iterator(ds, samp, batch):
        yield jax.device_put(bx, sh), jax.device_put(by, sh)


def test_sgd_step_loss_decreases_and_counts_all_examples():
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1)
    losses = []
    seen = 0
    for _ in range(3):
        for bx, by in _data_stream(tree):
            ts, loss = step(ts, bx, by)
            losses.append(float(loss))
            seen += bx.shape[0]
    assert losses[-1] < losses[0]
    cm = reduce_confusion(ts.cm)
    assert int(cm.sum()) == seen  # every example counted exactly once


def test_sgd_params_replicated_bitwise():
    """The reference's oracle: params identical on all nodes after sync
    (test/test_AllReduceSGD.lua:38).  With the fused step params are
    replicated *every* step — check the addressable shards agree bitwise."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1, donate=False)
    for bx, by in _data_stream(tree, n=256, batch=64):
        ts, _ = step(ts, bx, by)
    for leaf in jax.tree_util.tree_leaves(ts.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_eval_step_confusion_and_loss():
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    params, mstate = model.init(random.PRNGKey(0))
    ev = build_eval_step(model, tree)
    cm = jax.device_put(jnp.zeros((4, 10, 10), jnp.int32),
                        NamedSharding(tree.mesh, P("data")))
    n = 0
    for bx, by in _data_stream(tree, n=256, batch=64):
        cm, loss = ev(params, mstate, cm, bx, by)
        n += bx.shape[0]
    g = reduce_confusion(cm)
    assert int(g.sum()) == n
    assert 0.0 <= M.total_valid(g) <= 1.0


def test_sgd_uneven_participation_and_winner_sync():
    """Uneven-data-partition path: contrib masks non-stepping nodes out of the
    gradient sum (lua/AllReduceSGD.lua:22-27); winner-takes-all sync keeps
    params bitwise-identical afterwards (lua :33-54 / test oracle :38)."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1, donate=False, with_contrib=True)
    sync = build_sync_step(tree)
    sh = NamedSharding(tree.mesh, P("data"))
    contrib = jax.device_put(np.array([1, 1, 1, 0], np.int32), sh)
    total = 0
    for bx, by in _data_stream(tree, n=256, batch=64):
        ts, loss = step(ts, bx, by, contrib)
        total += 3 * (bx.shape[0] // 4)  # only 3 of 4 nodes count examples
    steps = np.asarray(jax.device_get(ts.sync.my_steps))
    np.testing.assert_array_equal(steps, [4, 4, 4, 0])
    assert int(reduce_confusion(ts.cm).sum()) == total
    ts = sync(ts)
    assert np.asarray(jax.device_get(ts.sync.my_steps)).sum() == 0
    for leaf in jax.tree_util.tree_leaves(ts.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_contrib_masks_batchnorm_stats():
    """Non-contributing nodes must not feed the sync-BN statistics (the
    BN analogue of lua/AllReduceSGD.lua:22-27 contributor masking)."""
    from distlearn_tpu.models import cifar_convnet
    tree = MeshTree(num_nodes=4)
    model = cifar_convnet(dropout_rate=0.0)
    step = build_sgd_step(model, tree, lr=0.0, donate=False, with_contrib=True)
    sh = NamedSharding(tree.mesh, P("data"))
    rng = np.random.RandomState(0)
    x = rng.randn(16, 32, 32, 3).astype(np.float32)
    y = (np.arange(16) % 10).astype(np.int32)
    # node 3's shard is wildly out-of-distribution; masked out -> stats should
    # match running the same step with only nodes 0-2's data
    x_bad = x.copy()
    x_bad[12:] *= 100.0
    contrib = jax.device_put(np.array([1, 1, 1, 0], np.int32), sh)
    ts1 = init_train_state(model, tree, random.PRNGKey(0), 10)
    ts1, _ = step(ts1, jax.device_put(x, sh), jax.device_put(y, sh), contrib)
    ts2 = init_train_state(model, tree, random.PRNGKey(0), 10)
    ts2, _ = step(ts2, jax.device_put(x_bad, sh), jax.device_put(y, sh), contrib)
    m1 = np.asarray(jax.device_get(ts1.model_state["bn1"]["mean"]))
    m2 = np.asarray(jax.device_get(ts2.model_state["bn1"]["mean"]))
    np.testing.assert_allclose(m1, m2, rtol=1e-6)


def _stacked_batches(tree, k, batch=32, seed=0):
    """k distinct batches stacked along a leading step axis, plus the same
    batches as a list (for the per-call reference path)."""
    pairs = []
    it = _data_stream(tree, n=k * batch, batch=batch, seed=seed)
    for bx, by in it:
        pairs.append((np.asarray(jax.device_get(bx)),
                      np.asarray(jax.device_get(by))))
    pairs = pairs[:k]
    xs = np.stack([p[0] for p in pairs])
    ys = np.stack([p[1] for p in pairs])
    sh = NamedSharding(tree.mesh, P(None, "data"))
    return jax.device_put(xs, sh), jax.device_put(ys, sh), pairs


def test_sgd_scan_step_matches_per_call_steps():
    """build_sgd_scan_step(K steps in one XLA program) must produce the same
    trajectory as K calls of build_sgd_step — same psum order, same update."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    k = 4
    xs, ys, pairs = _stacked_batches(tree, k)
    sh = NamedSharding(tree.mesh, P("data"))

    ts_ref = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1, donate=False)
    ref_losses = []
    for bx, by in pairs:
        ts_ref, loss = step(ts_ref, jax.device_put(bx, sh),
                            jax.device_put(by, sh))
        ref_losses.append(float(loss))

    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    scan_step = build_sgd_scan_step(model, tree, lr=0.1, donate=False)
    ts, losses = scan_step(ts, xs, ys)
    assert losses.shape == (k,)
    np.testing.assert_allclose(np.asarray(jax.device_get(losses)),
                               np.asarray(ref_losses), rtol=1e-5, atol=1e-6)
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ts_ref.params))
    got_leaves = jax.tree_util.tree_leaves(jax.device_get(ts.params))
    for a, b in zip(ref_leaves, got_leaves):
        # atol 1e-5: scan fuses the k steps into one program, so XLA is free
        # to reassociate reductions differently than the per-call build —
        # identical math, different summation order, few-ulp f32 drift.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
    # step counters / confusion matrices advance identically
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ts.sync.my_steps)),
        np.asarray(jax.device_get(ts_ref.sync.my_steps)))
    np.testing.assert_array_equal(reduce_confusion(ts.cm),
                                  reduce_confusion(ts_ref.cm))


def test_sgd_scan_step_uneven_participation_matches_per_call():
    """The scanned step with a [K, num_nodes] participation matrix must
    reproduce K per-call with_contrib steps — the uneven-data-partition
    semantics (lua/AllReduceSGD.lua:22-27) on the path the headline bench
    actually measures."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    k = 4
    xs, ys, pairs = _stacked_batches(tree, k, seed=2)
    sh = NamedSharding(tree.mesh, P("data"))
    # a different participation pattern each step, incl. one full row
    contribs = np.array([[1, 1, 1, 0],
                         [1, 0, 1, 1],
                         [1, 1, 1, 1],
                         [0, 1, 0, 1]], np.int32)

    ts_ref = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1, donate=False,
                          with_contrib=True)
    ref_losses = []
    for (bx, by), c in zip(pairs, contribs):
        ts_ref, loss = step(ts_ref, jax.device_put(bx, sh),
                            jax.device_put(by, sh), jax.device_put(c, sh))
        ref_losses.append(float(loss))

    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    scan_step = build_sgd_scan_step(model, tree, lr=0.1, donate=False,
                                    with_contrib=True)
    cs = jax.device_put(contribs, NamedSharding(tree.mesh, P(None, "data")))
    ts, losses = scan_step(ts, xs, ys, cs)
    np.testing.assert_allclose(np.asarray(jax.device_get(losses)),
                               np.asarray(ref_losses), rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ts_ref.params)),
                    jax.tree_util.tree_leaves(jax.device_get(ts.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ts.sync.my_steps)),
        np.asarray(jax.device_get(ts_ref.sync.my_steps)))
    # per-step column sums: only contributing steps advanced the counter
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ts.sync.my_steps)), contribs.sum(axis=0))
    np.testing.assert_array_equal(reduce_confusion(ts.cm),
                                  reduce_confusion(ts_ref.cm))


def test_ea_cycle_matches_local_steps_plus_round():
    """build_ea_cycle(τ local steps + elastic round, one dispatch) must match
    τ local() calls followed by one rnd() call."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    tau = 3
    xs, ys, pairs = _stacked_batches(tree, tau, seed=1)
    sh = NamedSharding(tree.mesh, P("data"))

    ets_ref = init_ea_state(model, tree, random.PRNGKey(0), 10)
    local, rnd = build_ea_steps(model, tree, lr=0.1, alpha=0.25, donate=False)
    for bx, by in pairs:
        ets_ref, _ = local(ets_ref, jax.device_put(bx, sh),
                           jax.device_put(by, sh))
    ets_ref = rnd(ets_ref)

    ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
    cycle = build_ea_cycle(model, tree, lr=0.1, alpha=0.25, donate=False)
    ets, losses = cycle(ets, xs, ys)
    assert losses.shape == (tau, 4)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ets_ref.params)),
                    jax.tree_util.tree_leaves(jax.device_get(ets.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ets_ref.center)),
                    jax.tree_util.tree_leaves(jax.device_get(ets.center))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_ea_local_steps_diverge_then_round_contracts():
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
    local, rnd = build_ea_steps(model, tree, lr=0.1, alpha=0.25, donate=False)

    def spread(ts):
        leaf = jax.tree_util.tree_leaves(ts.params)[0]
        arr = np.asarray(jax.device_get(leaf))
        return float(np.abs(arr - arr[0]).max())

    assert spread(ets) == 0.0
    for bx, by in _data_stream(tree, n=256, batch=64):
        ets, _ = local(ets, bx, by)
    d_before = spread(ets)
    assert d_before > 0  # nodes saw different shards -> divergence
    ets2 = rnd(ets)
    assert spread(ets2) < d_before  # elastic round contracts the gap

    # center replicas stay bitwise identical across nodes (deterministic psum)
    c = jax.tree_util.tree_leaves(ets2.center)[0]
    arr = np.asarray(jax.device_get(c))
    for i in range(1, arr.shape[0]):
        np.testing.assert_array_equal(arr[0], arr[i])


def test_eamsgd_momentum_local_steps():
    """EAMSGD (arXiv:1412.6651 §3): with momentum the velocity buffer moves
    and training converges; with momentum=0 velocity stays zero and the
    trajectory matches plain EASGD bitwise."""
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    xs, ys, pairs = _stacked_batches(tree, 3, seed=2)
    sh = NamedSharding(tree.mesh, P("data"))

    # momentum=0 path is bitwise the plain-EASGD path, vel untouched
    e0 = init_ea_state(model, tree, random.PRNGKey(0), 10)
    l0, _ = build_ea_steps(model, tree, lr=0.1, alpha=0.25, donate=False)
    em = init_ea_state(model, tree, random.PRNGKey(0), 10)
    lm, _ = build_ea_steps(model, tree, lr=0.1, alpha=0.25, donate=False,
                           momentum=0.0)
    for bx, by in pairs:
        bx, by = jax.device_put(bx, sh), jax.device_put(by, sh)
        e0, _ = l0(e0, bx, by)
        em, _ = lm(em, bx, by)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(e0.params)),
                    jax.tree_util.tree_leaves(jax.device_get(em.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert all(float(np.abs(np.asarray(jax.device_get(v))).max()) == 0.0
               for v in jax.tree_util.tree_leaves(em.vel))

    # momentum>0: velocity becomes non-zero, loss still decreases over epochs
    ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
    local, rnd = build_ea_steps(model, tree, lr=0.05, alpha=0.2,
                                momentum=0.9)
    first = last = None
    k = 0
    for _ in range(3):
        for bx, by in _data_stream(tree, seed=3):
            ets, losses = local(ets, bx, by)
            k += 1
            if k % 10 == 0:
                ets = rnd(ets)
            m = float(np.mean(np.asarray(losses)))
            first = m if first is None else first
            last = m
    assert last < first
    assert any(float(np.abs(np.asarray(jax.device_get(v))).max()) > 0
               for v in jax.tree_util.tree_leaves(ets.vel))


def test_ea_training_converges():
    tree = MeshTree(num_nodes=4)
    model = mnist_cnn()
    ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
    local, rnd = build_ea_steps(model, tree, lr=0.1, alpha=0.2)
    first = last = None
    k = 0
    for _ in range(3):
        for bx, by in _data_stream(tree):
            ets, losses = local(ets, bx, by)
            k += 1
            if k % 10 == 0:
                ets = rnd(ets)
            m = float(np.mean(np.asarray(losses)))
            first = m if first is None else first
            last = m
    assert last < first
