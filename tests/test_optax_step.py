"""Optax-backed fused step: with plain SGD it must match build_sgd_step
bitwise; with momentum/adam it must train; optimizer state must stay
replicated across the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from distlearn_tpu.data import synthetic_mnist
from distlearn_tpu.models import mnist_cnn
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.train import (build_optax_step, build_sgd_step,
                                 init_optax_state, init_train_state)


def _setup(n=4, batch=16):
    tree = MeshTree(num_nodes=n)
    x, y, nc = synthetic_mnist(batch, seed=0)
    sh = NamedSharding(tree.mesh, P("data"))
    model = mnist_cnn()
    return tree, model, nc, jax.device_put(x, sh), jax.device_put(y, sh)


def test_optax_sgd_matches_bare_sgd_bitwise():
    tree, model, nc, bx, by = _setup()
    lr = 0.1
    ts = init_train_state(model, tree, random.PRNGKey(0), nc)
    ots = init_optax_state(model, tree, optax.sgd(lr), random.PRNGKey(0), nc)
    # the bare path's Pallas bucketing reorders float ops; compare against
    # the per-leaf path, which optax.sgd reproduces exactly
    step = build_sgd_step(model, tree, lr=lr, fused=False)
    ostep = build_optax_step(model, tree, optax.sgd(lr))
    for _ in range(3):
        ts, loss = step(ts, bx, by)
        ots, oloss = ostep(ots, bx, by)
    np.testing.assert_array_equal(np.asarray(loss), np.asarray(oloss))
    for a, b in zip(jax.tree_util.tree_leaves(ts.params),
                    jax.tree_util.tree_leaves(ots.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_optax_momentum_and_adam_train():
    tree, model, nc, bx, by = _setup()
    for tx in (optax.sgd(0.05, momentum=0.9), optax.adam(1e-3)):
        ots = init_optax_state(model, tree, tx, random.PRNGKey(1), nc)
        ostep = build_optax_step(model, tree, tx)
        losses = []
        for _ in range(8):
            ots, loss = ostep(ots, bx, by)
            losses.append(float(loss))
        assert losses[-1] < losses[0], (tx, losses)


def test_optax_state_stays_replicated():
    tree, model, nc, bx, by = _setup()
    tx = optax.sgd(0.05, momentum=0.9)
    ots = init_optax_state(model, tree, tx, random.PRNGKey(2), nc)
    ostep = build_optax_step(model, tree, tx)
    for _ in range(2):
        ots, _ = ostep(ots, bx, by)
    for leaf in jax.tree_util.tree_leaves(ots.opt_state):
        if not hasattr(leaf, "sharding"):
            continue
        assert leaf.sharding.is_fully_replicated, leaf.sharding
