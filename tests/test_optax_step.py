"""Optax-backed fused step: with plain SGD it must match build_sgd_step
bitwise; with momentum/adam it must train; optimizer state must stay
replicated across the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from distlearn_tpu.data import synthetic_mnist
from distlearn_tpu.models import mnist_cnn
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.train import (build_optax_step, build_sgd_step,
                                 init_optax_state, init_train_state)


def _setup(n=4, batch=16):
    tree = MeshTree(num_nodes=n)
    x, y, nc = synthetic_mnist(batch, seed=0)
    sh = NamedSharding(tree.mesh, P("data"))
    model = mnist_cnn()
    return tree, model, nc, jax.device_put(x, sh), jax.device_put(y, sh)


def test_optax_sgd_matches_bare_sgd_bitwise():
    tree, model, nc, bx, by = _setup()
    lr = 0.1
    ts = init_train_state(model, tree, random.PRNGKey(0), nc)
    ots = init_optax_state(model, tree, optax.sgd(lr), random.PRNGKey(0), nc)
    # the bare path's Pallas bucketing reorders float ops; compare against
    # the per-leaf path, which optax.sgd reproduces exactly
    step = build_sgd_step(model, tree, lr=lr, fused=False)
    ostep = build_optax_step(model, tree, optax.sgd(lr))
    for _ in range(3):
        ts, loss = step(ts, bx, by)
        ots, oloss = ostep(ots, bx, by)
    np.testing.assert_array_equal(np.asarray(loss), np.asarray(oloss))
    for a, b in zip(jax.tree_util.tree_leaves(ts.params),
                    jax.tree_util.tree_leaves(ots.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_optax_momentum_and_adam_train():
    tree, model, nc, bx, by = _setup()
    for tx in (optax.sgd(0.05, momentum=0.9), optax.adam(1e-3)):
        ots = init_optax_state(model, tree, tx, random.PRNGKey(1), nc)
        ostep = build_optax_step(model, tree, tx)
        losses = []
        for _ in range(8):
            ots, loss = ostep(ots, bx, by)
            losses.append(float(loss))
        assert losses[-1] < losses[0], (tx, losses)


def test_optax_state_stays_replicated():
    tree, model, nc, bx, by = _setup()
    tx = optax.sgd(0.05, momentum=0.9)
    ots = init_optax_state(model, tree, tx, random.PRNGKey(2), nc)
    ostep = build_optax_step(model, tree, tx)
    for _ in range(2):
        ots, _ = ostep(ots, bx, by)
    for leaf in jax.tree_util.tree_leaves(ots.opt_state):
        if not hasattr(leaf, "sharding"):
            continue
        assert leaf.sharding.is_fully_replicated, leaf.sharding


def test_zero_sharded_adam_matches_full_optax():
    """ZeRO-1: sliced elementwise update + all_gather must produce the SAME
    params as the full (replicated-state) optax step."""
    from distlearn_tpu.train import (build_zero_optax_step, init_zero_state)

    tree, model, nc, bx, by = _setup()
    tx = optax.adam(1e-3)
    ots = init_optax_state(model, tree, tx, random.PRNGKey(3), nc)
    zts = init_zero_state(model, tree, tx, random.PRNGKey(3), nc)
    ostep = build_optax_step(model, tree, tx)
    zstep = build_zero_optax_step(model, tree, tx)
    for _ in range(3):
        ots, oloss = ostep(ots, bx, by)
        zts, zloss = zstep(zts, bx, by)
    np.testing.assert_allclose(float(oloss), float(zloss), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(ots.params),
                    jax.tree_util.tree_leaves(zts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_zero_opt_state_is_sharded():
    from distlearn_tpu.train import init_zero_state

    tree, model, nc, _, _ = _setup()
    zts = init_zero_state(model, tree, optax.adam(1e-3), random.PRNGKey(4),
                          nc)
    # adam's mu/nu slices: stacked [N, chunk], one row per device
    big = [l for l in jax.tree_util.tree_leaves(zts.opt_state)
           if l.ndim == 2]
    assert big, "expected sliced mu/nu leaves"
    for leaf in big:
        assert leaf.shape[0] == tree.num_nodes
        assert not leaf.sharding.is_fully_replicated


def test_zero_rejects_non_f32_params():
    import pytest
    from distlearn_tpu.train import init_zero_state

    tree, _, _, _, _ = _setup()
    from distlearn_tpu.models.core import Model

    def init(key):
        return {"w": jnp.zeros((4,), jnp.bfloat16)}, {}

    bad = Model(init=init, apply=lambda *a, **k: None, name="bad",
                input_shape=(4,), num_classes=2)
    with pytest.raises(ValueError, match="f32"):
        init_zero_state(bad, tree, optax.adam(1e-3), random.PRNGKey(0), 2)


def test_zero_rejects_slice_coupling_optimizer():
    import pytest
    from distlearn_tpu.train import init_zero_state

    tree, model, nc, _, _ = _setup()
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3))
    with pytest.raises(ValueError, match="not elementwise"):
        init_zero_state(model, tree, tx, random.PRNGKey(0), nc)


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=k on a BN-free model must match the single-shot step to
    float tolerance (same effective batch, same psum'd gradient)."""
    tree, model, nc, bx, by = _setup(n=4, batch=16)
    tx = optax.sgd(0.1)
    ts1 = init_optax_state(model, tree, tx, random.PRNGKey(5), nc)
    ts2 = init_optax_state(model, tree, tx, random.PRNGKey(5), nc)
    full = build_optax_step(model, tree, tx)
    accum = build_optax_step(model, tree, tx, accum_steps=2)
    for _ in range(2):
        ts1, l1 = full(ts1, bx, by)
        ts2, l2 = accum(ts2, bx, by)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(ts2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # confusion matrices identical: every example was still counted once
    np.testing.assert_array_equal(np.asarray(ts1.cm), np.asarray(ts2.cm))


def test_gradient_accumulation_rejects_indivisible():
    import pytest

    tree, model, nc, bx, by = _setup(n=4, batch=16)  # 4 per device
    tx = optax.sgd(0.1)
    ts = init_optax_state(model, tree, tx, random.PRNGKey(6), nc)
    step = build_optax_step(model, tree, tx, accum_steps=3)
    with pytest.raises(ValueError, match="not divisible"):
        step(ts, bx, by)


def test_accum_steps_validated_at_build():
    import pytest

    tree, model, _, _, _ = _setup()
    with pytest.raises(ValueError, match="accum_steps must be"):
        build_optax_step(model, tree, optax.sgd(0.1), accum_steps=0)


def _lm_zero_oracle(lm, params, tokens_np, tx, steps, lr_spec=None):
    """Single-device f32-master mixed-precision oracle: grads of the global
    batch, packed f32, full tx.update against the f32 master, params
    re-materialized in the model dtype."""
    from distlearn_tpu.models.transformer import lm_loss
    from distlearn_tpu.ops import flatten as flatten_lib

    spec = flatten_lib.make_spec(params)
    master = flatten_lib.pack(spec, params)           # f32
    state = tx.init(master)
    toks = jnp.asarray(tokens_np)
    p = params
    for _ in range(steps):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(lm, q, toks, seq_axis=None, tp_axis=None))(p)
        gf = flatten_lib.pack(spec, g)                # cast f32
        u, state = tx.update(gf, state, master)
        master = master + u
        p = flatten_lib.unpack(spec, master)          # cast to model dtype
    return p, float(loss), master


def _lm_zero_run(lm, params, tokens_np, tx, steps, tree):
    from distlearn_tpu.train import build_lm_zero_step, init_lm_zero_state

    st = init_lm_zero_state(params, tree, tx)
    step = build_lm_zero_step(lm, tree, tx, donate=False)
    toks = jax.device_put(tokens_np,
                          NamedSharding(tree.mesh, P("data")))
    for _ in range(steps):
        st, loss = step(st, toks)
    return st, float(loss)


def test_lm_zero_step_matches_replicated_oracle_f32():
    """build_lm_zero_step (reduce-scatter + sharded Adam + all-gather) must
    match the single-device full-state oracle on the same global batch."""
    from distlearn_tpu.models.transformer import transformer_lm

    tree = MeshTree(num_nodes=4)
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16)
    params, _ = lm.init(random.PRNGKey(0))
    toks = np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32)
    tx = optax.adam(1e-3)
    p_ref, l_ref, _ = _lm_zero_oracle(lm, params, toks, tx, 3)
    st, l = _lm_zero_run(lm, params, toks, tx, 3, tree)
    np.testing.assert_allclose(l, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(st.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lm_zero_step_bf16_params_f32_master():
    """bf16 param trees train against sharded f32 masters: the master must
    track the oracle's f32 master closely (bf16 rounding only at the
    param re-materialization, never accumulated into the state)."""
    from distlearn_tpu.models.transformer import transformer_lm

    tree = MeshTree(num_nodes=4)
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16,
                        dtype=jnp.bfloat16)
    params, _ = lm.init(random.PRNGKey(1))
    assert jax.tree_util.tree_leaves(params)[0].dtype == jnp.bfloat16
    toks = np.random.RandomState(1).randint(0, 64, (8, 16)).astype(np.int32)
    tx = optax.adam(1e-3)
    p_ref, _, m_ref = _lm_zero_oracle(lm, params, toks, tx, 3)
    st, _ = _lm_zero_run(lm, params, toks, tx, 3, tree)
    # reassemble the sharded master in node order
    m = np.concatenate([np.asarray(s.data).reshape(-1) for s in
                        sorted(st.master.addressable_shards,
                               key=lambda s: s.index[0].start or 0)]
                       )[:m_ref.size]
    # bf16 fwd/bwd rounds differently for sharded vs global batch grouping,
    # and Adam normalizes grads to ~lr-sized moves: allow a few lr of
    # absolute drift on the handful of sign-flipped elements
    np.testing.assert_allclose(m, np.asarray(m_ref), rtol=5e-2, atol=5e-3)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(st.params)):
        assert np.asarray(b).dtype == np.asarray(a).dtype   # stays bf16


def test_lm_zero_state_memory_is_sharded():
    """The point of ZeRO-1: Adam state (and the f32 master) per device is
    1/N of the packed parameter size."""
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.ops import flatten as flatten_lib
    from distlearn_tpu.train import init_lm_zero_state

    tree = MeshTree(num_nodes=4)
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16)
    params, _ = lm.init(random.PRNGKey(2))
    st = init_lm_zero_state(params, tree, optax.adam(1e-3))
    spec = flatten_lib.make_spec(params)
    chunk = st.master.shape[1]
    assert chunk * tree.num_nodes >= spec.padded
    assert chunk <= spec.padded // tree.num_nodes + 1024  # ~1/N each
    for s in st.master.addressable_shards:      # one row per device
        assert s.data.shape[0] == 1
    sliced = [l for l in jax.tree_util.tree_leaves(st.opt_state)
              if getattr(l, "ndim", 0) == 2]
    assert sliced
    for leaf in sliced:
        assert leaf.shape == (tree.num_nodes, chunk)
        assert not leaf.sharding.is_fully_replicated


def test_lm_zero_mesh_step_composes_with_tp_sp():
    """ZeRO-1 over the data axis of a dp2 x sp2 x tp2 mesh (sharded Adam
    state + f32 masters covering each device's LOCAL TP shards) must match
    the single-device full-state oracle."""
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import (param_specs,
                                                  transformer_lm)
    from distlearn_tpu.train import (build_lm_zero_mesh_step,
                                     init_lm_zero_mesh_state)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "seq", "model"))
    L = 32
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=L)
    params, _ = lm.init(random.PRNGKey(0))
    toks = np.random.RandomState(0).randint(0, 64, (8, L)).astype(np.int32)
    tx = optax.adam(1e-3)
    p_ref, l_ref, _ = _lm_zero_oracle(lm, params, toks, tx, 3)

    sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                param_specs(params, tp_axis="model"))
    placed = jax.device_put(params, sh)
    st = init_lm_zero_mesh_state(placed, mesh, tx)
    step = build_lm_zero_mesh_step(lm, mesh, params, tx, donate=False)
    tk = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    for _ in range(3):
        st, loss = step(st, tk)
    np.testing.assert_allclose(float(loss), l_ref, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(jax.device_get(st.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # state memory: master covers local params / n_data per device
    assert st.master.shape[0] == 2 and st.master.shape[1] == 2
    for s in st.master.addressable_shards:
        assert s.data.shape[:2] == (1, 1)


def test_lm_optax_step_matches_single_device_oracle():
    """build_lm_optax_step (replicated Adam state over a dp x sp mesh)
    must match single-device jax + optax on the same global batch, and
    the optimizer state must stay replicated."""
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import lm_loss, transformer_lm
    from distlearn_tpu.train import LMOptaxState, build_lm_optax_step

    L = 32
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=L)
    params, _ = lm.init(random.PRNGKey(0))
    toks = np.random.RandomState(0).randint(0, 64, (8, L)).astype(np.int32)
    tx = optax.adam(1e-3)

    # single-device oracle (standard jax+optax loop)
    p_ref, s_ref = params, tx.init(params)
    for _ in range(3):
        l_ref, g = jax.value_and_grad(
            lambda q: lm_loss(lm, q, jnp.asarray(toks), seq_axis=None,
                              tp_axis=None))(p_ref)
        u, s_ref = tx.update(g, s_ref, p_ref)
        p_ref = jax.tree_util.tree_map(lambda a, b: a + b, p_ref, u)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                ("data", "seq", "model"))
    st = LMOptaxState(params, tx.init(params))
    step = build_lm_optax_step(lm, mesh, tx, donate=False)
    tk = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    for _ in range(3):
        st, loss = step(st, tk)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(jax.device_get(st.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    for leaf in jax.tree_util.tree_leaves(st.opt_state):
        if hasattr(leaf, "sharding"):
            assert leaf.sharding.is_fully_replicated


def test_lm_optax_step_moe_with_balance_trains():
    """The optax LM step handles all-experts-resident MoE models with the
    Switch balance loss folded in."""
    from jax.sharding import Mesh
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import LMOptaxState, build_lm_optax_step

    L = 16
    lm = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L,
                        moe_experts=4, moe_every=2)
    params, _ = lm.init(random.PRNGKey(1))
    tx = optax.adam(3e-3)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1, 1),
                ("data", "seq", "model"))
    st = LMOptaxState(params, tx.init(params))
    step = build_lm_optax_step(lm, mesh, tx,
                               moe_balance_weight=0.01, donate=False)
    base = np.random.RandomState(1).randint(0, 32, (1, L)).astype(np.int32)
    tk = jax.device_put(np.tile(base, (4, 1)),
                        NamedSharding(mesh, P("data", "seq")))
    losses = []
    for _ in range(20):
        st, loss = step(st, tk)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.3, losses
    assert np.isfinite(losses).all()


def test_lm_zero_state_checkpoint_roundtrip_resumes_training(tmp_path):
    """Resume ZeRO-1 LM training from a sharded checkpoint: save the
    LMZeroState (params replicated, master + Adam state sharded over the
    data axis), restore, and verify the resumed trajectory matches an
    uninterrupted run exactly."""
    from jax.sharding import NamedSharding
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (LMZeroState, build_lm_zero_step,
                                     init_lm_zero_state)
    from distlearn_tpu.utils import checkpoint as ckpt

    tree = MeshTree(num_nodes=4)
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16)
    params, _ = lm.init(random.PRNGKey(0))
    tx = optax.adam(1e-3)
    st = init_lm_zero_state(params, tree, tx)
    step = build_lm_zero_step(lm, tree, tx, donate=False)
    toks = jax.device_put(
        np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32),
        NamedSharding(tree.mesh, P("data")))

    for _ in range(2):
        st, _ = step(st, toks)
    ckpt.save_sharded_checkpoint(str(tmp_path), 2, st._asdict())
    # uninterrupted reference: two more steps
    ref = st
    for _ in range(2):
        ref, ref_loss = step(ref, toks)

    # restore into a freshly-initialized state (as a resume would)
    st2 = init_lm_zero_state(params, tree, tx)
    restored, meta = ckpt.restore_sharded_checkpoint(str(tmp_path),
                                                     st2._asdict())
    # re-place onto the mesh with the ZeRO shardings
    st2 = LMZeroState(
        params=jax.device_put(restored["params"],
                              NamedSharding(tree.mesh, P())),
        master=jax.device_put(restored["master"],
                              NamedSharding(tree.mesh, P("data"))),
        opt_state=jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(tree.mesh,
                                                      P("data"))),
            restored["opt_state"]))
    for _ in range(2):
        st2, loss2 = step(st2, toks)
    np.testing.assert_allclose(float(loss2), float(ref_loss), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ref.params)),
                    jax.tree_util.tree_leaves(jax.device_get(st2.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lm_fsdp_step_matches_replicated_step():
    """ZeRO-3 / FSDP (jit + GSPMD: params live sharded, XLA inserts the
    gathers) must compute the SAME update as the replicated-param
    shard_map step on the same global batch — the two TPU idioms are
    numerically interchangeable."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (build_lm_fsdp_step, build_lm_step,
                                     init_lm_fsdp_params)

    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    toks = np.random.RandomState(0).randint(0, 32, (n, L)).astype(np.int32)

    ref_step = build_lm_step(model, mesh, params, lr=0.1, seq_axis=None,
                             tp_axis=None, donate=False)
    tok_ref = jax.device_put(toks, NamedSharding(mesh, P("data")))
    p_ref, l_ref = ref_step(params, tok_ref)

    placed = init_lm_fsdp_params(params, mesh)
    # storage really is 1/n per device for every divisible leaf
    any_sharded = False
    for leaf in jax.tree_util.tree_leaves(placed):
        shard = leaf.addressable_shards[0].data
        if shard.size != leaf.size:
            assert leaf.size == shard.size * n
            any_sharded = True
    assert any_sharded
    fsdp_step = build_lm_fsdp_step(model, mesh, params, lr=0.1,
                                   donate=False)
    p_f, l_f = fsdp_step(placed, tok_ref)
    np.testing.assert_allclose(float(l_f), float(l_ref), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lm_fsdp_step_trains_donated():
    """The production shape (donated sharded params): loss decreases and
    the returned params keep their FSDP shardings across steps."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_fsdp_step, init_lm_fsdp_params

    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    L = 32
    model = transformer_lm(vocab=32, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_fsdp_step(model, mesh, params, lr=0.1)
    p = init_lm_fsdp_params(params, mesh)
    base = np.random.RandomState(0).randint(0, 32, (1, L)).astype(np.int32)
    toks = jax.device_put(np.tile(base, (n, 1)),
                          NamedSharding(mesh, P("data")))
    losses = []
    for _ in range(12):
        p, loss = step(p, toks)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # returned params KEEP the 1/n storage (a regression to replicated
    # out_shardings would silently defeat the ZeRO-3 memory claim)
    any_sharded = False
    for leaf in jax.tree_util.tree_leaves(p):
        shard = leaf.addressable_shards[0].data
        if shard.size != leaf.size:
            assert leaf.size == shard.size * n
            any_sharded = True
    assert any_sharded


def test_lm_fsdp_accum_matches_single_shot():
    """accum_steps=k under FSDP: same update as the single-shot step
    (equal microbatches — mean-of-means is the global mean)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import build_lm_fsdp_step, init_lm_fsdp_params

    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=1, heads=2, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    toks = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (2 * n, L))
        .astype(np.int32), NamedSharding(mesh, P("data")))
    one = build_lm_fsdp_step(model, mesh, params, lr=0.1, donate=False)
    two = build_lm_fsdp_step(model, mesh, params, lr=0.1, donate=False,
                             accum_steps=2)
    placed = init_lm_fsdp_params(params, mesh)
    p1, l1 = one(placed, toks)
    p2, l2 = two(placed, toks)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
