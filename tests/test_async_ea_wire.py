"""Wire-codec negotiation and byte-reduction tests for the AsyncEA
protocol: packed/quantized sync handshakes, mixed-version fleets
(old client / old server emulation), error-feedback convergence parity,
the compute/communication overlap sender, and the obs-verified e2e
byte-reduction acceptance criterion (ISSUE 4).
"""

import threading
import time

import numpy as np
import pytest

from distlearn_tpu import obs
from distlearn_tpu.comm import ProtocolError, Server, wire
from distlearn_tpu.parallel.async_ea import (ACK, CENTER_Q, DELTA, DELTA_Q,
                                             ENTER, ENTER_Q, REJOIN,
                                             AsyncEAClient, AsyncEAServer,
                                             AsyncEATester,
                                             _check_wire_reply,
                                             _parse_wire_request)
from distlearn_tpu.utils.logging import set_verbose

set_verbose(False)

from tests.net_util import reserve_port_window

pytestmark = pytest.mark.comm_perf


def _ports(n: int = 8) -> int:
    return reserve_port_window(n)


def _params():
    return {"w": np.zeros((4, 3), np.float32), "b": np.zeros((3,), np.float32)}


# ---------------------------------------------------------------------------
# Negotiation unit behavior (the handshake legs, no sockets).

def test_parse_wire_request_variants():
    assert _parse_wire_request("Enter?") == (None, None)
    assert _parse_wire_request({"q": ENTER_Q, "clientID": 1}) == (None, None)
    codec, err = _parse_wire_request(
        {"q": ENTER_Q, "wire": {"v": 1, "codec": "int8"}})
    assert codec == "int8" and err is None
    codec, err = _parse_wire_request(
        {"q": ENTER_Q, "wire": {"v": 1, "codec": "zstd"}})
    assert codec == "zstd" and "unsupported" in err
    _, err = _parse_wire_request({"q": ENTER_Q, "wire": "bogus"})
    assert err is not None


def test_check_wire_reply_variants():
    # legacy plain-string reply -> fall back to per-leaf frames
    assert _check_wire_reply(ENTER, ENTER, "raw") is False
    # negotiated dict reply -> packed
    assert _check_wire_reply(
        {"a": ENTER, "wire": {"v": wire.WIRE_V, "codec": "int8"}},
        ENTER, "int8") is True
    # server-side rejection must be LOUD, not a silent downgrade
    with pytest.raises(ProtocolError, match="rejected"):
        _check_wire_reply({"a": ENTER, "wire": {"error": "unsupported"}},
                          ENTER, "int8")
    with pytest.raises(ProtocolError, match="desync"):
        _check_wire_reply({"a": ENTER, "wire": {"codec": "fp16"}},
                          ENTER, "int8")
    with pytest.raises(ProtocolError):
        _check_wire_reply("delta", ENTER, "raw")


def test_client_rejects_unknown_codec_at_construction():
    with pytest.raises(ValueError, match="unknown wire codec"):
        AsyncEAClient("127.0.0.1", 1, node=1, tau=1, alpha=0.5,
                      codec="zstd")
    with pytest.raises(ValueError, match="unknown wire codec"):
        AsyncEATester("127.0.0.1", 1, 1, codec="zstd")


# ---------------------------------------------------------------------------
# End-to-end negotiated syncs.

def _one_sync(port, codec, drift=2.0, overlap=False):
    """One client, one tau=1 sync against a serial server; returns
    (client_params, server_params, client)."""
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec=codec, overlap=overlap)
        p = c.init_client(_params())
        p = {"w": p["w"] + drift, "b": p["b"] + 2 * drift}
        p, synced = c.sync_client(p)
        assert synced
        out["p"] = p
        out["packed"] = c._packed
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server(_params())
    new_params = srv.sync_server(_params())
    th.join(timeout=30)
    srv.close()
    return out, new_params


@pytest.mark.parametrize("codec,packed", [("raw", True), (None, None)])
def test_sync_math_exact_per_codec(codec, packed):
    """raw-packed and legacy-per-leaf syncs produce bit-identical EASGD
    math (delta=(p-c)*alpha both ways)."""
    out, new_params = _one_sync(_ports(), codec)
    assert out["packed"] is packed
    np.testing.assert_allclose(out["p"]["w"], 1.0)
    np.testing.assert_allclose(out["p"]["b"], 2.0)
    np.testing.assert_allclose(new_params["w"], 1.0)
    np.testing.assert_allclose(new_params["b"], 2.0)


def test_int8_sync_within_quantization_tolerance():
    out, new_params = _one_sync(_ports(), "int8")
    assert out["packed"] is True
    # delta=1.0 quantized with scale=max|d|/127: error <= scale/2
    np.testing.assert_allclose(new_params["w"], 1.0, atol=0.02)
    np.testing.assert_allclose(new_params["b"], 2.0, atol=0.04)


def test_overlap_sync_math_unchanged():
    """The background sender must not change the EASGD math — flush at the
    next sync (or close) serializes the delta before any new handshake."""
    out, new_params = _one_sync(_ports(), "raw", overlap=True)
    np.testing.assert_allclose(out["p"]["w"], 1.0)
    np.testing.assert_allclose(new_params["w"], 1.0)


def test_overlap_multi_round_accumulation():
    """τ-overlapped rounds: every delta lands exactly once (the depth-1
    queue preserves the round-serial protocol on the wire)."""
    port = _ports()
    rounds = 6

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          overlap=True)
        p = c.init_client({"w": np.zeros((2, 2), np.float32)})
        for _ in range(rounds):
            p = {"w": p["w"] + 1.0}
            p, synced = c.sync_client(p)
            assert synced
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server({"w": np.zeros((2, 2), np.float32)})
    for _ in range(rounds):
        srv.sync_server({"w": np.zeros((2, 2), np.float32)})
    th.join(timeout=30)
    center = srv.center[0].copy()
    srv.close()
    # tau=1, alpha=.5, drift +1/round: closed-form fixed-point walk —
    # center_n and params converge toward drift*(alpha weights); exactness
    # matters less than EVERY delta landing exactly once: compare against
    # the same loop run serially (no overlap) below.
    port2 = _ports()

    def client2_fn():
        c = AsyncEAClient("127.0.0.1", port2, node=1, tau=1, alpha=0.5)
        p = c.init_client({"w": np.zeros((2, 2), np.float32)})
        for _ in range(rounds):
            p = {"w": p["w"] + 1.0}
            p, _ = c.sync_client(p)
        c.close()

    th2 = threading.Thread(target=client2_fn)
    th2.start()
    srv2 = AsyncEAServer("127.0.0.1", port2, num_nodes=1)
    srv2.init_server({"w": np.zeros((2, 2), np.float32)})
    for _ in range(rounds):
        srv2.sync_server({"w": np.zeros((2, 2), np.float32)})
    th2.join(timeout=30)
    np.testing.assert_allclose(center, srv2.center[0])
    srv2.close()


def test_tester_negotiates_packed_center():
    port = _ports()
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        p = c.init_client(_params())
        c.sync_client({"w": p["w"] + 1.0, "b": p["b"]})
        c.close()

    def tester_fn():
        t = AsyncEATester("127.0.0.1", port, num_nodes=1, codec="raw")
        out["p"] = t.start_test(_params())
        t.finish_test()
        t.close()

    tc = threading.Thread(target=client_fn)
    tt = threading.Thread(target=tester_fn)
    tc.start()
    tt.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, with_tester=True)
    srv.init_server(_params())
    srv.sync_server(_params())
    assert srv.test_net()
    tc.join(timeout=30)
    tt.join(timeout=30)
    srv.close()
    np.testing.assert_allclose(out["p"]["w"], 0.5)  # (1-0)*0.5 applied


# ---------------------------------------------------------------------------
# Mixed-version fleets.

def test_new_client_against_old_server_falls_back_to_per_leaf():
    """An old server replies with the PLAIN string and speaks per-leaf
    'T' frames; a codec-advertising client must silently downgrade (the
    backward-compat guard satellite)."""
    port = _ports(4)
    center = [np.full((2, 2), 5.0, np.float32)]
    errs = []

    def old_server():
        try:
            bsrv, dsrv = Server("127.0.0.1", port), Server("127.0.0.1",
                                                           port + 1)
            bconn = bsrv.accept(1, timeout=30)[0]
            dconn = dsrv.accept(1, timeout=30)[0]
            for a in center:                       # init broadcast
                bconn.send_tensor(a)
            msg = bconn.recv_msg()                 # Enter? (+ wire advert)
            assert isinstance(msg, dict) and msg["q"] == ENTER_Q
            assert "wire" in msg                   # client DID advertise
            dconn.send_msg(ENTER)                  # plain-string reply
            assert dconn.recv_msg() == CENTER_Q
            for a in center:
                dconn.send_tensor(a)
            assert dconn.recv_msg() == DELTA_Q
            dconn.send_msg(DELTA)
            deltas = [dconn.recv_tensor() for _ in center]
            np.testing.assert_allclose(deltas[0], 0.5)  # (6-5)*.5
            for c in (bconn, dconn):
                c.close()
            bsrv.close(); dsrv.close()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    th = threading.Thread(target=old_server, daemon=True)
    th.start()
    c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                      codec="int8")
    p = c.init_client({"w": np.zeros((2, 2), np.float32)})
    p = {"w": p["w"] + 1.0}                        # drift to 6.0
    p, synced = c.sync_client(p)
    assert synced and c._packed is False           # downgraded, pinned
    np.testing.assert_allclose(p["w"], 5.5)
    c.close()
    th.join(timeout=30)
    assert not errs, errs


def test_old_client_against_new_server_per_leaf():
    """codec=None emulates an old-wire client: plain-string handshake,
    per-leaf frames — the server must serve it unchanged."""
    out, new_params = _one_sync(_ports(), None)
    assert out["packed"] is None or out["packed"] is False
    np.testing.assert_allclose(new_params["w"], 1.0)


def test_server_rejects_unsupported_codec_loudly():
    """A peer advertising a codec this build does not support must get an
    explicit wire-error reply and an eviction — never a silent-corruption
    downgrade (tentpole piece 2)."""
    port = _ports()
    reply_box = {}

    def bogus_client():
        from distlearn_tpu.comm import connect
        b = connect("127.0.0.1", port)
        d = connect("127.0.0.1", port + 1)
        b.recv_tensors(n=2)                        # init broadcast
        b.send_msg({"q": ENTER_Q, "clientID": 1,
                    "wire": {"v": 1, "codec": "zstd"}})
        reply_box["reply"] = d.recv_msg()
        b.close(); d.close()

    th = threading.Thread(target=bogus_client)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server(_params())
    with pytest.raises((RuntimeError, TimeoutError, ProtocolError)):
        # the only client gets evicted -> no live conns to serve
        srv.sync_server(_params(), timeout=5.0)
    th.join(timeout=30)
    assert 1 in srv.evicted
    srv.close()
    reply = reply_box["reply"]
    assert isinstance(reply, dict) and reply["a"] == ENTER
    assert "unsupported" in reply["wire"]["error"]
    with pytest.raises(ProtocolError, match="rejected"):
        _check_wire_reply(reply, ENTER, "zstd")


def test_rejoin_renegotiates_packed_wire():
    """Rejoin must re-run the wire negotiation on the fresh channels and
    drain overlap state; math stays exact (codec=raw)."""
    port = _ports()
    out = {}
    evicted_ev = threading.Event()

    def flaky_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec="raw", overlap=True)
        c.init_client(_params())
        c.broadcast.send_msg({"q": ENTER_Q, "clientID": 1})
        evicted_ev.wait(timeout=60)
        p = c.rejoin(_params())
        out["packed_after_rejoin"] = c._packed
        p = {"w": p["w"] + 2.0, "b": p["b"] + 2.0}
        p, synced = c.sync_client(p)
        out["synced"] = synced
        out["p"] = p
        c.close()

    th = threading.Thread(target=flaky_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1,
                        handshake_timeout=0.5)
    srv.init_server(_params())
    with pytest.raises((RuntimeError, TimeoutError)):
        srv.sync_server(_params(), timeout=5.0)    # evicts the hung client
    assert 1 in srv.evicted
    evicted_ev.set()
    deadline = time.monotonic() + 30
    while True:
        try:
            new_params = srv.sync_server(_params(), timeout=5.0)
            break
        except (RuntimeError, TimeoutError):
            assert time.monotonic() < deadline, "rejoin never served"
            time.sleep(0.05)
    th.join(timeout=30)
    srv.close()
    assert out["synced"] and out["packed_after_rejoin"] is True
    np.testing.assert_allclose(out["p"]["w"], 1.0)
    np.testing.assert_allclose(new_params["w"], 1.0)


# ---------------------------------------------------------------------------
# Error feedback: quantized-EA tracks fp32-EA.

def _run_ea(port, codec, rounds=50, seed=3):
    """One client, ``rounds`` tau=1 syncs with a deterministic drift
    sequence; returns the final server center."""
    drifts = np.random.RandomState(seed).randn(rounds).astype(np.float32)
    shape = (8, 5)

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec=codec)
        p = c.init_client({"w": np.zeros(shape, np.float32)})
        for r in range(rounds):
            p = {"w": p["w"] + drifts[r]}
            p, _ = c.sync_client(p)
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server({"w": np.zeros(shape, np.float32)})
    for _ in range(rounds):
        srv.sync_server({"w": np.zeros(shape, np.float32)})
    th.join(timeout=60)
    center = srv.center[0].copy()
    srv.close()
    return center


@pytest.mark.parametrize("codec", ["int8", "fp16"])
def test_error_feedback_keeps_quantized_ea_near_fp32(codec):
    """50 rounds of quantized-EA with client-side residual error feedback
    must track the fp32-EA trajectory: the per-round quantization error is
    re-injected, so it cannot accumulate into drift (1-bit SGD, Seide et
    al. 2014)."""
    ref = _run_ea(_ports(), "raw")
    quant = _run_ea(_ports(), codec)
    scale = float(np.max(np.abs(ref))) + 1e-6
    # within a few quantization steps of the fp32 fixed point, NOT rounds
    # of accumulated bias (which would be ~50x a step)
    rel_err = float(np.max(np.abs(quant - ref))) / scale
    assert rel_err < 0.05, rel_err


# ---------------------------------------------------------------------------
# The obs-verified acceptance criterion: int8 moves >= 3x fewer payload
# bytes than legacy fp32 per-leaf, in O(1) frames per sync.

def _measure_sync_bytes(codec):
    """Run init + ONE tau-cycle; return (payload bytes the sync moved —
    both directions, from transport_bytes_sent_total — and the packed
    frame count for the cycle)."""
    obs.REGISTRY.reset()                  # fresh counters, fresh children
    port = _ports()
    # big enough that handshake JSON is noise: 2 leaves, 96 KB fp32 total
    leaves = {"w": np.random.RandomState(0).randn(128, 128)
              .astype(np.float32),
              "b": np.random.RandomState(1).randn(2048)
              .astype(np.float32)}
    marks = {}

    def _totals(name):
        for fam in obs.REGISTRY.snapshot():
            if fam["name"] == name:
                return sum(s["value"] for s in fam["samples"])
        return 0.0

    # the "before" mark must be read with BOTH init paths quiescent —
    # reading it from the client thread races the server's counter
    # increments for the init broadcast (sendall returns on the client
    # side before the sender thread books the bytes under suite load)
    inited = threading.Event()
    go = threading.Event()

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec=codec)
        p = c.init_client({k: v.copy() for k, v in leaves.items()})
        inited.set()
        go.wait(timeout=30)
        p = {k: v + 1.0 for k, v in p.items()}
        p, synced = c.sync_client(p)
        assert synced
        c.close()

    th = threading.Thread(target=client_fn)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1)
    srv.init_server({k: v.copy() for k, v in leaves.items()})
    inited.wait(timeout=30)
    marks["before"] = _totals("transport_bytes_sent_total")
    marks["frames_before"] = _totals("wire_packed_frames_total")
    go.set()
    srv.sync_server({k: v.copy() for k, v in leaves.items()})
    th.join(timeout=30)
    srv.close()
    sync_bytes = _totals("transport_bytes_sent_total") - marks["before"]
    frames = _totals("wire_packed_frames_total") - marks["frames_before"]
    return sync_bytes, frames


def test_int8_tau_cycle_moves_3x_fewer_bytes_than_legacy_fp32():
    legacy_bytes, legacy_frames = _measure_sync_bytes(None)
    int8_bytes, int8_frames = _measure_sync_bytes("int8")
    assert legacy_frames == 0             # old wire: no 'P' frames at all
    # O(1) frames per sync: exactly 2 packed frames (center down, delta
    # up) regardless of leaf count
    assert int8_frames == 2
    ratio = legacy_bytes / int8_bytes
    assert ratio >= 3.0, (legacy_bytes, int8_bytes, ratio)


def test_packed_raw_frame_count_is_o1_per_sync():
    raw_bytes, raw_frames = _measure_sync_bytes("raw")
    assert raw_frames == 2
    assert raw_bytes > 0
