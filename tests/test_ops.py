"""Pallas fused-op tests (interpret mode on the CPU mesh — identical kernel
code path as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import random

from distlearn_tpu.models import cifar_convnet, mnist_cnn
from distlearn_tpu.ops import (fused_elastic, fused_sgd, make_spec, pack,
                               unpack)


def test_pack_unpack_roundtrip():
    params, _ = mnist_cnn().init(random.PRNGKey(0))
    spec = make_spec(params)
    assert spec.padded % 1024 == 0
    rt = unpack(spec, pack(spec, params))
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_fused_sgd_matches_tree_update():
    params, _ = cifar_convnet().init(random.PRNGKey(1))
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 0.5), params)
    spec = make_spec(params)
    out = unpack(spec, fused_sgd(pack(spec, params), pack(spec, grads), 0.2))
    expected = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g, params, grads)
    for a, b in zip(jax.tree_util.tree_leaves(expected),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_fused_elastic_matches_reference_math():
    """delta = (p - c) * alpha; p' = p - delta (lua/AllReduceEA.lua:35-39)."""
    rng = np.random.RandomState(0)
    p = {"a": rng.randn(100, 7).astype(np.float32),
         "b": rng.randn(33).astype(np.float32)}
    c = {"a": rng.randn(100, 7).astype(np.float32),
         "b": rng.randn(33).astype(np.float32)}
    spec = make_spec(p)
    new_flat, delta_flat = fused_elastic(pack(spec, p), pack(spec, c), 0.4)
    new_p, delta = unpack(spec, new_flat), unpack(spec, delta_flat)
    for k in p:
        d = (p[k] - c[k]) * 0.4
        np.testing.assert_allclose(np.asarray(delta[k]), d, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_p[k]), p[k] - d, rtol=1e-5, atol=1e-6)


def test_fused_ops_jit_under_vmap_free_shapes():
    # padded length not a multiple of the default block: exercises the
    # block-rows fallback in _grid_for
    n = 1024 * 7
    x = jnp.arange(n, dtype=jnp.float32)
    out = fused_sgd(x, jnp.ones(n, jnp.float32), 1.0)
    np.testing.assert_allclose(np.asarray(out), np.arange(n) - 1.0, rtol=1e-6)
