"""Fleet observability plane (docs/OBSERVABILITY.md): cross-process
trace propagation — one trace per AsyncEA sync and per serve request,
stitched into a waterfall by tools/tracecat.py — the legacy wire parity
when propagation is off, the fleet aggregation + SLO engine
(obs/agg.py), the obs-driven autoscaler policy (tools/autoscaler.py),
and the traffic-shape chaos scenarios that soak the whole loop."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from distlearn_tpu import obs
from distlearn_tpu.obs import agg, core, trace
from distlearn_tpu.utils.logging import set_verbose

set_verbose(False)

from tests.net_util import reserve_port_window

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
sys.path.insert(0, _TOOLS)

import autoscaler as autoscaler_mod  # noqa: E402
import tracecat  # noqa: E402

pytestmark = pytest.mark.obsplane

VOCAB, DIM, DEPTH, HEADS, MAX_LEN = 61, 32, 2, 4, 64


@pytest.fixture()
def traced_obs():
    """Obs force-enabled with trace PROPAGATION on (the non-default the
    plane tests need), fresh registry/ring, everything restored after."""
    core.configure(True)
    core.REGISTRY.reset()
    trace.clear()
    trace.set_spill(None)
    trace.set_propagate(True)
    yield
    trace.set_propagate(None)
    trace.set_spill(None)
    trace.clear()
    core.REGISTRY.reset()
    core.configure(None)


@pytest.fixture(scope="module")
def lm_params():
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    model = transformer_lm(vocab=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                           max_len=MAX_LEN)
    params, _ = model.init(jax.random.PRNGKey(0))
    return params


def _ea_params():
    # same shape set as the shard tests: S=4 stripes AND splits the
    # dominant leaf, so every fanned-out leg appears in the trace
    return {"a": np.zeros((64, 3), np.float32),
            "b": np.zeros((7,), np.float32),
            "c": np.zeros((32, 32), np.float32),
            "d": np.zeros((5,), np.float32),
            "e": np.zeros((128,), np.float32)}


def _one_striped_sync(shards=4):
    """One serial S-striped AsyncEA sync (init + a single tau=1 round);
    returns the client's stripe plan."""
    from distlearn_tpu.parallel.async_ea import AsyncEAClient, AsyncEAServer
    port = reserve_port_window(12)
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          sharded=True)
        p = c.init_client(_ea_params())
        p = {k: v + 1.0 for k, v in p.items()}
        _, out["synced"] = c.sync_client(p)
        out["stripes"] = c._stripes
        c.close()

    th = threading.Thread(target=client_fn, daemon=True)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, shards=shards)
    srv.init_server(_ea_params())
    srv.sync_server(_ea_params())
    th.join(timeout=60)
    assert not th.is_alive() and out["synced"]
    srv.close()
    return out["stripes"]


# -- e2e: one trace per logical operation -------------------------------------

def test_async_ea_striped_sync_is_one_trace(traced_obs, tmp_path):
    """ISSUE acceptance: an S=4 striped sync emits exactly ONE trace —
    the client's ``async_ea.sync`` root — and tracecat stitches the
    spilled trail into a waterfall whose parentage matches ground truth:
    the server handshake, all four server stripe legs, and the client's
    four fetch + four push legs all hang directly off the root (the
    wire context every hop carried)."""
    log = str(tmp_path / "fleet.jsonl")
    trace.set_spill(log)
    try:
        stripes = _one_striped_sync(shards=4)
    finally:
        trace.set_spill(None)
    S = len(stripes)
    assert S == 4

    spans = tracecat.load_spans([log])
    traces = tracecat.group_traces(spans)
    assert len(traces) == 1, sorted(traces)
    (tid, recs), = traces.items()
    assert len(tid) == 16 and int(tid, 16) >= 0

    roots, children = tracecat.build_tree(recs)
    assert [r["name"] for r in roots] == ["async_ea.sync"]
    root_id = roots[0]["span"]
    by_name: dict[str, list] = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    # ground truth for one S=4 sync
    assert len(by_name["async_ea.handshake"]) == 1
    assert len(by_name["async_ea.stripe_leg"]) == S
    assert len(by_name["async_ea.fetch_center"]) == S
    assert len(by_name["async_ea.push_delta"]) == S
    for name, want_parent in (("async_ea.handshake", root_id),
                              ("async_ea.stripe_leg", root_id),
                              ("async_ea.fetch_center", root_id),
                              ("async_ea.push_delta", root_id)):
        for r in by_name[name]:
            assert r["trace"] == tid
            assert r.get("parent") == want_parent, (name, r)
    # shard labels cover every stripe on each fanned-out leg
    for name in ("async_ea.stripe_leg", "async_ea.fetch_center",
                 "async_ea.push_delta"):
        assert {r["labels"]["shard"] for r in by_name[name]} \
            == set(range(S))
    # the waterfall renders and the critical path starts at the root
    cp = tracecat.critical_path(recs)
    assert cp and cp[0]["name"] == "async_ea.sync"
    text = tracecat.render_trace(tid, recs)
    assert "async_ea.sync" in text and "critical path" in text


def test_serve_request_is_one_trace(traced_obs, lm_params, tmp_path):
    """One routed serve request = one trace: ``router.generate`` is the
    root; the replica's queue-wait, TTFT and every TPOT span stitch to
    it through the trace context on the 'G' frame."""
    from distlearn_tpu.serve import DecodeEngine, Router, ServeServer
    log = str(tmp_path / "serve.jsonl")
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)
    srv = ServeServer(eng, idle_wait=0.01).start()
    max_new = 5
    try:
        trace.set_spill(log)
        with Router([(srv.host, srv.port)], health_ttl=0.02,
                    retry_interval=0.01, dial_deadline=1.0) as router:
            r = router.generate([1, 2, 3], max_new, rid="q0")
        assert r["reason"] == "complete" and len(r["tokens"]) == max_new
    finally:
        trace.set_spill(None)
        srv.stop()

    traces = tracecat.group_traces(tracecat.load_spans([log]))
    assert len(traces) == 1, sorted(traces)
    (tid, recs), = traces.items()
    roots, _children = tracecat.build_tree(recs)
    assert [r["name"] for r in roots] == ["router.generate"]
    root_id = roots[0]["span"]
    by_name: dict[str, list] = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    assert len(by_name["serve.queue_wait"]) == 1
    assert len(by_name["serve.ttft"]) == 1
    assert len(by_name["serve.tpot"]) == max_new - 1
    for name in ("serve.queue_wait", "serve.ttft", "serve.tpot"):
        for r in by_name[name]:
            assert r["trace"] == tid and r.get("parent") == root_id
    # attribution accounts the decode legs against the request window
    shares = {a["name"] for a in tracecat.attribution(recs)}
    assert {"router.generate", "serve.ttft"} <= shares


def test_tracecat_cli_stitches_multiple_trails(traced_obs, tmp_path):
    """list/show over two trails (two "processes") joins spans by trace
    id — the multi-process stitch, exercised at the CLI boundary."""
    t0 = time.time()
    a, b = str(tmp_path / "router.jsonl"), str(tmp_path / "replica.jsonl")
    with open(a, "w") as fh:
        fh.write(json.dumps({
            "type": "span", "name": "router.generate", "ts": t0 + 0.1,
            "dur": 0.1, "trace": "ab" * 8, "span": "11111111",
            "proc": "router"}) + "\n")
    with open(b, "w") as fh:
        fh.write(json.dumps({
            "type": "span", "name": "serve.ttft", "ts": t0 + 0.08,
            "dur": 0.06, "trace": "ab" * 8, "span": "22222222",
            "parent": "11111111", "proc": "replica"}) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "tracecat.py"),
         "list", a, b], capture_output=True, text=True, check=True)
    assert "ab" * 8 in out.stdout and "2" in out.stdout
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "tracecat.py"),
         "show", a, b, "--format", "json"],
        capture_output=True, text=True, check=True)
    doc = json.loads(out.stdout)
    assert doc["summary"]["trace"] == "ab" * 8
    assert doc["summary"]["spans"] == 2
    assert doc["critical_path"] == ["11111111", "22222222"]
    assert sorted(doc["summary"]["procs"]) == ["replica", "router"]


# -- legacy parity: propagation off => bitwise-identical frames ---------------

def test_trace_absent_is_bitwise_legacy(traced_obs, monkeypatch):
    """With propagation OFF (the default), no control frame carries the
    ``tc`` field and the message stream is exactly the pre-plane one:
    the ON-run stream minus that one optional key.  This is the
    mixed-fleet interop guarantee — an untraced peer sees frames
    indistinguishable from a fleet that predates the plane."""
    from distlearn_tpu.comm import transport

    sent: list = []
    orig = transport.Conn.send_msg

    def spy(self, msg):
        sent.append(msg)
        return orig(self, msg)

    monkeypatch.setattr(transport.Conn, "send_msg", spy)

    trace.set_propagate(False)
    with trace.use_context(trace.new_trace()):
        assert trace.wire_context() is None     # nothing to stamp
    _one_striped_sync(shards=1)
    off_run = list(sent)
    assert all(trace.TRACE_KEY not in m
               for m in off_run if isinstance(m, dict))
    # propagation off: spans still record, but carry no trace ids
    assert all("trace" not in r for r in trace.spans())

    sent.clear()
    trace.set_propagate(True)
    _one_striped_sync(shards=1)
    on_run = list(sent)
    stamped = [m for m in on_run
               if isinstance(m, dict) and trace.TRACE_KEY in m]
    assert stamped, "propagation on stamped no frame"
    for m in stamped:
        assert trace.valid_context(m[trace.TRACE_KEY])
    stripped = [({k: v for k, v in m.items() if k != trace.TRACE_KEY}
                 if isinstance(m, dict) else m) for m in on_run]
    assert stripped == off_run


# -- fixed fleet: the plane observes, a disabled autoscaler never acts --------

def test_fixed_fleet_unaffected_when_autoscaler_disabled(traced_obs,
                                                         lm_params):
    """ISSUE acceptance: a fixed fleet with ``enabled=False`` decodes
    token-identically to a plain fleet — the disabled loop never polls,
    never evaluates, never touches the router."""
    from distlearn_tpu.models.transformer import greedy_generate
    from distlearn_tpu.serve import DecodeEngine, Router, ServeServer

    class _Untouchable:
        def __getattr__(self, name):
            raise AssertionError(f"disabled autoscaler used .{name}")

    act = autoscaler_mod.Actuator(
        spawn=lambda: (_ for _ in ()).throw(AssertionError("spawned")),
        retire=lambda h: (_ for _ in ()).throw(AssertionError("retired")),
        min_size=1, max_size=4, initial=1)
    scaler = autoscaler_mod.Autoscaler(
        _Untouchable(), _Untouchable(), act, enabled=False)

    prompts = [np.array([3, 1, 4], np.int32), np.array([2, 7], np.int32)]
    refs = [np.asarray(greedy_generate(
        lm_params, p[None], 4))[0].tolist() for p in prompts]
    eng = DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)
    srv = ServeServer(eng, idle_wait=0.01).start()
    try:
        with Router([(srv.host, srv.port)], health_ttl=0.02,
                    retry_interval=0.01, dial_deadline=1.0) as router:
            for i, p in enumerate(prompts):
                report = scaler.step()
                assert report == {"action": "disabled", "size": 1,
                                  "breached": [], "events": []}
                r = router.generate(p, 4, rid=f"q{i}")
                assert r["tokens"] == refs[i]
            assert len(router.replica_names()) == 1
    finally:
        srv.stop()
    # the obs kill switch disables the loop the same way
    core.configure(False)
    try:
        s2 = autoscaler_mod.Autoscaler(
            _Untouchable(), _Untouchable(), act, enabled=True)
        assert s2.step()["action"] == "disabled"
    finally:
        core.configure(True)


# -- fleet registry / collector -----------------------------------------------

def _snap(reg):
    return {"type": "snapshot", "ts": time.time(),
            "metrics": reg.snapshot()}


def test_fleet_registry_replace_not_add(traced_obs):
    """Per-source replace semantics: re-ingesting a later cumulative
    snapshot from the same process must not double its contribution."""
    fleet = agg.FleetRegistry()
    reg = core.Registry()
    c = reg.counter("t_fleet_total")
    c.inc(3)
    fleet.ingest(_snap(reg), source="p0")
    c.inc(4)
    fleet.ingest(_snap(reg), source="p0")
    assert fleet.total("t_fleet_total") == 7
    reg2 = core.Registry()
    reg2.counter("t_fleet_total").inc(10)
    fleet.ingest(_snap(reg2), source="p1")
    assert fleet.total("t_fleet_total") == 17
    assert fleet.breakdown("t_fleet_total") == {"p0": 7.0, "p1": 10.0}
    fleet.forget("p1")
    assert fleet.total("t_fleet_total") == 7
    with pytest.raises(ValueError):
        fleet.ingest({"type": "span"}, source="p0")


def test_fleet_registry_merges_histograms_and_matches(traced_obs):
    fleet = agg.FleetRegistry()
    for src, vals in (("p0", (0.05, 0.2)), ("p1", (0.05, 5.0))):
        reg = core.Registry()
        h = reg.histogram("t_fl_seconds", buckets=(0.1, 1.0))
        for v in vals:
            h.observe(v)
        reg.counter("t_out_total", labels=("outcome",)).labels(
            outcome="ok" if src == "p0" else "shed").inc(2)
        fleet.ingest(_snap(reg), source=src)
    merged = fleet.histogram("t_fl_seconds")
    assert merged["count"] == 4 and merged["inf"] == 1
    assert merged["buckets"] == {"0.1": 2, "1.0": 1}
    assert fleet.total("t_out_total", {"outcome": "ok"}) == 2
    assert fleet.total("t_out_total") == 4


def test_collector_polls_http_and_trail(traced_obs, tmp_path):
    """One poll round ingests a live /snapshot endpoint AND a JSONL
    trail; a dead endpoint counts a failure but leaves the rest of the
    fleet view intact."""
    obs.counter("t_live_total").inc(5)
    srv = obs.start_http_server(0)
    trail = str(tmp_path / "replica.jsonl")
    reg = core.Registry()
    reg.counter("t_live_total").inc(7)
    with open(trail, "w") as fh:
        fh.write(json.dumps({"type": "span", "name": "x", "ts": 0,
                             "dur": 0}) + "\n")
        fh.write(json.dumps(_snap(reg)) + "\n")
    dead = reserve_port_window(1)
    try:
        coll = agg.Collector(endpoints=[("127.0.0.1", srv.port),
                                        ("127.0.0.1", dead)],
                             trails=[trail], timeout=0.5)
        fleet = coll.poll()
    finally:
        srv.close()
    assert fleet.total("t_live_total") == 12
    assert set(fleet.sources()) == {f"http://127.0.0.1:{srv.port}",
                                    os.path.basename(trail)}
    assert core.REGISTRY._families["obs_agg_polls_total"].value == 1
    fails = {s["labels"]["source"]: s["value"]
             for s in core.REGISTRY._families[
                 "obs_agg_poll_failures_total"].sample()}
    assert fails == {f"http://127.0.0.1:{dead}": 1}


# -- SLO engine ---------------------------------------------------------------

def _fleet_with_hist(observations, *, name="t_slo_seconds",
                     buckets=(0.1, 1.0), source="p0", fleet=None):
    fleet = fleet if fleet is not None else agg.FleetRegistry()
    reg = core.Registry()
    h = reg.histogram(name, buckets=buckets)
    for v in observations:
        h.observe(v)
    fleet.ingest(_snap(reg), source=source)
    return fleet


def test_slo_windowed_quantile_breaches_then_recovers(traced_obs):
    """A burst breaches the windowed p50; once the burst leaves the
    trailing window (no new samples), the rule recovers — the property
    a cumulative histogram alone can never give."""
    slo = agg.SLOEngine([{"name": "lat", "kind": "quantile",
                          "metric": "t_slo_seconds", "q": 0.5,
                          "target": 0.1, "window_s": 5.0}])
    reg = core.Registry()
    h = reg.histogram("t_slo_seconds", buckets=(0.1, 1.0))
    fleet = agg.FleetRegistry()

    fleet.ingest(_snap(reg), source="p0")
    (e,) = slo.evaluate(fleet, now=0.0)
    assert e["ok"] and not e["changed"]         # no data: never pages

    for _ in range(10):
        h.observe(0.9)                          # the burst
    fleet.ingest(_snap(reg), source="p0")
    (e,) = slo.evaluate(fleet, now=2.0)
    assert not e["ok"] and e["changed"] and e["value"] > 0.1
    assert slo.breached() == ["lat"]
    (e,) = slo.evaluate(fleet, now=4.0)         # burst still in window
    assert not e["ok"] and not e["changed"]
    (e,) = slo.evaluate(fleet, now=8.0)         # burst aged out
    assert e["ok"] and e["changed"] and slo.breached() == []
    assert core.REGISTRY._families[
        "slo_breaches_total"].labels(slo="lat").value == 1
    assert core.REGISTRY._families[
        "slo_recoveries_total"].labels(slo="lat").value == 1
    names = [r["name"] for r in trace.spans()]
    assert names.count("slo.breach") == 1
    assert names.count("slo.recover") == 1


def test_slo_windowed_quantile_counter_reset(traced_obs):
    """A source restart (count shrinks) clears the window history and
    falls back to the fresh cumulative view instead of going negative."""
    slo = agg.SLOEngine([{"name": "lat", "kind": "quantile",
                          "metric": "t_slo_seconds", "q": 0.5,
                          "target": 0.1, "window_s": 5.0}])
    fleet = _fleet_with_hist([0.9] * 8)
    slo.evaluate(fleet, now=0.0)
    assert slo.breached() == ["lat"]
    fleet = _fleet_with_hist([0.05, 0.05, 0.05])    # restarted source
    (e,) = slo.evaluate(fleet, now=1.0)
    assert e["ok"] and 0 < e["value"] <= 0.1


def test_slo_cumulative_quantile_and_burn_rate(traced_obs):
    """Without window_s the quantile is over everything ever observed;
    the burn-rate rule pages on the windowed bad/total ratio."""
    slo = agg.SLOEngine([
        {"name": "lat", "kind": "quantile", "metric": "t_slo_seconds",
         "q": 0.95, "target": 1.0},
        {"name": "errs", "kind": "burn_rate", "total": "req_total",
         "bad": "bad_total", "budget": 0.1, "window_s": 10.0,
         "max_burn": 1.0},
    ])
    fleet = _fleet_with_hist([0.05] * 20)
    reg = core.Registry()
    t, b = reg.counter("req_total"), reg.counter("bad_total")
    t.inc(100)
    b.inc(1)
    fleet.ingest(_snap(reg), source="p1")
    events = {e["slo"]: e for e in slo.evaluate(fleet, now=0.0)}
    assert events["lat"]["ok"] and events["errs"]["ok"]
    t.inc(100)
    b.inc(49)                                   # 49% of the new traffic
    fleet.ingest(_snap(reg), source="p1")
    events = {e["slo"]: e for e in slo.evaluate(fleet, now=5.0)}
    assert not events["errs"]["ok"]
    assert abs(events["errs"]["value"] - 4.9) < 1e-9
    assert events["lat"]["ok"]                  # cumulative p95 unmoved


def test_slo_rule_validation():
    with pytest.raises(ValueError):
        agg.SLOEngine([{"kind": "quantile"}])               # no name
    with pytest.raises(ValueError):
        agg.SLOEngine([{"name": "x", "kind": "nope"}])      # bad kind
    with pytest.raises(ValueError):
        agg.SLOEngine([{"name": "x", "kind": "quantile"}])  # missing keys
    with pytest.raises(ValueError):
        agg.SLOEngine([{"name": "x", "kind": "burn_rate",
                        "total": "a", "bad": "b"}])


# -- autoscaler policy --------------------------------------------------------

class _ScriptedPlane:
    """A collector+SLO pair scripted per round: poll() returns an empty
    fleet, evaluate() replays the scripted ok/breach pattern."""

    def __init__(self, script):
        self.script = list(script)      # each round: list of breached rules
        self.fleet = agg.FleetRegistry()
        self._last: list = []

    def poll(self):
        return self.fleet

    def evaluate(self, fleet):
        bad = self.script.pop(0) if self.script else []
        self._last = bad
        return [{"slo": n, "kind": "quantile", "ok": n not in bad,
                 "value": 1.0, "target": 0.1, "changed": False}
                for n in ("ttft", "ignored")]


def test_autoscaler_scales_up_on_breach_down_after_cooldown(traced_obs):
    clk = {"t": 0.0}
    spawned, retired = [], []
    act = autoscaler_mod.Actuator(
        spawn=lambda: spawned.append(len(spawned)) or len(spawned),
        retire=retired.append, min_size=1, max_size=3, initial=1)
    plane = _ScriptedPlane([["ttft"], ["ttft"], ["ttft"], [], [], []])
    scaler = autoscaler_mod.Autoscaler(
        plane, plane, act, scale_on={"ttft"}, cooldown_s=10.0,
        clock=lambda: clk["t"])

    assert scaler.step()["action"] == "up" and act.size == 2
    clk["t"] = 1.0
    assert scaler.step()["action"] == "up" and act.size == 3
    clk["t"] = 2.0
    r = scaler.step()                           # max bound holds
    assert r["action"] == "hold" and act.size == 3 and r["breached"]
    clk["t"] = 5.0
    assert scaler.step()["action"] == "hold"    # clean but not cooled
    clk["t"] = 13.0                             # 11s after last breach
    assert scaler.step()["action"] == "down" and act.size == 2
    clk["t"] = 14.0
    assert scaler.step()["action"] == "hold"    # cooldown re-armed by act
    clk["t"] = 24.0
    assert scaler.step()["action"] == "down" and act.size == 1
    clk["t"] = 40.0
    assert scaler.step()["action"] == "hold"    # min bound holds
    assert retired == [2, 1]                    # LIFO: newest first
    ups = core.REGISTRY._families[
        "autoscaler_scale_events_total"].labels(direction="up").value
    downs = core.REGISTRY._families[
        "autoscaler_scale_events_total"].labels(direction="down").value
    assert (ups, downs) == (2, 2)
    assert core.REGISTRY._families["autoscaler_target_size"].value == 1
    names = [r["name"] for r in trace.spans()]
    assert names.count("autoscaler.scale_up") == 2
    assert names.count("autoscaler.scale_down") == 2


def test_autoscaler_ignores_unwatched_rules_and_steady_state(traced_obs):
    """Breaches outside scale_on never scale; a fleet that never
    breached never shrinks below what the operator started."""
    act = autoscaler_mod.Actuator(spawn=lambda: 1, retire=lambda h: None,
                                  min_size=1, max_size=3, initial=2)
    plane = _ScriptedPlane([["ignored"], [], []])
    clk = {"t": 0.0}
    scaler = autoscaler_mod.Autoscaler(
        plane, plane, act, scale_on={"ttft"}, cooldown_s=0.1,
        clock=lambda: clk["t"])
    assert scaler.step()["action"] == "hold"
    clk["t"] = 100.0
    assert scaler.step()["action"] == "hold" and act.size == 2
    with pytest.raises(ValueError):
        autoscaler_mod.Actuator(spawn=lambda: 1, retire=lambda h: None,
                                min_size=3, max_size=2)


def test_autoscaler_cli_dry_run(traced_obs, tmp_path):
    """The CLI monitor: rules from JSON, a trail as the fleet source,
    one JSON report per round, no spawn authority."""
    trail = str(tmp_path / "p0.jsonl")
    reg = core.Registry()
    h = reg.histogram("serve_ttft_seconds", buckets=(0.025, 0.1, 1.0))
    for _ in range(10):
        h.observe(0.9)
    with open(trail, "w") as fh:
        fh.write(json.dumps(_snap(reg)) + "\n")
    rules = str(tmp_path / "slo.json")
    with open(rules, "w") as fh:
        json.dump([{"name": "ttft-p95", "kind": "quantile",
                    "metric": "serve_ttft_seconds", "q": 0.95,
                    "target": 0.05}], fh)
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "autoscaler.py"),
         "--trail", trail, "--rules", rules, "--interval", "0",
         "--rounds", "2"],
        capture_output=True, text=True, check=True,
        env={**os.environ, "DISTLEARN_OBS": "1"})
    reports = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert len(reports) == 2
    assert reports[0]["action"] == "up"         # dry-run handle only
    assert reports[0]["breached"] == ["ttft-p95"]


# -- traffic-shape scenarios (tools/chaos.py) ---------------------------------

def _chaos():
    import chaos
    return chaos


@pytest.mark.chaos
def test_scenario_zipf_mix():
    report = _chaos().run_scenario("zipf_mix", rounds=8)
    assert report["failures"] == []
    assert report["head_share"] >= 0.25
    assert report["completed"] == report["requests"]


@pytest.mark.chaos
def test_scenario_diurnal():
    report = _chaos().run_scenario("diurnal", rounds=8)
    assert report["failures"] == []
    assert report["breaches"] >= 1 and report["recoveries"] >= 1
    assert report["phases_breached"] >= 1


@pytest.mark.chaos
def test_scenario_flash_crowd():
    """ISSUE acceptance: the obs-driven autoscaler rides a 10x flash
    crowd — scale up under breach, hold, retire after cooldown — and
    the SLO engine logs the breach AND the recovery."""
    report = _chaos().run_scenario("flash_crowd", rounds=8)
    assert report["failures"] == []
    assert report["burst"] == 10 * report["baseline"]
    assert report["peak_size"] >= 2 and report["scale_ups"] >= 1
    assert report["scale_downs"] >= 1
    assert report["breaches"] >= 1 and report["recoveries"] >= 1
