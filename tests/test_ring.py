"""Ring-allreduce backend tests: the same collective contract as the tree
backend (contributor count, flush identity, rider, scatter), the reference's
bitwise SGD invariant running unchanged over the ring, and a tree-vs-ring
numerical agreement check.  Threads over real localhost TCP, as in the
reference's ``ipc.map`` fixture (test/test_AllReduceSGD.lua:26-35)."""

import numpy as np
import pytest

from distlearn_tpu.comm.ring import LocalhostRing
from distlearn_tpu.comm.tree import LocalhostTree, tree_map_spawn
from distlearn_tpu.parallel.host_algorithms import TreeAllReduceSGD

from tests.net_util import reserve_port_window


def _port() -> int:
    return reserve_port_window(1)


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_ring_allreduce_sum_and_count(n):
    port = _port()
    rng = np.random.RandomState(0)
    values = [rng.randn(37, 5).astype(np.float32) for _ in range(n)]

    def node(rank):
        r = LocalhostRing(rank, n, port)
        red, m = r.all_reduce({"v": values[rank],
                               "s": np.float32(rank)})
        r.close()
        return red, m

    expected = np.sum(values, axis=0)
    for red, m in tree_map_spawn(node, n):
        np.testing.assert_allclose(red["v"], expected, rtol=1e-5)
        np.testing.assert_allclose(red["s"], sum(range(n)), rtol=1e-6)
        assert m == n


def test_ring_mixed_dtypes_and_scalar_leaves():
    """Leaves of different dtypes ride separate dtype-grouped ring passes;
    int64 sums are exact, scalars and empty-ish chunks (size < N) work."""
    n, port = 4, _port()

    def node(rank):
        r = LocalhostRing(rank, n, port)
        red, m = r.all_reduce({"f": np.full((9,), 1.5, np.float64),
                               "i": np.arange(3, dtype=np.int64) + rank,
                               "tiny": np.int64(1)})
        r.close()
        return red, m

    for red, m in tree_map_spawn(node, n):
        np.testing.assert_array_equal(red["f"], 6.0)
        np.testing.assert_array_equal(
            red["i"], n * np.arange(3) + sum(range(n)))
        assert red["tiny"] == n
        assert m == n


def test_ring_flush_and_rider():
    """contrib=False ranks count as op-identity and are excluded from n, but
    the rider sums across ALL ranks (Tree.all_reduce_ex contract)."""
    n, port = 4, _port()

    def node(rank):
        r = LocalhostRing(rank, n, port)
        red, m, rid = r.all_reduce_ex(np.ones(6, np.float64),
                                      contrib=(rank < 2), rider=10 + rank)
        mx, m2 = r.all_reduce(np.array([-3.0 - rank]), op="max",
                              contrib=(rank != 0))
        r.close()
        return red, m, rid, mx, m2

    for red, m, rid, mx, m2 in tree_map_spawn(node, n):
        np.testing.assert_array_equal(red, 2.0)
        assert m == 2
        assert rid == 10 + 11 + 12 + 13
        np.testing.assert_array_equal(mx, -4.0)  # rank 0 excluded (identity)
        assert m2 == n - 1


@pytest.mark.parametrize("n", [2, 3, 5])
def test_ring_scatter(n):
    port = _port()

    def node(rank):
        r = LocalhostRing(rank, n, port)
        sc = r.scatter({"v": np.full((4, 4), float(rank), np.float32),
                        "u": np.arange(5) + rank})
        r.barrier()
        r.close()
        return sc

    for sc in tree_map_spawn(node, n):
        np.testing.assert_array_equal(sc["v"], 0.0)   # rank 0's everywhere
        np.testing.assert_array_equal(sc["u"], np.arange(5))


def test_ring_matches_tree_bitwise():
    """Same float64 inputs through both backends: the ring's chunked
    reduction must agree with the tree to float64 round-off; int64 exactly."""
    n = 4
    rng = np.random.RandomState(5)
    values = [rng.randn(1000).astype(np.float64) for _ in range(n)]
    ints = [rng.randint(-100, 100, 257).astype(np.int64) for _ in range(n)]

    port_t = _port()

    def tnode(rank):
        t = LocalhostTree(rank, n, port_t)
        red, _ = t.all_reduce({"f": values[rank], "i": ints[rank]})
        t.close()
        return red

    port_r = _port()

    def rnode(rank):
        r = LocalhostRing(rank, n, port_r)
        red, _ = r.all_reduce({"f": values[rank], "i": ints[rank]})
        r.close()
        return red

    tree_res = tree_map_spawn(tnode, n)
    ring_res = tree_map_spawn(rnode, n)
    np.testing.assert_array_equal(tree_res[0]["i"], ring_res[0]["i"])
    np.testing.assert_allclose(tree_res[0]["f"], ring_res[0]["f"],
                               rtol=0, atol=1e-12)
    # all ring ranks agree among themselves bitwise
    for res in ring_res[1:]:
        np.testing.assert_array_equal(ring_res[0]["f"], res["f"])


def test_ring_sgd_reference_invariant():
    """The reference's AllReduceSGD bitwise oracle (test_AllReduceSGD.lua:38)
    over the RING backend: host_algorithms runs on either backend because the
    collective surface is identical."""
    rng = np.random.RandomState(11)
    n = int(rng.choice([2, 4, 8]))
    port = _port()

    def node(rank):
        r = LocalhostRing(rank, n, port)
        sgd = TreeAllReduceSGD(r)
        rr = np.random.RandomState(300 + rank)
        params = {"w": np.zeros((4, 3), np.float64)}
        for ep in range(2):
            for _ in range(int(rr.randint(4, 14))):  # uneven steps
                g, m = sgd.sum_and_normalize_gradients({"w": rr.randn(4, 3)})
                params = {"w": params["w"] - 0.01 * g["w"]}
            params = sgd.synchronize_parameters(params)
        r.close()
        return params["w"]

    results = tree_map_spawn(node, n)
    for w in results[1:]:
        np.testing.assert_array_equal(results[0], w)


def test_ring_single_node():
    r = LocalhostRing(0, 1, _port())
    red, m, rid = r.all_reduce_ex({"v": np.ones(3)}, rider=7)
    np.testing.assert_array_equal(red["v"], 1.0)
    assert (m, rid) == (1, 7)
    sc = r.scatter({"v": np.zeros(2)})
    np.testing.assert_array_equal(sc["v"], 0.0)
    r.close()


def test_ring_op_timeout_detects_dead_rank():
    """A dead neighbor raises TimeoutError/ConnectionError instead of
    wedging (SURVEY.md §5: the reference wedges)."""
    import time
    port = _port()

    def node(rank):
        r = LocalhostRing(rank, 2, port)
        if rank == 1:
            r.close()
            return None
        r.set_op_timeout(0.5)
        t0 = time.monotonic()
        try:
            r.all_reduce({"v": np.ones((4,), np.float32)})
            return ("no-error", time.monotonic() - t0)
        except (TimeoutError, ConnectionError) as e:
            return (type(e).__name__, time.monotonic() - t0)
        finally:
            r.close()

    results = tree_map_spawn(node, 2, timeout=30)
    kind, dt = results[0]
    # PeerClosed is the clean-FIN ConnectionError subclass: a dead peer
    # may be seen either mid-frame (reset/timeout) or between frames
    assert kind in ("TimeoutError", "ConnectionError", "PeerClosed"), kind
    assert dt < 10.0
