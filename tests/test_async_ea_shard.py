"""Sharded AsyncEA center (ISSUE 5): stripe/split planning units, striped
sync math and S-invariance, mixed-version fleets, mid-stripe eviction
cleanup + rejoin resync, connection-generation hygiene, per-shard
telemetry, and the sharded lint schedules.
"""

import threading
import time

import numpy as np
import pytest

from distlearn_tpu import obs
from distlearn_tpu.comm import wire
from distlearn_tpu.lint.protocol import (async_ea_rejoin_sharded_schedule,
                                         async_ea_sharded_schedule,
                                         check_schedules)
from distlearn_tpu.parallel.async_ea import (ENTER, ENTER_Q, AsyncEAClient,
                                             AsyncEAServer,
                                             AsyncEAServerConcurrent)
from distlearn_tpu.utils.logging import set_verbose

set_verbose(False)

from tests.net_util import reserve_port_window

pytestmark = pytest.mark.shard


def _params():
    # sized so S=4 both stripes the list AND splits the dominant leaf
    # ("c" is ~75% of the bytes — the Amdahl case sub-leaf striping fixes)
    return {"a": np.zeros((64, 3), np.float32),
            "b": np.zeros((7,), np.float32),
            "c": np.zeros((32, 32), np.float32),
            "d": np.zeros((5,), np.float32),
            "e": np.zeros((128,), np.float32),
            "f": np.zeros((2, 2), np.float32)}


# ---------------------------------------------------------------------------
# Planner units (no sockets).

def test_plan_stripes_byte_balanced():
    stripes = wire.plan_stripes([100, 200, 50, 50, 400, 10], 4)
    assert stripes == [(0, 2), (2, 4), (4, 5), (5, 6)]
    # contiguous cover, at least one leaf per stripe
    assert stripes[0][0] == 0 and stripes[-1][1] == 6
    assert all(lo < hi for lo, hi in stripes)
    assert all(stripes[i][1] == stripes[i + 1][0]
               for i in range(len(stripes) - 1))


def test_plan_stripes_clamps_and_degenerates():
    assert wire.plan_stripes([100], 4) == [(0, 1)]          # S > leaves
    assert wire.plan_stripes([], 4) == [(0, 0)]             # empty tree
    assert wire.plan_stripes([1, 2, 3], 1) == [(0, 3)]      # S=1 legacy
    assert len(wire.plan_stripes([10] * 3, 8)) == 3         # >=1 leaf each


def test_plan_splits_cuts_only_oversized_leaves():
    # total 1010, target 252.5: only the 1000-byte leaf splits (4 ways)
    assert wire.plan_splits([1000, 10], [250, 10], 4) == [4, 1]
    # everything under the share stays whole; S=1 never splits
    assert wire.plan_splits([100] * 4, [25] * 4, 4) == [1] * 4
    assert wire.plan_splits([1000, 10], [250, 10], 1) == [1, 1]
    # a split can never exceed the leaf's element count
    assert wire.plan_splits([4000, 1], [2, 1], 4) == [2, 1]


def test_split_views_roundtrip_and_write_through():
    rs = np.random.RandomState(0)
    leaves = [rs.randn(5, 4).astype(np.float32),
              rs.randn(7,).astype(np.float32)]
    splits = [3, 1]
    views = wire.split_views(leaves, splits)
    assert len(views) == 4
    assert sum(v.size for v in views[:3]) == 20
    merged = wire.merge_views(views, splits, [(5, 4), (7,)])
    np.testing.assert_array_equal(merged[0], leaves[0])
    assert merged[1] is leaves[1]                       # unsplit: no copy
    views[0][:] = 9.0                                   # chunk writes land
    assert (leaves[0].reshape(-1)[:views[0].size] == 9.0).all()


def test_sharded_server_splits_dominant_leaf():
    """The published stripe plan indexes the VIRTUAL leaf list: the
    dominant leaf is cut so no stripe holds most of the bytes."""
    port = reserve_port_window(12)
    done = threading.Event()

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5)
        c.init_client(_params())
        done.set()
        c.close()

    th = threading.Thread(target=client_fn, daemon=True)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, shards=4)
    srv.init_server(_params())
    assert max(srv.splits) > 1                  # "c" got cut
    vbytes = [v.nbytes for v in srv._vcenter]
    per_stripe = [sum(vbytes[lo:hi]) for lo, hi in srv.stripes]
    assert max(per_stripe) < 0.5 * sum(vbytes)  # no Amdahl stripe
    assert srv._shard_spec["stripes"][-1][1] == len(vbytes)
    th.join(timeout=30)
    assert done.is_set()
    srv.close()


# ---------------------------------------------------------------------------
# End-to-end striped syncs.

def _run_serial(codec, shards, rounds=3, sharded_client=True):
    port = reserve_port_window(12)
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec=codec, sharded=sharded_client)
        p = c.init_client(_params())
        for r in range(rounds):
            p = {k: v + (r + 1) for k, v in p.items()}
            p, synced = c.sync_client(p)
            assert synced
        out["p"] = p
        out["stripes"] = c._stripes
        c.close()

    th = threading.Thread(target=client_fn, daemon=True)
    th.start()
    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1, shards=shards)
    srv.init_server(_params())
    for _ in range(rounds):
        srv.sync_server(_params())
    th.join(timeout=60)
    assert not th.is_alive(), "client hung"
    center = [np.array(t) for t in srv.center]
    srv.close()
    return out, center


def test_striped_sync_math_exact():
    """One S=4 striped sync does the exact EASGD update: center +=
    alpha*(p - c) on every leaf, client takes p - delta."""
    out, center = _run_serial("raw", 4, rounds=1)
    assert out["stripes"] is not None and len(out["stripes"]) >= 2
    for t in center:
        np.testing.assert_array_equal(t, np.full_like(t, 0.5))
    for v in out["p"].values():
        np.testing.assert_array_equal(v, np.full_like(v, 0.5))


def test_sharded_client_against_unsharded_server():
    out, _ = _run_serial("raw", 1, rounds=2)
    assert out["stripes"] is None               # no plan advertised


def test_unsharded_client_against_sharded_server():
    """sharded=False pins the single-channel packed sync even when the
    server stripes for everyone else."""
    out, center = _run_serial("raw", 4, rounds=1, sharded_client=False)
    assert out["stripes"] is None
    for t in center:
        np.testing.assert_array_equal(t, np.full_like(t, 0.5))


def test_legacy_client_against_sharded_server():
    """Mixed-version fleet: a pre-shard (codec=None, per-leaf frames)
    client syncs against a sharded server via the S=1 legacy path."""
    out, center = _run_serial(None, 4, rounds=1)
    assert out["stripes"] is None
    for t in center:
        np.testing.assert_array_equal(t, np.full_like(t, 0.5))


def _run_concurrent(shards, rounds, port=None):
    port = port or reserve_port_window(12)
    out = {}

    def client_fn():
        c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                          codec="raw")
        p = c.init_client(_params())
        for r in range(rounds):
            p = {k: v + (r % 5) + 0.25 for k, v in p.items()}
            p, synced = c.sync_client(p)
            assert synced
        out["p"] = p
        c.close()

    th = threading.Thread(target=client_fn, daemon=True)
    th.start()
    srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=1,
                                  shards=shards)
    srv.init_server(_params())
    srv.start()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if srv.syncs_completed >= rounds and srv.drained:
            break
        time.sleep(0.01)
    th.join(timeout=60)
    assert not th.is_alive(), "client hung"
    assert srv.syncs_completed == rounds
    center = [np.array(t) for t in srv._snapshot()]
    srv.stop()
    srv.close()
    return out, center


def test_fifty_round_bitwise_parity_s4_vs_s1():
    """50 single-client raw-codec rounds: the S=4 striped pipeline and
    the S=1 packed path produce BITWISE-identical centers and params —
    striping (including sub-leaf chunking) is pure transport, zero math.
    Single client on purpose: with concurrent clients the sync
    interleaving differs between runs, which changes each client's
    fetched center legitimately."""
    out1, c1 = _run_concurrent(1, 50)
    out4, c4 = _run_concurrent(4, 50)
    for a, b in zip(c1, c4):
        np.testing.assert_array_equal(a, b)
    for k in out1["p"]:
        np.testing.assert_array_equal(out1["p"][k], out4["p"][k])


# ---------------------------------------------------------------------------
# Mid-stripe eviction + rejoin.

def test_mid_stripe_death_cleans_every_shard_and_rejoin_resyncs():
    """A client dying between the Enter reply and its stripe legs must be
    evicted with its conns dropped from EVERY shard endpoint (no leaked
    registrations serving a dead socket), its connection generation
    bumped, and a rejoin must re-dial + resync ALL stripes."""
    port = reserve_port_window(12)
    srv_box = {}

    def server_fn():
        srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=1,
                                      shards=4, handshake_timeout=5.0,
                                      rejoin_grace=60.0)
        srv.init_server(_params())
        srv_box["srv"] = srv
        srv.start()

    st = threading.Thread(target=server_fn, daemon=True)
    st.start()
    cl = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                       codec="raw")
    p = cl.init_client(_params())
    st.join(timeout=30)
    srv = srv_box["srv"]
    gen0 = srv._conn_gen[1]

    # get admitted (reply pins the stripe plan, shard conns dialed) ...
    assert cl._announce(ENTER_Q, ENTER) is True
    assert cl._stripes is not None and len(cl._stripes) >= 2
    # ... then die mid-sync, before any stripe leg completes
    for c in (cl.broadcast, cl.conn, *cl._shard_conns):
        c.close()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and 1 not in srv.evicted:
        time.sleep(0.02)
    assert 1 in srv.evicted
    assert srv._conn_gen[1] > gen0          # stale tokens can't replay
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and any(
            1 in ep.conns for ep in srv.shard_endpoints):
        time.sleep(0.02)
    for ep in srv.shard_endpoints:
        assert 1 not in ep.conns            # every shard channel dropped

    gen1 = srv._conn_gen[1]
    p = cl.rejoin(p)                        # fresh channels, full center
    deadline = time.monotonic() + 10        # readmit finishes server-side
    while time.monotonic() < deadline and srv._conn_gen[1] <= gen1:
        time.sleep(0.02)
    assert srv._conn_gen[1] > gen1          # readmit bumps again
    assert cl._stripes is not None          # plan re-advertised + re-dialed
    drift = {k: v + 2.0 for k, v in p.items()}
    p2, synced = cl.sync_client(drift)
    assert synced
    def settled():
        # shard legs apply asynchronously after leg 0 counts the sync:
        # the center is only stable once no handshake is in flight
        # (drained also needs the dispatcher gone, which needs the
        # client gone — too strong while cl stays connected)
        with srv._lock:
            infl = srv._inflight
        return (srv.syncs_completed >= 1 and infl == 0
                and all(q.empty() for q in srv._shard_queues.values()))

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not settled():
        time.sleep(0.02)
    assert srv.syncs_completed == 1
    assert settled()
    for t in srv._snapshot():               # every stripe took the delta
        np.testing.assert_array_equal(t, np.full_like(t, 1.0))
    cl.close()
    srv.stop()
    srv.close()


# ---------------------------------------------------------------------------
# Per-shard telemetry.

def test_per_shard_obs_counters(monkeypatch):
    from distlearn_tpu.obs import core
    core.configure(True)
    core.REGISTRY.reset()
    try:
        out, _ = _run_serial("raw", 4, rounds=2)
        nstripes = len(out["stripes"])
        fams = {f["name"]: f for f in core.REGISTRY.snapshot()}
        legs = fams["async_ea_shard_syncs_total"]["samples"]
        by_shard = {s["labels"]["shard"]: s["value"] for s in legs}
        assert by_shard == {str(i): 2 for i in range(nstripes)}
        wire_bytes = fams["async_ea_shard_wire_bytes_total"]["samples"]
        assert all(s["value"] > 0 for s in wire_bytes)
        assert len(wire_bytes) == nstripes
    finally:
        core.REGISTRY.reset()
        core.configure(None)


# ---------------------------------------------------------------------------
# Sharded lint schedules.

def test_lint_sharded_schedules_are_clean():
    assert check_schedules(async_ea_sharded_schedule(4)) == []
    assert check_schedules(async_ea_rejoin_sharded_schedule(4)) == []


def test_lint_evict_mid_stripe_clean_only_with_timeouts():
    # a client dying mid-stripe leaves the server's recv pending: armed
    # timeouts model the eviction and the simulation drains ...
    assert check_schedules(async_ea_sharded_schedule(
        4, server_timeouts=True, truncate_tail=1)) == []
    # ... without them it is a real deadlock and DL101 must fire
    fs = check_schedules(async_ea_sharded_schedule(4, truncate_tail=1),
                         name="evict-mid-stripe-naked")
    assert any(f.rule == "DL101" for f in fs)
