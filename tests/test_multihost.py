"""Multi-host tests — real OS processes, not threads (VERDICT r1 #3).

Two deployment shapes, each spawned with ``multiprocessing`` (spawn context:
fresh interpreters, like the reference's fresh-Lua-state workers):

* TCP-tree process-per-host training (the examples/client_remote.py shape):
  ranks train unevenly and synchronize through the socket tree; oracle =
  bitwise-identical params after sync (ref test_AllReduceSGD.lua:38).
* ``jax.distributed`` global-mesh SPMD (distlearn_tpu.parallel.init): two
  processes × two virtual CPU devices join one 4-device mesh and run the
  fused AllReduceSGD step; oracle = bitwise-identical replicated params on
  every process.
"""

from __future__ import annotations

import hashlib
import multiprocessing as mp
import os
import sys

import numpy as np

from tests.net_util import reserve_port_window

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _digest(leaves) -> str:
    flat = np.concatenate([np.asarray(x, np.float64).ravel() for x in leaves])
    return hashlib.sha256(flat.tobytes()).hexdigest()


def _tcp_worker(rank: int, n: int, port: int, q) -> None:
    sys.path.insert(0, _REPO)
    import numpy as np

    from distlearn_tpu.comm.tree import LocalhostTree
    from distlearn_tpu.parallel.host_algorithms import TreeAllReduceSGD

    try:
        t = LocalhostTree(rank, n, port)
        sgd = TreeAllReduceSGD(t)
        params = {"w": np.zeros((8, 4), np.float64),
                  "b": np.zeros((4,), np.float64)}
        params = sgd.synchronize_parameters(params)
        rng = np.random.RandomState(100 + rank)
        for _ in range(3 + rank):        # UNEVEN step counts across ranks
            grads = {"w": rng.randn(8, 4), "b": rng.randn(4)}
            g, m = sgd.sum_and_normalize_gradients(grads)
            params = {k: params[k] - 0.1 * g[k] for k in params}
        params = sgd.synchronize_parameters(params)
        t.close()
        q.put(("ok", rank, _digest(params.values())))
    except Exception as e:  # noqa: BLE001 — surface in parent
        q.put(("err", rank, repr(e)))


def _spmd_worker(pid: int, nprocs: int, port: int, q) -> None:
    sys.path.insert(0, _REPO)
    try:
        from distlearn_tpu.parallel.init import (global_mesh_tree,
                                                 host_local_batch, initialize)
        info = initialize(f"127.0.0.1:{port}", nprocs, pid,
                          local_device_count=2)
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import random

        from distlearn_tpu.models.core import Model
        from distlearn_tpu.train import build_sgd_step, init_train_state

        def init(key):
            k1, _ = random.split(key)
            return {"w": random.normal(k1, (16, 10)) * 0.1,
                    "b": jnp.zeros((10,))}, {}

        def apply(params, state, x, train=True, rng=None, axis_name=None,
                  bn_weight=None):
            logits = x.reshape(x.shape[0], -1) @ params["w"] + params["b"]
            return jax.nn.log_softmax(logits), state

        model = Model(init=init, apply=apply, name="toy",
                      input_shape=(4, 4, 1), num_classes=10)
        tree = global_mesh_tree()
        assert tree.num_nodes == info.global_devices == 2 * nprocs

        ts = init_train_state(model, tree, random.PRNGKey(0), 10)
        step = build_sgd_step(model, tree, lr=0.1)
        rs = np.random.RandomState(7)
        gx = rs.randn(8, 4, 4, 1).astype(np.float32)
        gy = rs.randint(0, 10, (8,)).astype(np.int32)
        per = 8 // info.num_processes            # this host's input shard
        bx = host_local_batch(tree, gx[pid * per:(pid + 1) * per])
        by = host_local_batch(tree, gy[pid * per:(pid + 1) * per])
        for _ in range(3):
            ts, loss = step(ts, bx, by)
        leaves = [np.asarray(jax.device_get(l.addressable_shards[0].data))
                  for l in jax.tree_util.tree_leaves(ts.params)]
        q.put(("ok", pid, _digest(leaves),
               float(loss.addressable_shards[0].data[()])))
    except Exception as e:  # noqa: BLE001
        q.put(("err", pid, repr(e)))


def _run_spawned(target, n: int, port: int, timeout: float):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=target, args=(i, n, port, q))
             for i in range(n)]
    for p in procs:
        p.start()
    try:
        results = [q.get(timeout=timeout) for _ in range(n)]
    finally:
        for p in procs:
            p.join(timeout=30)
        for p in procs:
            if p.is_alive():
                p.terminate()
    errs = [r for r in results if r[0] == "err"]
    assert not errs, f"worker failures: {errs}"
    return results


def test_tcp_tree_training_across_processes():
    port = reserve_port_window(1)
    results = _run_spawned(_tcp_worker, 2, port, timeout=120)
    digests = {r[2] for r in results}
    assert len(digests) == 1, f"params diverged across hosts: {results}"


def test_jax_distributed_global_mesh_spmd():
    port = reserve_port_window(1)
    results = _run_spawned(_spmd_worker, 2, port, timeout=300)
    digests = {r[2] for r in results}
    losses = {r[3] for r in results}
    assert len(digests) == 1, f"params diverged across processes: {results}"
    assert len(losses) == 1


def _shard_ckpt_worker(pid: int, nprocs: int, args, q) -> None:
    port, ckpt_dir = args
    sys.path.insert(0, _REPO)
    try:
        from distlearn_tpu.parallel.init import (global_mesh_tree,
                                                 host_local_batch, initialize)
        initialize(f"127.0.0.1:{port}", nprocs, pid, local_device_count=2)
        import jax
        import numpy as np
        from distlearn_tpu.utils import checkpoint as ckpt

        tree = global_mesh_tree()
        # a globally-known array sharded over all 4 devices (2 per process):
        # each process contributes its host-local half
        glob = np.arange(32, dtype=np.float32).reshape(8, 4)
        per = 8 // nprocs
        sharded = host_local_batch(tree, glob[pid * per:(pid + 1) * per])
        ckpt.save_sharded_checkpoint(ckpt_dir, 3, {"a": sharded},
                                     process_index=pid)
        q.put(("ok", pid, "saved"))
    except Exception as e:  # noqa: BLE001
        q.put(("err", pid, repr(e)))


def test_sharded_checkpoint_across_processes(tmp_path):
    """Each jax.distributed process saves only ITS addressable shards;
    offline reassembly recovers the exact global array (the pod-scale
    checkpoint shape — no single host ever held the whole array)."""
    import numpy as np

    from distlearn_tpu.utils import checkpoint as ckpt

    port = reserve_port_window(1)
    d = str(tmp_path)
    results = _run_spawned(_shard_ckpt_worker, 2, (port, d), timeout=300)
    assert all(r[0] == "ok" for r in results), results
    like = {"a": np.zeros((8, 4), np.float32)}
    restored, meta = ckpt.restore_sharded_checkpoint(d, like)
    assert meta["step"] == 3
    np.testing.assert_array_equal(
        restored["a"], np.arange(32, dtype=np.float32).reshape(8, 4))
