"""Explicit-state model checking (DL301-DL304) and schedule↔code
conformance (DL310).

Two halves mirror the gate's promise:

* the UNMUTATED protocol models and schedules are clean — exhaustively
  (every model reports its full state count, no max_states overflow);
* each seeded mutation is caught by EXACTLY its intended rule, with a
  readable counterexample trace: timeouts stripped -> DL301 deadlock,
  replay ledger dropped -> DL303 double-apply, epoch fence removed ->
  DL302 stale write, evict leaks the engine slot -> DL304, schedule tag
  edited / question order swapped -> DL310.
"""

import pytest

from distlearn_tpu.lint.model import (ModelSpec, builtin_models, check_model,
                                      failover_model, lint_models,
                                      membership_model, replay_model,
                                      router_model, serve_model,
                                      sharded_model, sync_model)

pytestmark = pytest.mark.model


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------ clean sweep

def test_builtin_models_all_clean_and_exhaustive():
    reports = lint_models()
    assert [spec.name for _rep, spec in reports] == [
        "sync", "sharded", "replay", "failover", "serve", "membership",
        "router", "backend_sync[host]", "backend_sync[hybrid]"]
    for rep, spec in reports:
        assert rep.findings == [], (
            f"{spec.name}: " + "; ".join(map(str, rep.findings)))
        # exhaustive: a state count exists and the search never overflowed
        assert rep.states > 0 and rep.transitions > 0
        assert rep.info == {"states": rep.states,
                            "transitions": rep.transitions}


def test_state_counts_are_deterministic():
    a = check_model(sharded_model())
    b = check_model(sharded_model())
    assert (a.states, a.transitions) == (b.states, b.transitions)
    assert a.states > 100        # interleavings, not a single trace


# ------------------------------------------------- seeded mutations fire

def test_dl301_sync_without_server_timeouts_deadlocks():
    """A client crash mid-handshake leaves the server recv hung forever
    once the eviction timeout is stripped."""
    rep = check_model(sync_model(server_timeouts=False))
    assert _rules(rep.findings) == ["DL301"]
    assert "counterexample" in rep.findings[0].message


def test_dl301_sharded_without_server_timeouts_deadlocks():
    rep = check_model(sharded_model(server_timeouts=False))
    assert _rules(rep.findings) == ["DL301"]


@pytest.mark.parametrize("backend", ["host", "hybrid"])
def test_dl301_backend_sync_without_op_timeouts_deadlocks(backend):
    """Strip the collective's op_timeout arming: a peer process crash
    mid-round leaves the blocked recv hung forever (SURVEY.md §5, the
    reference's documented failure mode) — for both the flat TCP tree
    and the hybrid one-leg-per-host topology."""
    from distlearn_tpu.lint.model import backend_sync_model
    rep = check_model(backend_sync_model(backend=backend,
                                         host_timeouts=False))
    assert _rules(rep.findings) == ["DL301"]
    assert "counterexample" in rep.findings[0].message


def test_dl303_replay_without_ledger_double_applies():
    """Drop the exactly-once ledger: the ack-drop retry re-delivers the
    same (client, seq) delta and the center applies it twice."""
    rep = check_model(replay_model(ledger=False))
    assert _rules(rep.findings) == ["DL303"]
    assert "counterexample" in rep.findings[0].message


def test_dl302_failover_without_fence_applies_stale_delta():
    """Remove the epoch fence: the paused-and-resumed zombie primary
    accepts a delta after the standby's promotion."""
    rep = check_model(failover_model(fence=False))
    assert _rules(rep.findings) == ["DL302"]
    assert "counterexample" in rep.findings[0].message


def test_dl304_serve_evict_leaking_slot_is_caught():
    rep = check_model(serve_model(finish_on_evict=False))
    assert _rules(rep.findings) == ["DL304"]


def test_dl302_membership_without_join_fence_applies_unadopted_delta():
    """Register the joiner before its center-adoption ACK: the server can
    apply a delta from a client that never adopted the center."""
    rep = check_model(membership_model(join_fence=False))
    assert _rules(rep.findings) == ["DL302"]
    assert "NEVER ADOPTED" in rep.findings[0].message
    assert "counterexample" in rep.findings[0].message


def test_dl303_membership_without_leave_flush_double_applies():
    """Read the applied-seq ledger while the leaver's apply is still in
    flight: the leave replay and the worker both land the delta."""
    rep = check_model(membership_model(leave_flush=False))
    assert _rules(rep.findings) == ["DL303"]
    assert "STILL IN FLIGHT" in rep.findings[0].message


def test_dl304_membership_without_renorm_breaks_weight_budget():
    """Skip the capacity-weight renormalization at join: live weights no
    longer sum to the fleet budget and the elastic average is biased."""
    rep = check_model(membership_model(renorm=False))
    assert _rules(rep.findings) == ["DL304"]
    assert "budget" in rep.findings[0].message


def test_dl301_router_without_retry_strands_the_request():
    """Strip retry-on-death: a request queued on a replica that dies
    before prefill has no owner and no resubmission — the request
    never reaches a terminal state."""
    rep = check_model(router_model(retry=False))
    assert _rules(rep.findings) == ["DL301"]


def test_dl302_router_without_epoch_fence_mixes_epochs():
    """Remove the fence: a stream that pinned epoch 0 can deliver a
    chunk decoded under the hot-swapped epoch-1 weights — two model
    versions spliced into one completion."""
    rep = check_model(router_model(fence=False))
    assert _rules(rep.findings) == ["DL302"]
    assert "counterexample" in rep.findings[0].message


def test_dl303_router_hedge_without_cancel_double_executes():
    """Hedge WITHOUT closing the first connection: the abandoned copy
    stays queued on the old replica while the hedge enqueues a second —
    execution is no longer at-most-once per request."""
    rep = check_model(router_model(single_dispatch=False))
    assert _rules(rep.findings) == ["DL303"]


def test_mutated_models_stay_clean_when_unmutated():
    """The flags default to the code's real behavior — the clean sweep
    above is the same checker, not a weaker configuration."""
    for spec in builtin_models():
        assert check_model(spec).findings == []


# ------------------------------------------------------- checker plumbing

def test_counterexample_trace_is_shortest_and_numbered():
    rep = check_model(failover_model(fence=False))
    msg = rep.findings[0].message
    assert "counterexample" in msg and "1)" in msg
    # BFS: the zombie trace needs pause -> promote -> resume -> apply,
    # so the minimal trace is short but not trivial
    import re
    m = re.search(r"counterexample \((\d+) step", msg)
    assert m is not None and 3 <= int(m.group(1)) <= 8


def test_max_states_overflow_is_reported_not_silent():
    """A state space bigger than the budget must surface as DL301
    evidence (analysis incomplete), never as a silent pass."""
    rep = check_model(sharded_model(), max_states=10)
    assert _rules(rep.findings) == ["DL301"]
    assert "state space exceeded" in rep.findings[0].message


def test_deadlock_freedom_of_trivial_custom_model():
    """The ModelSpec surface docs/LINT.md teaches: two actions, one
    terminal state, no invariant violations."""
    spec = ModelSpec(
        name="toy",
        init=(0,),
        actions=lambda s: [] if s[0] >= 2 else [
            (f"inc->{s[0] + 1}", (s[0] + 1,))],
        invariant=lambda s: [],
        is_terminal=lambda s: s[0] == 2)
    rep = check_model(spec)
    assert rep.findings == [] and rep.states == 3


def test_stuck_custom_model_is_dl301():
    spec = ModelSpec(
        name="stuck",
        init=(0,),
        actions=lambda s: [("step", (1,))] if s[0] == 0 else [],
        invariant=lambda s: [],
        is_terminal=lambda s: False)
    rep = check_model(spec)
    assert _rules(rep.findings) == ["DL301"]


# ---------------------------------------------------------- DL310 conformance

def test_conformance_clean_on_unmutated_tree():
    from distlearn_tpu.lint.conformance import lint_conformance
    assert lint_conformance() == []


def test_dl310_edited_schedule_tag_fires():
    from distlearn_tpu.lint.conformance import lint_conformance
    from distlearn_tpu.lint.protocol import Op, async_ea_sync_schedule
    sched = async_ea_sync_schedule()
    sched["C"] = [Op(o.kind, o.peer,
                     "delta2?" if o.tag == "delta?" else o.tag, o.timeout)
                  for o in sched["C"]]
    fs = lint_conformance(schedules={"sync": sched})
    assert _rules(fs) == ["DL310"]
    assert "delta2?" in fs[0].message


def test_dl310_swapped_question_order_fires():
    from distlearn_tpu.lint.conformance import lint_conformance
    from distlearn_tpu.lint.protocol import async_ea_sync_schedule
    sched = async_ea_sync_schedule(client_order=("delta?", "Center?"))
    fs = lint_conformance(schedules={"sync": sched})
    assert _rules(fs) == ["DL310"]
    assert fs[0].where == "sync:C"


def test_dl310_code_side_constant_drift_fires():
    import inspect
    from distlearn_tpu.lint.conformance import lint_conformance
    from distlearn_tpu.parallel import async_ea
    src = inspect.getsource(async_ea).replace(
        'DELTA_Q = "delta?"', 'DELTA_Q = "delta2?"', 1)
    assert src != inspect.getsource(async_ea)
    fs = lint_conformance(source=src)
    assert "DL310" in _rules(fs)
    assert any("disagree" in f.message for f in fs)


def test_dl310_unmodeled_message_type_fires():
    import inspect
    from distlearn_tpu.lint.conformance import lint_conformance
    from distlearn_tpu.parallel import async_ea
    src = inspect.getsource(async_ea) + '\nSNAPSHOT_Q = "Snapshot?"\n'
    fs = lint_conformance(source=src)
    assert _rules(fs) == ["DL310"]
    assert "SNAPSHOT_Q" in fs[0].message


# --------------------------------------------- DL310 serve-frame bindings

def test_serve_frames_clean_on_unmutated_tree():
    from distlearn_tpu.lint.conformance import lint_serve_frames
    assert lint_serve_frames() == []


def test_dl310_ghost_stream_field_fires():
    """A field the server starts emitting without a binding entry is a
    protocol change the model never reviewed."""
    import inspect
    from distlearn_tpu.lint.conformance import lint_serve_frames
    from distlearn_tpu.serve import server
    src = inspect.getsource(server) + (
        '\n\ndef _ghost(conn, rid):\n'
        '    conn.send_stream({"rid": rid, "shard_hint": 1})\n')
    fs = lint_serve_frames(server_source=src)
    assert _rules(fs) == ["DL310"]
    assert fs[0].where == "serve_frames.R.shard_hint"


def test_dl310_renamed_stream_field_fires_both_ways():
    """Renaming ``retry_after`` across every producer/consumer leaves the
    committed binding stale AND introduces an unbound field — the audit
    reports both directions so the fix is unambiguous."""
    import inspect
    from distlearn_tpu.lint.conformance import lint_serve_frames
    from distlearn_tpu.serve import client, router, server

    def ren(mod):
        return inspect.getsource(mod).replace('"retry_after"',
                                              '"retry_after_s"')

    fs = lint_serve_frames(server_source=ren(server),
                           router_source=ren(router),
                           client_source=ren(client))
    wheres = sorted(f.where for f in fs)
    assert _rules(fs) == ["DL310"]
    assert wheres == ["serve_frames.R.retry_after",
                      "serve_frames.R.retry_after_s"]
