"""Raw-speed serving tests: radix prefix cache bookkeeping (refcount
conservation, LRU eviction, epoch clear), speculative decode (exact
greedy equivalence, implicit rollback), in-tick sampling (seeded
determinism, temp=0 bitwise parity), and chunked prefill (token parity
plus the no-TPOT-stall scheduling contract under an injected clock).

The cache/drafter/sampling features are all latency trades on top of
the serving parity invariant (tests/test_serve.py): every test here
ultimately compares against ``greedy_generate`` — a cached, chunked,
or speculated stream must be token-identical to the plain one.
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.serve

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

VOCAB, DIM, DEPTH, HEADS, MAX_LEN = 61, 32, 2, 4, 64


@pytest.fixture(scope="module")
def lm_params():
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    model = transformer_lm(vocab=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                           max_len=MAX_LEN)
    params, _ = model.init(jax.random.PRNGKey(0))
    return params


def _greedy_ref(params, prompt, steps):
    from distlearn_tpu.models.transformer import greedy_generate
    out = greedy_generate(params, np.asarray(prompt, np.int32)[None], steps)
    return np.asarray(out)[0].tolist()


# -- radix prefix cache: pure bookkeeping (no jax) ----------------------------

def _kv_and_cache(num_slots=3, page=4, max_len=32, max_pages=None):
    from distlearn_tpu.serve.kv_cache import PagedKVCache
    from distlearn_tpu.serve.prefix_cache import RadixPrefixCache
    kv = PagedKVCache(num_slots=num_slots, page=page, max_len=max_len)
    return kv, RadixPrefixCache(kv, max_pages=max_pages)


def _fake_prefill(kv, cache, prompt, max_new=2):
    """Admit + pretend-prefill ``prompt`` (bookkeeping only: the radix
    tree never looks at array contents) and retain its whole pages."""
    cached, pages = cache.match(prompt)
    slot = kv.admit(len(prompt) + max_new, shared=pages)
    cache.insert(prompt, kv.block_table[slot])
    return slot


def test_radix_cacheable_len_caps_one_token_short():
    _, cache = _kv_and_cache(page=4)
    # the page holding the LAST prompt token must prefill fresh
    assert cache.cacheable_len(1) == 0
    assert cache.cacheable_len(4) == 0
    assert cache.cacheable_len(5) == 4
    assert cache.cacheable_len(8) == 4
    assert cache.cacheable_len(9) == 8


def test_radix_insert_match_roundtrip_and_refless_lookup():
    kv, cache = _kv_and_cache(page=4)
    prompt = np.arange(1, 13, dtype=np.int32)          # 12 toks -> 2 pages
    slot = kv.admit(16)
    row = kv.block_table[slot]
    assert cache.insert(prompt, row) == 2
    assert cache.pages_held == 2
    ref_before = kv.ref.copy()
    got_len, got_pages = cache.match(prompt)
    assert got_len == 8 and got_pages == [int(row[0]), int(row[1])]
    # match stamps recency but takes NO references — abandoning the
    # admission it was quoted for must leak nothing
    assert (kv.ref == ref_before).all()
    # divergence inside the second page shortens the match to one page
    fork = prompt.copy()
    fork[6] = 55
    assert cache.match(fork) == (4, [int(row[0])])
    # shorter than page+1 tokens can never match
    assert cache.match(prompt[:4]) == (0, [])
    cache.check()
    kv.release(slot)
    cache.check()


def test_radix_shared_pages_survive_slot_release():
    kv, cache = _kv_and_cache(page=4)
    prompt = np.arange(1, 13, dtype=np.int32)
    slot = _fake_prefill(kv, cache, prompt)
    kv.release(slot)                    # cache still holds the 2 pages
    assert kv.free_pages() == kv.num_pages - 1 - 2
    # a follow-up admission adopts them by reference
    cached, pages = cache.match(prompt)
    assert cached == 8
    s2 = kv.admit(len(prompt) + 2, shared=pages)
    assert all(kv.ref[p] == 2 for p in pages)
    kv.release(s2)
    assert all(kv.ref[p] == 1 for p in pages)
    cache.check()
    assert cache.clear() == 2
    assert kv.free_pages() == kv.num_pages - 1
    cache.check()


def test_radix_edge_split_and_first_writer_wins():
    kv, cache = _kv_and_cache(page=4)
    a = np.array(list(range(1, 9)) + [11, 12, 13, 14], np.int32)   # 12 toks
    b = np.array(list(range(1, 9)) + [21, 22, 23, 24, 25], np.int32)
    sa = _fake_prefill(kv, cache, a)
    sb = _fake_prefill(kv, cache, b)    # shares a's first page, splits
    assert cache.match(a)[0] == 8 and cache.match(b)[0] == 12
    assert cache.match(b)[1][0] == cache.match(a)[1][0]     # shared page
    # re-inserting an already-covered prefix retains nothing new
    sc = kv.admit(len(a) + 2, shared=cache.match(a)[1])
    assert cache.insert(a, kv.block_table[sc]) == 0
    cache.check()
    for s in (sa, sb, sc):
        kv.release(s)
    cache.check()
    cache.clear()
    assert kv.free_pages() == kv.num_pages - 1


def test_radix_lru_evicts_least_recently_matched_leaf():
    kv, cache = _kv_and_cache(num_slots=2, page=4, max_len=32, max_pages=2)
    old = np.arange(1, 7, dtype=np.int32)               # 1 cacheable page
    new = np.arange(30, 36, dtype=np.int32)
    s = _fake_prefill(kv, cache, old)
    kv.release(s)
    assert cache.pages_held == 1
    cache.match(old)                                    # stamp old as MRU
    s = _fake_prefill(kv, cache, new)                   # fits: 2 pages max
    kv.release(s)
    assert cache.pages_held == 2
    cache.match(new)                                    # now OLD is LRU
    third = np.arange(50, 56, dtype=np.int32)
    s = _fake_prefill(kv, cache, third)                 # evicts to fit
    kv.release(s)
    assert cache.match(old)[0] == 0                     # LRU victim gone
    assert cache.match(new)[0] == 4                     # MRU survived
    assert cache.pages_held <= 2
    cache.check()


def test_radix_evict_for_free_spares_pages_backing_live_slots():
    kv, cache = _kv_and_cache(num_slots=2, page=4, max_len=32)
    prompt = np.arange(1, 13, dtype=np.int32)
    slot = _fake_prefill(kv, cache, prompt)             # slot still LIVE
    free_before = kv.free_pages()
    # dropping the node releases the CACHE's reference, but the pages
    # stay allocated to the running slot — the pool grows by nothing
    freed = cache.evict_for_free(2)
    assert freed == 0
    assert cache.pages_held == 0
    assert kv.free_pages() == free_before
    kv.release(slot)                                    # now they free
    assert kv.free_pages() == kv.num_pages - 1
    cache.check()


def test_radix_max_pages_budget_truncates_retention():
    kv, cache = _kv_and_cache(num_slots=2, page=4, max_len=32, max_pages=1)
    prompt = np.arange(1, 14, dtype=np.int32)           # 3 cacheable pages
    slot = kv.admit(len(prompt) + 2)
    assert cache.insert(prompt, kv.block_table[slot]) == 1
    assert cache.pages_held == 1                        # budget, not demand
    assert cache.match(prompt)[0] == 4
    kv.release(slot)
    cache.check()


def test_radix_refcount_conservation_property():
    """Randomized soak: interleaved admit/insert/release/evict/clear
    must keep exact page conservation at every step — every page free,
    or held by exactly its refcount of owners, trash page untouched."""
    rng = np.random.default_rng(7)
    kv, cache = _kv_and_cache(num_slots=3, page=4, max_len=32, max_pages=8)
    # a tiny prefix pool makes radix collisions (splits, re-inserts) common
    pool = [rng.integers(1, 50, size=12).astype(np.int32) for _ in range(3)]
    live: list[int] = []
    for _ in range(200):
        op = rng.integers(0, 10)
        if op <= 5:                                     # admit + insert
            base = pool[int(rng.integers(0, len(pool)))]
            sfx = rng.integers(1, 50,
                               size=int(rng.integers(0, 6))).astype(np.int32)
            prompt = np.concatenate([base[:int(rng.integers(5, 13))], sfx])
            total = len(prompt) + int(rng.integers(1, 4))
            if total > kv.max_len:
                continue
            cached, pages = cache.match(prompt)
            short = (kv.pages_for(total) - len(pages)) - kv.free_pages()
            if short > 0:
                cache.evict_for_free(short)
                cached, pages = cache.match(prompt)
            if not kv.can_admit(total, shared_pages=len(pages)):
                continue
            slot = kv.admit(total, shared=pages)
            cache.insert(prompt, kv.block_table[slot])
            live.append(slot)
        elif op <= 7 and live:                          # finish a request
            kv.release(live.pop(int(rng.integers(0, len(live)))))
        elif op == 8:                                   # LRU pressure
            cache.evict_nodes(int(rng.integers(1, 4)))
        else:                                           # epoch fence
            cache.clear()
        cache.check()                                   # includes kv.check()
    for slot in live:
        kv.release(slot)
    cache.clear()
    cache.check()
    assert kv.free_pages() == kv.num_pages - 1
    assert cache.pages_held == 0 and kv.ref[0] == 0


# -- n-gram drafter (no model) ------------------------------------------------

def test_ngram_drafter_prompt_lookup():
    from distlearn_tpu.serve.speculate import NGramDrafter
    d = NGramDrafter(k=4, n_max=3)
    # ...5,6,7 occurred earlier followed by 8,9 — draft continues it
    assert d.propose([5, 6, 7, 8, 9, 1, 5, 6, 7]) == [8, 9, 1, 5]
    # most RECENT earlier occurrence wins over the older one
    assert d.propose([2, 9, 3, 2, 9, 4, 2, 9]) == [4, 2, 9]
    # budget clips the draft; a never-repeating context drafts nothing
    assert d.propose([5, 6, 7, 8, 9, 1, 5, 6, 7], k=1) == [8]
    assert d.propose([1, 2, 3, 4, 5]) == []
    with pytest.raises(ValueError):
        NGramDrafter(k=0)
    with pytest.raises(ValueError):
        NGramDrafter(n_max=1, n_min=2)


# -- engine: cached prefix / speculation / sampling / chunking ----------------

@pytest.fixture(scope="module")
def eng(lm_params):
    """One shared engine for the whole module: every test drains its
    slots (and clears any prefix cache it built) before returning, so
    the jitted tick/prefill/chunk/verify programs compile once."""
    from distlearn_tpu.serve.engine import DecodeEngine
    return DecodeEngine(lm_params, num_slots=2, max_len=MAX_LEN, page=8)


def _decode(eng, slot, first, steps):
    toks = [first]
    while len(toks) < steps:
        toks.append(eng.tick()[slot])
    eng.finish(slot)
    return toks


def test_cached_prefix_decode_parity(lm_params, eng):
    from distlearn_tpu.serve.prefix_cache import RadixPrefixCache
    cache = RadixPrefixCache(eng.cache)
    rng = np.random.default_rng(11)
    base = rng.integers(1, VOCAB, size=20).astype(np.int32)
    slot, first = eng.admit(base, 4)
    cache.insert(base, eng.cache.block_table[slot])
    _decode(eng, slot, first, 4)
    # 90%-overlap variant: shares both cacheable pages (16 of 20 toks)
    variant = base.copy()
    variant[18:] = (variant[18:] % (VOCAB - 1)) + 1
    cached, pages = cache.match(variant)
    assert cached == 16 and len(pages) == 2
    job = eng.begin(variant, 6, shared=pages)
    assert job.cached == 16
    first = None
    while first is None:
        first = eng.prefill_step(job)
    toks = _decode(eng, job.slot, first, 6)
    # the suffix-only prefill over adopted pages is token-exact
    assert toks == _greedy_ref(lm_params, variant, 6)
    cache.check()
    cache.clear()
    assert eng.cache.free_pages() == eng.cache.num_pages - 1


def test_verify_greedy_equivalence_and_implicit_rollback(lm_params, eng):
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, VOCAB, size=7).astype(np.int32)
    ref = _greedy_ref(lm_params, prompt, 10)
    slot, first = eng.admit(prompt, 10)
    assert first == ref[0]
    toks = [first]
    # round 1: a deliberately wrong draft — all rejected, the dispatch
    # still advances exactly like a plain tick (1 token, the argmax)
    out = eng.verify({slot: [(ref[1] + 1) % VOCAB, (ref[2] + 3) % VOCAB]})
    assert out[slot] == [ref[1]]
    toks += out[slot]
    # round 2 decodes PAST the rejected positions: their stale K/V must
    # be overwritten in place (implicit rollback — no restore pass)
    out = eng.verify({slot: ref[2:5]})          # perfect draft: k+1 toks
    assert out[slot] == ref[2:6]
    toks += out[slot]
    # round 3: first draft right, second wrong -> accept 1 + bonus
    out = eng.verify({slot: [ref[6], (ref[7] + 1) % VOCAB]})
    assert out[slot] == ref[6:8]
    toks += out[slot]
    while len(toks) < 10:                       # tail on the plain tick
        toks.append(eng.tick()[slot])
    eng.finish(slot)
    assert toks == ref
    eng.cache.check()


def test_scheduler_speculates_exactly(lm_params, eng):
    """The drafter-wired scheduler must stream token-identical output
    to plain greedy — speculation is a dispatch-count trade only."""
    from distlearn_tpu.serve.scheduler import Scheduler
    from distlearn_tpu.serve.speculate import NGramDrafter
    sched = Scheduler(eng, drafter=NGramDrafter())
    prompt = np.tile(np.array([3, 5, 7], np.int32), 8)  # self-quoting
    rid = sched.submit(prompt, 16)
    toks, done, verified = [], False, False
    for _ in range(200):
        for ev in sched.step():
            if ev.kind == "token" and ev.rid == rid:
                toks.append(ev.token)
                if ev.accepted is not None:
                    verified = True
            elif ev.kind == "finish" and ev.rid == rid:
                done = True
        if done:
            break
    assert done and toks == _greedy_ref(lm_params, prompt, 16)
    assert verified          # the verify path actually ran
    eng.cache.check()


def test_sampling_deterministic_and_temp0_bitwise(lm_params, eng):
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, VOCAB, size=7).astype(np.int32)
    ref = _greedy_ref(lm_params, prompt, 8)

    def run(**kw):
        slot, first = eng.admit(prompt, 8, **kw)
        return _decode(eng, slot, first, 8)

    # same seed -> bitwise-identical sampled stream, across admissions
    a = run(temperature=0.9, top_k=12, top_p=0.95, seed=123)
    b = run(temperature=0.9, top_k=12, top_p=0.95, seed=123)
    assert a == b
    # a hot-enough draw diverges from greedy for SOME seed
    assert any(run(temperature=3.0, seed=s) != ref for s in (7, 8, 9))
    # temp=0 is bitwise argmax even while batched WITH a sampled stream
    s_hot, f_hot = eng.admit(prompt, 8, temperature=1.5, seed=42)
    s_cold, f_cold = eng.admit(prompt, 8)
    assert f_cold == ref[0]
    cold = [f_cold]
    while len(cold) < 8:
        cold.append(eng.tick()[s_cold])
    assert cold == ref
    eng.finish(s_hot)
    eng.finish(s_cold)
    with pytest.raises(ValueError):
        eng.begin(prompt, 4, temperature=-0.5)
    with pytest.raises(ValueError):
        eng.begin(prompt, 4, top_p=1.5)
    eng.cache.check()


def test_chunked_prefill_token_parity(lm_params, eng):
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, VOCAB, size=20).astype(np.int32)
    ref = _greedy_ref(lm_params, prompt, 6)
    # chunk bound >= prompt takes the original full-bucket program
    slot, first = eng.admit(prompt, 6)
    assert _decode(eng, slot, first, 6) == ref
    # chunked resume (7+7+6 positions) must land on the same stream
    job = eng.begin(prompt, 6)
    first = None
    while first is None:
        first = eng.prefill_step(job, chunk=7)
    assert _decode(eng, job.slot, first, 6) == ref
    eng.cache.check()


# -- scheduler: chunked prefill protects TPOT (injected clock) ----------------

class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_chunked_prefill_never_stalls_running_streams(lm_params, eng):
    from distlearn_tpu.serve.scheduler import Scheduler
    clk = _Clock()
    sched = Scheduler(eng, clock=clk, prefill_chunk=8)
    rng = np.random.default_rng(4)
    short = rng.integers(1, VOCAB, size=5).astype(np.int32)
    long = rng.integers(1, VOCAB, size=40).astype(np.int32)
    rid_s = sched.submit(short, 12)
    clk.now += 1.0
    assert any(ev.kind == "token" and ev.first
               for ev in sched.step())          # short stream is running
    rid_l = sched.submit(long, 4)
    stamps, first_long_at = [], None
    for _ in range(20):
        clk.now += 1.0
        for ev in sched.step():
            if ev.kind == "token" and ev.rid == rid_s:
                stamps.append(clk.now)
            elif (ev.kind == "token" and ev.rid == rid_l
                    and first_long_at is None):
                first_long_at = clk.now
        if first_long_at is not None:
            break
    assert first_long_at is not None
    # the 40-token prompt needed >= ceil(40/8) bounded-chunk rounds...
    assert first_long_at - 1.0 >= 40 / 8
    # ...and the running stream got a token EVERY round meanwhile: its
    # TPOT never exceeds one scheduling round while the prefill chunks
    gaps = np.diff([1.0] + stamps)
    assert len(stamps) >= 5 and (gaps == 1.0).all()
    sched.cancel(rid_s)
    sched.cancel(rid_l)
    eng.cache.check()


def test_idle_burst_prefill_completes_in_one_round(lm_params, eng):
    from distlearn_tpu.serve.scheduler import Scheduler
    sched = Scheduler(eng, clock=_Clock(), prefill_chunk=8)
    rng = np.random.default_rng(6)
    long = rng.integers(1, VOCAB, size=30).astype(np.int32)
    rid = sched.submit(long, 2)
    # nobody is decoding, so there is nobody to stall: the whole prompt
    # prefills (and the first token lands) in the admission round
    evs = sched.step()
    assert any(ev.kind == "token" and ev.rid == rid and ev.first
               for ev in evs)
    sched.cancel(rid)
    eng.cache.check()


# -- DL310: new frame fields stay bound ---------------------------------------

def test_dl310_raw_speed_fields_are_bound():
    from distlearn_tpu.lint.conformance import (SERVE_FRAME_BINDINGS,
                                                lint_serve_frames)
    assert {"temperature", "top_k", "top_p", "seed",
            "speculate"} <= set(SERVE_FRAME_BINDINGS["G"])
    assert {"accepted", "cached_tokens"} <= set(SERVE_FRAME_BINDINGS["R"])
    assert "cached_pages" in SERVE_FRAME_BINDINGS["J"]
    assert lint_serve_frames() == []


def test_dl310_renamed_accepted_field_fires_both_ways():
    """Renaming ``accepted`` across every producer/consumer leaves the
    committed binding stale AND ships an unbound field — both fire."""
    import inspect
    from distlearn_tpu.lint.conformance import lint_serve_frames
    from distlearn_tpu.serve import client, router, server

    def ren(mod):
        return inspect.getsource(mod).replace('"accepted"', '"accepted_n"')

    fs = lint_serve_frames(server_source=ren(server),
                           router_source=ren(router),
                           client_source=ren(client))
    wheres = sorted(f.where for f in fs)
    assert all(f.rule == "DL310" for f in fs)
    assert wheres == ["serve_frames.R.accepted",
                      "serve_frames.R.accepted_n"]


def test_dl310_ghost_speculation_knob_fires():
    """A new 'G' sampling/speculation knob shipped without a binding is
    undocumented wire surface."""
    import inspect
    from distlearn_tpu.lint.conformance import lint_serve_frames
    from distlearn_tpu.serve import client
    src = inspect.getsource(client) + (
        '\n\ndef _ghost(msg):\n    msg["draft_k"] = 2\n')
    fs = lint_serve_frames(client_source=src)
    assert [f.rule for f in fs] == ["DL310"]
    assert fs[0].where == "serve_frames.G.draft_k"


# -- diststat raw-speed table -------------------------------------------------

def test_diststat_raw_speed_table():
    import diststat
    tab = diststat.raw_speed_table(
        {"serve_prefix_cache_hits_total": 8,
         "serve_prefix_cache_misses_total": 2,
         "serve_prefix_cache_evictions_total": 1,
         "serve_engine_verifies_total": 5,
         "serve_engine_prefill_chunks_total": 3},
        {"serve_prefix_cache_pages": 4},
        {"serve_spec_accepted_tokens": {"sum": 18.0, "count": 10,
                                        "buckets": {}, "inf": 0}},
        {"serve.verify": [0.01] * 5, "serve.prefill_chunk": [0.002] * 3})
    assert tab["prefix_cache"]["hits"] == 8
    assert abs(tab["prefix_cache"]["hit_rate"] - 0.8) < 1e-9
    assert tab["prefix_cache"]["pages_retained"] == 4
    assert abs(tab["speculation"]["accepted_tokens_per_tick"] - 1.8) < 1e-9
    assert tab["speculation"]["verify_dispatches"] == 5
    assert tab["prefill_chunks"] == 3
    assert set(tab["latency"]) == {"verify", "prefill_chunk"}
    # a run that never used the features renders an empty table
    assert diststat.raw_speed_table({}, {}, {}, {}) == {}
