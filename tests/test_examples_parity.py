"""Parity-harness smoke: the example's --parity mode must emit a valid JSON
accuracy line and demonstrably learn on the synthetic set (docs/PARITY.md)."""

import json
import os
import subprocess
import sys

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def test_mnist_parity_line():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # example sets its own device count
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "mnist.py"),
         "--numNodes", "2", "--numEpochs", "3", "--batchSize", "64",
         "--numExamples", "512", "--learningRate", "0.05",
         "--reportEvery", "1000", "--parity"],
        capture_output=True, text=True, timeout=900, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["example"] == "mnist" and rec["data"] == "synthetic"
    assert rec["nodes"] == 2 and rec["epochs"] == 3
    # synthetic set is separable: 3 epochs must beat chance by a wide margin
    # (docs/PARITY.md synthetic row; probe run reached ~0.9 by epoch 3)
    assert rec["train_acc"] > 0.5, rec


def test_bench_section_retry_semantics():
    """run_bench_section retries ONCE on the tunnel's transient signature
    and fails fast on deterministic errors."""
    import sys
    sys.path.insert(0, os.path.dirname(_EXAMPLES))  # repo root (bench.py)
    import bench

    calls = {"n": 0}

    def transient_then_ok():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("read body: response body closed before "
                               "all bytes were read")
        return {"ok": True}

    assert bench.run_bench_section("t", transient_then_ok) == {"ok": True}
    assert calls["n"] == 2

    calls["n"] = 0

    def deterministic():
        calls["n"] += 1
        raise ValueError("RESOURCE_EXHAUSTED: out of memory")

    assert bench.run_bench_section("d", deterministic) is None
    assert calls["n"] == 1          # no pointless second 30-iter run

    calls["n"] = 0

    def always_transient():
        calls["n"] += 1
        raise RuntimeError("response body closed")

    assert bench.run_bench_section("a", always_transient) is None
    assert calls["n"] == 2


def test_bench_outage_carries_last_good_forward(tmp_path, monkeypatch):
    """A dead tunnel must NOT report value 0.0 (reads as a catastrophic
    regression downstream) — it carries the last good measurement forward
    marked stale, from BENCH_LAST_GOOD.json or the newest real BENCH_r*
    driver artifact; 0.0 only when no good record exists at all."""
    import sys
    sys.path.insert(0, os.path.dirname(_EXAMPLES))  # repo root (bench.py)
    import bench

    # no record anywhere -> honest zero
    monkeypatch.setattr(
        bench, "_last_good_headline", lambda root=None: None)
    rec = bench._outage_headline()
    assert rec["value"] == 0.0 and "NO MEASUREMENT" in rec["unit"]
    monkeypatch.undo()

    # BENCH_LAST_GOOD.json wins
    good = {"metric": "cifar10_convnet_allreduce_sgd_steps_per_sec",
            "value": 347.29, "unit": "steps/s (global batch 256, 1 tpu "
            "chip(s), median of 5x100-step windows)",
            "vs_baseline": 45456.6, "recorded_at": "2026-07-30T09:00:00Z"}
    (tmp_path / bench._LAST_GOOD_BASENAME).write_text(json.dumps(good))
    last = bench._last_good_headline(root=str(tmp_path))
    assert last["value"] == 347.29

    monkeypatch.setattr(bench, "_last_good_headline",
                        lambda root=None: dict(good))
    rec = bench._outage_headline()
    assert rec["stale"] is True
    assert rec["value"] == 347.29 and rec["vs_baseline"] == 45456.6
    assert "STALE" in rec["unit"] and "2026-07-30T09:00:00Z" in rec["unit"]
    assert "outage" in rec["unit"]

    # fallback: newest BENCH_r*.json with a real parsed value
    monkeypatch.undo()
    (tmp_path / bench._LAST_GOOD_BASENAME).unlink()
    r03 = dict(good, value=300.0)
    del r03["recorded_at"]          # driver artifacts carry no timestamp
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"n": 3, "parsed": r03}))
    # r04: an outage round whose artifact is itself a carried-forward
    # stale record — must NOT be laundered into fresh r04 provenance;
    # r05: a degraded-chip round — real run, not a representative number
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"n": 4, "parsed": dict(good, stale=True)}))
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "parsed": dict(good, value=37.0, degraded=True)}))
    last = bench._last_good_headline(root=str(tmp_path))
    assert last["value"] == 300.0
    assert "round 3" in last["recorded_at"]
