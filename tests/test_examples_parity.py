"""Parity-harness smoke: the example's --parity mode must emit a valid JSON
accuracy line and demonstrably learn on the synthetic set (docs/PARITY.md)."""

import json
import os
import subprocess
import sys

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def test_mnist_parity_line():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # example sets its own device count
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "mnist.py"),
         "--numNodes", "2", "--numEpochs", "3", "--batchSize", "64",
         "--numExamples", "512", "--learningRate", "0.05",
         "--reportEvery", "1000", "--parity"],
        capture_output=True, text=True, timeout=900, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["example"] == "mnist" and rec["data"] == "synthetic"
    assert rec["nodes"] == 2 and rec["epochs"] == 3
    # synthetic set is separable: 3 epochs must beat chance by a wide margin
    # (docs/PARITY.md synthetic row; probe run reached ~0.9 by epoch 3)
    assert rec["train_acc"] > 0.5, rec


def test_bench_section_retry_semantics():
    """run_bench_section retries ONCE on the tunnel's transient signature
    and fails fast on deterministic errors."""
    import sys
    sys.path.insert(0, os.path.dirname(_EXAMPLES))  # repo root (bench.py)
    import bench

    calls = {"n": 0}

    def transient_then_ok():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("read body: response body closed before "
                               "all bytes were read")
        return {"ok": True}

    assert bench.run_bench_section("t", transient_then_ok) == {"ok": True}
    assert calls["n"] == 2

    calls["n"] = 0

    def deterministic():
        calls["n"] += 1
        raise ValueError("RESOURCE_EXHAUSTED: out of memory")

    assert bench.run_bench_section("d", deterministic) is None
    assert calls["n"] == 1          # no pointless second 30-iter run

    calls["n"] = 0

    def always_transient():
        calls["n"] += 1
        raise RuntimeError("response body closed")

    assert bench.run_bench_section("a", always_transient) is None
    assert calls["n"] == 2
