"""utils/flags.py env_truthy: the ONE truthiness parser for the
DISTLEARN_TPU_* feature switches, and its two call sites."""

import pytest

from distlearn_tpu.utils.flags import env_truthy

VAR = "DISTLEARN_TPU_TEST_FLAG"


def test_unset_is_none(monkeypatch):
    monkeypatch.delenv(VAR, raising=False)
    assert env_truthy(VAR) is None


@pytest.mark.parametrize("value", ["0", "false", "False", "FALSE", "off",
                                   "OFF", ""])
def test_falsy_spellings(monkeypatch, value):
    monkeypatch.setenv(VAR, value)
    assert env_truthy(VAR) is False


@pytest.mark.parametrize("value", ["1", "true", "True", "on", "yes", "2"])
def test_truthy_spellings(monkeypatch, value):
    monkeypatch.setenv(VAR, value)
    assert env_truthy(VAR) is True


def test_fused_enabled_uses_shared_parser(monkeypatch):
    from distlearn_tpu.ops.fused_update import fused_enabled
    monkeypatch.setenv("DISTLEARN_TPU_FUSED", "OFF")
    assert fused_enabled() is False
    monkeypatch.setenv("DISTLEARN_TPU_FUSED", "1")
    assert fused_enabled() is True
    assert fused_enabled(override=False) is False   # explicit arg wins


def test_flash_enabled_uses_shared_parser(monkeypatch):
    from distlearn_tpu.parallel.sequence import _flash_enabled
    monkeypatch.delenv("DISTLEARN_TPU_FLASH", raising=False)
    assert _flash_enabled(None) is False            # unset defaults off
    monkeypatch.setenv("DISTLEARN_TPU_FLASH", "on")
    assert _flash_enabled(None) is True
    monkeypatch.setenv("DISTLEARN_TPU_FLASH", "off")
    assert _flash_enabled(None) is False
