"""Ring attention correctness: sharded-by-sequence blockwise result must
match single-device full attention, causal and non-causal, including a
gradient check (the backward pass also rides the ring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.parallel.sequence import local_attention, ring_attention

B, L, H, D = 2, 32, 4, 16


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    return mk(), mk(), mk()


def _ring(mesh, causal):
    """Jitted sharded ring-attention wrapper (shared by the ring tests)."""
    return jax.jit(jax.shard_map(
        lambda qq, kk, vv: ring_attention(qq, kk, vv, "seq", causal=causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_local(causal):
    q, k, v = _qkv()
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("seq",))

    out = _ring(mesh, causal)(q, k, v)
    ref = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match():
    q, k, v = _qkv(1)
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("seq",))

    def ring_loss(qq, kk, vv):
        mapped = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, "seq", causal=True),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False)
        return jnp.sum(mapped(qq, kk, vv) ** 2)

    def local_loss(qq, kk, vv):
        return jnp.sum(local_attention(qq, kk, vv, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_local = jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_local):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_single_device_degenerate():
    """axis size 1: ring attention == local attention exactly."""
    q, k, v = _qkv(2)
    mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
    np.testing.assert_allclose(
        np.asarray(_ring(mesh, causal=True)(q, k, v)),
        np.asarray(local_attention(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 4])
def test_alltoall_matches_local(causal, n_dev):
    """Ulysses head-scatter variant == full attention (H=4 divisible)."""
    from distlearn_tpu.parallel.sequence import alltoall_attention
    q, k, v = _qkv(3)
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    a2a = jax.jit(jax.shard_map(
        lambda qq, kk, vv: alltoall_attention(qq, kk, vv, "seq",
                                              causal=causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))
    np.testing.assert_allclose(
        np.asarray(a2a(q, k, v)),
        np.asarray(local_attention(q, k, v, causal=causal)),
        rtol=2e-4, atol=2e-5)


def test_alltoall_gradients_match():
    from distlearn_tpu.parallel.sequence import alltoall_attention
    q, k, v = _qkv(4)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))

    def a2a_loss(qq, kk, vv):
        mapped = jax.shard_map(
            lambda a, b, c: alltoall_attention(a, b, c, "seq", causal=True),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False)
        return jnp.sum(mapped(qq, kk, vv) ** 2)

    def local_loss(qq, kk, vv):
        return jnp.sum(local_attention(qq, kk, vv, causal=True) ** 2)

    g_a = jax.grad(a2a_loss, argnums=(0, 1, 2))(q, k, v)
    g_l = jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_a, g_l):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_lm_alltoall_impl_matches_ring():
    """transformer_lm(seq_impl='alltoall') must produce the same logits as
    the ring implementation on the same shards."""
    from jax import random
    from distlearn_tpu.models.transformer import transformer_lm
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    L = 32
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, L)),
                       jnp.int32)
    outs = {}
    for impl in ("ring", "alltoall"):
        lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=L,
                            seq_impl=impl)
        params, _ = lm.init(random.PRNGKey(0))
        f = jax.jit(jax.shard_map(
            lambda p, t: lm.apply(p, {}, t, seq_axis="seq")[0],
            mesh=mesh, in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"), check_vma=False))
        outs[impl] = np.asarray(f(params, toks))
    np.testing.assert_allclose(outs["ring"], outs["alltoall"],
                               rtol=2e-4, atol=2e-5)


def test_alltoall_rejects_indivisible_heads():
    from distlearn_tpu.parallel.sequence import alltoall_attention
    q, k, v = _qkv(5)          # H=4 heads
    mesh = Mesh(np.array(jax.devices()[:3]), ("seq",))
    with pytest.raises(ValueError, match="divisible"):
        jax.shard_map(
            lambda qq, kk, vv: alltoall_attention(qq, kk, vv, "seq"),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False)(
            q[:, :30], k[:, :30], v[:, :30])


def _qkv_long(seed, L=256):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Pallas flash attention is a TPU kernel")
def test_flash_local_attention_matches_reference():
    q, k, v = _qkv_long(6)                 # L=256: kernel-block compatible
    out_f = local_attention(q, k, v, causal=True, flash=True)
    out_r = local_attention(q, k, v, causal=True, flash=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                               rtol=2e-2, atol=2e-2)


def test_flash_explicit_request_rejected_when_unsupported(monkeypatch):
    """flash=True must not be silently ignored: on a non-TPU backend (or
    incompatible L) it raises instead of materializing the O(L^2) buffer
    the caller asked to avoid."""
    monkeypatch.delenv("DISTLEARN_TPU_FLASH", raising=False)
    q, k, v = _qkv(7)                      # L=32 also violates blocking
    with pytest.raises(ValueError, match="flash attention needs"):
        local_attention(q, k, v, causal=True, flash=True)


def test_flash_env_fallback_on_unsupported(monkeypatch):
    """Env-enabled flash falls back to the portable path where the kernel
    can't run (CPU mesh / L % 128 != 0) — same numbers as flash off."""
    monkeypatch.setenv("DISTLEARN_TPU_FLASH", "1")
    q, k, v = _qkv(8)
    out = local_attention(q, k, v, causal=True)        # flash=None -> env
    ref = local_attention(q, k, v, causal=True, flash=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_bf16_attention_matches_f32_reference():
    """bf16 operands feed the matmuls natively with f32 accumulation
    (softmax stats stay f32): both the local and the ring path must stay
    within bf16 rounding of the f32 oracle, and ring must match local
    under the same dtype."""
    q, k, v = _qkv(7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = np.asarray(local_attention(q, k, v, causal=True))

    out_local = local_attention(qb, kb, vb, causal=True)
    assert out_local.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_local, np.float32), ref,
                               rtol=0.05, atol=0.02)

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    out_ring = _ring(mesh, causal=True)(qb, kb, vb)
    assert out_ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_ring, np.float32), ref,
                               rtol=0.05, atol=0.02)
    # ring vs local at the SAME dtype: much tighter (same rounding regime)
    np.testing.assert_allclose(np.asarray(out_ring, np.float32),
                               np.asarray(out_local, np.float32),
                               rtol=0.02, atol=0.01)


# --- chunked causal attention (parallel/sequence.py chunked_causal_attention)


def test_chunked_causal_matches_local():
    """The chunk-skipped score computation is the same math as the full
    masked path — forward and gradients (the saved-softmax backward)."""
    from distlearn_tpu.parallel.sequence import chunked_causal_attention
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32))
    q, k, v = mk(), mk(), mk()

    ref = local_attention(q, k, v, causal=True, impl="xla")
    got = chunked_causal_attention(q, k, v, chunk=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)

    g_ref = jax.grad(lambda a: jnp.sum(
        local_attention(a, k, v, causal=True, impl="xla") ** 2))(q)
    g_got = jax.grad(lambda a: jnp.sum(
        chunked_causal_attention(a, k, v, chunk=16) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-5)


def test_chunked_causal_ragged_falls_back():
    """L not divisible by the chunk (or too short) silently uses the xla
    path — same numbers either way."""
    from distlearn_tpu.parallel.sequence import chunked_causal_attention
    rng = np.random.RandomState(4)
    mk = lambda: jnp.asarray(rng.randn(1, 24, 2, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    got = chunked_causal_attention(q, k, v, chunk=16)
    ref = local_attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_local_attention_impl_validation():
    q = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(ValueError, match="impl"):
        local_attention(q, q, q, impl="bogus")


def test_local_attention_chunked_impl_dispatch():
    """impl='chunked' on a causal call routes through the chunked path and
    still matches the oracle (CPU: flash unsupported, chunked is portable)."""
    rng = np.random.RandomState(5)
    mk = lambda: jnp.asarray(rng.randn(1, 2048, 2, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    got = local_attention(q, k, v, causal=True, impl="chunked")
    ref = local_attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


# --- zigzag causal ring attention (balanced layout, masked-block skip) ------


def _zigzag(mesh, n, unroll=False):
    from distlearn_tpu.parallel.sequence import ring_attention
    return jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=True,
                                       layout="zigzag", unroll=unroll),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))


def test_zigzag_causal_matches_local():
    """Zigzag-laid-out causal ring == the full-attention oracle, after
    undoing the layout permutation (both 4 and 8 ranks: even/odd
    src-vs-my branches both exercised)."""
    from distlearn_tpu.parallel.sequence import zigzag_indices
    q, k, v = _qkv(7)
    for n in (4, 8):
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        idx = zigzag_indices(n, L)
        inv = np.argsort(idx)
        out = _zigzag(mesh, n)(q[:, idx], k[:, idx], v[:, idx])[:, inv]
        ref = local_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_zigzag_causal_gradients_match():
    from distlearn_tpu.parallel.sequence import zigzag_indices
    q, k, v = _qkv(8)
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
    idx = zigzag_indices(n, L)
    inv = np.argsort(idx)
    zz = _zigzag(mesh, n)

    def loss_z(a, b, c):
        return jnp.sum(zz(a[:, idx], b[:, idx], c[:, idx])[:, inv] ** 2)

    def loss_l(a, b, c):
        return jnp.sum(local_attention(a, b, c, causal=True) ** 2)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gl = jax.grad(loss_l, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_zigzag_halves_causal_flops():
    """The point of the layout: fully-masked blocks are never computed.
    Unrolled (so XLA's cost model counts every hop), the zigzag program's
    flops must be ~(2n+1)/(4n) of the contiguous causal ring's — about
    0.56 at n=4 — not merely 'a bit less'."""
    from distlearn_tpu.parallel.sequence import ring_attention
    # longer sequence than the shared fixture so the s^2 attention terms
    # dominate the per-hop softmax-stat overhead (at s=4 the overhead
    # hides the cut; the claim is about the quadratic terms)
    rng = np.random.RandomState(9)
    mk = lambda: jnp.asarray(rng.randn(1, 128, 2, 16).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))

    def build(layout):
        return jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, "seq", causal=True,
                                           layout=layout, unroll=True),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False))

    def flops(layout):
        ca = build(layout).lower(q, k, v).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):  # pre-0.5 jax: one dict per device
            ca = ca[0]
        return ca["flops"]

    fz = flops("zigzag")
    fc = flops("contig")
    assert fz / fc < 0.65, f"zigzag/contig flops = {fz/fc:.3f}"


def test_zigzag_indices_roundtrip_and_validation():
    from distlearn_tpu.parallel.sequence import zigzag_indices
    idx = zigzag_indices(4, 32)
    assert sorted(idx.tolist()) == list(range(32))
    # rank 0 holds stripes 0 and 7 (s=4): [0..3, 28..31]
    assert idx[:8].tolist() == [0, 1, 2, 3, 28, 29, 30, 31]
    with pytest.raises(ValueError, match="stripes"):
        zigzag_indices(4, 30)


def test_ring_layout_validation():
    from distlearn_tpu.parallel.sequence import ring_attention
    q, k, v = _qkv(10)
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    with pytest.raises(ValueError, match="layout"):
        jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, "seq", layout="spiral"),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False)(q, k, v)


def test_zigzag_noncausal_is_plain_ring():
    """Non-causal attention is permutation-equivariant: zigzag-ordered
    data through the standard ring already gives the right answer, so
    layout='zigzag' without causal must not change the math."""
    from distlearn_tpu.parallel.sequence import ring_attention, zigzag_indices
    q, k, v = _qkv(11)
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
    idx = zigzag_indices(n, L)
    inv = np.argsort(idx)
    out = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=False,
                                       layout="zigzag"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))(
            q[:, idx], k[:, idx], v[:, idx])[:, inv]
    ref = local_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_bf16_against_f32_oracle():
    """bf16 zigzag ring vs the f32 full-attention oracle: the f32
    softmax-stat accumulation must keep bf16 shards within bf16-level
    error of the exact result (mirrors the contiguous-ring bf16 test)."""
    from distlearn_tpu.parallel.sequence import ring_attention, zigzag_indices
    rng = np.random.RandomState(12)
    mk32 = lambda: jnp.asarray(rng.randn(1, 64, 2, 16).astype(np.float32))
    q, k, v = mk32(), mk32(), mk32()
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
    idx = zigzag_indices(n, 64)
    inv = np.argsort(idx)
    out = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=True,
                                       layout="zigzag"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))(
            q[:, idx].astype(jnp.bfloat16), k[:, idx].astype(jnp.bfloat16),
            v[:, idx].astype(jnp.bfloat16))[:, inv]
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)
