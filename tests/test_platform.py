"""utils/platform.py: the flag-replacement helper every entry point leans
on (a stale pre-set count silently overriding the request was a real bug
class — bench probes, examples, dryrun)."""

import os

from distlearn_tpu.utils.platform import set_host_device_count


def test_set_host_device_count_replaces_stale_flag(monkeypatch):
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_foo=1 --xla_force_host_platform_device_count=2 --xla_bar=2")
    set_host_device_count(8)
    flags = os.environ["XLA_FLAGS"].split()
    assert "--xla_force_host_platform_device_count=8" in flags
    assert "--xla_force_host_platform_device_count=2" not in flags
    assert "--xla_foo=1" in flags and "--xla_bar=2" in flags   # preserved


def test_set_host_device_count_from_empty(monkeypatch):
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    set_host_device_count(4)
    assert os.environ["XLA_FLAGS"] == \
        "--xla_force_host_platform_device_count=4"
