"""Packed/quantized wire codec tests (comm/wire.py + the 'P' frame in
comm/transport.py): round-trip properties across dtypes and layouts,
corrupt-manifest hardening (ProtocolError with the stream still
frame-aligned), legacy interop, and whole-frame throttle pacing.
"""

import json
import socket
import struct
import time

import numpy as np
import pytest

from distlearn_tpu.comm import wire
from distlearn_tpu.comm.transport import (_HDR, _THDR, Conn, ProtocolError,
                                          native)

pytestmark = pytest.mark.comm_perf


def _pair():
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    a = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    a.connect(lst.getsockname())
    b, _ = lst.accept()
    lst.close()
    return Conn(a), Conn(b)


def _leaf_zoo():
    """Every layout class the codec must survive: float/int/unsigned,
    0-d, empty, and non-C-contiguous leaves."""
    rng = np.random.RandomState(7)
    return [
        rng.randn(5, 3).astype(np.float32),
        rng.randn(17).astype(np.float64),
        rng.randn(2, 2).astype(np.float16),
        np.arange(12, dtype=np.int64).reshape(3, 4),
        np.arange(6, dtype=np.uint8),
        np.float32(3.25).reshape(()),          # 0-d
        np.zeros((0, 5), np.float32),          # empty
        np.asfortranarray(rng.randn(4, 6).astype(np.float32)),  # F-order
        rng.randn(8, 8).astype(np.float32)[::2, 1::3],          # strided view
    ]


# ---------------------------------------------------------------------------
# Codec level (no sockets).

@pytest.mark.parametrize("codec", wire.CODECS)
def test_encode_decoded_roundtrip_properties(codec):
    leaves = _leaf_zoo()
    payload = wire.encode_leaves(leaves, codec)
    assert payload.codec == codec
    assert payload.logical_nbytes == sum(np.asarray(a).nbytes
                                         for a in leaves)
    decs = payload.decoded()
    for a, entry, dec in zip(leaves, payload.manifest["leaves"], decs):
        a = np.asarray(a)
        assert dec.shape == a.shape and dec.dtype == a.dtype
        if entry["enc"] == "raw":
            np.testing.assert_array_equal(dec, a)
        elif entry["enc"] == "fp16":
            np.testing.assert_allclose(dec, a.astype(np.float16), rtol=0)
        else:                                   # int8: error <= scale/2
            tol = entry["scale"] / 2 + 1e-12
            assert np.max(np.abs(dec - a), initial=0.0) <= tol
    # non-float leaves always ride raw, even inside quantized frames
    int_entries = [e for a, e in zip(leaves, payload.manifest["leaves"])
                   if np.asarray(a).dtype.kind not in "fc"]
    assert all(e["enc"] == "raw" for e in int_entries)


def test_quantized_frames_shrink_wire_bytes():
    leaves = [np.random.RandomState(0).randn(64, 64).astype(np.float32)]
    raw = wire.encode_leaves(leaves, "raw")
    fp16 = wire.encode_leaves(leaves, "fp16")
    int8 = wire.encode_leaves(leaves, "int8")
    assert fp16.wire_nbytes == raw.wire_nbytes // 2
    assert int8.wire_nbytes == raw.wire_nbytes // 4


def test_int8_zero_leaf_and_nonfinite():
    payload = wire.encode_leaves([np.zeros((3, 3), np.float32)], "int8")
    np.testing.assert_array_equal(payload.decoded()[0], 0.0)
    with pytest.raises(ValueError, match="non-finite"):
        wire.encode_leaves([np.array([1.0, np.inf], np.float32)], "int8")


def test_unknown_codec_rejected():
    with pytest.raises(ValueError, match="unknown wire codec"):
        wire.encode_leaves([np.zeros(2, np.float32)], "zstd")


def _manifest_bytes(doc):
    return json.dumps(doc).encode()


def test_parse_manifest_structural_rejections():
    ok = wire.encode_leaves([np.arange(4, dtype=np.float32)], "raw")
    raw = _manifest_bytes(ok.manifest)
    assert wire.parse_manifest(raw, 16)[0] == "raw"

    cases = [
        (b"not json", 16, "undecodable"),
        (_manifest_bytes({"v": 1}), 16, "not .codec, leaves. shaped"),
        (_manifest_bytes({"codec": "zstd", "leaves": []}), 0,
         "unknown wire codec"),
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [-1], "enc": "raw",
             "offset": 0, "nbytes": 16}]}), 16, "negative dimension"),
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "gzip",
             "offset": 0, "nbytes": 16}]}), 16, "unknown encoding"),
        (_manifest_bytes({"codec": "int8", "leaves": [
            {"dtype": "int64", "shape": [4], "enc": "int8",
             "offset": 0, "nbytes": 4, "scale": 1.0}]}), 4, "non-float"),
        (_manifest_bytes({"codec": "int8", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "int8",
             "offset": 0, "nbytes": 4}]}), 4, "missing scale"),
        (_manifest_bytes({"codec": "int8", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "int8",
             "offset": 0, "nbytes": 4, "scale": float("nan")}]}), 4,
         "non-finite int8 scale"),
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "raw",
             "offset": 0, "nbytes": 8}]}), 8, "!= 16 expected"),
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "raw",
             "offset": 4, "nbytes": 16}]}), 20, "tile"),
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [4], "enc": "raw",
             "offset": 0, "nbytes": 16}]}), 99, "frame carries"),
        # hostile huge shape: python-int math, no C-long overflow
        (_manifest_bytes({"codec": "raw", "leaves": [
            {"dtype": "float32", "shape": [2 ** 62, 2 ** 62], "enc": "raw",
             "offset": 0, "nbytes": 16}]}), 16, "expected"),
    ]
    for raw, data_nbytes, match in cases:
        with pytest.raises(ValueError, match=match):
            wire.parse_manifest(raw, data_nbytes)
    with pytest.raises(ValueError, match="receiver expects"):
        wire.parse_manifest(_manifest_bytes(ok.manifest), 16, expect_n=3)


# ---------------------------------------------------------------------------
# Transport level: the 'P' frame over a real socket.

@pytest.mark.parametrize("codec", wire.CODECS)
def test_packed_socket_roundtrip(codec):
    tx, rx = _pair()
    leaves = _leaf_zoo()
    tx.send_tensors(leaves, codec=codec)
    got = rx.recv_tensors(n=len(leaves))
    for a, g in zip(leaves, got):
        a = np.asarray(a)
        assert g.shape == a.shape and g.dtype == a.dtype
        if codec == "raw":
            np.testing.assert_array_equal(g, a)
    tx.close(); rx.close()


def test_packed_recv_into_preallocated_buffers():
    tx, rx = _pair()
    leaves = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.arange(4, dtype=np.int64)]
    out = [np.zeros((2, 3), np.float32), np.zeros(4, np.int64)]
    tx.send_tensors(leaves)
    got = rx.recv_tensors(out=out)
    assert got[0] is out[0] and got[1] is out[1]   # zero realloc
    np.testing.assert_array_equal(out[0], leaves[0])
    np.testing.assert_array_equal(out[1], leaves[1])
    tx.close(); rx.close()


def test_recv_tensors_autodetects_legacy_per_leaf_stream():
    """An old-wire peer sends per-leaf 'T' frames; recv_tensors must parse
    them without any negotiation branch on the receive side."""
    tx, rx = _pair()
    leaves = [np.arange(3, dtype=np.float32), np.arange(5, dtype=np.int32)]
    tx.send_tensors(leaves, packed=False)          # legacy framing
    got = rx.recv_tensors(n=2)
    for a, g in zip(leaves, got):
        np.testing.assert_array_equal(g, a)
    tx.close(); rx.close()


def test_legacy_framing_rejects_quantized_codecs():
    tx, rx = _pair()
    with pytest.raises(ValueError, match="requires the packed frame"):
        tx.send_tensors([np.zeros(2, np.float32)], codec="int8",
                        packed=False)
    tx.close(); rx.close()


def test_empty_leaf_list_sends_no_frame():
    tx, rx = _pair()
    tx.send_tensors([])
    assert rx.recv_tensors(n=0) == []
    tx.send_msg("after")                  # stream still aligned
    assert rx.recv_msg() == "after"
    tx.close(); rx.close()


def _send_packed_frame(conn, manifest_doc, data: bytes):
    m = json.dumps(manifest_doc).encode()
    payload = _THDR.pack(len(m)) + m + data
    conn._send_frame(ord("P"), payload)


def test_corrupt_manifest_protocol_error_and_stream_aligned():
    """A hostile/corrupt manifest must raise ProtocolError AND consume the
    announced payload, so the next frame parses normally."""
    tx, rx = _pair()
    _send_packed_frame(tx, {"codec": "raw", "leaves": [
        {"dtype": "float32", "shape": [2], "enc": "raw",
         "offset": 0, "nbytes": 4}]}, b"\0" * 4)    # nbytes != 8
    tx.send_msg("still-aligned")
    with pytest.raises(ProtocolError):
        rx.recv_tensors(n=1)
    assert rx.recv_msg() == "still-aligned"
    tx.close(); rx.close()


def test_packed_leaf_count_mismatch_drains():
    tx, rx = _pair()
    tx.send_tensors([np.zeros(2, np.float32), np.ones(3, np.float32)])
    tx.send_msg("next")
    with pytest.raises(ProtocolError, match="expects"):
        rx.recv_tensors(n=5)
    assert rx.recv_msg() == "next"
    tx.close(); rx.close()


def test_packed_out_buffer_mismatch_drains():
    tx, rx = _pair()
    tx.send_tensors([np.zeros((2, 2), np.float32)])
    tx.send_msg("next")
    with pytest.raises(ProtocolError, match="mismatch"):
        rx.recv_tensors(out=[np.zeros((3, 3), np.float32)])
    assert rx.recv_msg() == "next"
    tx.close(); rx.close()


def test_recv_tensors_rejects_unexpected_kind():
    tx, rx = _pair()
    tx.send_msg("hello")
    with pytest.raises(ProtocolError, match="kind"):
        rx.recv_tensors(n=1)
    tx.close(); rx.close()


def test_recv_tensors_requires_out_or_n():
    _, rx = _pair()
    with pytest.raises(ValueError):
        rx.recv_tensors()
    rx.close()


def test_pure_python_sendv_path(monkeypatch):
    """Force the no-native fallback: single-sendmsg framing (the coalesced
    header+payload satellite) must round-trip msgs, tensors, and packed
    frames."""
    monkeypatch.setattr(native, "available", lambda: False)
    tx, rx = _pair()
    tx.send_msg({"q": "ping"})
    assert rx.recv_msg() == {"q": "ping"}
    arr = np.arange(10, dtype=np.float64).reshape(2, 5)
    tx.send_tensor(arr)
    np.testing.assert_array_equal(rx.recv_tensor(), arr)
    tx.send_tensor(np.array(2.5, np.float32))      # 0-d
    assert rx.recv_tensor().shape == ()
    leaves = _leaf_zoo()
    tx.send_tensors(leaves, codec="raw")
    got = rx.recv_tensors(n=len(leaves))
    np.testing.assert_array_equal(got[0], leaves[0])
    tx.close(); rx.close()


def test_throttle_budgets_whole_packed_frame():
    """throttle_bps must pace on the TOTAL packed frame size: sending
    ~400 KB at 1 MB/s takes >= ~0.4s whether packed or per-leaf (the
    satellite fix — a per-leaf-only budget would let packed frames bypass
    the localhost bandwidth emulation)."""
    tx, rx = _pair()
    tx.throttle_bps = 1e6
    leaves = [np.zeros(50_000, np.float32), np.zeros(50_000, np.float32)]
    nbytes = sum(a.nbytes for a in leaves)         # 400 KB
    done = []

    import threading
    th = threading.Thread(target=lambda: done.append(
        rx.recv_tensors(n=len(leaves))), daemon=True)
    th.start()
    t0 = time.perf_counter()
    tx.send_tensors(leaves, codec="raw")
    elapsed = time.perf_counter() - t0
    th.join(timeout=30)
    assert len(done) == 1
    assert elapsed >= 0.9 * nbytes / tx.throttle_bps
    tx.close(); rx.close()


def test_oversized_manifest_header_rejected():
    tx, rx = _pair()
    payload = _THDR.pack(10_000) + b"x" * 4        # hlen > frame
    tx._send_frame(ord("P"), payload)
    with pytest.raises(ProtocolError):
        rx.recv_tensors(n=1)
    tx.close(); rx.close()


def test_hdr_struct_unchanged():
    """The 'P' frame rides the existing kind:u8|len:u64le framing — a
    change here is a wire-protocol break."""
    assert _HDR.size == 9 and _HDR.pack(ord("P"), 1)[0] == ord("P")
