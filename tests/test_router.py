"""serve.router — the fault-tolerant fleet front (docs/SERVING.md).

Deterministic coverage of the dispatch/retry/shed/hedge/fence state
machine using scripted wire-level fake replicas (every failure mode is
a scripted behavior, not a race), plus a real two-replica fleet for
token parity.  The same transitions are model-checked exhaustively in
``lint/model.py`` (``router_model``) and soaked with real kills in
``tools/chaos.py`` (``replica_kill`` et al.); here each edge gets a
pinned, race-free unit test.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.router

VOCAB, DIM, DEPTH, HEADS, MAX_LEN = 61, 32, 2, 4, 64


@pytest.fixture(scope="module")
def lm_params():
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    model = transformer_lm(vocab=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                           max_len=MAX_LEN)
    params, _ = model.init(jax.random.PRNGKey(0))
    return params


def _greedy_ref(params, prompt, steps):
    from distlearn_tpu.models.transformer import greedy_generate
    out = greedy_generate(params, np.asarray(prompt, np.int32)[None], steps)
    return np.asarray(out)[0].tolist()


def _prompts(n, lo=3, hi=9, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, VOCAB, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def _serve_server(lm_params, **kw):
    from distlearn_tpu.serve import DecodeEngine, ServeServer
    eng = DecodeEngine(lm_params, num_slots=kw.pop("num_slots", 2),
                       max_len=MAX_LEN, page=8)
    return ServeServer(eng, idle_wait=0.01, **kw).start()


# -- scripted wire-level replica ----------------------------------------------

class _FakeReplica:
    """A replica that answers 'J' probes with a healthy snapshot and
    runs a scripted ``behavior(conn, msg, self)`` on each 'G' frame —
    deaths, sheds, stalls and fence violations on demand, with zero
    timing races."""

    def __init__(self, behavior, *, epoch=1, health=None):
        from distlearn_tpu.comm import transport
        self.behavior = behavior
        self.epoch = epoch
        self.health_extra = dict(health or {})
        self.srv = transport.Server()
        self.host, self.port = self.srv.host, self.srv.port
        self.name = f"{self.host}:{self.port}"
        self.seen_gen = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                (conn,) = self.srv.accept(1, timeout=0.05)
            except (TimeoutError, OSError):
                continue
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn):
        while not self._stop.is_set():
            try:
                kind, msg = conn.recv_serve(
                    deadline=time.monotonic() + 0.05)
            except TimeoutError:
                continue
            except Exception:  # noqa: BLE001 — peer gone, conn done
                return
            if kind == "J":
                conn.send_msg({"serving": True, "failed": None,
                               "draining": False, "queue_depth": 0,
                               "active": 0, "epoch": self.epoch,
                               **self.health_extra})
            elif kind == "G":
                self.seen_gen += 1
                try:
                    if self.behavior(conn, msg, self):
                        return
                except OSError:
                    return

    def close(self):
        self._stop.set()
        self.srv.close()
        self._thread.join(5.0)


def _die_on_gen(conn, msg, rep):
    """Queued-not-yet-prefilled death: accept the frame, cut the conn."""
    conn.close()
    return True


def _stall_on_gen(conn, msg, rep):
    """Sick-but-alive: admit the request, never produce a token."""
    return False


def _shed_on_gen(conn, msg, rep):
    conn.send_stream({"rid": msg.get("rid", ""), "done": True,
                      "error": "admission queue at capacity (1)",
                      "queue_depth": 3, "retry_after": 0.2,
                      "epoch": rep.epoch})
    return False


def _reject_on_gen(conn, msg, rep):
    """Non-load rejection: no retry_after — the request itself is bad."""
    conn.send_stream({"rid": msg.get("rid", ""), "done": True,
                      "error": "prompt + max_new exceeds max_len",
                      "epoch": rep.epoch})
    return False


def _die_mid_stream(conn, msg, rep):
    conn.send_stream({"rid": msg.get("rid", ""), "tokens": [5],
                      "done": False, "epoch": rep.epoch})
    conn.close()
    return True


def _fence_mid_stream(conn, msg, rep):
    conn.send_stream({"rid": msg.get("rid", ""), "tokens": [5],
                      "done": False, "epoch": rep.epoch})
    conn.send_stream({"rid": msg.get("rid", ""), "tokens": [6],
                      "done": False, "epoch": rep.epoch + 1})
    return False


def _router(replicas, **kw):
    from distlearn_tpu.serve import Router
    kw.setdefault("health_ttl", 0.02)
    kw.setdefault("retry_interval", 0.01)
    kw.setdefault("dial_deadline", 1.0)
    return Router([(r.host, r.port) for r in replicas], **kw)


# -- real fleet: parity and introspection -------------------------------------

def test_router_fleet_parity_and_health(lm_params):
    """Requests routed across two live replicas are token-identical to
    isolated greedy runs, results name their serving replica, and the
    fleet health aggregates both members."""
    prompts = _prompts(4, seed=5)
    max_new = 6
    refs = [_greedy_ref(lm_params, p, max_new) for p in prompts]
    a = _serve_server(lm_params, max_queue=8)
    b = _serve_server(lm_params, max_queue=8)
    try:
        with _router([a, b]) as router:
            names = {f"{a.host}:{a.port}", f"{b.host}:{b.port}"}
            for i, p in enumerate(prompts):
                r = router.generate(p, max_new, rid=f"r{i}")
                assert r["tokens"] == refs[i]
                assert r["reason"] == "complete"
                assert r["replica"] in names
            h = router.health()
            assert h["serving"] and h["live"] == 2
            assert len(h["replicas"]) == 2
    finally:
        a.stop()
        b.stop()


def test_router_requires_replicas_and_unique_addresses():
    from distlearn_tpu.serve import Router
    with pytest.raises(ValueError):
        Router([])
    with pytest.raises(ValueError):
        Router([("h", 1), ("h", 1)])


# -- retry on death -----------------------------------------------------------

def test_router_resubmits_queued_request_on_replica_death(lm_params):
    """The replica accepts the 'G' frame and dies before any token: the
    request was queued-not-yet-prefilled, so the router resubmits it to
    the survivor and the caller sees one clean completion."""
    dead = _FakeReplica(_die_on_gen)
    real = _serve_server(lm_params)
    try:
        # the fake is listed first: score ties break by list order
        with _router([dead, real]) as router:
            p = _prompts(1, seed=3)[0]
            r = router.generate(p, 4, rid="x")
            assert r["reason"] == "complete"
            assert r["tokens"] == _greedy_ref(lm_params, p, 4)
            assert r["replica"] == f"{real.host}:{real.port}"
            assert dead.seen_gen == 1      # it was tried, exactly once
    finally:
        dead.close()
        real.stop()


def test_router_all_replicas_dead_raises_replicadead():
    from distlearn_tpu.serve import ReplicaDead
    a, b = _FakeReplica(_die_on_gen), _FakeReplica(_die_on_gen)
    try:
        with _router([a, b]) as router:
            with pytest.raises(ReplicaDead, match="replicas tried"):
                router.generate([1, 2, 3], 4, rid="x")
        assert a.seen_gen == 1 and b.seen_gen == 1   # at most once each
    finally:
        a.close()
        b.close()


def test_router_no_listener_raises_replicadead_fast():
    import socket
    from distlearn_tpu.serve import Router, ReplicaDead
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()                          # nobody listening there now
    with Router([("127.0.0.1", port)], health_ttl=0.01,
                retry_interval=0.01, max_interval=0.05, max_attempts=2,
                dial_deadline=0.2) as router:
        with pytest.raises(ReplicaDead):
            router.generate([1, 2, 3], 4, timeout=10.0)


def test_router_mid_stream_death_is_clean_terminal_failure(lm_params):
    """Tokens already flowed when the replica died: resubmitting would
    duplicate output, so the caller gets reason='failed' with the
    partial tokens — and the healthy replica is never contacted."""
    dying = _FakeReplica(_die_mid_stream)
    real = _serve_server(lm_params)
    try:
        with _router([dying, real]) as router:
            r = router.generate([1, 2, 3], 4, rid="x")
            assert r["reason"] == "failed"
            assert r["tokens"] == [5]
            assert "died mid-stream" in r["error"]
            assert r["replica"] == dying.name
    finally:
        dying.close()
        real.stop()


# -- load shedding ------------------------------------------------------------

def test_router_sheds_at_watermark_without_dispatching():
    from distlearn_tpu.serve import RouterBusy
    busy = _FakeReplica(_stall_on_gen, health={"queue_depth": 5})
    try:
        with _router([busy], shed_watermark=4) as router:
            with pytest.raises(RouterBusy) as ei:
                router.generate([1, 2, 3], 4)
            assert ei.value.retry_after and ei.value.retry_after > 0
            assert ei.value.queue_depth == 5
            assert busy.seen_gen == 0      # refused before any dispatch
    finally:
        busy.close()


def test_router_surfaces_replica_shed_as_busy():
    """Every replica rejected with a retry_after hint: the router walks
    the fleet, collects the hints, and raises RouterBusy carrying the
    largest — callers back off once, not per replica."""
    from distlearn_tpu.serve import RouterBusy
    a, b = _FakeReplica(_shed_on_gen), _FakeReplica(_shed_on_gen)
    try:
        with _router([a, b]) as router:
            with pytest.raises(RouterBusy, match="every replica shed") as ei:
                router.generate([1, 2, 3], 4, rid="x")
            assert ei.value.retry_after == pytest.approx(0.2)
        assert a.seen_gen == 1 and b.seen_gen == 1
    finally:
        a.close()
        b.close()


def test_router_nonretryable_rejection_raises_serveerror_once():
    """A rejection WITHOUT retry_after means the request itself is bad
    (too long, duplicate rid): every replica would say the same, so the
    router must not walk the fleet."""
    from distlearn_tpu.serve import RouterBusy, ServeError
    a, b = _FakeReplica(_reject_on_gen), _FakeReplica(_reject_on_gen)
    try:
        with _router([a, b]) as router:
            with pytest.raises(ServeError, match="max_len") as ei:
                router.generate([1, 2, 3], 4, rid="x")
            assert not isinstance(ei.value, RouterBusy)
        assert a.seen_gen + b.seen_gen == 1
    finally:
        a.close()
        b.close()


# -- hedging ------------------------------------------------------------------

def test_router_hedges_off_stalled_replica(lm_params):
    """No first token within hedge_after from a sick-but-alive replica:
    the router cancels there (conn close) and completes on the
    alternative."""
    stalled = _FakeReplica(_stall_on_gen)
    real = _serve_server(lm_params)
    try:
        with _router([stalled, real], hedge_after=0.1) as router:
            p = _prompts(1, seed=11)[0]
            t0 = time.monotonic()
            r = router.generate(p, 4, rid="x", timeout=30.0)
            assert r["reason"] == "complete"
            assert r["tokens"] == _greedy_ref(lm_params, p, 4)
            assert r["replica"] == f"{real.host}:{real.port}"
            assert stalled.seen_gen == 1
            assert time.monotonic() - t0 < 20.0   # hedged, not timed out
    finally:
        stalled.close()
        real.stop()


def test_router_hedge_disarmed_without_alternative():
    """A lone stalled replica: nothing to hedge to, so the stall runs to
    the caller's timeout instead of busy-looping dispatches."""
    stalled = _FakeReplica(_stall_on_gen)
    try:
        with _router([stalled], hedge_after=0.05) as router:
            with pytest.raises(TimeoutError):
                router.generate([1, 2, 3], 4, rid="x", timeout=1.0)
        assert stalled.seen_gen == 1
    finally:
        stalled.close()


# -- epoch fence --------------------------------------------------------------

def test_router_epoch_fence_terminates_mixed_stream():
    """A stream that pins epoch 1 then receives an epoch-2 chunk is cut
    with a terminal failure — two model versions must never be spliced
    into one completion."""
    fencer = _FakeReplica(_fence_mid_stream)
    try:
        with _router([fencer]) as router:
            r = router.generate([1, 2, 3], 4, rid="x")
            assert r["reason"] == "failed"
            assert "epoch fence" in r["error"]
            assert r["tokens"] == [5]      # the epoch-2 token is dropped
            assert r["epoch"] == 1
    finally:
        fencer.close()


def test_router_health_reports_mixed_fleet_epochs():
    a = _FakeReplica(_stall_on_gen, epoch=3)
    b = _FakeReplica(_stall_on_gen, epoch=4)
    try:
        with _router([a, b]) as router:
            h = router.health()
            assert h["epochs"] == [3, 4]
            assert h["live"] == 2
    finally:
        a.close()
        b.close()


# -- observability ------------------------------------------------------------

def test_router_counters_record_the_walk(lm_params):
    """One death-retry request: dispatch counts both replicas, the
    retry names the dead one, and the failover histogram observed the
    recovery."""
    from distlearn_tpu.obs import core
    core.configure(True)
    core.REGISTRY.reset()
    try:
        dead = _FakeReplica(_die_on_gen)
        real = _serve_server(lm_params)
        try:
            with _router([dead, real]) as router:
                r = router.generate(_prompts(1, seed=3)[0], 4, rid="x")
                assert r["reason"] == "complete"
        finally:
            dead.close()
            real.stop()
        snap = core.REGISTRY.snapshot()

        def fam(name):
            for f in snap:
                if f["name"] == name:
                    return {tuple(sorted(s["labels"].items())): s["value"]
                            for s in f["samples"]}
            return {}

        dispatch = fam("router_dispatch_total")
        assert sum(dispatch.values()) == 2
        retries = fam("router_retries_total")
        assert retries == {(("replica", dead.name),): 1}
        hist = next(f for f in snap
                    if f["name"] == "router_failover_seconds")
        assert sum(s["count"] for s in hist["samples"]) == 1
    finally:
        core.REGISTRY.reset()
        core.configure(None)


# ------------------------------------------------ diststat router table

def _diststat():
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import diststat
    return diststat


def _fam(name, value, kind="counter", labels=None, labelnames=()):
    return {"name": name, "kind": kind, "help": "",
            "labelnames": list(labelnames),
            "samples": [{"labels": labels or {}, "value": value}]}


def test_diststat_router_table(tmp_path):
    import json
    diststat = _diststat()
    recs = [
        {"type": "span", "name": "router.failover", "ts": 1.0, "dur": 0.3},
        {"type": "span", "name": "router.failover", "ts": 1.4, "dur": 0.1},
        {"type": "span", "name": "router.hedge", "ts": 1.6, "dur": 0.2},
        {"type": "snapshot", "ts": 2.0, "metrics": [
            {"name": "router_dispatch_total", "kind": "counter",
             "help": "", "labelnames": ["replica"],
             "samples": [{"labels": {"replica": "r0"}, "value": 5},
                         {"labels": {"replica": "r1"}, "value": 3}]},
            _fam("router_retries_total", 2, labels={"replica": "r0"},
                 labelnames=["replica"]),
            _fam("router_shed_total", 4),
            _fam("router_hedges_total", 1, labels={"replica": "r1"},
                 labelnames=["replica"]),
        ]},
    ]
    log = tmp_path / "run.jsonl"
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    tab = diststat.summarize_run([str(log)])["router"]
    assert tab["dispatch"] == {"r0": 5, "r1": 3}
    assert tab["retries"] == 2 and tab["sheds"] == 4
    assert tab["hedges"] == 1
    assert "fence_violations" not in tab    # zero stays off the table
    assert tab["latency"]["failover"]["count"] == 2
    assert tab["latency"]["failover"]["p50"] == pytest.approx(0.1)
    assert tab["latency"]["hedge"]["count"] == 1


def test_diststat_router_table_empty_without_router(tmp_path):
    import json
    diststat = _diststat()
    log = tmp_path / "run.jsonl"
    log.write_text(json.dumps(
        {"type": "snapshot", "ts": 1.0, "metrics": [
            _fam("serve_requests_total", 5,
                 labels={"outcome": "complete"},
                 labelnames=["outcome"])]}) + "\n")
    assert diststat.summarize_run([str(log)])["router"] == {}


# ------------------------------------------------- chaos fleet smokes

def _chaos():
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import chaos
    return chaos


@pytest.mark.chaos
def test_scenario_replica_kill_every_request_terminal():
    report = _chaos().run_scenario("replica_kill", rounds=8)
    assert report["failures"] == []
    assert (report["completed"] + report["failed_mid_stream"]
            == report["requests"])
    assert report["retries"] >= 1
    assert report["replicas_dispatched"] >= 2


@pytest.mark.chaos
@pytest.mark.slow
def test_scenario_slow_replica_hedges_to_the_healthy_one():
    report = _chaos().run_scenario("slow_replica", rounds=8)
    assert report["failures"] == []
    assert report["completed"] == report["requests"]
    assert report["hedges"] >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_scenario_overload_shed_returns_retry_after():
    report = _chaos().run_scenario("overload_shed", rounds=8)
    assert report["failures"] == []
    assert report["sheds"] == 8
    assert report["retry_after_hint"] > 0
    assert report["shed_total"] >= report["sheds"]


@pytest.mark.chaos
@pytest.mark.slow
def test_scenario_swap_during_traffic_is_epoch_fenced():
    report = _chaos().run_scenario("swap_during_traffic", rounds=8)
    assert report["failures"] == []
    assert report["completed"] == report["requests"]
    assert report["fence_violations"] == 0
    assert report["swaps"] == 2
    assert set(report["stream_epochs"]) <= {1, 2}
