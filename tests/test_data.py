"""Dataset / sampler / prefetch tests — parity with torch-dataset semantics
(partition/partitions, permutation + label-uniform samplers, ceil(B/N))."""

import numpy as np

from distlearn_tpu.data import (Dataset, LabelUniformSampler,
                                PermutationSampler, batch_iterator,
                                make_dataset, make_sampler,
                                synthetic_cifar10, synthetic_mnist)
from distlearn_tpu.data.dataset import per_node_batch_size


def test_partition_covers_all_disjoint():
    x = np.arange(103, dtype=np.float32)[:, None]
    y = np.arange(103) % 10
    seen = []
    for p in range(4):
        ds = make_dataset(x, y, 10, partition=p, partitions=4)
        seen.extend(ds.x[:, 0].tolist())
    assert sorted(seen) == list(range(103))  # exhaustive & disjoint


def test_per_node_batch_ceil():
    # examples/cifar10.lua:36 — ceil(batchSize / numNodes)
    assert per_node_batch_size(16, 2) == 8
    assert per_node_batch_size(16, 3) == 6
    assert per_node_batch_size(1, 4) == 1


def test_permutation_sampler_full_epoch_no_repeat():
    s = PermutationSampler(100, seed=0)
    idx = np.concatenate(list(s.epoch(10)))
    assert len(idx) == 100 and len(set(idx.tolist())) == 100
    idx2 = np.concatenate(list(s.epoch(10)))
    assert not np.array_equal(idx, idx2)  # reshuffles each epoch


def test_label_uniform_sampler_balanced():
    labels = np.repeat(np.arange(10), [1000, 10, 10, 10, 10, 10, 10, 10, 10, 10])
    s = LabelUniformSampler(labels, seed=0)
    drawn = np.concatenate(list(s.epoch(100)))
    counts = np.bincount(labels[drawn], minlength=10)
    # class 0 is 91% of data but should be drawn ~10% of the time
    assert counts[0] < 0.2 * counts.sum()


def test_make_sampler_factory():
    labels = np.arange(20) % 4
    assert isinstance(make_sampler("permutation", labels), PermutationSampler)
    assert isinstance(make_sampler("label-uniform", labels), LabelUniformSampler)


def test_batch_iterator_shapes_and_processor():
    x, y, nc = synthetic_mnist(128)
    ds = make_dataset(x, y, nc)
    s = PermutationSampler(ds.size, seed=0)
    batches = list(batch_iterator(ds, s, 32,
                                  processor=lambda bx, by: (bx * 2.0, by)))
    assert len(batches) == 4
    bx, by = batches[0]
    assert bx.shape == (32, 32, 32, 1) and by.shape == (32,)


def test_synthetic_learnable_signal():
    x, y, _ = synthetic_cifar10(512, seed=0)
    # same-class examples correlate more than cross-class ones
    x = x.reshape(len(x), -1)
    c0 = x[y == 0]
    c1 = x[y == 1]
    within = np.corrcoef(c0[0], c0[1])[0, 1]
    across = np.corrcoef(c0[0], c1[0])[0, 1]
    assert within > across


def test_synthetic_non_multiple_of_four_size():
    from distlearn_tpu.data import synthetic_imagenet
    x, y, nc = synthetic_imagenet(4, image_size=30, num_classes=7)
    assert x.shape == (4, 30, 30, 3) and nc == 7


def test_device_dataset_gather_matches_host():
    """DeviceDataset: on-device gathered batches equal host fancy-indexed
    batches, land with the requested sharding, and iterate a full epoch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import DeviceDataset, PermutationSampler
    from distlearn_tpu.parallel.mesh import MeshTree

    tree = MeshTree(num_nodes=4)
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)
    dds = DeviceDataset(
        x, y, 10, sharding=NamedSharding(tree.mesh, P()),
        out_sharding=NamedSharding(tree.mesh, P("data")))
    assert dds.size == 64 and dds.batches_per_epoch(16) == 4

    idx = np.array([5, 3, 60, 1, 7, 2, 9, 11], np.int64)
    bx, by = dds.gather(idx)
    np.testing.assert_array_equal(np.asarray(jax.device_get(bx)), x[idx])
    np.testing.assert_array_equal(np.asarray(jax.device_get(by)), y[idx])
    assert len(bx.sharding.device_set) == 4  # sharded over the data axis

    seen = 0
    sampler = PermutationSampler(64, seed=1)
    for bx, by in dds.batches(sampler, 16):
        assert bx.shape[0] == 16
        seen += 16
    assert seen == 64


# --- non-separable synthetic (data/dataset.py synthetic_hard) --------------


def test_synthetic_hard_linear_probe_at_chance():
    """By construction every class mixes all factor modes equally, so the
    class MEANS coincide and a linear model on pixels sits near chance —
    unlike the easy class-template set a matched filter solves."""
    from distlearn_tpu.data import synthetic_hard
    x, y = synthetic_hard(3000, (16, 16, 1), 4, seed=0, label_noise=0.0)
    flat = x.reshape(len(x), -1)
    flat = np.concatenate([flat, np.ones((len(x), 1), np.float32)], 1)
    onehot = np.eye(4, dtype=np.float32)[y]
    w, *_ = np.linalg.lstsq(flat[:2000], onehot[:2000], rcond=None)
    pred = (flat[2000:] @ w).argmax(1)
    acc = float((pred == y[2000:]).mean())
    assert acc < 0.45, acc          # chance = 0.25; matched filter ~1.0

    # class means nearly identical (the structural reason)
    means = np.stack([x[y == c].mean(0) for c in range(4)])
    spread = np.abs(means - means.mean(0)).max()
    scale = np.abs(x).mean()
    assert spread < 0.15 * scale, (spread, scale)


def test_synthetic_hard_is_decodable_nonlinearly():
    """The information IS there: an oracle that recovers both latent
    factors (nearest mode centroid, estimated from labeled latents)
    reaches high accuracy — so a nonlinear learner has something real to
    learn, and the label-noise fraction caps it."""
    from distlearn_tpu.data import synthetic_hard
    C = 4
    x, y, a, b = synthetic_hard(4000, (16, 16, 1), C, seed=1,
                                label_noise=0.1, return_latents=True)
    tr, te = slice(0, 3000), slice(3000, None)
    flat = x.reshape(len(x), -1)
    # mode centroids from the training half
    cents, labels = [], []
    for ai in range(C):
        for bi in range(C):
            m = (a[tr] == ai) & (b[tr] == bi)
            if m.any():
                cents.append(flat[tr][m].mean(0))
                labels.append((ai + bi) % C)
    cents = np.stack(cents)
    labels = np.asarray(labels)
    d = ((flat[te][:, None] - cents[None]) ** 2).sum(-1)
    pred = labels[d.argmin(1)]
    acc = float((pred == y[te]).mean())
    # flips cap the oracle at ~1 - 0.1*(C-1)/C = 0.925
    assert 0.75 < acc < 0.97, acc
    # flipped fraction matches the knob
    clean = ((a + b) % C == y).mean()
    assert 0.85 < clean < 0.95, clean


def test_synthetic_hard_cifar_shape_and_export():
    from distlearn_tpu.data import synthetic_hard_cifar10
    x, y, nc = synthetic_hard_cifar10(64, seed=0)
    assert x.shape == (64, 32, 32, 3) and y.shape == (64,) and nc == 10
    assert x.dtype == np.float32 and y.dtype == np.int32
