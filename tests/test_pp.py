"""Pipeline parallelism: the GPipe microbatch pipeline must match running
the stages sequentially on one device, forward AND backward (jax.grad
through the scan is the pipeline backward schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.parallel.pp import pipeline_apply

DIM = 8


def _stage(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def _sequential(stacked, x):
    h = x
    for s in range(stacked["w"].shape[0]):
        h = _stage({"w": stacked["w"][s], "b": stacked["b"][s]}, h)
    return h


def _stacked_params(S, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(S, DIM, DIM).astype(np.float32) * 0.5),
            "b": jnp.asarray(rng.randn(S, DIM).astype(np.float32) * 0.1)}


def _pipeline_fn(mesh, M):
    def fn(stacked, x):
        local = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0), stacked)
        return pipeline_apply(_stage, local, x, M, axis_name="pipe")
    return jax.jit(jax.shard_map(fn, mesh=mesh,
                                 in_specs=(P("pipe"), P()),
                                 out_specs=P(), check_vma=False))


@pytest.mark.parametrize("S,M", [(2, 8), (4, 4), (4, 8), (8, 2)])
def test_pipeline_matches_sequential(S, M):
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))
    stacked = _stacked_params(S)
    x = jnp.asarray(np.random.RandomState(1).randn(16, DIM)
                    .astype(np.float32))
    out = _pipeline_fn(mesh, M)(stacked, x)
    ref = _sequential(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    S, M = 4, 4
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))
    stacked = _stacked_params(S, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, DIM)
                    .astype(np.float32))
    pipe = _pipeline_fn(mesh, M)

    g_pipe = jax.grad(lambda p: jnp.sum(pipe(p, x) ** 2))(stacked)
    g_ref = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_shape_changing_stage():
    mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
    stacked = {"w": jnp.zeros((2, DIM, DIM + 1))}

    def bad_stage(params, h):
        return h @ params["w"]

    def fn(st, x):
        local = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0), st)
        return pipeline_apply(bad_stage, local, x, 2, axis_name="pipe")

    with pytest.raises(ValueError, match="preserve activation shape"):
        jax.shard_map(fn, mesh=mesh, in_specs=(P("pipe"), P()),
                      out_specs=P(), check_vma=False)(
            stacked, jnp.zeros((4, DIM)))


def test_pipeline_rejects_indivisible_microbatches():
    mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
    stacked = _stacked_params(2)

    def fn(st, x):
        local = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0), st)
        return pipeline_apply(_stage, local, x, 3, axis_name="pipe")

    with pytest.raises(ValueError, match="not divisible"):
        jax.shard_map(fn, mesh=mesh, in_specs=(P("pipe"), P()),
                      out_specs=P(), check_vma=False)(
            stacked, jnp.zeros((8, DIM)))
