"""Fused wire-codec kernel tests (ISSUE 12): randomized round-trip
property tests against the committed numpy reference encoding, bitwise
parity of the blocked host kernels, <=1-ulp bounds on the Pallas route,
zero-copy/zero-alloc assertions for the staging fast path, undecoded
``recv_payload`` transport behavior, and the 50-round EASGD trajectory
parity acceptance (fused vs numpy, S=1 and S=4).
"""

import os
import threading
import time
import tracemalloc

import numpy as np
import pytest

from distlearn_tpu.comm import transport, wire
from distlearn_tpu.ops import wire_kernels as wk
from distlearn_tpu.ops import wire_native
from distlearn_tpu.utils.logging import set_verbose

set_verbose(False)

from tests.net_util import reserve_port_window

pytestmark = pytest.mark.perf


def _ref_int8(arr):
    """The committed reference encoding + residual: encode_leaves ->
    decoded -> subtract (the exact path _encode_stripe used pre-fusion)."""
    payload = wire.encode_leaves([arr], "int8")
    dec = payload.decoded()[0]
    return (payload.bufs[0], payload.manifest["leaves"][0].get("scale"),
            np.subtract(arr, dec))


# ---------------------------------------------------------------------------
# Blocked host kernels: bitwise parity with the numpy reference.

@pytest.mark.parametrize("shape,dtype", [
    ((1000,), np.float32), ((3, 5, 7), np.float32), ((0,), np.float32),
    ((1,), np.float32), ((257, 129), np.float32), ((513,), np.float64),
    ((300001,), np.float32),      # > _CHUNK: crosses a block boundary
])
def test_quantize_ef_bitwise_vs_reference(shape, dtype):
    rng = np.random.default_rng(hash((shape, np.dtype(dtype).name)) % 2**31)
    d = (rng.standard_normal(shape) * 3).astype(dtype)
    q = np.empty(shape, np.int8)
    r = np.empty(shape, dtype)
    scale = wk.quantize_ef_into(d.copy(), q, r)
    q_ref, s_ref, r_ref = _ref_int8(d)
    assert scale == s_ref                       # python-float, exact
    np.testing.assert_array_equal(q, q_ref)
    np.testing.assert_array_equal(r, r_ref)


def test_quantize_ef_scale_zero_carries_whole_delta():
    d = np.zeros(64, np.float32)
    q = np.empty(64, np.int8)
    r = np.empty(64, np.float32)
    assert wk.quantize_ef_into(d, q, r) == 0.0
    assert not q.any() and not r.any()
    # all-zero amax but nonzero input cannot happen; denormal-small does:
    d = np.full(64, 1e-42, np.float32)
    scale = wk.quantize_ef_into(d, q, r)
    q_ref, s_ref, r_ref = _ref_int8(d)
    assert scale == s_ref
    np.testing.assert_array_equal(q, q_ref)
    np.testing.assert_array_equal(r, r_ref)


@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
def test_quantize_ef_nonfinite_raises_like_reference(bad):
    d = np.ones(130000, np.float32)
    d[129999] = bad                              # in the LAST chunk
    q = np.empty_like(d, dtype=np.int8)
    r = np.empty_like(d)
    with pytest.raises(ValueError, match="non-finite"):
        wk.quantize_ef_into(d, q, r)
    with pytest.raises(ValueError, match="non-finite"):
        wire.encode_leaves([d], "int8")


def test_fp16_ef_bitwise_vs_reference():
    rng = np.random.default_rng(7)
    d = (rng.standard_normal(3001) * 10).astype(np.float32)
    h = np.empty_like(d, dtype=np.float16)
    r = np.empty_like(d)
    wk.fp16_ef_into(d, h, r)
    payload = wire.encode_leaves([d], "fp16")
    np.testing.assert_array_equal(h, payload.bufs[0])
    np.testing.assert_array_equal(r, d - payload.decoded()[0])


@pytest.mark.parametrize("scale", [0.037, None])
def test_dequant_add_matches_decode_then_add(scale):
    rng = np.random.default_rng(11)
    t = rng.standard_normal(200003).astype(np.float32)
    if scale is None:
        buf = rng.standard_normal(t.shape).astype(np.float16)
        entry = {"enc": "fp16", "dtype": "float32"}
    else:
        buf = rng.integers(-127, 128, t.shape).astype(np.int8)
        entry = {"enc": "int8", "dtype": "float32", "scale": scale}
    dec = np.empty_like(t)
    wire.decode_into(entry, buf, dec)
    want = t + dec
    got = wk.dequant_add(t, buf, scale)          # fresh
    np.testing.assert_array_equal(got, want)
    wk.dequant_add(t, buf, scale, out=t)         # in place, aliasing t
    np.testing.assert_array_equal(t, want)


# ---------------------------------------------------------------------------
# Randomized round-trip property tests over whole payloads.

@pytest.mark.parametrize("codec", ["int8", "fp16"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_encode_ef_into_randomized_parity(codec, seed):
    """Mixed raw/quantized frames, non-contiguous and zero-size leaves,
    f32/f64, with and without the frame buffer: manifest and wire bytes
    byte-identical to encode_leaves, residuals == d - decoded()."""
    rng = np.random.default_rng(seed)
    big = rng.standard_normal((64, 64)).astype(np.float32)
    leaves = [
        (rng.standard_normal(977) * 5).astype(np.float32),
        np.arange(17, dtype=np.int32),           # rides raw in any codec
        big[::2, ::2],                           # NON-contiguous view
        np.empty((0, 4), np.float32),            # zero-size
        rng.standard_normal((3, 1, 9)).astype(np.float64),
        np.zeros(33, np.float32),                # scale == 0
    ]
    ref = wire.encode_leaves(leaves, codec)
    ref_dec = ref.decoded()
    for use_fb in (False, True):
        res = [np.full(l.shape, np.nan, l.dtype if l.dtype.kind == "f"
                       else np.float32) for l in leaves]
        fb = wire.FrameBuffer() if use_fb else None
        payload = wk.encode_ef_into(leaves, res, codec, out=fb)
        assert payload.manifest == ref.manifest
        for buf, rbuf in zip(payload.bufs, ref.bufs):
            np.testing.assert_array_equal(np.asarray(buf),
                                          np.asarray(rbuf))
        for l, r, dec in zip(leaves, res, ref_dec):
            want = (np.asarray(l, r.dtype) - dec if l.dtype.kind == "f"
                    else np.zeros(l.shape, r.dtype))
            np.testing.assert_array_equal(r, want)
        if use_fb:
            assert payload.frame is not None
            cat = (np.concatenate([np.asarray(b).reshape(-1).view(np.uint8)
                                   for b in ref.bufs if b.nbytes])
                   if ref.wire_nbytes else np.empty(0, np.uint8))
            np.testing.assert_array_equal(payload.frame, cat)
        else:
            assert payload.frame is None


def test_encode_ef_into_rejects_raw():
    with pytest.raises(ValueError, match="lossy"):
        wk.encode_ef_into([np.zeros(3, np.float32)],
                          [np.zeros(3, np.float32)], "raw")


# ---------------------------------------------------------------------------
# Pallas route (interpret mode on CPU): wire-visible outputs bitwise,
# residual within 1 ulp (XLA may contract the dequant-subtract to FMA).

def _assert_within_one_ulp_of(got, want, magnitude):
    """|got - want| bounded per element by one ulp AT THE MAGNITUDE of the
    contracted product — the only drift FMA contraction can introduce
    (a plain int-representation diff misbehaves across zero crossings,
    where a 1-ulp-of-|x| error spans many representable tiny floats)."""
    tol = np.spacing(np.abs(magnitude).astype(np.float32))
    bad = np.abs(got - want) > tol
    assert not bad.any(), (
        f"{bad.sum()} elements beyond 1 ulp; worst "
        f"{np.abs(got - want).max()} vs tol {tol.max()}")


@pytest.mark.parametrize("n", [1, 5000, wk._TILE_Q])
def test_quantize_ef_jax_q_scale_bitwise_r_one_ulp(n):
    rng = np.random.default_rng(n)
    d = (rng.standard_normal(n) * 2).astype(np.float32)
    q, scale, r = wk.quantize_ef_jax(d)
    q_ref, s_ref, r_ref = _ref_int8(d)
    assert scale == s_ref
    np.testing.assert_array_equal(q, q_ref)
    _assert_within_one_ulp_of(r.astype(np.float32),
                              r_ref.astype(np.float32), d)


def test_dequant_add_jax_one_ulp():
    rng = np.random.default_rng(5)
    t = rng.standard_normal(4100).astype(np.float32)
    q = rng.integers(-127, 128, t.shape).astype(np.int8)
    got = wk.dequant_add_jax(t, q, 0.021)
    want = wk.dequant_add(t, q, 0.021)
    # two-rounding (mul, add) vs one-rounding (FMA): bounded by one ulp
    # at the magnitude of the larger intermediate, |t| + |q*scale|
    mag = np.abs(t) + np.abs(q.astype(np.float32) * 0.021)
    _assert_within_one_ulp_of(got, want, mag)


def test_quantize_ef_jax_nonfinite_raises():
    with pytest.raises(ValueError, match="non-finite"):
        wk.quantize_ef_jax(np.array([1.0, np.nan], np.float32))


# ---------------------------------------------------------------------------
# Zero-copy staging (satellite: encode_leaves raw leaves are views).

def test_encode_leaves_raw_contiguous_is_zero_copy():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    payload = wire.encode_leaves([a], "raw")
    assert payload.bufs[0] is a                 # no ascontiguousarray copy
    assert np.shares_memory(payload.bufs[0], a)
    # int leaves ride raw inside a quantized frame — still zero-copy
    b = np.arange(9, dtype=np.int64)
    payload = wire.encode_leaves([np.zeros(4, np.float32), b], "int8")
    assert payload.bufs[1] is b
    # non-contiguous inputs are the one case that must copy
    v = a[:, ::2]
    payload = wire.encode_leaves([v], "raw")
    assert not np.shares_memory(payload.bufs[0], a)


def test_frame_buffer_reserve_and_views():
    fb = wire.FrameBuffer()
    fb.reserve(100)
    buf0 = fb.buf
    fb.reserve(50)                               # grow-never-shrink
    assert fb.buf is buf0
    v = fb.view(4, 8, np.dtype(np.float32), (2,))
    v[...] = [1.5, -2.0]
    assert np.shares_memory(v, fb.buf)
    np.testing.assert_array_equal(
        fb.frame(12)[4:].view(np.float32), [1.5, -2.0])


def test_encode_ef_into_frame_buffer_reused_across_syncs():
    rng = np.random.default_rng(3)
    fb = wire.FrameBuffer()
    leaves = [rng.standard_normal(500).astype(np.float32)]
    res = [np.zeros(500, np.float32)]
    p1 = wk.encode_ef_into(leaves, res, "int8", out=fb)
    buf_before = fb.buf
    leaves[0][...] = rng.standard_normal(500).astype(np.float32)
    p2 = wk.encode_ef_into(leaves, res, "int8", out=fb)
    assert fb.buf is buf_before                  # no per-sync realloc
    assert np.shares_memory(np.asarray(p1.bufs[0]), fb.buf)
    assert np.shares_memory(np.asarray(p2.bufs[0]), fb.buf)


# ---------------------------------------------------------------------------
# Zero steady-state allocation (satellite: decoded_into + fused paths).

def test_steady_state_sync_math_allocates_nothing():
    """The residual walk (fused encode) and the center apply
    (decoded_into / dequant_add with out=) must allocate nothing once
    warm — tracemalloc-asserted, mirroring the obs NULL-object test."""
    rng = np.random.default_rng(9)
    d0 = rng.standard_normal(5000).astype(np.float32)
    d = d0.copy()
    q = np.empty_like(d, dtype=np.int8)
    r = np.empty_like(d)
    t = rng.standard_normal(5000).astype(np.float32)
    entry = {"enc": "int8", "dtype": "float32", "scale": 0.03}
    scratch = np.empty_like(t)

    def run(n):
        for _ in range(n):
            wk.quantize_ef_into(d, q, r)
            wk.dequant_add(t, q, 0.03, out=t)
            wire.decode_into(entry, q, scratch)

    run(10)                                      # warm caches / scratch
    tracemalloc.start()
    # One-time allocations (free-list growth, interpreter caches,
    # tracemalloc's own bookkeeping) can land in ANY early window
    # depending on what the rest of the suite ran first, so absorb
    # adaptively: a per-call leak can never produce a zero window, a
    # one-time blip always leaves the next window clean.
    delta = None
    for _ in range(4):
        run(10)
        before = tracemalloc.get_traced_memory()[0]
        run(50)
        delta = tracemalloc.get_traced_memory()[0] - before
        if delta == 0:
            break
    tracemalloc.stop()
    assert delta == 0


def test_decoded_into_reuses_buffers():
    rng = np.random.default_rng(2)
    leaves = [rng.standard_normal(100).astype(np.float32),
              np.arange(5, dtype=np.int32)]
    payload = wire.encode_leaves(leaves, "int8")
    out = [np.empty(100, np.float32), np.empty(5, np.int32)]
    dec = payload.decoded_into(out)
    assert dec[0] is out[0]                      # quantized -> decoded into
    assert dec[1] is payload.bufs[1]             # raw -> the wire view
    np.testing.assert_array_equal(dec[0], payload.decoded()[0])


# ---------------------------------------------------------------------------
# Transport: single-iovec frame sends and undecoded receives.

def test_send_packed_frame_and_recv_payload_loopback():
    srv = transport.Server("127.0.0.1", reserve_port_window(1))
    out = {}

    def server():
        c = srv.accept()[0]
        out["fb"] = c.recv_payload(n=3)
        out["gather"] = c.recv_payload(n=3)
        out["legacy"] = c.recv_payload(n=1)
        out["empty"] = c.recv_payload(n=0)
        c.close()

    th = threading.Thread(target=server, daemon=True)
    th.start()
    c = transport.connect("127.0.0.1", srv.sock.getsockname()[1])
    rng = np.random.default_rng(0)
    leaves = [rng.standard_normal(97).astype(np.float32),
              np.arange(10, dtype=np.int32),
              rng.standard_normal((3, 5)).astype(np.float32)]
    res = [np.zeros_like(l, dtype=np.float32) for l in leaves]
    fb = wire.FrameBuffer()
    pay = wk.encode_ef_into(leaves, res, "int8", out=fb)
    assert pay.frame is not None
    c.send_packed(pay)                           # single-iovec frame send
    c.send_packed(wire.encode_leaves(leaves, "int8"))   # per-leaf gather
    c.send_tensor(leaves[0])                     # legacy 'T'
    th.join(timeout=30)
    assert not th.is_alive()
    c.close()
    srv.close()
    for key in ("fb", "gather"):
        got = out[key]
        assert got.manifest == pay.manifest
        assert got.codec == "int8"
        for b, bref in zip(got.bufs, pay.bufs):
            np.testing.assert_array_equal(b, np.asarray(bref))
        assert got.logical_nbytes == sum(l.nbytes for l in leaves)
    leg = out["legacy"]
    assert leg.codec == "raw"
    np.testing.assert_array_equal(leg.bufs[0], leaves[0])
    assert out["empty"].bufs == []


# ---------------------------------------------------------------------------
# Acceptance: 50-round EASGD trajectory identical fused vs numpy.

def _toggle_wirek(monkeypatch, on: bool):
    monkeypatch.setenv("DISTLEARN_TPU_WIREK", "1" if on else "0")


def test_fifty_round_trajectory_parity_s1(monkeypatch):
    """50 int8-EA rounds, serial S=1: the fused kernels and the numpy
    reference path produce BITWISE-identical centers — the fused codec is
    a pure perf change, zero math drift."""
    from tests.test_async_ea_wire import _run_ea
    _toggle_wirek(monkeypatch, False)
    ref = _run_ea(reserve_port_window(8), "int8")
    _toggle_wirek(monkeypatch, True)
    fused = _run_ea(reserve_port_window(8), "int8")
    np.testing.assert_array_equal(ref, fused)


def test_fifty_round_trajectory_parity_s4(monkeypatch):
    """50 int8-EA rounds on the S=4 striped concurrent pipeline: fused vs
    numpy bitwise parity — per-stripe frame buffers, the undecoded
    recv_payload leg, and the fused stripe apply all preserve the exact
    trajectory."""
    from distlearn_tpu.parallel.async_ea import (AsyncEAClient,
                                                 AsyncEAServerConcurrent)

    def run(rounds=50):
        port = reserve_port_window(12)
        out = {}

        def client_fn():
            c = AsyncEAClient("127.0.0.1", port, node=1, tau=1, alpha=0.5,
                              codec="int8")
            p = c.init_client({"w": np.zeros((8, 5), np.float32),
                               "b": np.zeros((3,), np.float32)})
            for r in range(rounds):
                p = {k: v + (r % 5) + 0.25 for k, v in p.items()}
                p, synced = c.sync_client(p)
                assert synced
            out["p"] = p
            c.close()

        th = threading.Thread(target=client_fn, daemon=True)
        th.start()
        srv = AsyncEAServerConcurrent("127.0.0.1", port, num_nodes=1,
                                      shards=4)
        srv.init_server({"w": np.zeros((8, 5), np.float32),
                         "b": np.zeros((3,), np.float32)})
        srv.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if srv.syncs_completed >= rounds and srv.drained:
                break
            time.sleep(0.01)
        th.join(timeout=60)
        assert not th.is_alive(), "client hung"
        assert srv.syncs_completed == rounds
        center = [np.array(t) for t in srv._snapshot()]
        srv.stop()
        srv.close()
        return out["p"], center

    _toggle_wirek(monkeypatch, False)
    p_ref, c_ref = run()
    _toggle_wirek(monkeypatch, True)
    p_fused, c_fused = run()
    for a, b in zip(c_ref, c_fused):
        np.testing.assert_array_equal(a, b)
    for k in p_ref:
        np.testing.assert_array_equal(p_ref[k], p_fused[k])


def test_wirek_env_gate_pins_numpy_path(monkeypatch):
    _toggle_wirek(monkeypatch, False)
    assert wk.wirek_enabled() is False
    _toggle_wirek(monkeypatch, True)
    assert wk.wirek_enabled() is True
    assert wk.wirek_enabled(override=False) is False
    monkeypatch.delenv("DISTLEARN_TPU_WIREK")
    assert wk.wirek_enabled() is True            # default on


# ---------------------------------------------------------------------------
# Native (compiled C) backend: must agree bitwise with the blocked-numpy
# tier, and must degrade silently when disabled/unavailable.

_needs_native = pytest.mark.skipif(
    not wire_native.available(),
    reason=f"native wire codec unavailable: {wire_native.why_unavailable()}")


@_needs_native
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_vs_blocked_bitwise(monkeypatch, seed):
    """The two host tiers are interchangeable bit for bit: quantize the
    same delta with the C kernel and with the blocked numpy loop (pinned
    via DISTLEARN_TPU_WIREC=0) and compare q/scale/r — then the same for
    the fused apply, fresh and in-place."""
    rng = np.random.default_rng(seed)
    d = (rng.standard_normal(40013) * 10.0 ** rng.integers(-12, 12)
         ).astype(np.float32)
    q_n = np.empty(d.size, np.int8)
    r_n = np.empty_like(d)
    assert wire_native.usable_quant(d, q_n, r_n)
    s_n = wk.quantize_ef_into(d.copy(), q_n, r_n)

    monkeypatch.setenv("DISTLEARN_TPU_WIREC", "0")
    assert not wire_native.available()
    q_b = np.empty(d.size, np.int8)
    r_b = np.empty_like(d)
    s_b = wk.quantize_ef_into(d.copy(), q_b, r_b)
    assert s_n == s_b
    np.testing.assert_array_equal(q_n, q_b)
    np.testing.assert_array_equal(r_n, r_b)

    t = rng.standard_normal(d.size).astype(np.float32)
    blocked_fresh = wk.dequant_add(t, q_b, s_b)
    blocked_inpl = t.copy()
    wk.dequant_add(blocked_inpl, q_b, s_b, out=blocked_inpl)
    monkeypatch.delenv("DISTLEARN_TPU_WIREC")
    native_fresh = wk.dequant_add(t, q_n, s_n)
    native_inpl = t.copy()
    wk.dequant_add(native_inpl, q_n, s_n, out=native_inpl)
    np.testing.assert_array_equal(native_fresh, blocked_fresh)
    np.testing.assert_array_equal(native_inpl, blocked_inpl)


@_needs_native
def test_native_partial_overlap_falls_back():
    """A partially-overlapping out/t pair would break the C kernel's
    restrict contract — dequant_add must detect it and take the numpy
    route (whose ufuncs are overlap-safe)."""
    base = np.zeros(150, np.float32)
    base[:100] = np.arange(100, dtype=np.float32)
    t = base[:100]
    out = base[50:150]
    q = np.full(100, 3, np.int8)
    want = t.copy() + q * np.float32(0.5)
    got = wk.dequant_add(t, q, 0.5, out=out)
    np.testing.assert_array_equal(got, want)


def test_native_gate_and_usability(monkeypatch):
    monkeypatch.setenv("DISTLEARN_TPU_WIREC", "0")
    assert wire_native.available() is False
    assert "disabled" in wire_native.why_unavailable()
    d = np.zeros(8, np.float32)
    assert not wire_native.usable_quant(d, np.zeros(8, np.int8), d.copy())
    monkeypatch.delenv("DISTLEARN_TPU_WIREC")
    if wire_native.available():
        assert wire_native.why_unavailable() is None
        # non-contiguous / wrong-dtype inputs must route to numpy
        big = np.zeros((8, 8), np.float32)
        assert not wire_native.usable_quant(
            big[::2, ::2], np.zeros((4, 4), np.int8),
            np.zeros((4, 4), np.float32))
        d64 = np.zeros(8, np.float64)
        assert not wire_native.usable_quant(
            d64, np.zeros(8, np.int8), d64.copy())
