"""Elastic fleet membership (docs/ELASTIC.md): fast units for the
comm-layer fault plan's determinism, capacity-weight normalization,
the straggler-adaptive τ bounds, and the membership protocol model —
plus the seeded chaos scenarios (tools/chaos.py ``scenario``; the long
ones also carry ``slow``)."""

from __future__ import annotations

import os
import sys
import types

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import chaos  # noqa: E402

from distlearn_tpu.comm import FaultInjected, FaultPlan  # noqa: E402
from distlearn_tpu.lint.model import check_model, membership_model  # noqa: E402
from distlearn_tpu.parallel.async_ea import (  # noqa: E402
    ALPHA_TAU_PRODUCT, AsyncEAServer, adaptive_tau_bounds)

pytestmark = pytest.mark.elastic


# ------------------------------------------------------ fault plan units

def _drive(plan: FaultPlan) -> None:
    """One fixed mutator/dial sequence — refused dials never touch the
    network, so the decision log is pure plan state."""
    plan.partition("a", "send")
    plan.delay("b", 0.01)
    plan.bandwidth("b", 1e6)
    plan.heal("a")
    plan.fail_dials("a", 2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            plan.connect("127.0.0.1", 1, link="a")
    plan.flaky_dials("a", 1.0)       # p=1: refuses, but draws the RNG
    with pytest.raises(FaultInjected):
        plan.connect("127.0.0.1", 1, link="a")
    plan.cut_after("b", 64)


def test_fault_plan_same_seed_same_decisions():
    p1, p2 = FaultPlan(seed=7), FaultPlan(seed=7)
    _drive(p1)
    _drive(p2)
    assert p1.decisions() == p2.decisions()
    assert len(p1.decisions()) >= 9


def test_fault_plan_per_link_rng_streams_are_independent():
    """Decisions on one link must not perturb another's RNG stream: a
    plan that also exercises link 'z' first still refuses the same 'a'
    dials."""
    p1, p2 = FaultPlan(seed=7), FaultPlan(seed=7)
    p2.flaky_dials("z", 1.0)
    with pytest.raises(FaultInjected):
        p2.connect("127.0.0.1", 1, link="z")
    _drive(p1)
    _drive(p2)
    a1 = [e for e in p1.decisions() if e[0] == "a"]
    a2 = [e for e in p2.decisions() if e[0] == "a"]
    assert a1 == a2


# ------------------------------------------- capacity-weight normalization

def _srv(members, capacity=(), num_nodes=2, elastic=True, evicted=()):
    """The attribute slice ``AsyncEAServer._delta_weight`` reads."""
    return types.SimpleNamespace(
        elastic=elastic, members=set(members), evicted=set(evicted),
        _capacity=dict(capacity), num_nodes=num_nodes)


def _w(ns, cid):
    return AsyncEAServer._delta_weight(ns, cid)


def test_initial_equal_capacity_fleet_weighs_exactly_one():
    ns = _srv({1, 2})
    assert _w(ns, 1) == 1.0 and _w(ns, 2) == 1.0


def test_non_elastic_server_never_scales():
    ns = _srv({1, 2, 3}, capacity={3: 5.0}, elastic=False)
    assert _w(ns, 3) == 1.0


def test_weights_renormalize_on_join_and_sum_to_budget():
    # a capacity-2 joiner on a num_nodes=2 fleet: w = cap*N/Σcap
    ns = _srv({1, 2, 3}, capacity={3: 2.0})
    assert _w(ns, 1) == pytest.approx(0.5)
    assert _w(ns, 3) == pytest.approx(1.0)
    live = ns.members - ns.evicted
    assert sum(_w(ns, c) for c in live) == pytest.approx(ns.num_nodes)


def test_weights_renormalize_on_leave_and_eviction():
    ns = _srv({1, 2, 3}, capacity={3: 2.0})
    ns.members.discard(3)            # graceful leave
    assert _w(ns, 1) == 1.0
    ns = _srv({1, 2, 3}, capacity={3: 2.0}, evicted={3})
    assert _w(ns, 1) == 1.0          # evicted drops out of the denominator


# --------------------------------------------------- adaptive-τ bounds

def test_adaptive_tau_bounds_values():
    assert adaptive_tau_bounds(4, 0.05) == (4, 18)
    assert adaptive_tau_bounds(1, 0.1) == (1, 9)
    assert adaptive_tau_bounds(2, 0.1) == (2, 9)


def test_adaptive_tau_never_shrinks_below_configured_tau():
    lo, hi = adaptive_tau_bounds(8, 0.5)   # 0.9/α = 1 < τ
    assert (lo, hi) == (8, 8)


def test_adaptive_tau_ceiling_respects_stability_product():
    for tau in (1, 2, 4):
        for alpha in (0.02, 0.05, 0.1, 0.3):
            lo, hi = adaptive_tau_bounds(tau, alpha)
            assert 1 <= lo <= hi
            # the stretch ceiling never crosses α·τ ≤ 0.9 unless the
            # CONFIGURED τ already does (we never shrink below it)
            assert hi * alpha <= ALPHA_TAU_PRODUCT or hi == lo


# ------------------------------------------------- membership model gate

def test_membership_model_clean():
    rep = check_model(membership_model())
    assert rep.findings == [] and rep.states > 20


@pytest.mark.parametrize("mutation,rule", [
    ("join_fence", "DL302"), ("leave_flush", "DL303"),
    ("renorm", "DL304")])
def test_membership_mutations_each_caught_by_exactly_their_rule(
        mutation, rule):
    rep = check_model(membership_model(**{mutation: False}))
    assert sorted({f.rule for f in rep.findings}) == [rule]


# ------------------------------ joiner failover via the join-ACK roster

@pytest.mark.chaos
def test_joiner_survives_center_kill_via_join_ack_roster(tmp_path):
    """A Join?-admitted client never saw ``--centers`` on any command
    line — its failover dial list arrives in the join ACK.  Kill the
    primary and promote the advertised standby: the joiner re-enters
    through a fresh Join? under a new cid (its ephemeral dedicated
    listener died with the primary) and keeps syncing alongside the
    founding clients' Rejoin? failover."""
    from distlearn_tpu.parallel import ha
    from distlearn_tpu.parallel.async_ea import (AsyncEAClient,
                                                 AsyncEAServerConcurrent)

    host = "127.0.0.1"
    base = chaos._params()
    win_a = chaos._reserve_window(8, host)
    win_b = chaos._reserve_window(8, host)
    srv, clients, ps = chaos._spawn_fleet(
        host, win_a, 2, 1, ["raw"], False, [(host, win_b)], base,
        elastic=True, server_centers=[(host, win_b)])
    joiner = None
    try:
        srv.enable_checkpoint(str(tmp_path), every=1)
        for r in range(2):
            for i, cl in enumerate(clients):
                ps[i] = chaos._drift(ps[i], r)
                ps[i], _ = cl.sync_client(ps[i])
        joiner, pj = AsyncEAClient.join(host, win_a, chaos._params(),
                                        1, 0.5, sharded=False)
        # the ACK roster, not a flag, armed the joiner's failover()
        assert (host, win_b) in joiner._centers
        pj, _ = joiner.sync_client(chaos._drift(pj, 0))
        chaos._settle_fleet(clients + [joiner], srv)
        srv.checkpoint_now(wait=True)
        srv.stop(deadline=2.0)
        srv.close()
        srv = AsyncEAServerConcurrent(host, win_b, num_nodes=2, shards=1,
                                      handshake_timeout=5.0,
                                      rejoin_grace=60.0, standby=True,
                                      elastic=True)
        ha.promote(srv, str(tmp_path), base)
        srv.start()
        pj = chaos._sync_with_failover(joiner, chaos._drift(pj, 1))
        # re-entry was a fresh Join? (ephemeral dedicated port), not a
        # Rejoin? under the dead primary's roster
        assert joiner._ded_port is not None
        for i, cl in enumerate(clients):
            ps[i] = chaos._sync_with_failover(cl, chaos._drift(ps[i], 2))
        chaos._settle_fleet(clients + [joiner], srv)
        assert joiner.node in srv.members
    finally:
        chaos._teardown(clients + ([joiner] if joiner else []), srv)


# ------------------------------------------- diststat membership table

def _fam(name, value, kind="counter", labels=None, labelnames=()):
    return {"name": name, "kind": kind, "help": "",
            "labelnames": list(labelnames),
            "samples": [{"labels": labels or {}, "value": value}]}


def test_diststat_membership_table(tmp_path):
    import json

    import diststat
    recs = [
        {"type": "span", "name": "async_ea.join", "ts": 1.0, "dur": 0.2},
        {"type": "span", "name": "async_ea.leave", "ts": 1.5, "dur": 0.1},
        {"type": "snapshot", "ts": 2.0, "metrics": [
            _fam("async_ea_membership_joins_total", 2),
            _fam("async_ea_membership_join_failures_total", 1),
            {"name": "async_ea_membership_leaves_total", "kind": "counter",
             "help": "", "labelnames": ["outcome"],
             "samples": [{"labels": {"outcome": "flushed"}, "value": 1},
                         {"labels": {"outcome": "clean"}, "value": 1}]},
            _fam("async_ea_membership_size", 2, kind="gauge"),
            _fam("async_ea_adaptive_tau", 9, kind="gauge",
                 labels={"cid": "1"}, labelnames=["cid"]),
        ]},
    ]
    log = tmp_path / "run.jsonl"
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    tab = diststat.summarize_run([str(log)])["membership"]
    assert tab["joins"] == 2 and tab["join_failures"] == 1
    assert tab["leaves"] == {"clean": 1, "flushed": 1}
    assert tab["fleet_size"] == 2
    assert tab["adaptive_tau"] == {"1": 9}
    assert tab["latency"]["async_ea.join"]["count"] == 1


def test_diststat_membership_table_empty_on_fixed_fleet(tmp_path):
    import json

    import diststat
    log = tmp_path / "run.jsonl"
    log.write_text(json.dumps(
        {"type": "snapshot", "ts": 1.0, "metrics": [
            _fam("async_ea_syncs_total", 5)]}) + "\n")
    assert diststat.summarize_run([str(log)])["membership"] == {}


# ------------------------------------------------------ chaos scenarios

@pytest.mark.chaos
def test_scenario_flash_join_doubles_fleet_and_converges():
    report = chaos.run_scenario("flash_join", rounds=10)
    assert report["failures"] == []
    assert report["peak_members"] == 4
    assert report["dist"] <= report["tol"]


@pytest.mark.chaos
@pytest.mark.slow
def test_scenario_rolling_leave_returns_to_founding_fleet():
    report = chaos.run_scenario("rolling_leave", rounds=12)
    assert report["failures"] == []
    assert report["peak_members"] == 4 and report["final_members"] == 2


@pytest.mark.chaos
@pytest.mark.slow
def test_scenario_slow_node_stretches_tau_within_bounds():
    report = chaos.run_scenario("slow_node", rounds=12)
    assert report["failures"] == []
    lo, hi = report["tau_bounds"]
    assert lo < report["tau_slow"] <= hi
    assert report["tau_fast"] == lo
