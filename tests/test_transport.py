"""Transport-level protocol hardening tests (ADVICE r1): a desynced or
corrupt peer must produce a ProtocolError, never a buffer under/overrun,
and Server.accept must fail cleanly on timeout."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from distlearn_tpu.comm.transport import (Conn, ProtocolError, Server,
                                          connect)


def _pair():
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    a = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    a.connect(lst.getsockname())
    b, _ = lst.accept()
    lst.close()
    return Conn(a), Conn(b)


def test_tensor_roundtrip_and_buffer_reuse():
    tx, rx = _pair()
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    tx.send_tensor(arr)
    out = np.zeros((3, 4), np.float32)
    got = rx.recv_tensor(out=out)
    assert got is out
    np.testing.assert_array_equal(out, arr)
    tx.close(); rx.close()


def test_corrupt_frame_payload_size_rejected():
    """Frame length disagrees with header-declared shape*itemsize: the
    receiver must raise ProtocolError before touching the data buffer."""
    tx, rx = _pair()
    header = b'{"dtype": "float32", "shape": [4]}'
    payload = struct.pack("<I", len(header)) + header + b"\0" * 8  # 8 != 16
    tx._send_frame(ord("T"), payload)
    with pytest.raises(ProtocolError, match="payload"):
        rx.recv_tensor()
    tx.close(); rx.close()


def test_header_longer_than_frame_rejected():
    tx, rx = _pair()
    payload = struct.pack("<I", 10_000) + b"x" * 4
    tx._send_frame(ord("T"), payload)
    with pytest.raises(ProtocolError, match="header"):
        rx.recv_tensor()
    tx.close(); rx.close()


def test_negative_shape_rejected():
    tx, rx = _pair()
    header = b'{"dtype": "float32", "shape": [-1]}'
    payload = struct.pack("<I", len(header)) + header
    tx._send_frame(ord("T"), payload)
    with pytest.raises(ProtocolError):
        rx.recv_tensor()
    tx.close(); rx.close()


def test_recv_buffer_mismatch_rejected():
    tx, rx = _pair()
    tx.send_tensor(np.zeros(4, np.float32))
    with pytest.raises(ProtocolError, match="mismatch"):
        rx.recv_tensor(out=np.zeros(8, np.float32))
    tx.close(); rx.close()


def test_recv_buffer_mismatch_drains_payload():
    """The mismatch error must leave the connection frame-aligned: the
    offending payload is consumed, so the NEXT frame parses normally
    instead of tensor bytes being read as a header."""
    tx, rx = _pair()
    tx.send_tensor(np.arange(4, dtype=np.float32))
    tx.send_tensor(np.arange(6, dtype=np.float64))
    with pytest.raises(ProtocolError, match="mismatch"):
        rx.recv_tensor(out=np.zeros((2, 2), np.float32))  # shape skew
    got = rx.recv_tensor(out=np.zeros(6, np.float64))
    np.testing.assert_array_equal(got, np.arange(6, dtype=np.float64))
    tx.close(); rx.close()


def test_accept_timeout_restores_socket_and_names_count():
    srv = Server("127.0.0.1", 0)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="0 of 2"):
        srv.accept(2, timeout=0.2)
    assert time.monotonic() - t0 < 5.0
    # Listening socket must still work after the timeout (timeout cleared).
    done = threading.Event()

    def dial():
        c = connect("127.0.0.1", srv.port)
        done.set()
        c.close()

    th = threading.Thread(target=dial, daemon=True)
    th.start()
    got = srv.accept(1, timeout=5.0)
    assert len(got) == 1 and done.wait(2.0)
    srv.close()


def test_recv_any_drops_desynced_peer_keeps_serving():
    """A peer that puts a non-control frame (or garbage) on the control
    channel must be dropped by recv_any, not crash the server loop."""
    srv = Server("127.0.0.1", 0)
    bad = connect("127.0.0.1", srv.port)
    good = connect("127.0.0.1", srv.port)
    srv.accept(2, timeout=5.0)
    bad.send_tensor(np.arange(4, dtype=np.float32))   # wrong frame kind
    time.sleep(0.2)                                   # bad's frame lands first
    t = threading.Timer(0.5, lambda: good.send_msg({"q": "hello"}))
    t.start()
    # One call must survive the desynced peer and return the good message.
    _, msg = srv.recv_any(timeout=10.0)
    assert msg == {"q": "hello"}
    open_conns = [c for c in srv.conns if c.sock.fileno() >= 0]
    assert len(open_conns) == 1                       # bad peer was dropped
    t.join(); bad.close(); good.close(); srv.close()


def test_byte_counters_count_the_wire():
    """bytes_sent/received track frame+tensor payloads — the per-link
    traffic evidence the tree-vs-ring analysis reports."""
    tx, rx = _pair()
    arr = np.zeros(1024, np.float32)            # 4096 payload bytes
    tx.send_tensor(arr)
    rx.recv_tensor()
    assert tx.bytes_sent >= 4096
    assert tx.bytes_sent < 4096 + 256           # + frame/header overhead
    assert rx.bytes_received == tx.bytes_sent
    tx.send_msg({"q": "x"})
    rx.recv_msg()
    assert rx.bytes_received == tx.bytes_sent
    tx.close(); rx.close()


def test_throttle_paces_sends():
    """throttle_bps emulates a bandwidth-limited link: a 1 MB send at
    10 MB/s must take ~0.1s instead of the loopback's near-zero."""
    tx, rx = _pair()
    arr = np.zeros(1024 * 1024 // 4, np.float32)    # 1 MB
    got = {}
    t = threading.Thread(target=lambda: got.update(r=rx.recv_tensor()),
                         daemon=True)
    t.start()
    tx.throttle_bps = 10e6
    t0 = time.perf_counter()
    tx.send_tensor(arr)
    dt = time.perf_counter() - t0
    t.join(timeout=10)
    assert dt >= 0.08, dt
    assert got["r"].nbytes == arr.nbytes
    tx.close(); rx.close()


def test_mid_frame_fin_raises_reset_not_clean_eof():
    """A peer that dies after sending PART of a frame is a torn stream,
    not a finished peer: the read must raise ConnectionResetError (the
    abnormal-drop class recv_any's on_drop reports) while a FIN between
    frames stays the plain 'peer closed connection' ConnectionError —
    the discriminator the AsyncEA eviction/rejoin policy keys on."""
    import struct as _struct

    # FIN after 5 of 9 header bytes -> reset
    tx, rx = _pair()
    tx.sock.sendall(_struct.pack("<BQ", ord("J"), 64)[:5])
    tx.close()
    try:
        rx.recv_msg()
        raise AssertionError("expected ConnectionResetError")
    except ConnectionResetError:
        pass
    rx.close()

    # FIN after a complete header but before the payload -> reset
    tx, rx = _pair()
    tx.sock.sendall(_struct.pack("<BQ", ord("J"), 64))
    tx.close()
    try:
        rx.recv_msg()
        raise AssertionError("expected ConnectionResetError")
    except ConnectionResetError:
        pass
    rx.close()

    # FIN on a fresh frame boundary -> clean EOF (plain ConnectionError)
    tx, rx = _pair()
    tx.send_msg({"q": "bye"})
    tx.close()
    assert rx.recv_msg() == {"q": "bye"}
    try:
        rx.recv_msg()
        raise AssertionError("expected ConnectionError")
    except ConnectionResetError:
        raise AssertionError("clean EOF misread as reset")
    except ConnectionError:
        pass
    rx.close()


def test_clean_fin_is_peer_closed_type():
    """Drop-policy code classifies a clean FIN by TYPE — isinstance of
    PeerClosed — not by matching the exception's message string (which
    drifted between the Python and native receive paths).  A mid-frame
    FIN must NOT be PeerClosed: it is the reset subclass."""
    import struct as _struct

    from distlearn_tpu.comm import PeerClosed
    from distlearn_tpu.comm.errors import PeerClosed as PeerClosed2

    assert PeerClosed is PeerClosed2          # one canonical class
    assert issubclass(PeerClosed, ConnectionError)

    # clean FIN on a frame boundary -> PeerClosed, whichever recv path
    tx, rx = _pair()
    tx.close()
    try:
        rx.recv_msg()
        raise AssertionError("expected PeerClosed")
    except ConnectionError as e:
        assert isinstance(e, PeerClosed), e
        assert not isinstance(e, ConnectionResetError)
    rx.close()

    # FIN mid-frame -> reset, and NOT PeerClosed
    tx, rx = _pair()
    tx.sock.sendall(_struct.pack("<BQ", ord("J"), 64)[:5])
    tx.close()
    try:
        rx.recv_msg()
        raise AssertionError("expected ConnectionResetError")
    except ConnectionError as e:
        assert isinstance(e, ConnectionResetError), e
        assert not isinstance(e, PeerClosed)
    rx.close()


def test_recv_any_classifies_clean_fin_without_on_drop_callback():
    """Server.recv_any treats a PeerClosed as a finished peer (silent
    drop, no on_drop eviction) while keeping other conns served."""
    srv = Server("127.0.0.1", 0)
    quitter = connect("127.0.0.1", srv.port)
    good = connect("127.0.0.1", srv.port)
    srv.accept(2, timeout=5.0)
    quitter.close()                           # clean FIN, nothing sent
    time.sleep(0.1)
    dropped = []
    t = threading.Timer(0.3, lambda: good.send_msg({"q": "hi"}))
    t.start()
    _, msg = srv.recv_any(timeout=10.0,
                          on_drop=lambda i, e: dropped.append((i, e)))
    assert msg == {"q": "hi"}
    assert dropped == []                      # clean exit is not a drop
    t.join(); good.close(); srv.close()


def test_trickling_peer_cut_by_frame_deadline():
    """frame_timeout must bound the WHOLE frame read: a peer trickling one
    byte per just-under-timeout interval re-arms a kernel SO_RCVTIMEO on
    every byte and would wedge forever — the monotonic deadline cuts it."""
    import struct as _struct

    from distlearn_tpu.comm.transport import Server, connect

    srv = Server("127.0.0.1", 0)
    peer = connect("127.0.0.1", srv.port)
    srv.accept(1)

    stop = threading.Event()

    def trickle():
        hdr = _struct.pack("<BQ", ord("J"), 64)
        for b in hdr:
            if stop.is_set():
                return
            try:
                peer.sock.sendall(bytes([b]))
            except OSError:
                return
            time.sleep(0.3)

    t = threading.Thread(target=trickle, daemon=True)
    t.start()
    dropped = {}
    t0 = time.perf_counter()
    try:
        srv.recv_any(timeout=10.0, frame_timeout=0.5,
                     on_drop=lambda i, e: dropped.update(i=i, e=e))
        raise AssertionError("expected the trickler to be dropped")
    except TimeoutError:
        pass
    dt = time.perf_counter() - t0
    assert dt < 5.0, f"deadline did not bound the trickle ({dt:.1f}s)"
    assert "e" in dropped and isinstance(dropped["e"], TimeoutError)
    stop.set()
    peer.close()
    srv.close()
