"""MoE transformer LM: the expert-parallel training step over a
(data, seq) mesh with experts sharded on the data axis must match the
single-device all-experts-resident model exactly (same routing, no
capacity drops), and the MoE model must train."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import random
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.models.transformer import (lm_loss, param_specs,
                                              transformer_lm)
from distlearn_tpu.train.lm import build_lm_step

V, DIM, DEPTH, HEADS, L, B = 64, 32, 2, 4, 16, 4


def _model(**kw):
    return transformer_lm(vocab=V, dim=DIM, depth=DEPTH, heads=HEADS,
                          max_len=L, moe_experts=2, moe_every=2,
                          moe_capacity_factor=2.0, **kw)


def _tokens(seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, V, (B, L)),
                       jnp.int32)


def test_moe_lm_single_device_learns():
    lm = _model()
    params, _ = lm.init(random.PRNGKey(0))
    toks = _tokens()
    step = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda w, g: w - 0.5 * g, p,
        jax.grad(lambda q: lm_loss(lm, q, toks))(p)))
    l0 = float(lm_loss(lm, params, toks))
    for _ in range(10):
        params = step(params)
    l1 = float(lm_loss(lm, params, toks))
    assert l1 < l0 - 0.1, (l0, l1)


def test_moe_lm_param_specs_shard_expert_leaves():
    lm = _model()
    params, _ = lm.init(random.PRNGKey(0))
    specs = param_specs(params, tp_axis=None, ep_axis="data")
    blk = specs["block1"]             # block index 1 is the MoE block
    assert blk["we1"] == P("data") and blk["we2"] == P("data")
    assert blk["wb1"] == P("data")
    assert blk["router"] == P()
    assert specs["block0"]["w1"] == P()


def test_moe_lm_ep_step_matches_single_device():
    """One fused train step with experts sharded over the data axis ==
    one plain step with all experts resident (ample capacity)."""
    lm = _model()
    params, _ = lm.init(random.PRNGKey(1))
    toks = _tokens(2)
    lr = 0.3

    # single-device reference step (global mean loss; same objective)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: lm_loss(lm, p, toks))(params)
    ref_params = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, params, ref_grads)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                ("data", "seq", "model"))
    step = build_lm_step(lm, mesh, params, lr=lr, ep_axis="data")
    sharded = jax.device_put(
        params, jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            param_specs(params, tp_axis="model", ep_axis="data")))
    tok_sh = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    new_params, loss = step(sharded, tok_sh)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    flat_new = jax.tree_util.tree_leaves_with_path(new_params)
    flat_ref = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(ref_params))
    for path, leaf in flat_new:
        ref_leaf = flat_ref[jax.tree_util.keystr(path)]
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(path))


def test_moe_balance_loss_rebalances_collapsed_router():
    """The Switch auxiliary loss must actively push a skewed router back
    toward balance, where the plain LM loss leaves the skew in place —
    the failure mode of top-1 routing the aux term exists for
    (arXiv:2101.03961 §2.2).  Start from a router biased onto expert 0
    and train with and without the aux term."""
    from distlearn_tpu.train.lm import build_lm_moe_metrics

    lm = transformer_lm(vocab=V, dim=DIM, depth=DEPTH, heads=HEADS,
                        max_len=L, moe_experts=4, moe_every=2,
                        moe_capacity_factor=1.0)
    params0, _ = lm.init(random.PRNGKey(0))
    # collapse the router: W = [w, -w, 0, 0] — tokens with h@w > 0 go to
    # expert 0, the rest to expert 1, experts 2/3 are starved, and the
    # sharpening factor aligns the gate probabilities with the usage so
    # the f·P balance loss sees the collapse (~1.8 vs 1.0 balanced)
    w = params0["block1"]["router"][:, :1] * 4.0
    z = jnp.zeros_like(w)
    params0["block1"]["router"] = jnp.concatenate([w, -w, z, z], axis=1)

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                ("data", "seq", "model"))
    metrics = build_lm_moe_metrics(lm, mesh, params0, seq_axis=None,
                                   tp_axis=None)
    toks = _tokens(3)

    def train(weight, steps=60):
        step = build_lm_step(lm, mesh, params0, lr=0.2, seq_axis=None,
                             tp_axis=None, moe_balance_weight=weight,
                             donate=False)
        p = params0
        for _ in range(steps):
            p, _ = step(p, toks)
        return metrics(p, toks)

    m0 = jax.device_get(metrics(params0, toks))
    assert float(m0["moe_balance_loss"]) > 1.5   # skew is real at init
    m_no = jax.device_get(train(0.0))
    m_aux = jax.device_get(train(1.0))
    bal_no = float(m_no["moe_balance_loss"])
    bal_aux = float(m_aux["moe_balance_loss"])
    # without the aux term the router stays collapsed (nothing pushes it
    # back); with it, balance is restored most of the way toward 1.0
    assert bal_no > 1.5, (bal_no, bal_aux)
    assert bal_aux < 1.25, (bal_no, bal_aux)
    assert bal_aux < bal_no - 0.25
    # capacity 1.0 + collapse = drops; the rebalanced router drops less
    assert float(m_aux["moe_dropped_frac"]) \
        <= float(m_no["moe_dropped_frac"])


def test_moe_config_validation():
    import pytest
    with pytest.raises(ValueError, match="silently train dense"):
        transformer_lm(vocab=V, dim=DIM, depth=2, heads=HEADS, max_len=L,
                       moe_experts=4, moe_every=4)
    with pytest.raises(ValueError, match="moe_every >= 1"):
        transformer_lm(vocab=V, dim=DIM, depth=2, heads=HEADS, max_len=L,
                       moe_experts=4, moe_every=0)
