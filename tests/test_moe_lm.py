"""MoE transformer LM: the expert-parallel training step over a
(data, seq) mesh with experts sharded on the data axis must match the
single-device all-experts-resident model exactly (same routing, no
capacity drops), and the MoE model must train."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import random
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.models.transformer import (lm_loss, param_specs,
                                              transformer_lm)
from distlearn_tpu.train.lm import build_lm_step

V, DIM, DEPTH, HEADS, L, B = 64, 32, 2, 4, 16, 4


def _model(**kw):
    return transformer_lm(vocab=V, dim=DIM, depth=DEPTH, heads=HEADS,
                          max_len=L, moe_experts=2, moe_every=2,
                          moe_capacity_factor=2.0, **kw)


def _tokens(seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, V, (B, L)),
                       jnp.int32)


def test_moe_lm_single_device_learns():
    lm = _model()
    params, _ = lm.init(random.PRNGKey(0))
    toks = _tokens()
    step = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda w, g: w - 0.5 * g, p,
        jax.grad(lambda q: lm_loss(lm, q, toks))(p)))
    l0 = float(lm_loss(lm, params, toks))
    for _ in range(10):
        params = step(params)
    l1 = float(lm_loss(lm, params, toks))
    assert l1 < l0 - 0.1, (l0, l1)


def test_moe_lm_param_specs_shard_expert_leaves():
    lm = _model()
    params, _ = lm.init(random.PRNGKey(0))
    specs = param_specs(params, tp_axis=None, ep_axis="data")
    blk = specs["block1"]             # block index 1 is the MoE block
    assert blk["we1"] == P("data") and blk["we2"] == P("data")
    assert blk["wb1"] == P("data")
    assert blk["router"] == P()
    assert specs["block0"]["w1"] == P()


def test_moe_lm_ep_step_matches_single_device():
    """One fused train step with experts sharded over the data axis ==
    one plain step with all experts resident (ample capacity)."""
    lm = _model()
    params, _ = lm.init(random.PRNGKey(1))
    toks = _tokens(2)
    lr = 0.3

    # single-device reference step (global mean loss; same objective)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: lm_loss(lm, p, toks))(params)
    ref_params = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, params, ref_grads)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                ("data", "seq", "model"))
    step = build_lm_step(lm, mesh, params, lr=lr, ep_axis="data")
    sharded = jax.device_put(
        params, jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            param_specs(params, tp_axis="model", ep_axis="data")))
    tok_sh = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    new_params, loss = step(sharded, tok_sh)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    flat_new = jax.tree_util.tree_leaves_with_path(new_params)
    flat_ref = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(ref_params))
    for path, leaf in flat_new:
        ref_leaf = flat_ref[jax.tree_util.keystr(path)]
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(path))


def test_moe_config_validation():
    import pytest
    with pytest.raises(ValueError, match="silently train dense"):
        transformer_lm(vocab=V, dim=DIM, depth=2, heads=HEADS, max_len=L,
                       moe_experts=4, moe_every=4)
    with pytest.raises(ValueError, match="moe_every >= 1"):
        transformer_lm(vocab=V, dim=DIM, depth=2, heads=HEADS, max_len=L,
                       moe_experts=4, moe_every=0)
