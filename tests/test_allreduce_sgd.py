"""AllReduceSGD invariants, mirroring test/test_AllReduceSGD.lua.

Reference oracle: randomized trials over 2/4/8 nodes where each node performs a
random (uneven) number of steps per epoch — 4..13 (lua :13) — of
fill-random-grads / sumAndNormalizeGradients / SGD update, then
``synchronizeParameters``; afterwards params must be **bitwise identical** on
every node (lua :38).  Uneven per-node step counts are expressed with
participation masks (the gang-scheduled-mesh equivalent of the reference's
flush allreduce — SURVEY.md §7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distlearn_tpu.parallel import allreduce_sgd as ars
from distlearn_tpu.parallel.mesh import MeshTree


def _param_like(rng, num_nodes, shapes):
    """Identical initial params on every node (ref: torch.manualSeed(0))."""
    base = [rng.standard_normal(s).astype(np.float32) for s in shapes]
    return [np.broadcast_to(b[None], (num_nodes,) + b.shape).copy() for b in base]


SHAPES = [(5, 3), (7,), (2, 4, 3)]


@pytest.mark.parametrize("trial", range(5))
def test_params_bitwise_equal_after_sync_host_api(trial):
    rng = np.random.default_rng(trial)
    num_nodes = int(rng.choice([2, 4, 8]))
    tree = MeshTree(num_nodes=num_nodes)
    sgd = ars.AllReduceSGD(tree)

    params = tree.put_per_node(_param_like(rng, num_nodes, SHAPES))
    lr = 0.01

    for _epoch in range(3):
        steps_per_node = rng.integers(4, 14, size=num_nodes)
        max_steps = int(steps_per_node.max())
        for s in range(max_steps):
            contrib = (s < steps_per_node).astype(np.int32)
            # Each contributing node produces its own random gradient.
            grads = [rng.standard_normal((num_nodes,) + sh).astype(np.float32)
                     for sh in SHAPES]
            grads = tree.put_per_node(grads)
            summed, n = sgd.sum_and_normalize_gradients(grads, contrib=contrib)
            assert n == int(contrib.sum())
            # SGD update only on contributing nodes (a node that didn't step
            # leaves its params untouched, as in the reference).
            params = [
                p - lr * g * jnp.asarray(contrib, jnp.float32).reshape(
                    (num_nodes,) + (1,) * (p.ndim - 1))
                for p, g in zip(params, summed)
            ]
        params = sgd.synchronize_parameters(params)
        rows = [tree.node_slice(params, i) for i in range(num_nodes)]
        for i in range(1, num_nodes):
            for a, b in zip(rows[0], rows[i]):
                assert np.array_equal(a, b), "params differ bitwise after sync"


def test_winner_takes_all_semantics():
    """The node with the most steps provides the synced params (lua :41-47);
    ties go to the highest node index (sort-ascending, take last)."""
    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    sgd = ars.AllReduceSGD(tree)
    params = tree.put_per_node(
        np.arange(num_nodes * 2, dtype=np.float32).reshape(num_nodes, 2))

    # node 2 steps twice, node 1 steps once, others none
    for contrib in ([0, 1, 1, 0], [0, 0, 1, 0]):
        grads = tree.put_per_node(np.zeros((num_nodes, 2), np.float32))
        sgd.sum_and_normalize_gradients(grads, contrib=np.array(contrib, np.int32))
    synced = sgd.synchronize_parameters(params)
    for i in range(num_nodes):
        np.testing.assert_array_equal(
            tree.node_slice(synced, i), np.array([4.0, 5.0]))  # node 2's row


def test_no_steps_scatters_from_root():
    """With zero steps this epoch, sync degenerates to scatter from node 0 (lua :52)."""
    num_nodes = 4
    tree = MeshTree(num_nodes=num_nodes)
    sgd = ars.AllReduceSGD(tree)
    params = tree.put_per_node(
        np.arange(num_nodes * 2, dtype=np.float32).reshape(num_nodes, 2))
    synced = sgd.synchronize_parameters(params)
    for i in range(num_nodes):
        np.testing.assert_array_equal(
            tree.node_slice(synced, i), np.array([0.0, 1.0]))


@pytest.mark.parametrize("trial", range(3))
def test_in_step_api_inside_one_jitted_step(trial):
    """The hot path: grads psum + normalize + update fused in ONE shard_map'd
    jitted step; params stay replicated and bitwise identical by construction."""
    rng = np.random.default_rng(100 + trial)
    num_nodes = 8
    tree = MeshTree(num_nodes=num_nodes)
    axis = tree.axis_name

    def step(params, grads, state, contrib):
        grads = jnp.squeeze(grads, 0)
        contrib = jnp.squeeze(contrib, 0)
        state = ars.SGDSyncState(my_steps=jnp.squeeze(state.my_steps, 0))
        g, st, n = ars.sum_and_normalize_gradients(grads, state, contrib, axis)
        # Replicated-params DP: the psum'd gradient is identical on every node,
        # so all nodes (contributing or not) apply the same update and params
        # never drift — the TPU-first design that makes winner-takes-all sync
        # a no-op in the fused trainer.
        new_p = params - 0.1 * g
        return new_p, g[None], ars.SGDSyncState(my_steps=st.my_steps[None]), n[None]

    fn = tree.spmd(step,
                   in_specs=(P(), P(axis), ars.SGDSyncState(my_steps=P(axis)), P(axis)),
                   out_specs=(P(), P(axis), ars.SGDSyncState(my_steps=P(axis)), P(axis)))

    params = jnp.asarray(rng.standard_normal(6).astype(np.float32))
    grads = rng.standard_normal((num_nodes, 6)).astype(np.float32)
    contrib = np.array([1, 1, 0, 1, 0, 1, 1, 1], np.float32)
    state = ars.SGDSyncState(my_steps=np.zeros(num_nodes, np.int32))

    new_p, g, state, n = fn(params, grads, state, contrib)
    expected_g = (grads * contrib[:, None]).sum(0) / contrib.sum()
    np.testing.assert_allclose(np.asarray(g)[0], expected_g, rtol=1e-6)
    assert np.asarray(n)[0] == 6
    np.testing.assert_array_equal(np.asarray(state.my_steps), contrib.astype(np.int32))
    # masked nodes left params untouched... params are replicated: updated once
    np.testing.assert_allclose(
        np.asarray(new_p), np.asarray(params) - 0.1 * expected_g, rtol=1e-6)
