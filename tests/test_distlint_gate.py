"""Tier-1 distlint gate: every registered step family lints clean.

This is the CI wiring for tools/distlint.py — the same registry the CLI
runs is executed in-process over the conftest's 8-device CPU mesh, so a
change that introduces a branch-divergent collective, a shared PRNG key,
an f16 psum, a wasted donation, or a comm-schedule deadlock fails tier-1
with the rule id and jaxpr path in the assertion message.
"""

import pytest

from distlearn_tpu.lint import registry
from distlearn_tpu.lint.core import format_findings

pytestmark = pytest.mark.lint


@pytest.mark.parametrize("family", sorted(registry.families()))
def test_family_lints_clean(family, devices):
    results = registry.run_family(family)
    assert results, f"family {family!r} registered no units"
    report = "\n".join(format_findings(r.findings, header=f"{r.name}:")
                       for r in results if r.findings)
    assert all(r.ok for r in results), f"distlint findings:\n{report}"


def _tool_or_skip(tool: str, require_var: str):
    """Resolve an external lint tool.  A tool-less environment skips —
    unless ``require_var`` is set (CI installs ``.[lint]`` and sets it),
    in which case a missing binary is a hard gate failure instead of a
    silent pass."""
    import os
    import shutil
    path = shutil.which(tool)
    if path is None:
        if os.environ.get(require_var):
            pytest.fail(f"{require_var} is set but no {tool!r} binary is "
                        f"on PATH — install the 'lint' extra "
                        f"(pip install .[lint])")
        pytest.skip(f"{tool} not installed in this environment")
    return path


def test_ruff_clean_repo_wide():
    """Enforce the [tool.ruff] config over the whole repo (the PR-1 config
    only gated the lint package); skipped where the container has no ruff
    binary, FAILED if DISTLEARN_REQUIRE_RUFF=1 promises one."""
    import os
    import subprocess
    ruff = _tool_or_skip("ruff", "DISTLEARN_REQUIRE_RUFF")
    root = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run([ruff, "check", "."],
                          cwd=root, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_mypy_clean():
    """Typecheck distlearn_tpu/lint + distlearn_tpu/serve with the
    committed [tool.mypy] config; skip-if-absent like ruff, enforced
    under DISTLEARN_REQUIRE_MYPY=1."""
    import os
    import subprocess
    mypy = _tool_or_skip("mypy", "DISTLEARN_REQUIRE_MYPY")
    root = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run([mypy, "--config-file", "pyproject.toml"],
                          cwd=root, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_runs_protocol_family_in_process(devices):
    """Exercise the argument/exit-code surface without a subprocess (the
    jax import cost is already paid)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "distlint_cli", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "distlint.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    assert cli.main(["--family", "protocol", "-q"]) == 0
    assert cli.main(["--list"]) == 0
    assert cli.main(["--family", "nope"]) == 2
    assert cli.main([]) == 2
    assert cli.main(["--family", "protocol", "--disable", "DL999"]) == 2


def test_cli_json_schema_covers_serve_rules(devices, capsys):
    """The JSON document advertises the serve-path rules and the per-family
    compile summary — the machine surface downstream dashboards key on."""
    import importlib.util
    import json
    import os
    spec = importlib.util.spec_from_file_location(
        "distlint_cli_json", os.path.join(os.path.dirname(__file__), "..",
                                          "tools", "distlint.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    assert cli.main(["--family", "decode", "--family", "races",
                     "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    for rule in ("DL206", "DL207", "DL208", "DL209"):
        assert rule in doc["rules"]
    # 10 programs: tick + verify + 4 prefill buckets + 4 chunk buckets
    # (the committed decode.json budget pins the exact set)
    assert doc["compiles"]["decode"]["count"] == 10, doc["compiles"]
    assert doc["compiles"]["decode"]["warmup_s_estimate"] > 0
    assert doc["errors"] == 0
