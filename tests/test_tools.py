"""tools/make_npz.py — converter tests (fake raw dumps -> npz schema)."""

import gzip
import os
import pickle
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import make_npz  # noqa: E402

from distlearn_tpu.data import load_npz  # noqa: E402


def _write_idx_images(path, images: np.ndarray, gz=False):
    header = struct.pack(">IIII", 0x00000803, *images.shape)
    opener = gzip.open if gz else open
    with opener(path + (".gz" if gz else ""), "wb") as fh:
        fh.write(header + images.tobytes())


def _write_idx_labels(path, labels: np.ndarray):
    with open(path, "wb") as fh:
        fh.write(struct.pack(">II", 0x00000801, len(labels)) + labels.tobytes())


def test_mnist_idx_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (12, 28, 28)).astype(np.uint8)
    labels = rng.randint(0, 10, 12).astype(np.uint8)
    # train as .gz (converter must accept both), test as raw
    _write_idx_images(str(tmp_path / "train-images-idx3-ubyte"), imgs, gz=True)
    _write_idx_labels(str(tmp_path / "train-labels-idx1-ubyte"), labels)
    _write_idx_images(str(tmp_path / "t10k-images-idx3-ubyte"), imgs[:5])
    _write_idx_labels(str(tmp_path / "t10k-labels-idx1-ubyte"), labels[:5])

    out = str(tmp_path / "mnist.npz")
    assert make_npz.main(["mnist", str(tmp_path), "-o", out]) == 0
    x, y, nc = load_npz(out)
    assert x.shape == (12, 32, 32, 1) and x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0
    np.testing.assert_array_equal(x[:, 2:30, 2:30, 0],
                                  imgs.astype(np.float32) / 255.0)
    np.testing.assert_array_equal(y, labels.astype(np.int32))
    xt, yt, _ = load_npz(str(tmp_path / "mnist_test.npz"))
    assert xt.shape == (5, 32, 32, 1) and len(yt) == 5


def test_cifar10_pickle_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    per = 4
    all_data, all_labels = [], []
    for i in range(1, 6):
        data = rng.randint(0, 256, (per, 3 * 32 * 32)).astype(np.uint8)
        labels = rng.randint(0, 10, per).tolist()
        with open(d / f"data_batch_{i}", "wb") as fh:
            pickle.dump({b"data": data, b"labels": labels}, fh)
        all_data.append(data)
        all_labels += labels
    with open(d / "test_batch", "wb") as fh:
        pickle.dump({b"data": all_data[0], b"labels": all_labels[:per]}, fh)

    out = str(tmp_path / "cifar10.npz")
    assert make_npz.main(["cifar10", str(tmp_path), "-o", out]) == 0
    x, y, nc = load_npz(out)
    assert x.shape == (20, 32, 32, 3) and x.dtype == np.float32
    np.testing.assert_array_equal(y, np.asarray(all_labels, np.int32))
    # channel layout: pickles are CHW-flat; npz must be NHWC
    ref = all_data[0].reshape(per, 3, 32, 32).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(x[:per], ref.astype(np.float32) / 255.0)
    xt, yt, _ = load_npz(str(tmp_path / "cifar10_test.npz"))
    assert xt.shape == (per, 32, 32, 3)


def test_plot_errors_renders_tester_jsonl(tmp_path):
    """tools/plot_errors.py renders the tester's JSONL into an image —
    the optim.Logger+gnuplot half of the reference's tester
    (EASGD_tester.lua:161-165) the JSONL log replaced."""
    import importlib.util
    import json as _json
    import subprocess
    import sys as _sys

    if importlib.util.find_spec("matplotlib") is None:
        import pytest
        pytest.skip("matplotlib not installed")
    log = tmp_path / "tester.jsonl"
    log.write_text("\n".join(
        _json.dumps({"round": i, "train_error": 0.8 / i,
                     "test_error": 0.9 / i}) for i in range(1, 4)) + "\n")
    out = tmp_path / "curve.png"
    import pathlib
    tool = pathlib.Path(__file__).parent.parent / "tools" / "plot_errors.py"
    res = subprocess.run([_sys.executable, str(tool), str(log),
                          "-o", str(out)], capture_output=True, text=True,
                         timeout=300)
    assert res.returncode == 0, res.stderr
    assert out.exists() and out.stat().st_size > 1000


def _distlint_cli():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "distlint_cli", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "distlint.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    return cli


def test_distlint_json_format_and_update_budgets(tmp_path, capsys):
    """tools/distlint.py --format json and --update-budgets, in-process
    (the jax import cost is already paid), against a throwaway budget dir:
    no lockfile -> DL203 in the JSON findings and exit 1; --update-budgets
    writes the lockfile; the re-run is clean with populated cost tables."""
    import json as _json
    cli = _distlint_cli()
    bdir = str(tmp_path / "budgets")

    assert cli.main(["--family", "ep", "--format", "json",
                     "--budget-dir", bdir]) == 1
    doc = _json.loads(capsys.readouterr().out)
    assert any(f["rule"] == "DL203" for f in doc["findings"])
    assert "moe_fwd" in doc["costs"]["ep"]

    assert cli.main(["--update-budgets", "--family", "ep",
                     "--budget-dir", bdir]) == 0
    capsys.readouterr()
    assert os.path.exists(os.path.join(bdir, "ep.json"))

    assert cli.main(["--family", "ep", "--format", "json",
                     "--budget-dir", bdir]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert doc["findings"] == [] and doc["errors"] == 0
    table = doc["costs"]["ep"]["moe_fwd"]
    assert table["collective_bytes"].get("all-to-all", 0) > 0
    assert table["peak_bytes"] is None or table["peak_bytes"] > 0


def test_distlint_model_and_races_flags(capsys):
    """--model/--races shorthands: exit 0 on the clean tree, text output
    carries the exhaustive state counts, and the JSON schema is stable
    (findings/costs/info/units/errors with per-model state counts)."""
    import json as _json
    cli = _distlint_cli()

    assert cli.main(["--model", "--races"]) == 0
    out = capsys.readouterr().out
    assert "model:sync: OK (" in out and "states)" in out
    assert "races:lockset: OK" in out
    assert "races:router: OK" in out
    assert "model:conformance: OK" in out
    assert "model:serve_frames: OK" in out

    assert cli.main(["--model", "--races", "--format", "json"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert set(doc) == {"findings", "costs", "compiles", "rules", "info",
                        "units", "errors"}
    assert doc["findings"] == [] and doc["errors"] == 0
    assert doc["units"] == 13
    for unit in ("model:sync", "model:sharded", "model:replay",
                 "model:failover", "model:serve", "model:membership",
                 "model:router", "model:backend_sync[host]",
                 "model:backend_sync[hybrid]"):
        assert doc["info"][unit]["states"] > 0
        assert doc["info"][unit]["transitions"] > 0


def test_ea_convergence_tool_runs():
    """Smoke the EASGD-vs-SGD convergence harness end-to-end (tiny budget,
    2 ranks, throttled links): both algorithms complete, curves land on
    disk, and the losses are finite."""
    import subprocess
    import sys
    out = tmp = None
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        r = subprocess.run(
            [sys.executable, "tools/ea_convergence.py", "--ranks", "2",
             "--budget", "1.5", "--linkMBs", "50", "--out", tmp],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr[-800:]
        assert "sgd" in r.stdout and "ea_tau16" in r.stdout
        files = os.listdir(tmp)
        assert any(f.startswith("sgd") for f in files), files
        assert any(f.startswith("ea_tau") for f in files), files


# -- tools/diststat.py -------------------------------------------------------

def _fixture_run(path, syncs=3, base_dur=0.010):
    """Write a small but structurally complete obs JSONL run: spans (one
    errored), two snapshots (diststat must use the LAST), counters with
    and without labels, a gauge, a histogram."""
    import json as _json
    recs = []
    for i in range(syncs):
        recs.append({"type": "span", "name": "async_ea.handshake",
                     "ts": 1000.0 + i, "dur": base_dur * (i + 1),
                     "labels": {"cid": 1}})
    recs.append({"type": "span", "name": "async_ea.handshake",
                 "ts": 1000.5, "dur": 0.5, "err": "TimeoutError"})
    mk = lambda n: {"type": "snapshot", "ts": 2000.0 + n, "metrics": [
        {"name": "async_ea_syncs_total", "kind": "counter", "help": "",
         "labelnames": [], "samples": [{"labels": {}, "value": n}]},
        {"name": "transport_bytes_sent_total", "kind": "counter",
         "help": "", "labelnames": ["conn"],
         "samples": [{"labels": {"conn": "0"}, "value": 100 * n},
                     {"labels": {"conn": "1"}, "value": 50 * n}]},
        {"name": "async_ea_inflight", "kind": "gauge", "help": "",
         "labelnames": [], "samples": [{"labels": {}, "value": 0}]},
        {"name": "transport_frame_recv_seconds", "kind": "histogram",
         "help": "", "labelnames": [],
         "samples": [{"labels": {}, "sum": 0.25 * n, "count": 5 * n,
                      "buckets": {"0.001": 2 * n, "1.0": 3 * n},
                      "inf": 0}]},
    ]}
    recs.append(mk(1))       # an intermediate snapshot...
    recs.append(mk(syncs))   # ...must be superseded by the final one
    with open(path, "w") as fh:
        for r in recs:
            fh.write(_json.dumps(r) + "\n")
        fh.write("{torn line\n")   # live-run tail: must be skipped


def test_diststat_summarize(tmp_path, capsys):
    import json as _json
    import diststat

    log = str(tmp_path / "run.jsonl")
    _fixture_run(log, syncs=3)
    assert diststat.main(["summarize", log, "--format", "json"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    hs = doc["spans"]["async_ea.handshake"]
    assert hs["count"] == 4 and hs["errors"] == 1
    assert abs(hs["p50"] - 0.030) < 1e-9        # sorted durs: 10/20/30/500ms
    assert abs(hs["p95"] - 0.5) < 1e-9
    assert doc["counter_totals"]["async_ea_syncs_total"] == 3   # LAST snapshot
    assert doc["counter_totals"]["transport_bytes_sent_total"] == 450
    assert doc["counters"]['transport_bytes_sent_total{conn="0"}'] == 300
    assert doc["gauges"]["async_ea_inflight"] == 0
    assert doc["histograms"]["transport_frame_recv_seconds"]["count"] == 15
    # text mode renders without blowing up
    assert diststat.main(["summarize", log]) == 0
    out = capsys.readouterr().out
    assert "async_ea.handshake" in out and "p95" in out


def test_diststat_summarize_merges_files(tmp_path):
    import diststat

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _fixture_run(a, syncs=2)
    _fixture_run(b, syncs=3)
    doc = diststat.summarize_run([a, b])
    # spans concatenate; counters sum across files (per-process logs)
    assert doc["spans"]["async_ea.handshake"]["count"] == 7
    assert doc["counter_totals"]["async_ea_syncs_total"] == 5


def test_diststat_diff(tmp_path, capsys):
    import json as _json
    import diststat

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _fixture_run(a, syncs=2, base_dur=0.010)
    _fixture_run(b, syncs=4, base_dur=0.020)
    assert diststat.main(["diff", a, b, "--format", "json"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    row = doc["counters"]["async_ea_syncs_total"]
    assert row == {"a": 2, "b": 4, "delta": 2}
    assert doc["spans"]["async_ea.handshake"]["count"] == {"a": 3, "b": 5}
    assert diststat.main(["diff", a, b]) == 0          # text mode
    assert "async_ea_syncs_total" in capsys.readouterr().out


def test_diststat_cli_errors(tmp_path, capsys):
    import diststat

    assert diststat.main([]) == 2                      # no subcommand
    assert diststat.main(["summarize",
                          str(tmp_path / "missing.jsonl")]) == 2
