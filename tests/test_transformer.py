"""Transformer LM: sequence-parallel (ring attention) and tensor-parallel
outputs must match the single-device model exactly (same full params)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.models.transformer import (lm_loss, param_specs,
                                              transformer_lm)


def _model_and_batch(seed=0, L=32):
    model = transformer_lm(vocab=64, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(seed))
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, 64, (2, L)).astype(np.int32))
    return model, params, tokens


def test_seq_parallel_matches_local():
    model, params, tokens = _model_and_batch()
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, seq_axis="seq")[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_tensor_parallel_matches_local():
    model, params, tokens = _model_and_batch(1)
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    specs = param_specs(params, "model")
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, tp_axis="model")[0],
        mesh=mesh, in_specs=(specs, P()),
        out_specs=P(), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_seq_x_tensor_2d_mesh():
    """Combined SP x TP over a 2D mesh: still exact."""
    model, params, tokens = _model_and_batch(2)
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("seq", "model"))
    specs = param_specs(params, "model")
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, seq_axis="seq",
                                 tp_axis="model")[0],
        mesh=mesh, in_specs=(specs, P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_lm_loss_seq_parallel_matches_local():
    model, params, tokens = _model_and_batch(3)
    ref = lm_loss(model, params, tokens)

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    mapped = jax.jit(jax.shard_map(
        lambda p, t: lm_loss(model, p, t, seq_axis="seq"),
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P(),
        check_vma=False))
    np.testing.assert_allclose(float(mapped(params, tokens)), float(ref),
                               rtol=1e-4)


def test_lm_gradients_flow():
    model, params, tokens = _model_and_batch(4)
    grads = jax.grad(lambda p: lm_loss(model, p, tokens))(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()


def test_remat_matches_no_remat():
    """jax.checkpoint'ed blocks: identical logits and gradients, just a
    different backward-pass memory/compute trade."""
    import numpy as np
    from jax import random
    from distlearn_tpu.models.transformer import lm_loss, transformer_lm

    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)),
                       jnp.int32)
    outs, grads = {}, {}
    for remat in (False, True, "mlp"):
        lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16,
                            remat=remat)
        params, _ = lm.init(random.PRNGKey(0))
        outs[remat] = np.asarray(lm.apply(params, {}, toks)[0])
        grads[remat] = jax.grad(
            lambda p: lm_loss(lm, p, toks))(params)
    for mode in (True, "mlp"):
        np.testing.assert_allclose(outs[False], outs[mode],
                                   rtol=1e-6, atol=1e-7)
        for a, b in zip(jax.tree_util.tree_leaves(grads[False]),
                        jax.tree_util.tree_leaves(grads[mode])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)


def test_remat_mode_validation():
    from distlearn_tpu.models.transformer import transformer_lm
    with pytest.raises(ValueError, match="remat"):
        transformer_lm(vocab=8, dim=8, depth=1, heads=1, remat="bogus")
