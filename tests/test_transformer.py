"""Transformer LM: sequence-parallel (ring attention) and tensor-parallel
outputs must match the single-device model exactly (same full params)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.models.transformer import (lm_loss, param_specs,
                                              transformer_lm)


def _model_and_batch(seed=0, L=32):
    model = transformer_lm(vocab=64, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(seed))
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, 64, (2, L)).astype(np.int32))
    return model, params, tokens


def test_seq_parallel_matches_local():
    model, params, tokens = _model_and_batch()
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, seq_axis="seq")[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_tensor_parallel_matches_local():
    model, params, tokens = _model_and_batch(1)
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    specs = param_specs(params, "model")
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, tp_axis="model")[0],
        mesh=mesh, in_specs=(specs, P()),
        out_specs=P(), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_seq_x_tensor_2d_mesh():
    """Combined SP x TP over a 2D mesh: still exact."""
    model, params, tokens = _model_and_batch(2)
    ref_logits, _ = model.apply(params, {}, tokens, train=False)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("seq", "model"))
    specs = param_specs(params, "model")
    mapped = jax.jit(jax.shard_map(
        lambda p, t: model.apply(p, {}, t, seq_axis="seq",
                                 tp_axis="model")[0],
        mesh=mesh, in_specs=(specs, P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    out = mapped(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_lm_loss_seq_parallel_matches_local():
    model, params, tokens = _model_and_batch(3)
    ref = lm_loss(model, params, tokens)

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    mapped = jax.jit(jax.shard_map(
        lambda p, t: lm_loss(model, p, t, seq_axis="seq"),
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P(),
        check_vma=False))
    np.testing.assert_allclose(float(mapped(params, tokens)), float(ref),
                               rtol=1e-4)


def test_lm_gradients_flow():
    model, params, tokens = _model_and_batch(4)
    grads = jax.grad(lambda p: lm_loss(model, p, tokens))(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()


def test_remat_matches_no_remat():
    """jax.checkpoint'ed blocks: identical logits and gradients, just a
    different backward-pass memory/compute trade."""
    import numpy as np
    from jax import random
    from distlearn_tpu.models.transformer import lm_loss, transformer_lm

    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)),
                       jnp.int32)
    outs, grads = {}, {}
    for remat in (False, True, "mlp"):
        lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16,
                            remat=remat)
        params, _ = lm.init(random.PRNGKey(0))
        outs[remat] = np.asarray(lm.apply(params, {}, toks)[0])
        grads[remat] = jax.grad(
            lambda p: lm_loss(lm, p, toks))(params)
    for mode in (True, "mlp"):
        np.testing.assert_allclose(outs[False], outs[mode],
                                   rtol=1e-6, atol=1e-7)
        for a, b in zip(jax.tree_util.tree_leaves(grads[False]),
                        jax.tree_util.tree_leaves(grads[mode])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)


def test_remat_mode_validation():
    from distlearn_tpu.models.transformer import transformer_lm
    with pytest.raises(ValueError, match="remat"):
        transformer_lm(vocab=8, dim=8, depth=1, heads=1, remat="bogus")


def test_scan_blocks_matches_unrolled():
    """The scanned-depth layout is the same function: identical logits and
    gradients once the parameters are stacked."""
    from distlearn_tpu.models.transformer import (lm_loss,
                                                  stack_block_params,
                                                  transformer_lm,
                                                  unstack_block_params)
    depth = 3
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)),
                       jnp.int32)
    lm_u = transformer_lm(vocab=64, dim=32, depth=depth, heads=4, max_len=16)
    lm_s = transformer_lm(vocab=64, dim=32, depth=depth, heads=4, max_len=16,
                          scan_blocks=True)
    params_u, _ = lm_u.init(jax.random.PRNGKey(0))
    params_s = stack_block_params(params_u, depth)
    # round trip
    rt = unstack_block_params(params_s, depth)
    for a, b in zip(jax.tree_util.tree_leaves(params_u),
                    jax.tree_util.tree_leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    out_u = np.asarray(lm_u.apply(params_u, {}, toks)[0])
    out_s = np.asarray(lm_s.apply(params_s, {}, toks)[0])
    # same math, different op order (gathered stacked leaves): f32 noise
    np.testing.assert_allclose(out_s, out_u, rtol=2e-5, atol=5e-6)

    g_u = jax.grad(lambda p: lm_loss(lm_u, p, toks))(params_u)
    g_s = jax.grad(lambda p: lm_loss(lm_s, p, toks))(params_s)
    g_s_unstacked = unstack_block_params(g_s, depth)
    for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(g_u)[0],
                   key=lambda t: str(t[0])),
            sorted(jax.tree_util.tree_flatten_with_path(g_s_unstacked)[0],
                   key=lambda t: str(t[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-6, err_msg=str(pa))


def test_scan_blocks_program_size_flat_in_depth():
    """The point of the scanned layout: the jitted program stops growing
    ~linearly with depth (the unrolled loop's growth is what made deep
    long-context configs exceed compile limits)."""
    from distlearn_tpu.models.transformer import lm_loss, transformer_lm

    def hlo_len(depth, scan):
        lm = transformer_lm(vocab=64, dim=32, depth=depth, heads=4,
                            max_len=16, scan_blocks=scan)
        params, _ = lm.init(jax.random.PRNGKey(0))
        toks = jnp.zeros((1, 16), jnp.int32)
        f = jax.jit(jax.grad(lambda p: lm_loss(lm, p, toks)))
        return len(f.lower(params).as_text())

    grow_unrolled = hlo_len(8, False) / hlo_len(2, False)
    grow_scanned = hlo_len(8, True) / hlo_len(2, True)
    assert grow_unrolled > 2.5, grow_unrolled    # ~4x expected
    assert grow_scanned < 1.4, grow_scanned      # ~flat


def test_scan_blocks_with_lm_step_and_tp():
    """The scanned layout composes with the fused train step: param_specs
    shifts the TP axes one right for the stacked leaves."""
    from distlearn_tpu.models.transformer import (lm_loss,
                                                  stack_block_params,
                                                  transformer_lm)
    from distlearn_tpu.train.lm import build_lm_step

    depth, L = 2, 32
    lm_u = transformer_lm(vocab=32, dim=32, depth=depth, heads=4, max_len=L)
    lm_s = transformer_lm(vocab=32, dim=32, depth=depth, heads=4, max_len=L,
                          scan_blocks=True)
    params_u, _ = lm_u.init(jax.random.PRNGKey(0))
    params_s = stack_block_params(params_u, depth)
    toks = np.random.RandomState(0).randint(0, 32, (4, L)).astype(np.int32)
    _, ref_g = jax.value_and_grad(
        lambda p: lm_loss(lm_u, p, jnp.asarray(toks)))(params_u)
    from distlearn_tpu.models.transformer import stack_block_params as sbp
    ref_g_s = sbp(ref_g, depth)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "seq", "model"))
    step = build_lm_step(lm_s, mesh, params_s, lr=1.0, donate=False)
    tk = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    newp, _ = step(params_s, tk)
    for a, b, g in zip(jax.tree_util.tree_leaves(params_s),
                       jax.tree_util.tree_leaves(newp),
                       jax.tree_util.tree_leaves(ref_g_s)):
        implied = np.asarray(a) - np.asarray(b)
        denom = max(1e-12, float(np.abs(np.asarray(g)).max()))
        err = float(np.abs(implied - np.asarray(g)).max()) / denom
        assert err < 3e-5, err


def test_scan_blocks_rejects_moe():
    from distlearn_tpu.models.transformer import transformer_lm
    with pytest.raises(ValueError, match="scan_blocks"):
        transformer_lm(vocab=8, dim=8, depth=2, heads=1, scan_blocks=True,
                       moe_experts=2)


def test_greedy_generate_matches_no_cache_rollout():
    """The KV-cached decode must emit the SAME tokens as the naive
    rollout (re-run the full forward on the growing sequence, argmax the
    last position each time) — the cache is an optimization, not a
    different model."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  transformer_lm)

    model = transformer_lm(vocab=43, dim=32, depth=2, heads=2, max_len=48)
    params, _ = model.init(jax.random.PRNGKey(3))
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 43, (2, 8)).astype(np.int32)
    steps = 12

    # naive rollout oracle
    seq = jnp.asarray(prompt)
    naive = []
    for _ in range(steps):
        logits, _ = model.apply(params, {}, seq, train=False)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        naive.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None].astype(jnp.int32)], 1)
    naive = np.stack(naive, axis=1)                 # [B, steps]

    got = np.asarray(greedy_generate(params, jnp.asarray(prompt), steps))
    np.testing.assert_array_equal(got, naive)


def test_greedy_generate_ragged_matches_per_row():
    """A left-padded ragged batch with ``prompt_lens`` must emit, per
    row, the same tokens as running that row alone at its true length —
    the pads must be invisible to positions and attention."""
    import jax
    import numpy as np

    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  transformer_lm)

    model = transformer_lm(vocab=43, dim=32, depth=2, heads=2, max_len=48)
    params, _ = model.init(jax.random.PRNGKey(3))
    rng = np.random.RandomState(0)
    lens = [3, 8, 5, 1]
    P, steps = max(lens), 9
    rows = [rng.randint(0, 43, (n,)).astype(np.int32) for n in lens]
    batch = np.zeros((len(rows), P), np.int32)
    for b, row in enumerate(rows):
        batch[b, P - len(row):] = row                    # left-pad
    got = np.asarray(greedy_generate(params, batch, steps,
                                     prompt_lens=np.array(lens)))
    for b, row in enumerate(rows):
        ref = np.asarray(greedy_generate(params, row[None], steps))[0]
        np.testing.assert_array_equal(got[b], ref, err_msg=f"row {b}")


def test_greedy_generate_full_prompt_lens_identical():
    """``prompt_lens`` set to the full width is the no-padding case and
    must be bit-identical to the ``prompt_lens=None`` fast path."""
    import jax
    import numpy as np

    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  transformer_lm)

    model = transformer_lm(vocab=43, dim=32, depth=2, heads=2, max_len=48)
    params, _ = model.init(jax.random.PRNGKey(3))
    prompt = np.random.RandomState(1).randint(0, 43, (3, 7)) \
        .astype(np.int32)
    want = np.asarray(greedy_generate(params, prompt, 10))
    got = np.asarray(greedy_generate(params, prompt, 10,
                                     prompt_lens=np.full(3, 7)))
    np.testing.assert_array_equal(got, want)


def test_greedy_generate_rejects_overlong():
    import jax
    import numpy as np
    import pytest as _pytest

    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  transformer_lm)

    model = transformer_lm(vocab=17, dim=32, depth=1, heads=2, max_len=16)
    params, _ = model.init(jax.random.PRNGKey(0))
    with _pytest.raises(ValueError, match="max_len"):
        greedy_generate(params, np.zeros((1, 10), np.int32), 10)


def test_greedy_generate_scanned_layout_and_moe_gate():
    """Scanned-layout trees unstack automatically; MoE trees are
    rejected loudly (per-tick routing would not match the trained
    capacity math)."""
    import jax
    import numpy as np
    import pytest as _pytest

    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  stack_block_params,
                                                  transformer_lm)

    model = transformer_lm(vocab=43, dim=32, depth=2, heads=2, max_len=48)
    params, _ = model.init(jax.random.PRNGKey(3))
    prompt = np.random.RandomState(0).randint(0, 43, (1, 8)) \
        .astype(np.int32)
    want = np.asarray(greedy_generate(params, prompt, 6))
    scanned = stack_block_params(params, 2)
    got = np.asarray(greedy_generate(scanned, prompt, 6))
    np.testing.assert_array_equal(got, want)

    moe = transformer_lm(vocab=43, dim=32, depth=2, heads=2, max_len=48,
                         moe_experts=2)
    mp, _ = moe.init(jax.random.PRNGKey(0))
    with _pytest.raises(ValueError, match="dense"):
        greedy_generate(mp, prompt, 4)
