"""3D-parallel LM train step: loss decreases; TP shards update consistently."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlearn_tpu.models.transformer import transformer_lm
from distlearn_tpu.train.lm import build_lm_step


def test_lm_step_3d_mesh_loss_decreases():
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_step(model, mesh, params, lr=0.1, donate=False)

    rng = np.random.RandomState(0)
    # learnable: repeated token pattern
    base = rng.randint(0, 32, (1, L)).astype(np.int32)
    tokens = jax.device_put(np.tile(base, (2 * dp, 1)),
                            NamedSharding(mesh, P("data", "seq")))
    losses = []
    for _ in range(12):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_lm_step_gradients_match_single_device_all_mesh_shapes():
    """The implied update (params - new_params)/lr must equal the
    single-device gradient of the same global batch for every dp/sp/tp
    factorization — guards the psum-transpose scaling bugs (dp unaveraged,
    sp loss-psum, tp without the f/g pattern)."""
    import jax.numpy as jnp
    from distlearn_tpu.models.transformer import lm_loss
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L,
                           dtype=jnp.float64)
    params, _ = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (4, L)).astype(np.int32))
    _, ref_g = jax.value_and_grad(lambda p: lm_loss(model, p, tokens))(params)

    for dp, sp, tp in [(2, 1, 1), (1, 2, 1), (1, 1, 2), (2, 2, 2)]:
        mesh = Mesh(np.array(jax.devices()[:dp * sp * tp]).reshape(dp, sp, tp),
                    ("data", "seq", "model"))
        step = build_lm_step(model, mesh, params, lr=1.0, donate=False)
        tk = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
        newp, _ = step(params, tk)
        for a, b, g in zip(jax.tree_util.tree_leaves(params),
                           jax.tree_util.tree_leaves(newp),
                           jax.tree_util.tree_leaves(ref_g)):
            implied = np.asarray(a) - np.asarray(b)
            denom = max(1e-12, float(np.abs(np.asarray(g)).max()))
            err = float(np.abs(implied - np.asarray(g)).max()) / denom
            assert err < 1e-5, (dp, sp, tp, err)


def test_lm_mixed_step_f32_master_matches_plain_step():
    """With an f32 working copy the mixed step IS the plain step (same
    grads, same update applied to the master) — the equivalence anchor
    for the bf16 scheme (VERDICT r4 weak #2 / next #3)."""
    from distlearn_tpu.train.lm import (build_lm_mixed_step,
                                        init_lm_mixed_state,
                                        build_lm_step)
    dp, sp, tp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(dp, sp, tp),
                ("data", "seq", "model"))
    L = 16 * sp
    model = transformer_lm(vocab=32, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    plain = build_lm_step(model, mesh, params, lr=0.1, donate=False)
    mixed = build_lm_mixed_step(model, mesh, params, lr=0.1, donate=False)
    st = init_lm_mixed_state(params, param_dtype=jnp.float32)

    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (2 * dp, L))
        .astype(np.int32), NamedSharding(mesh, P("data", "seq")))
    p_ref = params
    for _ in range(3):
        p_ref, l_ref = plain(p_ref, tokens)
        st, l_mx = mixed(st, tokens)
        np.testing.assert_allclose(float(l_mx), float(l_ref), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(st.master)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_lm_mixed_step_bf16_trains_and_keeps_invariant():
    """bf16 working copy: params == master.astype(bf16) after every step
    (the master is the source of truth) and the loss still decreases —
    the f32 master absorbs updates bf16 alone would underflow."""
    from distlearn_tpu.train.lm import (build_lm_mixed_step,
                                        init_lm_mixed_state)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1, 1),
                ("data", "seq", "model"))
    L = 32
    model = transformer_lm(vocab=32, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_mixed_step(model, mesh, params, lr=0.1, donate=False)
    st = init_lm_mixed_state(params)
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(st.params))
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(st.master))

    base = np.random.RandomState(0).randint(0, 32, (1, L)).astype(np.int32)
    tokens = jax.device_put(np.tile(base, (4, 1)),
                            NamedSharding(mesh, P("data", "seq")))
    losses = []
    for _ in range(12):
        st, loss = step(st, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()
    for p, m in zip(jax.tree_util.tree_leaves(st.params),
                    jax.tree_util.tree_leaves(st.master)):
        np.testing.assert_array_equal(
            np.asarray(p), np.asarray(m.astype(jnp.bfloat16)))


def test_lm_mixed_step_accum_matches_single_shot():
    """Gradient accumulation under the mixed builder: k scanned
    microbatches must produce the same master update as the single-shot
    step (dense model, f32 working copy so the comparison is exact)."""
    from distlearn_tpu.train.lm import (build_lm_mixed_step,
                                        init_lm_mixed_state)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1, 1),
                ("data", "seq", "model"))
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=1, heads=2, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (4, L)).astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))
    one = build_lm_mixed_step(model, mesh, params, lr=0.1, donate=False)
    two = build_lm_mixed_step(model, mesh, params, lr=0.1, donate=False,
                              accum_steps=2)
    st1, _ = one(init_lm_mixed_state(params, jnp.float32), tokens)
    st2, _ = two(init_lm_mixed_state(params, jnp.float32), tokens)
    for a, b in zip(jax.tree_util.tree_leaves(st1.master),
                    jax.tree_util.tree_leaves(st2.master)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_lm_mixed_step_zigzag_layout_trains():
    """--mixed composes with the zigzag causal ring layout (the two
    features meet in lm_local_grads): loss finite and decreasing."""
    from distlearn_tpu.parallel.sequence import zigzag_indices
    from distlearn_tpu.train.lm import (build_lm_mixed_step,
                                        init_lm_mixed_state)
    sp, L = 4, 64
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, sp, 1),
                ("data", "seq", "model"))
    model = transformer_lm(vocab=32, dim=64, depth=2, heads=4, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_mixed_step(model, mesh, params, lr=0.1,
                               donate=False, seq_layout="zigzag")
    st = init_lm_mixed_state(params)
    base = np.random.RandomState(0).randint(0, 32, (1, L)).astype(np.int32)
    toks = np.tile(base, (4, 1))[:, zigzag_indices(sp, L)]
    tokens = jax.device_put(toks, NamedSharding(mesh, P("data", "seq")))
    losses = []
    for _ in range(10):
        st, loss = step(st, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_lm_mixed_optax_step_f32_matches_plain_optax():
    """Same equivalence anchor for the optax variant (adam)."""
    import optax
    from distlearn_tpu.train.optim import (LMOptaxState,
                                           build_lm_mixed_optax_step,
                                           build_lm_optax_step,
                                           init_lm_mixed_optax_state)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "seq"))
    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=1, heads=2, max_len=L)
    params, _ = model.init(jax.random.PRNGKey(0))
    tx = optax.adam(1e-2)
    plain = build_lm_optax_step(model, mesh, tx, donate=False)
    mixed = build_lm_mixed_optax_step(model, mesh, tx, donate=False)
    st_p = LMOptaxState(params, tx.init(params))
    st_m = init_lm_mixed_optax_state(params, tx,
                                     param_dtype=jnp.float32)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (4, L)).astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))
    for _ in range(3):
        st_p, l_ref = plain(st_p, tokens)
        st_m, l_mx = mixed(st_m, tokens)
        np.testing.assert_allclose(float(l_mx), float(l_ref), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(st_p.params),
                    jax.tree_util.tree_leaves(st_m.master)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_lm_step_dp_only_matches_structure():
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    model = transformer_lm(vocab=32, dim=32, depth=1, heads=2, max_len=16)
    params, _ = model.init(jax.random.PRNGKey(0))
    step = build_lm_step(model, mesh, params, lr=0.1, seq_axis=None,
                         tp_axis=None, donate=False)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32, (4, 16)).astype(np.int32),
        NamedSharding(mesh, P("data")))
    new_params, loss = step(params, tokens)
    assert np.isfinite(float(loss))
    # structure preserved
    assert jax.tree_util.tree_structure(new_params) == \
        jax.tree_util.tree_structure(params)


def test_lm_gradient_accumulation_matches_full():
    """accum_steps=2 must reproduce the single-shot LM step exactly (the
    transformer is deterministic — no dropout)."""
    import numpy as np
    from jax import random

    from distlearn_tpu.models.transformer import param_specs, transformer_lm
    from distlearn_tpu.train.lm import build_lm_step

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                ("data", "seq", "model"))
    lm = transformer_lm(vocab=64, dim=32, depth=2, heads=4, max_len=16)
    params, _ = lm.init(jax.random.PRNGKey(0))
    toks = jax.device_put(
        jnp.asarray(np.random.RandomState(1).randint(0, 64, (8, 16)),
                    jnp.int32),
        NamedSharding(mesh, P("data", "seq")))
    sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                param_specs(params, tp_axis="model"))
    outs = {}
    for k in (1, 2):
        step = build_lm_step(lm, mesh, params, lr=0.1, accum_steps=k,
                             donate=False)
        p = jax.device_put(params, sh)
        for _ in range(2):
            p, loss = step(p, toks)
        outs[k] = (float(loss), jax.tree_util.tree_leaves(p))
    np.testing.assert_allclose(outs[1][0], outs[2][0], rtol=1e-6)
    for a, b in zip(outs[1][1], outs[2][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def _pp_vs_sequential(depth, n_stages, num_microbatches, remat,
                      unroll=False, schedule="gpipe"):
    """PP step on dp2 x pipe{n_stages} vs the plain single-mesh LM step:
    same loss, same updated params (gradient reassembly across pipe ranks
    is exact)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (build_lm_pp_1f1b_step, build_lm_pp_step,
                                     build_lm_step, stack_blocks,
                                     unstack_blocks)

    dim, vocab, L, B = 32, 64, 16, 8
    lm = transformer_lm(vocab=vocab, dim=dim, depth=depth, heads=2,
                        max_len=L)
    params, _ = lm.init(jax.random.PRNGKey(0))
    tokens = np.random.RandomState(0).randint(0, vocab, (B, L)) \
        .astype(np.int32)

    # reference: plain data-parallel step on a 1-device mesh (no seq/tp)
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                 ("data", "seq", "model"))
    step_ref = build_lm_step(lm, mesh1, params, lr=0.1, donate=False)
    t_ref = jax.device_put(tokens,
                           NamedSharding(mesh1, P("data", "seq")))
    p_ref, loss_ref = step_ref(params, t_ref)

    mesh = Mesh(np.array(jax.devices()[:2 * n_stages]).reshape(2, n_stages),
                ("data", "pipe"))
    shared, stacked = stack_blocks(params, depth)
    shared_d = jax.device_put(shared, NamedSharding(mesh, P()))
    stacked_d = jax.device_put(stacked, NamedSharding(mesh, P("pipe")))
    if schedule == "1f1b":
        step_pp = build_lm_pp_1f1b_step(mesh, shared, stacked, lr=0.1,
                                        num_microbatches=num_microbatches,
                                        remat=remat, donate=False)
    else:
        step_pp = build_lm_pp_step(mesh, shared, stacked, lr=0.1,
                                   num_microbatches=num_microbatches,
                                   remat=remat, unroll=unroll, donate=False)
    t_pp = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    shared_n, stacked_n, loss_pp = step_pp(shared_d, stacked_d, t_pp)

    np.testing.assert_allclose(float(loss_pp), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    got = unstack_blocks(jax.device_get(shared_n),
                         jax.device_get(stacked_n), depth)
    for (pa, a), (pb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(
                jax.device_get(p_ref))[0], key=lambda t: str(t[0])),
            sorted(jax.tree_util.tree_flatten_with_path(got)[0],
                   key=lambda t: str(t[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6, err_msg=str(pa))


def test_lm_pp_step_matches_sequential():
    _pp_vs_sequential(depth=4, n_stages=4, num_microbatches=2, remat=False)


def test_lm_pp_step_k_blocks_per_stage_remat():
    """depth=8 over 4 stages (k=2 blocks per stage) with per-block remat —
    the generalized GPipe path — still matches the sequential step."""
    _pp_vs_sequential(depth=8, n_stages=4, num_microbatches=4, remat=True)


def test_lm_pp_step_unrolled_ticks_match():
    """unroll=True (inlined tick scan, the measured-1.68x bench setting)
    must not change the math."""
    _pp_vs_sequential(depth=4, n_stages=2, num_microbatches=4, remat=False,
                      unroll=True)


def test_lm_ea_diverge_contract_converge():
    """EASGD on the transformer LM (the reference's core algorithm on the
    model family it never had): replicas diverge over collective-free
    local steps, one elastic round contracts them, training converges;
    center replicas stay bitwise identical."""
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import build_lm_ea_steps, init_lm_ea_state

    tree = MeshTree(num_nodes=4)
    vocab, L, B = 32, 16, 8
    lm = transformer_lm(vocab=vocab, dim=32, depth=2, heads=2, max_len=L)
    st = init_lm_ea_state(lm, tree, jax.random.PRNGKey(0))
    local, rnd = build_lm_ea_steps(lm, tree, lr=0.1, alpha=0.25,
                                   momentum=0.9, donate=False)
    rng = np.random.RandomState(0)
    sh = NamedSharding(tree.mesh, P("data"))

    def spread(s):
        leaf = jax.tree_util.tree_leaves(s.params)[0]
        arr = np.asarray(jax.device_get(leaf))
        return float(np.abs(arr - arr[0]).max())

    assert spread(st) == 0.0
    first = last = None
    for k in range(30):
        toks = jax.device_put(
            rng.randint(0, vocab, (B, L)).astype(np.int32), sh)
        st, losses = local(st, toks)
        m = float(np.mean(np.asarray(losses)))
        first = m if first is None else first
        last = m
        if k == 14:
            d_before = spread(st)
            assert d_before > 0      # replicas saw different shards
            st = rnd(st)
            assert spread(st) < d_before   # elastic round contracts
    assert last < first
    c = jax.tree_util.tree_leaves(st.center)[0]
    arr = np.asarray(jax.device_get(c))
    for i in range(1, arr.shape[0]):
        np.testing.assert_array_equal(arr[0], arr[i])


def test_lm_step_zigzag_matches_single_device():
    """seq_layout='zigzag' (balanced causal ring, masked blocks skipped)
    computes the SAME global objective: the implied update on
    column-permuted tokens must equal the single-device gradient of the
    natural-order batch — positions, shifted targets, and the loss mask
    all survive the layout change."""
    from distlearn_tpu.models.transformer import lm_loss
    from distlearn_tpu.parallel.sequence import zigzag_indices

    L = 32
    model = transformer_lm(vocab=32, dim=32, depth=2, heads=4, max_len=L,
                           dtype=jnp.float64)
    params, _ = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 32, (4, L)).astype(np.int32))
    _, ref_g = jax.value_and_grad(lambda p: lm_loss(model, p, tokens))(params)

    for dp, sp in [(1, 2), (2, 4), (1, 8)]:
        mesh = Mesh(np.array(jax.devices()[:dp * sp]).reshape(dp, sp, 1),
                    ("data", "seq", "model"))
        step = build_lm_step(model, mesh, params, lr=1.0, donate=False,
                             seq_layout="zigzag")
        idx = zigzag_indices(sp, L)
        tk = jax.device_put(np.asarray(tokens)[:, idx],
                            NamedSharding(mesh, P("data", "seq")))
        newp, loss = step(params, tk)
        ref_loss = float(lm_loss(model, params, tokens))
        # the loss itself is reduced in f32 regardless of model dtype
        assert abs(float(loss) - ref_loss) < 1e-5, (sp, float(loss), ref_loss)
        for a, b, g in zip(jax.tree_util.tree_leaves(params),
                           jax.tree_util.tree_leaves(newp),
                           jax.tree_util.tree_leaves(ref_g)):
            implied = np.asarray(a) - np.asarray(b)
            denom = max(1e-12, float(np.abs(np.asarray(g)).max()))
            err = float(np.abs(implied - np.asarray(g)).max()) / denom
            assert err < 1e-5, (dp, sp, err)


def test_lm_zigzag_layout_validation():
    from distlearn_tpu.models.transformer import transformer_lm as tl
    model = tl(vocab=8, dim=8, depth=1, heads=1, max_len=8,
               seq_impl="alltoall")
    toks = jnp.zeros((1, 8), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2, 1),
                ("data", "seq", "model"))
    import pytest
    with pytest.raises(ValueError, match="ring"):
        build_lm_step(model, mesh, model.init(jax.random.PRNGKey(0))[0],
                      lr=0.1, seq_layout="zigzag")(
            model.init(jax.random.PRNGKey(0))[0],
            jax.device_put(np.zeros((1, 8), np.int32),
                           NamedSharding(mesh, P("data", "seq"))))
    model2 = tl(vocab=8, dim=8, depth=1, heads=1, max_len=8)
    with pytest.raises(ValueError, match="zigzag"):
        model2.apply(model2.init(jax.random.PRNGKey(0))[0], {}, toks,
                     seq_layout="zigzag")   # no seq axis


def test_lm_pp_1f1b_matches_sequential():
    """The 1F1B schedule (manual per-tick vjp, O(S) liveness) computes the
    SAME update as the sequential reference — drop-in with GPipe."""
    _pp_vs_sequential(depth=4, n_stages=4, num_microbatches=4,
                      remat=False, schedule="1f1b")


def test_lm_pp_1f1b_k_blocks_remat_matches_sequential():
    _pp_vs_sequential(depth=8, n_stages=4, num_microbatches=4,
                      remat=True, schedule="1f1b")


def test_lm_pp_1f1b_liveness_beats_gpipe():
    """The point of 1F1B: compiled temp memory stays O(S) while GPipe's
    autodiff residuals grow O(M).  At M=32 over 4 stages the 1F1B
    program's temp allocation must be well under GPipe's."""
    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train import (build_lm_pp_1f1b_step,
                                     build_lm_pp_step, stack_blocks)

    S, M, L, dim = 4, 32, 64, 64
    lm = transformer_lm(vocab=64, dim=dim, depth=S, heads=4, max_len=L)
    params, _ = lm.init(jax.random.PRNGKey(0))
    shared, stacked = stack_blocks(params, S)
    mesh = Mesh(np.array(jax.devices()[:S]).reshape(1, S), ("data", "pipe"))
    toks = np.zeros((M * 2, L), np.int32)

    def temp_bytes(builder):
        step = builder(mesh, shared, stacked, lr=1.0, num_microbatches=M,
                       remat=True, donate=False)
        return step.lower(shared, stacked, toks).compile() \
            .memory_analysis().temp_size_in_bytes

    gpipe = temp_bytes(build_lm_pp_step)
    f1b = temp_bytes(build_lm_pp_1f1b_step)
    assert f1b < 0.6 * gpipe, (f1b, gpipe)
