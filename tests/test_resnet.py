"""ResNet-50 v1.5 stretch model (BASELINE.md row 5): structure parity with
the torchvision reference config, and the bucketed-gradient distributed step
on the 8-device mesh."""

import jax
import numpy as np
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from distlearn_tpu.models import param_count, resnet
from distlearn_tpu.parallel.mesh import MeshTree
from distlearn_tpu.train import build_sgd_step, init_train_state


def test_resnet50_param_count_matches_torchvision():
    m = resnet(50, num_classes=1000)
    params, state = m.init(random.PRNGKey(0))
    assert param_count(params) == 25_557_032  # torchvision resnet50
    assert len(jax.tree_util.tree_leaves(params)) == 161


def test_resnet50_forward_shapes_and_zero_gamma():
    m = resnet(50, num_classes=10)
    params, state = m.init(random.PRNGKey(0))
    # zero-init residual gamma: block output == shortcut at init
    assert float(np.abs(np.asarray(
        params["stage1_block1"]["bn3"]["scale"])).max()) == 0.0
    x = np.random.RandomState(0).randn(2, 64, 64, 3).astype(np.float32)
    lp, ns = m.apply(params, state, x, train=True)
    assert lp.shape == (2, 10)
    np.testing.assert_allclose(np.exp(np.asarray(lp, np.float64)).sum(-1),
                               1.0, rtol=1e-4)
    # eval path uses running stats
    lp2, _ = m.apply(params, ns, x, train=False)
    assert lp2.shape == (2, 10)


def test_resnet_distributed_bucketed_step():
    """The full data-parallel fused+bucketed step on the 8-device mesh —
    the dryrun-style gate for the stretch config (VERDICT r1 #4)."""
    tree = MeshTree(num_nodes=8)
    m = resnet(50, num_classes=10, image_size=32)
    ts = init_train_state(m, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(m, tree, lr=0.1, fused=True,
                          max_bucket_bytes=8 * 1024 * 1024)
    sh = NamedSharding(tree.mesh, P(tree.axis_name))
    x = jax.device_put(np.random.RandomState(0)
                       .randn(8, 32, 32, 3).astype(np.float32), sh)
    y = jax.device_put(np.arange(8, dtype=np.int32) % 10, sh)
    ts, loss = step(ts, x, y)
    ts, loss2 = step(ts, x, y)
    assert np.isfinite(float(loss)) and np.isfinite(float(loss2))
    # params stay replicated bitwise across shards
    leaf = jax.tree_util.tree_leaves(ts.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_resnet_skipinit_structure_and_identity_start():
    """norm='none' (SkipInit): no batch statistics exist anywhere, every
    residual branch starts as identity (zero alpha), and gradients flow."""
    import jax
    import jax.numpy as jnp

    m = resnet(50, num_classes=10, image_size=32, norm="none")
    params, state = m.init(jax.random.PRNGKey(0))
    leaves = jax.tree_util.tree_leaves_with_path(params)
    names = " ".join(str(p) for p, _ in leaves)
    assert "bn" not in names            # no BN params at all
    assert "alpha" in names
    assert not jax.tree_util.tree_leaves(state)    # stateless: no stats

    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    logits, new_state = m.apply(params, state, x, train=True)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()

    def loss(p):
        lp, _ = m.apply(p, state, x, train=True)
        return -lp[np.arange(2), np.zeros(2, np.int32)].mean()

    g = jax.grad(loss)(params)
    # alpha is zero at init, but its OWN gradient must be nonzero
    # (otherwise the branches could never turn on)
    alphas = [leaf for path, leaf in
              jax.tree_util.tree_leaves_with_path(g)
              if "alpha" in str(path)]
    assert alphas and any(float(jnp.abs(a)) > 0 for a in alphas)


def test_resnet_norm_validation():
    import pytest
    with pytest.raises(ValueError, match="norm"):
        resnet(50, norm="layer")
