"""Chaos harness (tools/chaos.py): the scripted kill -> promote ->
rejoin sequence converges to BITWISE parity with an unkilled reference
run (tier-1, deterministic), the mid-flight kill recovers through the
rejoin replay rather than the checkpoint alone, and the multi-client
churn soak (slow) stays live with zero leaked fds/threads."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import chaos  # noqa: E402

pytestmark = pytest.mark.chaos


def test_parity_boundary_kill_promote_rejoin():
    """Kill the center between rounds, promote a standby on another port
    window, client fails over (same object, no restart): ten+ more
    rounds later the fleet is bitwise identical to the unkilled S=1
    reference — run_parity raises on any divergence or leak."""
    report = chaos.run_parity(rounds=16, kills=(5,), shards=4)
    assert report["failures"] == []
    assert report["promotions"] == 1
    assert report["redials"] >= 1
    assert sum(report["replays"].values()) == 1


def test_parity_double_kill_ping_pongs_windows():
    """Two kills re-promote across the same two port windows — proves
    the promoted center's checkpoints supersede the dead primary's
    (step adoption), or the second promotion would restore stale state."""
    report = chaos.run_parity(rounds=14, kills=(4, 9), shards=4)
    assert report["failures"] == []
    assert report["promotions"] == 2


def test_parity_mid_stripe_kill_replays_pending_delta():
    """Kill while the round's delta is on the wire: the restored ledger
    tells the rejoining client which stripes never landed and the replay
    re-applies exactly those — bitwise parity still holds."""
    report = chaos.run_parity(rounds=12, kills=(6,), shards=4,
                              mid_flight=True)
    assert report["failures"] == []
    assert sum(report["replays"].values()) == 1


def test_parity_without_overlap():
    report = chaos.run_parity(rounds=10, kills=(4,), shards=2,
                              overlap=False)
    assert report["failures"] == []
    assert report["promotions"] == 1


def test_cli_parity_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos.py"),
         "parity", "--rounds", "6", "--kills", "2", "--shards", "2"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-800:]
    report = json.loads(r.stdout[r.stdout.index("{"):])
    assert report["failures"] == [] and report["promotions"] == 1


@pytest.mark.elastic
def test_partition_mid_sync_heal_replays_to_bitwise_parity():
    """One-way partition injected exactly between a round's parameter
    math and its delta push: the blackholed delta times the client out
    of the fleet, the heal lets the failover rejoin through, and the
    applied-seq ledger replays the lost delta exactly once — center AND
    client are bitwise identical to the unpartitioned reference."""
    report = chaos.run_scenario("partition_heal", rounds=12)
    assert report["failures"] == []          # includes the bitwise diff
    assert report["dropped_bytes"] > 0       # the delta really blackholed
    assert report["evictions"] >= 1 and report["rejoins"] >= 1


@pytest.mark.elastic
def test_cli_scenario_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos.py"),
         "scenario", "--name", "partition_heal", "--rounds", "8"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-800:]
    report = json.loads(r.stdout[r.stdout.index("{"):])
    assert report["failures"] == [] and report["rejoins"] >= 1


@pytest.mark.slow
def test_churn_soak_liveness_and_leaks():
    """The soak: three mixed-codec clients each self-kill mid-handshake,
    the center dies twice under load — everyone finishes their rounds,
    one promotion per center kill, no fd/thread accumulation."""
    report = chaos.run_churn(rounds=14, num_clients=3, shards=4,
                             server_kills=2)
    assert report["failures"] == []
    assert report["promotions"] == report["server_kills"] == 2
    assert report["evictions"] >= 3 and report["rejoins"] >= 3
