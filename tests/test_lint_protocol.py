"""distlint protocol rules (DL101-DL104): the real tree/ring/AsyncEA
schedules pass; deliberately broken variants deadlock/desync; the lock
audit finds cycles and blocking-under-lock in synthetic sources and stays
quiet on the repo's threaded modules."""

import pytest

from distlearn_tpu.lint.protocol import (async_ea_sync_schedule,
                                         check_schedules,
                                         lint_comm_protocols,
                                         lock_order_audit, recv, recv_any,
                                         ring_allreduce_schedule, send,
                                         tree_allreduce_schedule)


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------- real protocols

def test_repo_protocols_are_clean():
    assert lint_comm_protocols(num_nodes=7) == []


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8, 15])
def test_tree_schedule_completes_any_size(n):
    assert check_schedules(tree_allreduce_schedule(n)) == []
    # ...even under rendezvous sends: each up-send meets a posted recv.
    assert check_schedules(tree_allreduce_schedule(n),
                           buffered_sends=False) == []


@pytest.mark.parametrize("base", [2, 3, 4])
def test_tree_schedule_completes_any_base(base):
    assert check_schedules(tree_allreduce_schedule(9, base=base)) == []


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_ring_schedule_completes_with_buffered_sends(n):
    assert check_schedules(ring_allreduce_schedule(n)) == []


def test_async_ea_handshake_is_clean():
    assert check_schedules(async_ea_sync_schedule()) == []


# --------------------------------------------------------- DL101 deadlock

def test_dl101_ring_under_rendezvous_sends_deadlocks():
    """Why ring.py owns a _Sender thread: synchronous sends turn the
    send-first full-duplex step into an all-ranks-blocked cycle."""
    fs = check_schedules(ring_allreduce_schedule(4), buffered_sends=False,
                         name="ring-sync")
    assert _rules(fs) == ["DL101"]
    assert "cycle" in fs[0].message


def test_dl101_mutual_recv_first_deadlocks():
    sched = {0: [recv(1, "x"), send(1, "y")],
             1: [recv(0, "y"), send(0, "x")]}
    fs = check_schedules(sched, name="recv-first")
    assert _rules(fs) == ["DL101"]


def test_dl101_starvation_on_terminated_peer():
    sched = {0: [send(1, "a")], 1: [recv(0, "a"), recv(0, "b")]}
    fs = check_schedules(sched, name="starve")
    assert _rules(fs) == ["DL101"]
    assert "blocked" in fs[0].message


# ----------------------------------------------------------- DL104 desync

def test_dl104_swapped_handshake_questions_desync():
    fs = check_schedules(
        async_ea_sync_schedule(client_order=("delta?", "Center?")),
        name="swapped")
    assert "DL104" in _rules(fs)
    assert "disagree on message order" in fs[0].message


def test_dl104_tag_skew_detected_point_to_point():
    sched = {0: [send(1, "hdr"), send(1, "tensor")],
             1: [recv(0, "tensor"), recv(0, "hdr")]}
    fs = check_schedules(sched, name="skew")
    assert _rules(fs) == ["DL104"]


def test_dl104_recv_any_tag_mismatch_buffered():
    """recv_any still checks the DELIVERED tag: accepting any sender is
    not accepting any message."""
    sched = {0: [send(1, "hdr")], 1: [recv_any("payload")]}
    fs = check_schedules(sched, name="any-skew")
    assert _rules(fs) == ["DL104"]
    assert "disagree on message order" in fs[0].message


def test_dl104_recv_any_tag_mismatch_rendezvous():
    """Same desync through the rendezvous delivery path (the send fires
    directly into the posted recv_any, no channel queue involved)."""
    sched = {0: [send(1, "hdr")], 1: [recv_any("payload")]}
    fs = check_schedules(sched, buffered_sends=False, name="any-skew-rdv")
    assert _rules(fs) == ["DL104"]


def test_dl104_tag_skew_under_rendezvous():
    sched = {0: [send(1, "a"), recv(1, "b")],
             1: [recv(0, "x"), send(0, "b")]}
    fs = check_schedules(sched, buffered_sends=False, name="skew-rdv")
    assert _rules(fs) == ["DL104"]


def test_recv_any_admits_either_sender_both_modes():
    sched = {0: [send(2, "hello")], 1: [send(2, "hello")],
             2: [recv_any("hello"), recv_any("hello")]}
    assert check_schedules(sched) == []
    assert check_schedules(sched, buffered_sends=False) == []


def test_async_ea_handshake_clean_under_rendezvous():
    """The AsyncEA handshake is strictly alternating (ask, answer), so
    unlike the ring it needs no sender thread: every send meets a posted
    recv even under rendezvous semantics, in both wire framings."""
    assert check_schedules(async_ea_sync_schedule(),
                           buffered_sends=False) == []
    assert check_schedules(async_ea_sync_schedule(packed=True),
                           buffered_sends=False) == []


# --------------------------------------------------------- DL102 / DL103

_BAD_LOCKS = """
class A:
    def f(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def g(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""

_BAD_BLOCKING = """
class B:
    def f(self):
        with self._lock:
            self.conn.recv_msg()
"""

_GOOD_LOCKS = """
class C:
    def f(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def g(self):
        with self._a_lock:
            self.x += 1
    def h(self):
        msg = self.conn.recv_msg()   # blocking call OUTSIDE the lock
        with self._b_lock:
            self.apply(msg)
    def spawn(self):
        with self._a_lock:
            def worker():
                # runs on another thread later — the lexically enclosing
                # lock is NOT held at call time
                self.conn.recv_msg()
            return worker
"""


def test_dl102_lock_order_cycle_fires():
    fs = lock_order_audit([_BAD_LOCKS])
    assert _rules(fs) == ["DL102"]
    assert "_a_lock" in fs[0].message and "_b_lock" in fs[0].message


def test_dl102_cycle_across_modules_fires():
    half_a = "class A:\n    def f(self):\n        with self._a_lock:\n            with self._b_lock:\n                pass\n"
    half_b = "class A:\n    def g(self):\n        with self._b_lock:\n            with self._a_lock:\n                pass\n"
    assert _rules(lock_order_audit([half_a, half_b])) == ["DL102"]
    assert lock_order_audit([half_a]) == []


def test_dl103_blocking_call_under_lock_fires():
    fs = lock_order_audit([_BAD_BLOCKING])
    assert _rules(fs) == ["DL103"]
    assert "recv_msg" in fs[0].message


def test_lock_audit_quiet_on_consistent_order():
    assert lock_order_audit([_GOOD_LOCKS]) == []


def test_lock_audit_quiet_on_repo_threaded_modules():
    from distlearn_tpu.comm import ring, transport, tree
    from distlearn_tpu.parallel import async_ea
    assert lock_order_audit([transport, tree, ring, async_ea]) == []


# --------------------------------------------------- HA failover schedules

def test_failover_promote_schedule_is_clean():
    from distlearn_tpu.lint.protocol import async_ea_failover_schedule
    assert check_schedules(async_ea_failover_schedule()) == []
    assert check_schedules(async_ea_failover_schedule(num_shards=1)) == []


def test_failover_without_timeouts_deadlocks():
    """Why every stripe-leg recv is timeout-armed: if the surviving legs
    waited forever on the killed primary, the whole fleet would wedge
    instead of failing over (DL101 on the strict variant)."""
    from distlearn_tpu.lint.protocol import async_ea_failover_schedule
    fs = check_schedules(async_ea_failover_schedule(strict=True),
                         name="failover-strict")
    assert _rules(fs) == ["DL101"]


def test_promote_rejoin_herd_schedule_is_clean():
    from distlearn_tpu.lint.protocol import async_ea_promote_rejoin_schedule
    assert check_schedules(async_ea_promote_rejoin_schedule()) == []
    assert check_schedules(
        async_ea_promote_rejoin_schedule(num_clients=5)) == []


def test_stale_epoch_refusal_schedule_is_clean():
    from distlearn_tpu.lint.protocol import async_ea_stale_epoch_schedule
    assert check_schedules(async_ea_stale_epoch_schedule()) == []
