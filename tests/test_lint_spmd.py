"""distlint SPMD rules: each rule fires on a deliberately broken step
function and stays quiet on the repaired twin, on a 2-device CPU mesh.

The known-good cases are shaped after the repo's real patterns (the
uniform-predicate cond of parallel/allreduce_ea.py, the fold_in-then-draw
dropout key of train/trainer.py), so a linter change that starts flagging
them is a regression against the codebase itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, random
from jax.sharding import Mesh, PartitionSpec as P

from distlearn_tpu.lint import Finding, lint_step
from distlearn_tpu.lint.core import filter_suppressed, format_findings
from distlearn_tpu.utils import compat


@pytest.fixture
def mesh(devices):
    return Mesh(np.array(devices[:2]), ("data",))


def _sm(mesh, f, in_specs, out_specs):
    # check_vma=False: several known-bad bodies are exactly the programs the
    # static replication checker refuses; the linter must catch them anyway.
    return compat.shard_map(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------- DL001

def test_dl001_unknown_axis_fires(mesh):
    def bad(x):
        return lax.psum(x, "batch")  # deployment mesh only has 'data'
    fs = lint_step(bad, [jnp.ones((4,))], mesh=mesh,
                   axis_env=[("batch", 2)], name="bad")
    assert _rules(fs) == ["DL001"]
    assert "batch" in fs[0].message


def test_dl001_quiet_on_mesh_axis(mesh):
    def good(x):
        return lax.psum(x, "data")
    assert lint_step(good, [jnp.ones((4,))], mesh=mesh,
                     axis_env=[("data", 2)], name="good") == []


# ---------------------------------------------------------------------- DL002

def test_dl002_collective_in_one_cond_branch_fires(mesh):
    def bad(x):
        def body(x):
            # Predicate computed from the LOCAL shard: devices disagree,
            # and only one branch issues a psum.
            return lax.cond(x.sum() > 0,
                            lambda v: lax.psum(v, "data"),
                            lambda v: v, x)
        return _sm(mesh, body, P("data"), P("data"))(x)
    fs = lint_step(bad, [jnp.ones((2, 4))], mesh=mesh, name="bad")
    assert _rules(fs) == ["DL002"]
    assert "cond" in fs[0].where


def test_dl002_quiet_on_uniform_predicate(mesh):
    """allreduce_ea.average_parameters pattern: branches diverge but the
    predicate is psum-derived, hence identical on every device — safe."""
    def good(x):
        def body(x):
            due = lax.psum((x.sum() > 0).astype(jnp.int32), "data") > 0
            return lax.cond(due,
                            lambda v: lax.psum(v, "data") / 2,
                            lambda v: v, x)
        return _sm(mesh, body, P("data"), P())(x)
    assert lint_step(good, [jnp.ones((2, 4))], mesh=mesh, name="good") == []


def test_dl002_quiet_when_branches_agree(mesh):
    def good(x):
        def body(x):
            return lax.cond(x.sum() > 0,
                            lambda v: lax.psum(v, "data"),
                            lambda v: lax.psum(2.0 * v, "data"), x)
        return _sm(mesh, body, P("data"), P())(x)
    assert lint_step(good, [jnp.ones((2, 4))], mesh=mesh, name="good") == []


def test_dl002_data_dependent_while_with_collective_fires(mesh):
    def bad(x):
        def body(x):
            def w_body(c):
                i, v = c
                return i + 1, lax.psum(v, "data")
            def w_cond(c):
                i, v = c
                return (v.sum() > 0) & (i < 3)  # local shard decides
            return lax.while_loop(w_cond, w_body, (0, x))[1]
        return _sm(mesh, body, P("data"), P("data"))(x)
    fs = lint_step(bad, [jnp.ones((2, 4))], mesh=mesh, name="bad")
    assert "DL002" in _rules(fs)
    assert "while" in fs[0].where


# ---------------------------------------------------------------------- DL003

def test_dl003_shared_key_fires(mesh):
    def bad(x, key):
        def body(x, key):
            return x + random.normal(key, x.shape)  # same draw on all nodes
        return _sm(mesh, body, (P("data"), P()), P("data"))(x, key)
    fs = lint_step(bad, [jnp.ones((2, 4)), random.PRNGKey(0)],
                   mesh=mesh, name="bad")
    assert _rules(fs) == ["DL003"]
    assert "fold_in" in fs[0].message


def test_dl003_quiet_after_axis_index_fold_in(mesh):
    """trainer._make_sgd_body's dropout-key pattern."""
    def good(x, key):
        def body(x, key):
            key = random.fold_in(key, lax.axis_index("data"))
            return x + random.normal(key, x.shape)
        return _sm(mesh, body, (P("data"), P()), P("data"))(x, key)
    assert lint_step(good, [jnp.ones((2, 4)), random.PRNGKey(0)],
                     mesh=mesh, name="good") == []


def test_dl003_quiet_outside_spmd_region(mesh):
    def good(key):
        return random.normal(key, (4,))  # single-program, no mesh axes
    assert lint_step(good, [random.PRNGKey(0)], mesh=mesh, name="good") == []


# ---------------------------------------------------------------------- DL004

def test_dl004_f16_psum_fires(mesh):
    def bad(x):
        def body(x):
            return lax.psum(x.astype(jnp.float16), "data")
        return _sm(mesh, body, P("data"), P())(x)
    fs = lint_step(bad, [jnp.ones((2, 4), jnp.float16)], mesh=mesh,
                   name="bad")
    assert _rules(fs) == ["DL004"]
    assert "float16" in fs[0].message


def test_dl004_quiet_on_f32_upcast(mesh):
    def good(x):
        def body(x):
            return lax.psum(x.astype(jnp.float32), "data").astype(jnp.float16)
        return _sm(mesh, body, P("data"), P())(x)
    assert lint_step(good, [jnp.ones((2, 4), jnp.float16)], mesh=mesh,
                     name="good") == []


def test_dl004_quiet_on_f16_pmax(mesh):
    """pmax/pmin are exact in any dtype — only accumulation loses bits."""
    def good(x):
        def body(x):
            return lax.pmax(x.astype(jnp.float16), "data")
        return _sm(mesh, body, P("data"), P())(x)
    assert lint_step(good, [jnp.ones((2, 4), jnp.float16)], mesh=mesh,
                     name="good") == []


# ---------------------------------------------------------------------- DL005

def test_dl005_unmatched_donation_fires():
    bad = jax.jit(lambda s, x: (x * 2.0).sum(), donate_argnums=(0,))
    args = [jnp.ones((8, 8)), jnp.ones((8, 8))]
    fs = lint_step(bad, args, name="bad")
    assert _rules(fs) == ["DL005"]


def test_dl005_quiet_on_aliasable_donation():
    good = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
    args = [jnp.ones((8, 8)), jnp.ones((8, 8))]
    assert lint_step(good, args, name="good") == []


# ----------------------------------------------------------- shared machinery

def test_suppression_and_unknown_rule(mesh):
    def bad(x):
        def body(x):
            return lax.psum(x.astype(jnp.float16), "data")
        return _sm(mesh, body, P("data"), P())(x)
    args = [jnp.ones((2, 4), jnp.float16)]
    assert lint_step(bad, args, mesh=mesh, suppress={"DL004"}) == []
    with pytest.raises(ValueError, match="unknown rule"):
        filter_suppressed([], {"DL999"})
    with pytest.raises(ValueError, match="unknown rule"):
        Finding("DL999", "nope")


def test_walker_descends_scan_and_nested_jit(mesh):
    """Findings inside scan bodies and nested jits are not lost."""
    def bad(x):
        def body(x):
            inner = jax.jit(lambda v: lax.psum(v.astype(jnp.float16), "data"))
            def scanned(c, _):
                return c + inner(x).astype(x.dtype).sum(), None
            return lax.scan(scanned, 0.0, None, length=3)[0]
        return _sm(mesh, body, P("data"), P())(x)
    fs = lint_step(bad, [jnp.ones((2, 4))], mesh=mesh, name="bad")
    assert _rules(fs) == ["DL004"]
    assert "scan" in fs[0].where


def test_format_findings_renders_rule_and_location(mesh):
    def bad(x):
        return lax.psum(x, "batch")
    fs = lint_step(bad, [jnp.ones((4,))], mesh=mesh,
                   axis_env=[("batch", 2)], name="unit")
    text = format_findings(fs, header="unit:")
    assert "DL001" in text and "unit" in text
