#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Headline metric (BASELINE.json "metric"): CIFAR-10 ConvNet training
throughput in steps/sec/chip with the fused AllReduceSGD step — the
reference's own hot path (examples/cifar10.lua per-batch loop, SURVEY.md
§3.1) on whatever accelerator is attached (real TPU chip under the driver;
CPU fallback elsewhere).

The reference publishes no measured numbers (BASELINE.md), so
``vs_baseline`` is reported against a modeled reference throughput: the same
step on this host's CPU via XLA — a stand-in for the reference's
CPU-FloatTensor path (its default; examples/cifar10.sh runs CPU nodes).
vs_baseline > 1 means faster than the modeled baseline.

Extra diagnostic metrics go to stderr; stdout carries exactly the one line.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _bench_backend(batch: int, iters: int, warmup: int = 3):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import synthetic_cifar10
    from distlearn_tpu.models import cifar_convnet
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import build_sgd_step, init_train_state

    n_dev = len(jax.devices())
    tree = MeshTree(num_nodes=n_dev)
    platform = jax.devices()[0].platform
    # bf16 compute on TPU (MXU path); f32 on CPU
    model = cifar_convnet(
        compute_dtype=jnp.bfloat16 if platform == "tpu" else None)
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    step = build_sgd_step(model, tree, lr=0.1)

    x, y, _ = synthetic_cifar10(batch, seed=0)
    sh = NamedSharding(tree.mesh, P("data"))
    bx = jax.device_put(x, sh)
    by = jax.device_put(y, sh)

    for _ in range(warmup):
        ts, loss = step(ts, bx, by)
    jax.block_until_ready(ts.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, loss = step(ts, bx, by)
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0
    return iters / dt, n_dev, platform, float(loss)


def main():
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))

    steps_per_sec, n_dev, platform, loss = _bench_backend(batch, iters)
    per_chip = steps_per_sec / max(1, n_dev)
    print(f"[bench] platform={platform} devices={n_dev} batch={batch} "
          f"steps/s={steps_per_sec:.3f} loss={loss:.3f}", file=sys.stderr)

    # Modeled baseline: measured once on this host's CPU and cached, so TPU
    # runs don't pay a slow CPU benchmark every time.
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cpu_baseline.json")
    baseline = None
    if os.path.exists(cache):
        try:
            with open(cache) as fh:
                rec = json.load(fh)
            if rec.get("batch") == batch:   # cache only valid for same config
                baseline = rec["steps_per_sec"]
        except (OSError, ValueError, KeyError):
            baseline = None
    if baseline is None and platform == "cpu":
        baseline = steps_per_sec
        with open(cache, "w") as fh:
            json.dump({"steps_per_sec": baseline, "batch": batch}, fh)
    if baseline is None:
        # TPU run with no cached CPU number: benchmark a short CPU run now.
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_ITERS="3",
                   BENCH_BATCH=str(batch))
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--cpu-probe"],
                env=env, capture_output=True, timeout=1200, text=True)
            baseline = json.loads(out.stdout.strip().splitlines()[-1])["value"]
            with open(cache, "w") as fh:
                json.dump({"steps_per_sec": baseline, "batch": batch}, fh)
        except Exception as e:  # noqa: BLE001 — bench must always print
            print(f"[bench] cpu probe failed: {e}", file=sys.stderr)
            baseline = None

    vs = (steps_per_sec / baseline) if baseline else 1.0
    print(json.dumps({
        "metric": "cifar10_convnet_allreduce_sgd_steps_per_sec",
        "value": round(steps_per_sec, 4),
        "unit": f"steps/s (global batch {batch}, {n_dev} {platform} chip(s))",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    if "--cpu-probe" in sys.argv:
        sps, n, plat, _ = _bench_backend(
            int(os.environ.get("BENCH_BATCH", "256")),
            int(os.environ.get("BENCH_ITERS", "3")), warmup=1)
        print(json.dumps({"value": sps}))
    else:
        main()
