#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Headline metric (BASELINE.json "metric"): CIFAR-10 ConvNet training
throughput in steps/sec with the fused AllReduceSGD step — the reference's
own hot path (examples/cifar10.lua per-batch loop, SURVEY.md §3.1) on the
attached accelerator.

Measurement protocol (designed so the number is physically defensible):

* ``BENCH_WINDOWS`` (default 5) timed windows of ``BENCH_ITERS`` (default
  100) *chained* steps each — state threads through the loop, so every step
  depends on the previous one and XLA cannot elide or overlap beyond a real
  pipeline.  The reported time is the MEDIAN window.
* Each window ends with ``jax.device_get`` of the final loss scalar — an
  actual device→host byte transfer.  ``block_until_ready`` alone is not
  trusted: on experimental platforms the completion signal can be
  optimistic, which produced round 1's impossible (>100% MFU) figure.
* MFU is computed per run: XLA ``cost_analysis`` flops of the compiled
  step ÷ step time ÷ the detected chip's bf16 peak.  MFU > 1.0 is a
  HARNESS ERROR — the process exits non-zero rather than report it.
* ``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
  comparison is against a *modeled* reference path: the identical step on
  this host's CPU via XLA (stand-in for the reference's default
  CPU-FloatTensor path — examples/cifar10.sh runs CPU nodes), measured with
  the same windowed protocol and cached in ``.bench_cpu_baseline.json``.

Secondary diagnostics (stderr + ``BENCH_DETAILS.json``): images/s, MFU,
per-step flops, a ResNet-50 utilization bench (the MFU-meaningful model),
gradient-allreduce GB/s (real mesh when >1 device; 8-device virtual CPU
mesh as the ICI proxy otherwise — BASELINE.md "gradient allreduce GB/s over
ICI" row), and the fused-vs-unfused Pallas update delta.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

PROTOCOL = "v3-scan-windowed-devget"


def _reserve_port_window(n: int, host: str = "127.0.0.1") -> int:
    """Base port ``p`` with ``p .. p+n-1`` all bindable a moment ago (the
    AsyncEA server binds a fan of ports — port, port+1..port+clients,
    port+clients+1; same pattern as tests/net_util.py)."""
    import socket
    from contextlib import closing
    for _ in range(256):
        with closing(socket.socket()) as probe:
            probe.bind((host, 0))
            base = probe.getsockname()[1]
        if base + n >= 65535:
            continue
        socks = []
        try:
            try:
                for i in range(n):
                    s = socket.socket()
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((host, base + i))
                    socks.append(s)
            except OSError:
                continue
            return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"could not reserve a window of {n} free ports")


def _enable_compile_cache():
    """Persistent XLA compilation cache: repeated bench runs (driver reruns,
    probe subprocesses) skip the 15-60s single-core compiles."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"[bench] no persistent compile cache: {e}", file=sys.stderr)


def _pin_cpu(n_devices: int | None = None):
    """Force the CPU backend in probe subprocesses (the env's sitecustomize
    may pre-import jax pinned to an attached TPU)."""
    from distlearn_tpu.utils.platform import force_cpu
    force_cpu(n_devices)

# bf16 peak FLOP/s per chip, by device_kind substring (public spec sheets).
_CHIP_PEAKS = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def detect_peak_flops():
    """(platform, device_kind, peak_bf16_flops_per_chip_or_None)."""
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform)
    if d.platform != "tpu":
        return d.platform, kind, None
    lk = kind.lower()
    for sub, peak in _CHIP_PEAKS:
        if sub in lk:
            return d.platform, kind, peak
    return d.platform, kind, None


def step_flops(jitted, *args):
    """XLA cost-analysis flops for one call of the compiled step."""
    try:
        ca = jitted.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):   # older jax returns [dict]
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill bench
        print(f"[bench] cost_analysis failed: {e}", file=sys.stderr)
        return None


def timed_windows(run_window, warmup_window, windows: int):
    """Median seconds per window.  ``run_window()`` must run the chained
    iterations AND force completion via a real device→host transfer."""
    warmup_window()
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        run_window()
        times.append(time.perf_counter() - t0)
    return statistics.median(times), times


def _cifar_model_and_tree():
    """(tree, model) with the bench's dtype policy (bf16 compute on TPU) —
    ONE place, so every CIFAR-based row benches the same model."""
    import jax
    import jax.numpy as jnp

    from distlearn_tpu.models import cifar_convnet
    from distlearn_tpu.parallel.mesh import MeshTree

    tree = MeshTree(num_nodes=len(jax.devices()))
    platform = jax.devices()[0].platform
    model = cifar_convnet(
        compute_dtype=jnp.bfloat16 if platform == "tpu" else None)
    return tree, model


def _stacked_cifar_batches(tree, batch: int, k: int):
    """K distinct synthetic batches stacked on a leading step axis, placed
    for the scanned trainers (spec ``P(None, data)``)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import synthetic_cifar10

    xs, ys = [], []
    for i in range(k):
        x, y, _ = synthetic_cifar10(batch, seed=i)
        xs.append(x); ys.append(y)
    sh = NamedSharding(tree.mesh, P(None, "data"))
    return jax.device_put(np.stack(xs), sh), jax.device_put(np.stack(ys), sh)


def _build_cifar(batch: int, fused=None, data=None, scan_k: int = 0):
    """``scan_k=0``: the per-call step (one host dispatch per step).
    ``scan_k=K``: the scanned step (K chained steps per dispatch,
    ``train.build_sgd_scan_step``) with K distinct stacked batches."""
    import jax
    from jax import random
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import synthetic_cifar10
    from distlearn_tpu.train import (build_sgd_scan_step, build_sgd_step,
                                     init_train_state)

    tree, model = _cifar_model_and_tree()
    n_dev = tree.num_nodes
    ts = init_train_state(model, tree, random.PRNGKey(0), 10)
    if scan_k:
        step = build_sgd_scan_step(model, tree, lr=0.1, fused=fused)
        bx, by = _stacked_cifar_batches(tree, batch, scan_k)
    else:
        step = build_sgd_step(model, tree, lr=0.1, fused=fused)
        if data is not None:
            bx, by = data           # reuse already-placed device batches
        else:
            x, y, _ = synthetic_cifar10(batch, seed=0)
            sh = NamedSharding(tree.mesh, P("data"))
            bx, by = jax.device_put(x, sh), jax.device_put(y, sh)
    return step, ts, bx, by, n_dev


def bench_step_fn(step, ts, bx, by, iters: int, windows: int, warmup: int,
                  steps_per_call: int = 1):
    """Windowed throughput of a ``step(ts,x,y)->(ts,loss)`` fn.  With
    ``steps_per_call=K`` (the scanned step) each call advances K training
    steps; ``iters`` always counts STEPS.  Returns
    (steps_per_sec, window_times, final_loss)."""
    import numpy as np
    import jax
    state = {"ts": ts, "loss": None}
    steps_per_call = max(1, steps_per_call)
    calls = max(1, iters // steps_per_call)
    steps = calls * steps_per_call

    def run(n_calls):
        ts = state["ts"]
        for _ in range(n_calls):
            ts, loss = step(ts, bx, by)
        state["ts"] = ts
        # Force REAL completion: pull the final loss over the wire.
        state["loss"] = float(np.ravel(jax.device_get(loss))[-1])

    med, times = timed_windows(
        lambda: run(calls), lambda: run(max(1, warmup // steps_per_call)),
        windows)
    return steps / med, times, state["loss"]


def run_bench_section(name: str, fn):
    """Run one bench section; retry ONCE iff the failure matches the
    tunnel's known transient signature (the remote-compile response body
    drops mid-read sporadically — observed twice on this host).
    Deterministic failures (OOM, HTTP 500 program-too-large, shape
    errors) fail fast.  Returns the section dict or None."""
    transient = ("response body closed", "read body")
    for attempt in (1, 2):
        try:
            return fn()
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 — a section must not kill bench
            print(f"[bench] {name} failed (attempt {attempt}): {e}",
                  file=sys.stderr)
            if attempt == 2 or not any(s in str(e) for s in transient):
                return None


def check_mfu(name: str, flops, steps_per_sec: float, peak):
    if not flops or not peak:
        return None
    mfu = flops * steps_per_sec / peak
    if mfu > 1.0:
        print(f"[bench] HARNESS ERROR: {name} MFU={mfu:.3f} > 1.0 "
              f"({flops:.3e} flops/step at {steps_per_sec:.1f} steps/s "
              f"exceeds chip peak {peak:.3e} FLOP/s). The timing or "
              f"completion signaling is broken; refusing to report.",
              file=sys.stderr)
        sys.exit(2)
    return mfu


def cpu_baseline(batch: int) -> float | None:
    """Measured-once-and-cached CPU steps/s for the same step (the modeled
    reference CPU-FloatTensor path)."""
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cpu_baseline.json")
    if os.path.exists(cache):
        try:
            with open(cache) as fh:
                rec = json.load(fh)
            if rec.get("batch") == batch and rec.get("protocol") == PROTOCOL:
                return rec["steps_per_sec"]
        except (OSError, ValueError, KeyError):
            pass
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_BATCH=str(batch),
               BENCH_ITERS="5", BENCH_WINDOWS="2", BENCH_WARMUP="1")
    env.pop("XLA_FLAGS", None)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-probe"],
            env=env, capture_output=True, timeout=3000, text=True)
        val = json.loads(out.stdout.strip().splitlines()[-1])["value"]
        with open(cache, "w") as fh:
            json.dump({"steps_per_sec": val, "batch": batch,
                       "protocol": PROTOCOL}, fh)
        return val
    except Exception as e:  # noqa: BLE001
        print(f"[bench] cpu probe failed: {e}", file=sys.stderr)
        return None


def allreduce_bench(size_mb: int, iters: int = 20):
    """Gradient-allreduce bandwidth on the current device mesh.  Returns a
    dict with algorithm bandwidth (payload/time) and ring bus bandwidth
    (2(n-1)/n · payload/time — the NCCL busbw convention, comparable to the
    ICI link spec)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("d",))
    nelem = size_mb * 1024 * 1024 // 4
    x = jax.device_put(
        np.random.RandomState(0).randn(n, nelem).astype(np.float32),
        NamedSharding(mesh, P("d")))

    def _pmean(v):
        return lax.pmean(jnp.squeeze(v, 0), "d")[None]

    f = jax.jit(jax.shard_map(_pmean, mesh=mesh, in_specs=(P("d"),),
                              out_specs=P("d"), check_vma=False))
    red = jax.jit(lambda v: jnp.sum(v[:, :8]))

    def run(k):
        nonlocal x
        for _ in range(k):
            x = f(x)
        float(jax.device_get(red(x)))   # force completion

    med, times = timed_windows(lambda: run(iters), lambda: run(3), 3)
    payload = nelem * 4
    t = med / iters
    return {
        "devices": n,
        "payload_mb": size_mb,
        "sec_per_allreduce": t,
        "algbw_gb_s": payload / t / 1e9,
        "busbw_gb_s": (2 * (n - 1) / n) * payload / t / 1e9,
        "window_times": times,
    }


def allreduce_proxy_cpu8(size_mb: int):
    """1-chip host: measure the allreduce microbench on an 8-device virtual
    CPU mesh (the BASELINE.md ICI-efficiency proxy available without a pod)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               BENCH_AR_MB=str(size_mb))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--allreduce-probe"],
            env=env, capture_output=True, timeout=1200, text=True)
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        rec["proxy"] = "cpu8_virtual_mesh"
        return rec
    except Exception as e:  # noqa: BLE001
        print(f"[bench] allreduce proxy failed: {e}", file=sys.stderr)
        return None


# Approximate PUBLIC per-link one-direction ICI bandwidth (GB/s) by chip
# generation — the ring-allreduce busbw ceiling (each chip drives one link
# per direction in the steady state).  Used only to turn a measured busbw
# into the BASELINE.md "ICI allreduce efficiency" percentage on REAL
# multi-chip meshes; never applied to the CPU proxy.
_ICI_LINK_GB_S = (
    ("v6", 90.0),
    ("v5p", 90.0),
    ("v5 lite", 45.0),
    ("v5e", 45.0),
    ("v4", 45.0),
    ("v3", 70.0),
)


def _ici_link_spec():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for sub, bw in _ICI_LINK_GB_S:
        if sub in kind:
            return bw
    return None


def multichip_suite(ar_mb: int = 64):
    """The measurements that only mean something on a multi-device mesh,
    in one function that runs UNMODIFIED on any device count — so the day
    real multi-chip hardware is attached, hardware day is measurement day
    (VERDICT r3 #3).  Rows:

    * ``allreduce``: psum busbw on the full mesh; on a real TPU mesh also
      ``ici_efficiency`` vs the public per-link spec (BASELINE.md's >=90%
      v4-32 target row).
    * ``dp_scaling``: the headline CIFAR scanned AllReduceSGD step at
      fixed per-device batch on a 1-device vs full mesh — weak-scaling
      efficiency (each n-device step does n times the work).
    * ``easgd_round``: one fused elastic round (the EASGD collective) on
      the full mesh.
    * ``pp_lm``: a REAL S>1 pipeline row — GPipe LM train step over
      (1, S) stages, microbatched.

    On the 1-real-chip host, main() runs this via a subprocess on the
    8-device virtual CPU mesh and labels every row ``proxy`` — protocol
    evidence, not bandwidth evidence.
    """
    import jax
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    out: dict = {"devices": n_dev, "platform": platform}

    # -- allreduce busbw vs ICI spec ----------------------------------------
    # (CPU proxy: fewer iterations — the 8-virtual-devices-on-one-core
    # collective is minutes per window at full count)
    ar = allreduce_bench(ar_mb, iters=20 if platform == "tpu" else 5)
    spec = _ici_link_spec() if platform == "tpu" else None
    if spec:
        ar["ici_link_spec_gb_s"] = spec
        ar["ici_efficiency"] = ar["busbw_gb_s"] / spec
    out["allreduce"] = ar

    # -- DP weak scaling of the headline step -------------------------------
    # CPU-proxy runs shrink the workload: the convnet step is seconds per
    # call on one CPU core, and the proxy's job is protocol/scaling-shape
    # evidence, not throughput
    on_tpu = platform == "tpu"
    per_dev_batch = int(os.environ.get("BENCH_MC_BATCH",
                                       "64" if on_tpu else "4"))
    scan_k = max(1, int(os.environ.get("BENCH_MC_SCAN_K",
                                       "4" if on_tpu else "2")))
    iters = int(os.environ.get("BENCH_MC_ITERS", "5" if on_tpu else "1"))
    mc_windows = 3 if on_tpu else 2

    def cifar_sps(num_nodes):
        from distlearn_tpu.train import build_sgd_scan_step, init_train_state
        from distlearn_tpu.models import cifar_convnet
        from distlearn_tpu.parallel.mesh import MeshTree
        import jax.numpy as jnp
        tree = MeshTree(num_nodes=num_nodes)
        model = cifar_convnet(
            compute_dtype=jnp.bfloat16 if platform == "tpu" else None)
        ts = init_train_state(model, tree, random.PRNGKey(0), 10)
        step = build_sgd_scan_step(model, tree, lr=0.1)
        bx, by = _stacked_cifar_batches(tree, per_dev_batch * num_nodes,
                                        scan_k)
        sps, _, _ = bench_step_fn(step, ts, bx, by, iters * scan_k,
                                  mc_windows, scan_k,
                                  steps_per_call=scan_k)
        return sps

    sps_1 = cifar_sps(1)
    sps_n = cifar_sps(n_dev) if n_dev > 1 else sps_1
    out["dp_scaling"] = {
        "per_device_batch": per_dev_batch,
        "steps_per_sec_1dev": sps_1,
        "steps_per_sec_full": sps_n,
        # each full-mesh step processes n_dev x the examples
        "weak_scaling_efficiency": (sps_n / sps_1) if sps_1 else None,
    }

    # -- one fused EASGD elastic round --------------------------------------
    from distlearn_tpu.train import build_ea_cycle, init_ea_state
    tree, model = _cifar_model_and_tree()
    ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
    cyc = build_ea_cycle(model, tree, lr=0.1, alpha=0.2)
    tau = int(os.environ.get("BENCH_EA_TAU", "10" if on_tpu else "2"))
    bx, by = _stacked_cifar_batches(tree, per_dev_batch * n_dev, tau)
    # one cyc() call = tau local steps + ONE elastic round
    ea_sps, _, _ = bench_step_fn(cyc, ets, bx, by,
                                 (3 if on_tpu else 1) * tau, mc_windows,
                                 tau, steps_per_call=tau)
    out["easgd_round"] = {"tau": tau,
                          "cycles_per_sec": ea_sps / tau,
                          "local_steps_per_sec": ea_sps}

    # -- real S>1 pipeline row ----------------------------------------------
    if n_dev >= 2:
        import jax.numpy as jnp
        from distlearn_tpu.models.transformer import transformer_lm
        from distlearn_tpu.train.lm import (build_lm_pp_1f1b_step,
                                            build_lm_pp_step, stack_blocks)
        S = min(4, n_dev)
        M = int(os.environ.get("BENCH_MC_PP_MICROBATCHES",
                               "8" if on_tpu else "4"))
        dim = int(os.environ.get("BENCH_MC_PP_DIM",
                                 "256" if on_tpu else "64"))
        seq = int(os.environ.get("BENCH_MC_PP_SEQ",
                                 "128" if on_tpu else "64"))
        depth = 2 * S
        pp_mesh = Mesh(np.asarray(jax.devices()[:S]).reshape(1, S),
                       ("data", "pipe"))
        lm = transformer_lm(vocab=2048, dim=dim, depth=depth,
                            heads=max(1, dim // 64), max_len=seq,
                            compute_dtype=jnp.bfloat16
                            if platform == "tpu" else None)
        params, _ = lm.init(random.PRNGKey(1))
        shared, stacked = stack_blocks(params, depth)
        shared = jax.device_put(shared, NamedSharding(pp_mesh, P()))
        stacked = jax.device_put(stacked, NamedSharding(pp_mesh, P("pipe")))
        # donate=False: both schedules start from the SAME placed arrays
        # (a donating step would consume them on its first call)
        step = build_lm_pp_step(pp_mesh, shared, stacked, lr=0.1,
                                num_microbatches=M, remat=True,
                                donate=False)
        toks = jax.device_put(
            np.random.RandomState(0).randint(0, 2048, (M * 2, seq))
            .astype(np.int32), NamedSharding(pp_mesh, P("data")))
        st = {"s": shared, "k": stacked}

        def run_pp(k):
            sh, stk = st["s"], st["k"]
            for _ in range(k):
                sh, stk, loss = step(sh, stk, toks)
            st["s"], st["k"] = sh, stk
            float(jax.device_get(loss))

        med, _ = timed_windows(lambda: run_pp(3), lambda: run_pp(1), 3)
        out["pp_lm"] = {
            "stages": S, "microbatches": M, "dim": dim, "depth": depth,
            "seq_len": seq, "steps_per_sec": 3 / med,
            "tokens_per_sec": 3 * M * 2 * seq / med,
            "bubble_fraction": (S - 1) / (M + S - 1),
        }

        # same pipeline under the 1F1B schedule: O(S) activation liveness
        # vs GPipe's O(M) — throughput comparison + the compiled temp
        # memory delta where the platform exposes it
        step_f = build_lm_pp_1f1b_step(pp_mesh, shared, stacked, lr=0.1,
                                       num_microbatches=M, remat=True,
                                       donate=False)
        st_f = {"s": shared, "k": stacked}

        def run_pp_f(k):
            sh, stk = st_f["s"], st_f["k"]
            for _ in range(k):
                sh, stk, loss = step_f(sh, stk, toks)
            st_f["s"], st_f["k"] = sh, stk
            float(jax.device_get(loss))

        med_f, _ = timed_windows(lambda: run_pp_f(3), lambda: run_pp_f(1), 3)
        row = {"stages": S, "microbatches": M,
               "steps_per_sec": 3 / med_f,
               "tokens_per_sec": 3 * M * 2 * seq / med_f,
               "vs_gpipe": med / med_f}
        try:
            tb = (lambda fn: fn.lower(shared, stacked, toks).compile()
                  .memory_analysis().temp_size_in_bytes)
            row["temp_bytes"] = tb(step_f)
            row["gpipe_temp_bytes"] = tb(step)
        except Exception:   # noqa: BLE001 — not all platforms expose it
            pass
        out["pp_lm_1f1b"] = row

        # compile-time memory evidence for the schedule trade (exact
        # allocator facts — valid on the proxy; see pp_memory_sweep).
        # Supplementary: a parse/setup failure must not discard the rows
        # already collected above.
        try:
            ms = tuple(int(v.strip()) for v in os.environ.get(
                "BENCH_PP_MEM_MS", "4,16").split(","))
            pm = pp_memory_sweep(S=min(4, n_dev), Ms=ms)
            if pm:
                out["pp_memory"] = pm
        except Exception as e:  # noqa: BLE001
            print(f"[bench] pp_memory sweep failed: {e}", file=sys.stderr)

    if os.environ.get("BENCH_SKIP_SCALING") != "1":
        budget = None                       # sweep's own default
        deadline_ts = os.environ.get("BENCH_PROXY_DEADLINE_TS")
        if deadline_ts:
            remaining = float(deadline_ts) - time.time()
            if remaining < 60.0:
                print("[bench] skipping scaling sweep: <60s left before "
                      "the proxy subprocess deadline", file=sys.stderr)
                out["scaling_sweep"] = {"skipped": "proxy deadline"}
                return out
            budget = min(remaining, float(os.environ.get(
                "BENCH_SCALING_BUDGET_S", "600")))
        try:
            out["scaling_sweep"] = multichip_scaling_sweep(
                budget_s=budget)
        except Exception as e:  # noqa: BLE001 — trend is supplementary
            print(f"[bench] scaling sweep failed: {e}", file=sys.stderr)
    return out


def multichip_scaling_sweep(Ns=None, reps: int = 2,
                            budget_s: float | None = None):
    """Per-N step-time trend for the five parallel modes, N in {1,2,4,8}
    capped by the attached mesh — the quantitative curve behind the
    multichip dryrun's pass/fail evidence (VERDICT r4 next #5).

    Scaling mode per component: ``weak`` holds PER-DEVICE work constant
    (sgd / easgd / pipeline / moe — batch, tau-cycle, one stage-block, or
    one expert per device), ``strong`` holds TOTAL work constant and
    shards it (zigzag-SP: one fixed sequence split over N ring ranks).

    CPU-PROXY CAVEAT (stated in the record): the 1-core host TIME-SHARES
    the N virtual devices, so raw weak-scaling time grows ~N by
    construction.  The meaningful proxy number is ``overhead_share`` =
    1 - ideal/t(N) with ideal = N*t(1) (weak) or t(1) (strong) — the
    fraction of the N-device step NOT explained by serialized copies of
    the single-device compute (collectives + resharding + schedule
    bubbles + runtime).  On a real mesh the same record computes the
    standard efficiencies (ideal = t(1) weak, t(1)/N strong)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    Ns = Ns or [n for n in (1, 2, 4, 8) if n <= n_dev]
    budget_s = budget_s if budget_s is not None else float(
        os.environ.get("BENCH_SCALING_BUDGET_S", "600"))
    t_start = time.monotonic()

    def timed(fn, reps=reps):
        import time as _t
        fn()                                    # warmup (compile)
        best = float("inf")
        for _ in range(reps):
            t0 = _t.perf_counter()
            fn()
            best = min(best, _t.perf_counter() - t0)
        return best

    def sgd_t(N):
        from distlearn_tpu.models import cifar_convnet
        from distlearn_tpu.parallel.mesh import MeshTree
        from distlearn_tpu.train import build_sgd_step, init_train_state
        tree = MeshTree(num_nodes=N)
        model = cifar_convnet(dropout_rate=0.0)
        ts = init_train_state(model, tree, random.PRNGKey(0), 10)
        step = build_sgd_step(model, tree, lr=0.1, donate=False)
        sh = NamedSharding(tree.mesh, P(tree.axis_name))
        rng = np.random.RandomState(0)
        b = 2 * N                     # 2/device: trend, not throughput
        bx = jax.device_put(rng.randn(b, 32, 32, 3)
                            .astype(np.float32), sh)
        by = jax.device_put(rng.randint(0, 10, (b,))
                            .astype(np.int32), sh)
        return timed(lambda: jax.block_until_ready(step(ts, bx, by)[1]))

    def ea_t(N):
        from distlearn_tpu.models import cifar_convnet
        from distlearn_tpu.parallel.mesh import MeshTree
        from distlearn_tpu.train import build_ea_cycle, init_ea_state
        tree = MeshTree(num_nodes=N)
        model = cifar_convnet(dropout_rate=0.0)
        ets = init_ea_state(model, tree, random.PRNGKey(0), 10)
        tau = 2
        cyc = build_ea_cycle(model, tree, lr=0.1, alpha=0.2,
                             donate=False)
        bx, by = _stacked_cifar_batches(tree, 2 * N, tau)
        return timed(lambda: jax.block_until_ready(cyc(ets, bx, by)[1]))

    def zigzag_t(N):
        from distlearn_tpu.models.transformer import transformer_lm
        from distlearn_tpu.parallel.sequence import zigzag_indices
        from distlearn_tpu.train.lm import build_lm_step
        L = 256                                  # TOTAL length, fixed
        mesh = Mesh(np.asarray(jax.devices()[:N]).reshape(1, N, 1),
                    ("data", "seq", "model"))
        lm = transformer_lm(vocab=64, dim=64, depth=2, heads=2,
                            max_len=L)
        params, _ = lm.init(random.PRNGKey(1))
        layout = "zigzag" if N > 1 else "contig"
        step = build_lm_step(lm, mesh, params, lr=0.1, donate=False,
                             seq_layout=layout)
        toks = np.random.RandomState(0).randint(0, 64, (2, L))
        if N > 1:
            toks = toks[:, zigzag_indices(N, L)]
        toks = jax.device_put(toks.astype(np.int32),
                              NamedSharding(mesh, P("data", "seq")))
        return timed(lambda: jax.block_until_ready(step(params, toks)[1]))

    def pp_t(N):
        from distlearn_tpu.models.transformer import transformer_lm
        from distlearn_tpu.train.lm import build_lm_pp_step, stack_blocks
        mesh = Mesh(np.asarray(jax.devices()[:N]).reshape(1, N),
                    ("data", "pipe"))
        lm = transformer_lm(vocab=64, dim=64, depth=N, heads=2,
                            max_len=32)
        params, _ = lm.init(random.PRNGKey(2))
        shared, stacked = stack_blocks(params, N)
        shared = jax.device_put(shared, NamedSharding(mesh, P()))
        stacked = jax.device_put(stacked,
                                 NamedSharding(mesh, P("pipe")))
        step = build_lm_pp_step(mesh, shared, stacked, lr=0.1,
                                num_microbatches=4, donate=False)
        toks = jax.device_put(
            np.random.RandomState(0).randint(0, 64, (8, 32))
            .astype(np.int32), NamedSharding(mesh, P("data")))
        return timed(
            lambda: jax.block_until_ready(step(shared, stacked, toks)[2]))

    def moe_t(N):
        from distlearn_tpu.parallel.ep import moe_ffn
        mesh = Mesh(np.asarray(jax.devices()[:N]), ("expert",))
        rng = np.random.RandomState(3)
        p = {"experts": jnp.asarray(rng.randn(N, 16, 16)
                                    .astype(np.float32) * 0.5),
             "router": jnp.asarray(rng.randn(16, N).astype(np.float32))}
        x = jnp.asarray(rng.randn(N, 8, 16).astype(np.float32))

        def _moe(pp, xx):
            return moe_ffn(lambda w, h: jnp.tanh(h @ w),
                           jnp.squeeze(pp["experts"], 0), pp["router"],
                           jnp.squeeze(xx, 0), axis_name="expert")[None]

        f = jax.jit(jax.shard_map(
            _moe, mesh=mesh,
            in_specs=({"experts": P("expert"), "router": P()},
                      P("expert")),
            out_specs=P("expert"), check_vma=False))
        return timed(lambda: jax.block_until_ready(f(p, x)))

    comps = {"allreduce_sgd": (sgd_t, "weak"),
             "easgd_cycle": (ea_t, "weak"),
             "zigzag_sp_lm": (zigzag_t, "strong"),
             "pipeline_lm": (pp_t, "weak"),
             "moe_ep": (moe_t, "weak")}
    out = {"devices": n_dev, "platform": platform, "Ns": Ns,
           "proxy_caveat": (
               "1-core host: N virtual devices serialize compute, so "
               "weak times grow ~N by construction; overhead_share is "
               "the proxy-meaningful number" if platform != "tpu"
               else None),
           "components": {}}
    for name, (fn, mode) in comps.items():
        if time.monotonic() - t_start > budget_s:
            # the sweep is supplementary evidence riding the dryrun: it
            # must never push the dryrun itself past ITS budget
            out["truncated_after"] = name
            print(f"[bench] scaling sweep budget ({budget_s:.0f}s) "
                  f"reached — stopping before {name}", file=sys.stderr)
            break
        times, t1 = {}, None
        for N in Ns:
            if time.monotonic() - t_start > budget_s:
                # also between Ns: one slow compile must not let a
                # component overshoot the budget unboundedly
                out["truncated_after"] = f"{name} N<{N}"
                break
            try:
                t = fn(N)
            except Exception as e:  # noqa: BLE001
                print(f"[bench] scaling {name} N={N} failed: {e}",
                      file=sys.stderr)
                break
            times[N] = t
            if N == 1:
                t1 = t
        rec = {"mode": mode, "step_seconds": times}
        if t1:
            if platform == "tpu":
                ideal = {N: (t1 if mode == "weak" else t1 / N)
                         for N in times}
            else:
                ideal = {N: (N * t1 if mode == "weak" else t1)
                         for N in times}
            rec["efficiency"] = {N: ideal[N] / times[N] for N in times}
            rec["overhead_share"] = {
                N: max(0.0, 1.0 - ideal[N] / times[N]) for N in times}
        out["components"][name] = rec
        if times:
            print(f"[bench] scaling {name} ({mode}): "
                  + ", ".join(f"N={N}:{t*1e3:.0f}ms"
                              + (f" eff={rec['efficiency'][N]:.2f}"
                                 if t1 else "")
                              for N, t in times.items()),
                  file=sys.stderr)
    return out


def pp_memory_sweep(S: int = 4, Ms=(4, 8, 16, 32), dim: int = 64,
                    seq: int = 64, vocab: int = 64):
    """Compiled peak-temp-memory evidence for the 1F1B schedule's O(S)
    activation-liveness claim (parallel/pp.py): lower+compile the SAME
    pipeline under GPipe and 1F1B across a microbatch sweep and record
    ``memory_analysis().temp_size_in_bytes`` plus the bubble fraction.
    GPipe's autodiff residuals grow with M (every in-flight microbatch's
    saved inputs stay live through the reversed backward scan); 1F1B
    holds at most ``2S-1`` stage inputs, so its temp memory should stay
    ~flat while M climbs — the reason M can be cranked for bubble
    amortization.  Pure compile-time analysis: no step executes, so the
    numbers are exact allocator facts, valid on the CPU proxy."""
    import jax
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import (build_lm_pp_1f1b_step,
                                        build_lm_pp_step, stack_blocks)

    if len(jax.devices()) < S:
        return None
    mesh = Mesh(np.asarray(jax.devices()[:S]).reshape(1, S),
                ("data", "pipe"))
    lm = transformer_lm(vocab=vocab, dim=dim, depth=S,
                        heads=max(1, dim // 32), max_len=seq)
    params, _ = lm.init(random.PRNGKey(1))
    shared, stacked = stack_blocks(params, S)
    shared = jax.device_put(shared, NamedSharding(mesh, P()))
    stacked = jax.device_put(stacked, NamedSharding(mesh, P("pipe")))
    rows = []
    for M in Ms:
        toks = jax.device_put(
            np.zeros((M * 2, seq), np.int32),
            NamedSharding(mesh, P("data")))

        def temp_bytes(builder):
            step = builder(mesh, shared, stacked, lr=0.1,
                           num_microbatches=M, remat=True, donate=False)
            return int(step.lower(shared, stacked, toks).compile()
                       .memory_analysis().temp_size_in_bytes)

        try:
            g = temp_bytes(build_lm_pp_step)
            f = temp_bytes(build_lm_pp_1f1b_step)
        except Exception as e:  # noqa: BLE001 — platform w/o the API
            print(f"[bench] pp_memory_sweep M={M} failed: {e}",
                  file=sys.stderr)
            return rows or None
        rows.append({
            "stages": S, "microbatches": M, "dim": dim, "seq": seq,
            "gpipe_temp_bytes": g, "f1b_temp_bytes": f,
            "f1b_over_gpipe": f / g,
            "bubble_fraction_gpipe": (S - 1) / (M + S - 1),
            "bubble_fraction_1f1b": (2 * S - 2) / (M + 2 * S - 2),
        })
        print(f"[bench] pp_memory S={S} M={M}: gpipe {g/1e6:.1f} MB, "
              f"1f1b {f/1e6:.1f} MB ({f/g:.2f}x)", file=sys.stderr)
    return rows


def multichip_proxy_cpu(n: int = 8):
    """1-chip host: run :func:`multichip_suite` on an ``n``-device virtual
    CPU mesh in a subprocess (same command path real hardware will take),
    labeling the result a proxy.  The proxy defaults to a smaller
    allreduce payload than the real-mesh default — 8 virtual devices
    time-share ONE core here, and a 64 MB collective pushed the run past
    its timeout (observed) for no extra protocol coverage."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={n}")
    env.setdefault("BENCH_AR_MB", "16")
    # absolute wall deadline for the SUPPLEMENTARY sections (the scaling
    # sweep): whatever time the earlier suite rows consumed, the sweep
    # only gets what remains before the subprocess kill below — losing
    # the sweep is fine, losing every already-measured row to the kill
    # is not.  150s slack covers teardown + JSON emit.
    env["BENCH_PROXY_DEADLINE_TS"] = str(time.time() + 2700 - 150)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--multichip-probe"],
            env=env, capture_output=True, timeout=2700, text=True)
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        rec["proxy"] = "cpu_virtual_mesh"
        return rec
    except Exception as e:  # noqa: BLE001
        print(f"[bench] multichip proxy failed: {e}", file=sys.stderr)
        if 'out' in dir() and out.stderr:
            print(out.stderr[-800:], file=sys.stderr)
        return None


def host_allreduce_bench(size_mb: int = 16, n: int = 4, iters: int = 5):
    """Host (DCN/TCP) backend microbench: the same payload allreduced through
    the base-2 tree (the reference's topology, ``T*log2(N)`` —
    lua/AllReduceEA.md:26-30) and the bandwidth-optimal ring
    (``2T*(N-1)/N`` per link).  Localhost threads are a protocol proxy — on
    real multi-host DCN the ring's lower per-link traffic is the win.
    Returns busbw GB/s for both (NCCL convention)."""
    import time as _t

    import numpy as np

    from distlearn_tpu.comm.ring import LocalhostRing
    from distlearn_tpu.comm.tree import LocalhostTree, tree_map_spawn

    def _port():
        return _reserve_port_window(1)

    nelem = size_mb * 1024 * 1024 // 4
    payload = nelem * 4

    def run_once_iters(make, k):
        port = _port()

        def node(rank):
            h = make(rank, port)
            x = np.random.RandomState(rank).randn(nelem).astype(np.float32)
            h.all_reduce(x)         # warmup
            h.barrier()
            t0 = _t.perf_counter()
            for _ in range(k):
                h.all_reduce(x)
            dt = _t.perf_counter() - t0
            h.close()
            return dt
        times = tree_map_spawn(node, n, timeout=600)
        return max(times) / k         # collective ends when slowest ends

    def run_once(make):
        return run_once_iters(make, iters)

    def run(make, reps: int = 3):
        # localhost on a shared CPU is noisy (observed 0.8-1.5x run-to-run):
        # take the median of independent topologies
        return statistics.median(run_once(make) for _ in range(reps))

    def _conns(h):
        if hasattr(h, "_succ"):          # Ring: successor + predecessor
            return [c for c in (h._succ, h._pred) if c is not None]
        return ([h._parent] if h._parent else []) + list(h._kids)   # Tree

    def _throttled(make, bps):
        def mk(rank, port):
            h = make(rank, port)
            for c in _conns(h):
                c.throttle_bps = bps
            return h
        return mk

    def max_nic_bytes(make):
        """One allreduce; the busiest HOST's total wire traffic (sent +
        received over every one of that rank's connections) — the per-NIC
        contention the bandwidth claims are about, MEASURED.  Base-2 tree
        root: 2 children x payload up and down = ~4T; ring rank: 
        2T(N-1)/N out + the same in = ~3T at N=4, -> 2T as N grows."""
        port = _port()

        def node(rank):
            h = make(rank, port)
            x = np.random.RandomState(rank).randn(nelem).astype(np.float32)
            base = sum(c.bytes_sent + c.bytes_received for c in _conns(h))
            h.all_reduce(x)
            got = sum(c.bytes_sent + c.bytes_received
                      for c in _conns(h)) - base
            h.close()
            return got
        return max(tree_map_spawn(node, n, timeout=600))

    t_tree = run(lambda r, p: LocalhostTree(r, n, p, base=2))
    t_ring = run(lambda r, p: LocalhostRing(r, n, p))
    bus = lambda t: (2 * (n - 1) / n) * payload / t / 1e9  # noqa: E731
    out = {
        "devices": n, "payload_mb": size_mb,
        "tree_sec": t_tree, "ring_sec": t_ring,
        "tree_busbw_gb_s": bus(t_tree), "ring_busbw_gb_s": bus(t_ring),
        "ring_speedup": t_tree / t_ring,
        # measured per-NIC traffic (the structural claim, independent of
        # this host's shared-CPU wall clock)
        "tree_max_nic_bytes": max_nic_bytes(
            lambda r, p: LocalhostTree(r, n, p, base=2)),
        "ring_max_nic_bytes": max_nic_bytes(
            lambda r, p: LocalhostRing(r, n, p)),
        "payload_bytes": payload,
    }
    # Bandwidth-limited emulation: pace every link to a fixed bytes/sec
    # (slow enough that the shared CPU is NOT the bottleneck).  This is
    # the regime the ring is for — real per-host NICs — and where its
    # 2T(N-1)/N per-link traffic beats the tree's root hotspot; on the
    # unthrottled loopback above both backends move the same TOTAL bytes
    # through one CPU, so the tree's fewer rounds win instead.
    bps = float(os.environ.get("BENCH_HOST_EMULATED_LINK_MB_S",
                               "200")) * 1e6
    emu_iters = 2
    t_tree_e = run_once_iters(
        _throttled(lambda r, p: LocalhostTree(r, n, p, base=2), bps),
        emu_iters)
    t_ring_e = run_once_iters(
        _throttled(lambda r, p: LocalhostRing(r, n, p), bps), emu_iters)
    out.update({
        "emulated_link_mb_s": bps / 1e6,
        "tree_sec_emulated": t_tree_e, "ring_sec_emulated": t_ring_e,
        "ring_speedup_emulated": t_tree_e / t_ring_e,
    })
    return out


def _host_sync_hybrid_child(rank, hosts, local, port, nelem, iters, bps,
                            conn):
    """One hybrid host rank in its own process (module-level for
    multiprocessing spawn): its private XLA runtime hosts the L-device
    mesh; the TCP leg joins the other host over real localhost sockets.
    Reports ``(host_leg_nic_bytes_per_sync, timed_seconds)``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={local}")
    import time as _t

    import numpy as np

    from distlearn_tpu.comm.backend import HybridBackend

    b = HybridBackend(rank, hosts, "127.0.0.1", port,
                      num_devices=local, base=2)
    if bps is not None:
        for c in b.host_leg._links():
            c.throttle_bps = bps
    rows = np.stack([
        np.random.RandomState(rank * local + i).randn(nelem)
        .astype(np.float32) for i in range(local)])
    b.all_reduce(rows)                            # warmup (jit + caches)
    b.barrier()
    nic0 = b.host_leg.nic_bytes()
    b.all_reduce(rows)
    nic = b.host_leg.nic_bytes() - nic0
    b.barrier()
    t0 = _t.perf_counter()
    for _ in range(iters):
        b.all_reduce(rows)
    dt = _t.perf_counter() - t0
    b.close()
    conn.send((nic, dt))
    conn.close()


def host_sync_bench(size_mb: int = 2, hosts: int = 2, local: int = 8,
                    iters: int = 3):
    """Collective-backend comparison (ISSUE 20): the same H*L-node
    allreduce through (a) ``HostBackend`` — every logical node its own
    TCP tree rank, the flat reference topology — vs (b)
    ``HybridBackend`` — L device-nodes behind ONE TCP rank per host,
    in-mesh reduce-scatter / host tree leg / in-mesh all-gather.

    Two measurements per backend:

    * **Host-leg bytes per host** (unthrottled, MEASURED off
      ``Conn.bytes_sent + bytes_received``): the busiest host's total
      TCP traffic for one sync.  Flat: each of a host's L ranks moves
      >= 2T up+down, so >= 2*L*T per host.  Hybrid: ~2T — the
      hierarchical win is ~L-fold, structural, independent of wall
      clock.
    * **Syncs/s on an emulated slow link** (every conn paced to
      ``BENCH_HOST_EMULATED_LINK_MB_S``, default 200 — the multi-host
      DCN regime): fewer bytes through the bottleneck = more syncs/s.

    The flat topology is localhost threads (no device work); each
    hybrid host rank is its OWN process — one XLA runtime per host, as
    deployed — so the two hosts' in-mesh shard_map collectives cannot
    cross-join one process's rendezvous.
    """
    import multiprocessing as _mp
    import time as _t

    import numpy as np

    from distlearn_tpu.comm.backend import HostBackend
    from distlearn_tpu.comm.tree import LocalhostTree, tree_map_spawn

    n = hosts * local
    nelem = size_mb * 1024 * 1024 // 4
    payload = nelem * 4

    def _run_flat(bps=None):
        """Flat HostBackend: warmup sync, NIC-byte-metered sync, then
        ``iters`` timed syncs (throttled when ``bps``).  Returns
        (max per-host host-leg bytes, sec_per_sync)."""
        port = _reserve_port_window(1)

        def node(rank):
            b = HostBackend(LocalhostTree(rank, n, port, base=2))
            if bps is not None:
                for c in b.handle._links():
                    c.throttle_bps = bps
            v = np.random.RandomState(rank).randn(nelem).astype(np.float32)
            b.all_reduce(v)                       # warmup
            b.barrier()
            nic0 = b.handle.nic_bytes()
            b.all_reduce(v)
            nic = b.handle.nic_bytes() - nic0
            b.barrier()
            t0 = _t.perf_counter()
            for _ in range(iters):
                b.all_reduce(v)
            dt = _t.perf_counter() - t0
            b.close()
            return nic, dt
        res = tree_map_spawn(node, n, timeout=600)
        # a "host" is a group of L adjacent ranks; its NIC moves the
        # sum of their tree traffic
        per_host = [sum(res[h * local + i][0] for i in range(local))
                    for h in range(hosts)]
        return max(per_host), max(r[1] for r in res) / iters

    def _run_hybrid(bps=None):
        port = _reserve_port_window(1)
        ctx = _mp.get_context("spawn")
        pipes, procs = [], []
        for r in range(hosts):
            rd, wr = ctx.Pipe(False)
            p = ctx.Process(target=_host_sync_hybrid_child,
                            args=(r, hosts, local, port, nelem, iters,
                                  bps, wr))
            p.start()
            procs.append(p)
            pipes.append(rd)
        res = []
        for rd in pipes:
            if not rd.poll(570):
                for p in procs:
                    p.terminate()
                raise TimeoutError("hybrid sync child did not report")
            res.append(rd.recv())
        for p in procs:
            p.join(60)
        return max(r[0] for r in res), max(r[1] for r in res) / iters

    bus = lambda t: (2 * (n - 1) / n) * payload / t / 1e9  # noqa: E731
    bps = float(os.environ.get("BENCH_HOST_EMULATED_LINK_MB_S",
                               "200")) * 1e6

    flat_bytes, flat_t = _run_flat()
    hyb_bytes, hyb_t = _run_hybrid()
    _, flat_te = _run_flat(bps=bps)
    _, hyb_te = _run_hybrid(bps=bps)

    def row(host_bytes, t, te):
        return {"host_leg_bytes_per_host": host_bytes,
                "sec_per_sync": t, "busbw_gb_s": bus(t),
                "sec_per_sync_emulated": te,
                "syncs_per_sec_emulated": 1.0 / te,
                "busbw_gb_s_emulated": bus(te)}

    return {
        "hosts": hosts, "local_devices": local, "logical_nodes": n,
        "payload_mb": size_mb, "payload_bytes": payload,
        "emulated_link_mb_s": bps / 1e6,
        "host_backend": row(flat_bytes, flat_t, flat_te),
        "hybrid_backend": row(hyb_bytes, hyb_t, hyb_te),
        "host_leg_byte_reduction": flat_bytes / hyb_bytes,
        "hybrid_sync_speedup_emulated": flat_te / hyb_te,
    }


#: EASGD-shaped pytree leaf lists for the wire microbench — the EXACT
#: leaf shapes of the repo's models (distlearn_tpu/models/, hardcoded so
#: the bench stays chip-free and jax-import-free): many small bias/bn
#: vectors + a few large kernels, NOT one flat blob, since per-leaf
#: framing overhead is what the packed wire removes.  fp32 sizes:
#: mnist_cnn 43 KB / 6 leaves, cifar_convnet 17.3 MB / 26 leaves.
_WIRE_PARAM_SETS = {
    "mnist_cnn": [(16,), (5, 5, 1, 16), (16,), (5, 5, 16, 16),
                  (10,), (400, 10)],
    "cifar_convnet": [
        (64,), (64,), (128,), (128,), (256,), (256,), (512,), (512,),
        (64,), (5, 5, 3, 64), (128,), (5, 5, 64, 128),
        (256,), (5, 5, 128, 256), (512,), (5, 5, 256, 512),
        (10,), (2048, 10),
        (64,), (64,), (128,), (128,), (256,), (256,), (512,), (512,)],
}


def host_wire_bench(iters: int = 20, reps: int = 3):
    """Chip-free host-comm wire microbench (runs even while the TPU tunnel
    is down): one EASGD-shaped echo sync — leaf list up, echo back down —
    over localhost TCP, per wire mode.  ``perleaf`` is the legacy one
    frame per leaf ('T'); ``raw``/``fp16``/``int8`` are the packed 'P'
    frame per codec (comm/wire.py).  Reports syncs/s (best of ``reps``
    timed windows — localhost on a shared CPU is noisy) and measured wire
    bytes/sync from the Conn byte counters.

    Two regimes per param set: the raw loopback (syscall/framing-bound at
    MNIST scale, memcpy-bound at CIFAR scale — coalescing wins where
    framing dominates) and an emulated fixed-bandwidth link via
    ``Conn.throttle_bps`` (the multi-host regime, where the quantized
    codecs' byte reduction converts directly into syncs/s)."""
    import threading
    import time as _t

    import numpy as np

    from distlearn_tpu.comm import Server, connect

    modes = ("perleaf", "raw", "fp16", "int8")

    def measure(leaves, mode, k, r, bps=None):
        srv = Server("127.0.0.1", 0)
        errs: list = []
        nsync = 2 + r * k             # warmup + timed windows

        def echo():
            try:
                c = srv.accept(1)[0]
                if bps:
                    c.throttle_bps = bps
                bufs = [np.empty(a.shape, a.dtype) for a in leaves]
                for _ in range(nsync):
                    got = c.recv_tensors(out=bufs)
                    if mode == "perleaf":
                        for a in got:
                            c.send_tensor(a)
                    else:
                        c.send_tensors(got, codec=mode)
                c.close()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        th = threading.Thread(target=echo, daemon=True)
        th.start()
        c = connect("127.0.0.1", srv.port)
        if bps:
            c.throttle_bps = bps
        bufs = [np.empty(a.shape, a.dtype) for a in leaves]

        def one_sync():
            if mode == "perleaf":
                for a in leaves:
                    c.send_tensor(a)
                for b in bufs:
                    c.recv_tensor(out=b)
            else:
                c.send_tensors(leaves, codec=mode)
                c.recv_tensors(out=bufs)

        for _ in range(2):
            one_sync()
        base = c.bytes_sent + c.bytes_received
        best = float("inf")
        for _ in range(r):
            t0 = _t.perf_counter()
            for _ in range(k):
                one_sync()
            best = min(best, _t.perf_counter() - t0)
        wire_bytes = (c.bytes_sent + c.bytes_received - base) / (r * k)
        c.close()
        th.join(timeout=120)
        srv.close()
        if errs:
            raise errs[0]
        return {"syncs_per_sec": k / best, "bytes_per_sync": wire_bytes}

    bps = float(os.environ.get("BENCH_HOST_EMULATED_LINK_MB_S",
                               "200")) * 1e6
    out: dict = {}
    for set_name, shapes in _WIRE_PARAM_SETS.items():
        leaves = [np.random.RandomState(i).randn(*s).astype(np.float32)
                  for i, s in enumerate(shapes)]
        rows: dict = {}
        for mode in modes:
            rows[mode] = measure(leaves, mode, iters, reps)
        # emulated-link regime: few iters — each sync costs payload/bps
        emu_iters = max(2, int(bps * 0.05 / (2 * sum(a.nbytes
                                                     for a in leaves))))
        for mode in ("perleaf", "int8"):
            rows[mode + "_emulated"] = measure(leaves, mode,
                                               min(emu_iters, iters), 1,
                                               bps=bps)
        rows["emulated_link_mb_s"] = bps / 1e6
        rows["logical_bytes_per_sync"] = 2 * sum(a.nbytes for a in leaves)
        rows["leaves"] = len(leaves)
        rows["packed_raw_speedup"] = (rows["raw"]["syncs_per_sec"]
                                      / rows["perleaf"]["syncs_per_sec"])
        rows["int8_byte_reduction"] = (rows["perleaf"]["bytes_per_sync"]
                                       / rows["int8"]["bytes_per_sync"])
        rows["int8_emulated_speedup"] = (
            rows["int8_emulated"]["syncs_per_sec"]
            / rows["perleaf_emulated"]["syncs_per_sec"])
        out[set_name] = rows
    return out


def wire_cpu_bench(reps: int = 9, sync_rounds: int = 30):
    """Fused wire-codec CPU cost (the zero-copy wire gate): ns/byte of
    the int8 encode (quantize + error-feedback residual) and apply
    (dequantize + elastic add) stripe paths — the reference numpy
    pipeline (``encode_leaves`` then a decoded() f32 copy then
    ``subtract``; ``decode_into`` scratch then ``add``) against the
    fused blocked kernels (ops/wire_kernels: one cache-sized chunk pass,
    no decoded f32 round-trip) — plus an UNTHROTTLED int8 EASGD
    echo-sync loop's whole-process CPU time (``time.process_time``,
    both ends in-process) with the fused path off/on via
    ``DISTLEARN_TPU_WIREK`` resolved at construction.

    Best of ``reps`` trials on the CIFAR-shaped leaf list (same
    convention as host_wire_bench: this shared 1-core host's noise is
    strictly additive, so min is the least-contaminated estimate of the
    intrinsic codec cost — a median still wobbles ~10% run to run).
    Chip-free and jax-import-free (the fused CPU route is the compiled
    SIMD kernel or blocked numpy, not XLA — see docs/PERF.md)."""
    import threading
    import time as _t

    import numpy as np

    from distlearn_tpu.comm import wire
    from distlearn_tpu.ops import wire_kernels

    shapes = _WIRE_PARAM_SETS["cifar_convnet"]
    rs = np.random.RandomState(0)
    deltas = [rs.randn(*s).astype(np.float32) * 0.01 for s in shapes]
    logical = sum(a.nbytes for a in deltas)

    def best_ns_per_byte(fn):
        best = float("inf")
        fn()                                   # warmup (allocs, caches)
        for _ in range(reps):
            t0 = _t.perf_counter()
            fn()
            best = min(best, _t.perf_counter() - t0)
        return best / logical * 1e9

    # -- encode: reference = the pre-fusion _encode_stripe body ----------
    res = [np.zeros_like(a) for a in deltas]

    def enc_ref():
        p = wire.encode_leaves(deltas, "int8")
        for d, r, dec in zip(deltas, res, p.decoded()):
            np.subtract(d, dec, out=r)

    fb = wire.FrameBuffer()

    def enc_fused():
        wire_kernels.encode_ef_into(deltas, res, "int8", out=fb)

    # -- apply: reference = recv-decode into f32 scratch, then += --------
    pay = wire.encode_leaves(deltas, "int8")
    entries = pay.manifest["leaves"]
    center = [np.zeros(s, np.float32) for s in shapes]
    scratch = [np.empty(s, np.float32) for s in shapes]

    def apply_ref():
        for t, e, b, sc in zip(center, entries, pay.bufs, scratch):
            wire.decode_into(e, b, sc)
            np.add(t, sc, out=t)

    def apply_fused():
        for t, e, b in zip(center, entries, pay.bufs):
            wire_kernels.dequant_add(t, b, e["scale"], out=t)

    from distlearn_tpu.ops import wire_native
    row: dict = {
        "leaves": len(deltas), "logical_mb": logical / 1e6,
        "reps": reps,
        # which fused tier measured: the compiled SIMD kernel or the
        # blocked-numpy fallback (no compiler on the host)
        "native_backend": wire_native.available(),
        "int8_encode_ref_ns_per_byte": best_ns_per_byte(enc_ref),
        "int8_encode_fused_ns_per_byte": best_ns_per_byte(enc_fused),
        "int8_apply_ref_ns_per_byte": best_ns_per_byte(apply_ref),
        "int8_apply_fused_ns_per_byte": best_ns_per_byte(apply_fused),
    }
    row["int8_encode_speedup"] = (row["int8_encode_ref_ns_per_byte"]
                                  / row["int8_encode_fused_ns_per_byte"])
    row["int8_apply_speedup"] = (row["int8_apply_ref_ns_per_byte"]
                                 / row["int8_apply_fused_ns_per_byte"])

    # -- end-to-end: unthrottled int8 sync loop, fused path off vs on ----
    from distlearn_tpu.parallel.async_ea import AsyncEAClient, AsyncEAServer
    from distlearn_tpu.utils.logging import set_verbose
    set_verbose(False)

    params = {f"p{i}": rs.randn(*s).astype(np.float32)
              for i, s in enumerate(shapes)}

    def sync_loop_cpu(wirek: str) -> float:
        old = os.environ.get("DISTLEARN_TPU_WIREK")
        os.environ["DISTLEARN_TPU_WIREK"] = wirek
        try:
            port = _reserve_port_window(3)
            errs: list = []

            def server():
                try:
                    srv = AsyncEAServer("127.0.0.1", port, num_nodes=1,
                                        accept_timeout=60.0)
                    srv.init_server({k: v.copy()
                                     for k, v in params.items()})
                    p = dict(params)
                    for _ in range(sync_rounds):
                        p = srv.sync_server(p)
                    srv.close()
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            th = threading.Thread(target=server, daemon=True)
            th.start()
            cl = AsyncEAClient("127.0.0.1", port, node=1, tau=1,
                               alpha=0.5, codec="int8")
            p = cl.init_client({k: v.copy() for k, v in params.items()})
            c0 = _t.process_time()
            for _ in range(sync_rounds):
                p, _ = cl.sync_client(p)
            cpu = _t.process_time() - c0
            cl.close()
            th.join(timeout=120)
            if errs:
                raise errs[0]
            return cpu
        finally:
            if old is None:
                os.environ.pop("DISTLEARN_TPU_WIREK", None)
            else:
                os.environ["DISTLEARN_TPU_WIREK"] = old

    row["sync_rounds"] = sync_rounds
    row["sync_loop_cpu_s_numpy"] = sync_loop_cpu("0")
    row["sync_loop_cpu_s_fused"] = sync_loop_cpu("1")
    row["sync_loop_cpu_reduction"] = (row["sync_loop_cpu_s_numpy"]
                                      / row["sync_loop_cpu_s_fused"])
    return row


def async_ea_bench(param_mb: int = 8, n_clients: int = 2,
                   syncs_per_client: int = 10,
                   server_impl: str = "serial"):
    """AsyncEA parameter-server protocol throughput: how many full
    Enter?/Center?/delta? sync cycles per second the server sustains, and
    the payload rate through it (each sync moves the center down and the
    delta up — 2x the param bytes per cycle).  Localhost TCP through the
    same framed transport (C++ hot path) the real deployment uses; the
    reference has no perf visibility on this path at all.

    ``server_impl="concurrent"`` serves clients on overlapped per-client
    worker threads (AsyncEAServerConcurrent) instead of the reference's
    one-at-a-time critical section — the ResNet-scale (100 MB) row uses
    it.  NB on this 1-core host the overlap gain is bounded by the shared
    CPU doing all ranks' memcpys; on real multi-host NICs the overlap is
    the point."""
    import threading
    import time as _t

    import numpy as np

    from distlearn_tpu.parallel.async_ea import (AsyncEAClient, AsyncEAServer,
                                                 AsyncEAServerConcurrent)
    from distlearn_tpu.utils.logging import set_verbose
    set_verbose(False)

    # port fan: broadcast + one dedicated per client + test channel
    port = _reserve_port_window(n_clients + 2)

    nelem = param_mb * 1024 * 1024 // 4
    params = {"w": np.random.RandomState(0).randn(nelem).astype(np.float32)}
    total_syncs = n_clients * syncs_per_client
    out: dict = {}

    def server():
        if server_impl == "concurrent":
            srv = AsyncEAServerConcurrent("127.0.0.1", port,
                                          num_nodes=n_clients,
                                          accept_timeout=60.0)
            srv.init_server({"w": params["w"].copy()})
            t0 = _t.perf_counter()
            srv.start()
            while (srv.syncs_completed < total_syncs
                   and srv.live_clients > 0
                   and _t.perf_counter() - t0 < 600):
                _t.sleep(0.005)
            out["sec"] = _t.perf_counter() - t0
            out["syncs"] = srv.syncs_completed
            srv.stop()
        else:
            srv = AsyncEAServer("127.0.0.1", port, num_nodes=n_clients,
                                accept_timeout=60.0)
            srv.init_server({"w": params["w"].copy()})
            t0 = _t.perf_counter()
            done = 0
            p = {"w": params["w"]}
            while done < total_syncs and srv.live_clients > 0:
                p = srv.sync_server(p)
                done += 1
            out["sec"] = _t.perf_counter() - t0
            out["syncs"] = done
        srv.close()

    def client(node):
        cl = AsyncEAClient("127.0.0.1", port, node=node, tau=1, alpha=0.5)
        p = cl.init_client({"w": params["w"].copy()})
        for _ in range(syncs_per_client):
            p, _ = cl.sync_client(p)
        cl.close()

    ts = [threading.Thread(target=server, daemon=True)]
    ts += [threading.Thread(target=client, args=(i + 1,), daemon=True)
           for i in range(n_clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=600)
    if "sec" not in out or not out["syncs"]:
        raise RuntimeError("async EA bench did not complete")
    sps = out["syncs"] / out["sec"]
    return {
        "clients": n_clients, "param_mb": param_mb, "server": server_impl,
        "syncs_completed": out["syncs"], "syncs_per_sec": sps,
        # center down + delta up per sync
        "payload_gb_s": sps * 2 * nelem * 4 / 1e9,
    }


def host_shard_bench(n_clients: int = 4, syncs_per_client: int = 4,
                     shard_counts=(1, 2, 4)):
    """Striped parameter-server scaling: the CONCURRENT AsyncEA server at
    S ∈ ``shard_counts`` stripes with ``n_clients`` hammering it, per
    wire param set, plus a ``baseline`` run (S=1 server, clients with the
    shard negotiation DISABLED — exactly the pre-shard packed path, so
    ``s1_vs_baseline`` measures what the sharded plumbing costs when it
    buys nothing).

    Two regimes: the raw loopback (memcpy/GIL-bound on a shared CPU —
    sharding mostly can't win here and the numbers say by how much it
    doesn't lose) and emulated fixed-bandwidth links via
    ``Conn.throttle_bps`` (the multi-host regime sharding is FOR: each
    stripe channel is its own paced link, the way each shard of a real
    deployment owns its own NIC path, so one client's sync drains S links
    concurrently and ``shard_speedup`` approaches S)."""
    import threading
    import time as _t

    import numpy as np

    from distlearn_tpu.parallel.async_ea import (AsyncEAClient,
                                                 AsyncEAServerConcurrent)
    from distlearn_tpu.utils.logging import set_verbose
    set_verbose(False)

    smax = max(shard_counts)

    def run(shapes, shards, sharded_clients, bps, spc):
        # broadcast + dedicated per client + test + S-1 shard listeners
        port = _reserve_port_window(n_clients + smax + 1)
        params = {f"p{i}": np.random.RandomState(i).randn(*s)
                  .astype(np.float32) for i, s in enumerate(shapes)}
        total = n_clients * spc
        out: dict = {}
        errs: list = []

        def server():
            try:
                srv = AsyncEAServerConcurrent(
                    "127.0.0.1", port, num_nodes=n_clients,
                    accept_timeout=60.0, shards=shards, throttle_bps=bps)
                srv.init_server({k: v.copy() for k, v in params.items()})
                srv.start()
                t0 = _t.perf_counter()
                while (srv.syncs_completed < total and srv.live_clients > 0
                       and _t.perf_counter() - t0 < 600):
                    _t.sleep(0.005)
                out["sec"] = _t.perf_counter() - t0
                out["syncs"] = srv.syncs_completed
                out["stripes"] = len(srv.stripes)
                srv.stop()
                srv.close()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        def client(node):
            try:
                cl = AsyncEAClient("127.0.0.1", port, node=node, tau=1,
                                   alpha=0.5, sharded=sharded_clients,
                                   throttle_bps=bps)
                p = cl.init_client({k: v.copy()
                                    for k, v in params.items()})
                for _ in range(spc):
                    p, _ = cl.sync_client(p)
                cl.close()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=server, daemon=True)]
        ts += [threading.Thread(target=client, args=(i + 1,), daemon=True)
               for i in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        if errs:
            raise errs[0]
        if "sec" not in out or out["syncs"] < total:
            raise RuntimeError(
                f"shard bench incomplete: {out.get('syncs')} of {total}")
        return {"syncs_per_sec": out["syncs"] / out["sec"],
                "stripes": out["stripes"]}

    # 25 MB/s keeps the paced wire-time (which striping parallelizes)
    # well above the encode/memcpy CPU time (which it cannot), so the
    # emulated rows measure the link-bound regime sharding targets
    # rather than this host's single-core codec throughput.
    bps = float(os.environ.get("BENCH_SHARD_EMULATED_LINK_MB_S",
                               "25")) * 1e6
    result: dict = {}
    for set_name, shapes in _WIRE_PARAM_SETS.items():
        nbytes = sum(4 * int(np.prod(s)) for s in shapes)
        rows: dict = {"leaves": len(shapes), "param_mb": nbytes / 1e6,
                      "clients": n_clients,
                      "syncs_per_client": syncs_per_client,
                      "emulated_link_mb_s": bps / 1e6}
        for regime, rbps in (("loopback", None), ("emulated", bps)):
            reg: dict = {"baseline": run(shapes, 1, False, rbps,
                                         syncs_per_client)}
            for s in shard_counts:
                reg[f"s{s}"] = run(shapes, s, True, rbps,
                                   syncs_per_client)
            rows[regime] = reg
            rows[f"{regime}_shard_speedup"] = (
                reg[f"s{smax}"]["syncs_per_sec"]
                / reg["s1"]["syncs_per_sec"])
            rows[f"{regime}_s1_vs_baseline"] = (
                reg["s1"]["syncs_per_sec"]
                / reg["baseline"]["syncs_per_sec"])
        result[set_name] = rows
    return result


def bench_resnet50(batch: int, iters: int, windows: int, peak,
                   norm: str = "batch"):
    """ResNet-50/ImageNet-shape utilization bench (the model where MFU is
    meaningful — BASELINE.md stretch config).  ``norm="none"`` benches the
    SkipInit norm-free variant — the r3 profile put ~50% of the BN
    model's step time in channel-statistics reductions, so the delta
    between the two rows IS the measured BN bandwidth cost."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.resnet import resnet50
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import build_sgd_step, init_train_state

    n_dev = len(jax.devices())
    tree = MeshTree(num_nodes=n_dev)
    platform = jax.devices()[0].platform
    model = resnet50(
        compute_dtype=jnp.bfloat16 if platform == "tpu" else None,
        norm=norm)
    ts = init_train_state(model, tree, random.PRNGKey(0), 1000)
    step = build_sgd_step(model, tree, lr=0.1)
    rs = np.random.RandomState(0)
    x = rs.randn(batch, 224, 224, 3).astype(np.float32)
    y = rs.randint(0, 1000, (batch,)).astype(np.int32)
    sh = NamedSharding(tree.mesh, P("data"))
    bx, by = jax.device_put(x, sh), jax.device_put(y, sh)

    flops = step_flops(step, ts, bx, by)
    sps, times, loss = bench_step_fn(step, ts, bx, by, iters, windows,
                                     warmup=5)
    mfu = check_mfu("resnet50", flops, sps, peak)
    return {
        "batch": batch, "norm": norm, "steps_per_sec": sps,
        "images_per_sec": sps * batch,
        "flops_per_step": flops, "mfu": mfu, "window_times": times,
        "final_loss": loss,
    }


def bench_transformer_lm(batch: int, seq: int, iters: int, windows: int,
                         peak, attn: str | None = None,
                         remat: bool | str = False,
                         scan_blocks: bool = False):
    """Long-context transformer LM utilization bench: the fused LM train
    step (next-token loss, full backward, SGD) on one chip, bf16 compute.
    On a pod the same step shards over (data, seq, model) axes — see
    distlearn_tpu.train.lm; this measures the per-chip compute story.
    ``attn`` picks the attention kernel ("xla"/"flash"/"chunked" — see
    distlearn_tpu.parallel.sequence.local_attention); ``remat`` is the
    transformer's mode (False / "full" / "mlp"); ``scan_blocks`` uses the
    scanned-depth layout (program size flat in depth — the recipe for
    configs whose unrolled program exceeds the compile limits).  MFU for
    scanned rows is analytic-only: XLA cost_analysis reports a scan
    body's flops ONCE, so the compiled-program figure would undercount
    by ~depth."""
    return _bench_transformer_lm(batch, seq, iters, windows, peak, attn,
                                 remat, scan_blocks)


def _lm_dim_depth():
    """The LM bench model size, shared by the measurement and the
    remat-mode heuristic so the two can never size different models."""
    dim = int(os.environ.get("BENCH_LM_DIM", "1024"))
    depth = int(os.environ.get("BENCH_LM_DEPTH", "8"))
    if dim < 64 or dim % 64:
        raise ValueError(f"BENCH_LM_DIM must be a multiple of 64 "
                         f"(64-dim heads), got {dim}")
    return dim, depth


def _bench_transformer_lm(batch, seq, iters, windows, peak, attn, remat,
                          scan_blocks=False):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import build_lm_step

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1, 1),
                ("data", "seq", "model"))
    dim, depth = _lm_dim_depth()
    lm = transformer_lm(vocab=32768, dim=dim, depth=depth, heads=dim // 64,
                        max_len=seq, compute_dtype=jnp.bfloat16, remat=remat,
                        attn_impl=attn, scan_blocks=scan_blocks)
    params, _ = lm.init(random.PRNGKey(0))
    step = build_lm_step(lm, mesh, params, lr=1e-2)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32768, (batch, seq))
        .astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))

    flops = None if scan_blocks else step_flops(step, params, tokens)
    # With remat, the executed program's flops INCLUDE activation recompute
    # — that ratio is HFU (hardware FLOPs utilization), not MFU.  The MFU
    # numerator is the MODEL's flops: lower (never execute — it would not
    # fit HBM) the same step without remat and take its cost_analysis, the
    # same convention every non-remat row uses.
    flops_model = flops
    if remat and flops and not scan_blocks:
        lm_nr = transformer_lm(vocab=32768, dim=dim, depth=depth,
                               heads=dim // 64, max_len=seq,
                               compute_dtype=jnp.bfloat16, remat=False,
                               attn_impl=attn)
        step_nr = build_lm_step(lm_nr, mesh, params, lr=1e-2, donate=False)
        # None (not the remat figure) when the no-remat program cannot be
        # lowered here — reporting HFU as MFU would overstate utilization;
        # the lm_long section backfills an analytic calibrated estimate
        flops_model = step_flops(step_nr, params, tokens)
    state = {"p": params}

    def run(n):
        p = state["p"]
        for _ in range(n):
            p, loss = step(p, tokens)
        state["p"] = p
        state["loss"] = float(jax.device_get(loss))

    med, times = timed_windows(lambda: run(iters), lambda: run(5), windows)
    sps = iters / med
    hfu = check_mfu("transformer_lm(hw)", flops, sps, peak)
    mfu = check_mfu("transformer_lm", flops_model, sps, peak)
    return {
        "batch": batch, "seq_len": seq, "dim": dim, "depth": depth,
        "attn": attn, "remat": remat, "scan_blocks": scan_blocks,
        "steps_per_sec": sps,
        "tokens_per_sec": sps * batch * seq, "flops_per_step": flops_model,
        "hw_flops_per_step": flops, "mfu": mfu,
        "hfu": hfu if remat else None,
        "window_times": times, "final_loss": state["loss"],
    }


def bench_lm_mixed_sweep(dims, batch, seq, iters, windows, peak):
    """Before/after rows for the mixed-precision LM step (VERDICT r4
    next #3): at each width, the SAME model trained by ``build_lm_step``
    (f32 params — every matmul pass reads 4-byte weights; f32 update
    tail measured ~21% of the dim-4096 step) and by
    ``build_lm_mixed_step`` (bf16 working params + f32 masters), back to
    back.  MFU uses the plain program's cost_analysis for both (the
    schemes run identical model flops)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import (build_lm_mixed_step,
                                        build_lm_step,
                                        init_lm_mixed_state)

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1, 1),
                ("data", "seq", "model"))
    depth = int(os.environ.get("BENCH_LM_DEPTH", "8"))
    rows = []
    for dim in dims:
        lm = transformer_lm(vocab=32768, dim=dim, depth=depth,
                            heads=dim // 64, max_len=seq,
                            compute_dtype=jnp.bfloat16)
        params, _ = lm.init(random.PRNGKey(0))
        tokens = jax.device_put(
            np.random.RandomState(0).randint(0, 32768, (batch, seq))
            .astype(np.int32),
            NamedSharding(mesh, P("data", "seq")))

        # Both steps donate their state like production, and a donating
        # step's first call DELETES the tree it was handed — so the
        # mixed run gets its own fresh init (sharing/aliasing `params`
        # into the mixed state would hand it deleted buffers — r5
        # review), created only after the plain run's state is freed
        # (both trees resident at once would not fit HBM at dim 4096).
        # Builders only read avals from the template, so `params` being
        # donated later does not affect them.
        plain = build_lm_step(lm, mesh, params, lr=1e-2)
        mixed = build_lm_mixed_step(lm, mesh, params, lr=1e-2)
        flops = step_flops(plain, params, tokens)
        st = {"p": params}

        def run_plain(n):
            p = st["p"]
            for _ in range(n):
                p, loss = plain(p, tokens)
            st["p"] = p
            float(jax.device_get(loss))

        med_p, _ = timed_windows(lambda: run_plain(iters),
                                 lambda: run_plain(3), windows)
        del st, params

        params_m, _ = lm.init(random.PRNGKey(0))
        stm = {"s": init_lm_mixed_state(params_m)}
        del params_m

        def run_mixed(n):
            s = stm["s"]
            for _ in range(n):
                s, loss = mixed(s, tokens)
            stm["s"] = s
            float(jax.device_get(loss))

        med_m, _ = timed_windows(lambda: run_mixed(iters),
                                 lambda: run_mixed(3), windows)
        row = {
            "dim": dim, "depth": depth, "batch": batch, "seq_len": seq,
            "flops_per_step": flops,
            "plain_steps_per_sec": iters / med_p,
            "mixed_steps_per_sec": iters / med_m,
            "speedup": med_p / med_m,
            "plain_mfu": check_mfu("lm_plain", flops, iters / med_p,
                                   peak),
            "mixed_mfu": check_mfu("lm_mixed", flops, iters / med_m,
                                   peak),
        }
        rows.append(row)
        print(f"[bench] lm_mixed dim={dim}: plain "
              f"{row['plain_steps_per_sec']:.2f} -> mixed "
              f"{row['mixed_steps_per_sec']:.2f} steps/s "
              f"({row['speedup']:.2f}x"
              + (f", MFU {row['plain_mfu']:.3f} -> "
                 f"{row['mixed_mfu']:.3f}" if row["plain_mfu"] else "")
              + ")", file=sys.stderr)
        del plain, mixed, stm
    return rows


def _analytic_lm_train_flops(batch, seq, dim, depth, vocab=32768):
    """Closed-form model-flops for one LM train step (fwd + 2x bwd;
    matmul/attention terms only, causal halved) — the PaLM-appendix-style
    count, used ONLY to extrapolate MFU to configs whose no-remat program
    the environment cannot lower, after calibration against a config where
    XLA cost_analysis is available."""
    hidden = 4 * dim
    fwd = batch * (depth * (seq * (8 * dim * dim + 4 * dim * hidden)
                            + 2 * seq * seq * dim)
                   + seq * 2 * dim * vocab)
    return 3.0 * fwd


def bench_easgd_cycle(batch, tau, iters, windows):
    """EASGD throughput — the reference's second core algorithm
    (lua/AllReduceEA.lua) as the scanned one-dispatch τ-cycle
    (``train.build_ea_cycle``: τ collective-free local steps + ONE fused
    elastic round per dispatch).  Reported per LOCAL step so it is
    directly comparable to the AllReduceSGD headline: EASGD's point is
    that τ−1 of every τ steps skip the gradient collective."""
    from jax import random

    from distlearn_tpu.train import build_ea_cycle, init_ea_state

    tree, model = _cifar_model_and_tree()
    ts = init_ea_state(model, tree, random.PRNGKey(0), 10)
    cycle = build_ea_cycle(model, tree, lr=0.1, alpha=0.2)
    bx, by = _stacked_cifar_batches(tree, batch, tau)

    # No MFU here: cost_analysis on the scanned cycle reports one loop
    # iteration's flops, so steps/s is the comparable, defensible number
    # (the headline SGD row carries the utilization story).
    sps, times, loss = bench_step_fn(cycle, ts, bx, by, iters, windows,
                                     warmup=tau, steps_per_call=tau)
    return {
        "batch": batch, "tau": tau, "steps_per_sec": sps,
        "images_per_sec": sps * batch,
        "cycles_per_sec": sps / tau, "window_times": times,
        "final_loss": loss, "devices": tree.num_nodes,
    }


def bench_moe_lm(batch, seq, iters, windows, peak):
    """Routed-MoE LM utilization on one chip (experts all-resident —
    the ``moe_ffn_local`` path; on a pod the same model shards one
    expert per device over the data axis with two all-to-alls).  Every
    second block is a top-1 (Switch) mixture of 8 experts with the
    load-balancing auxiliary loss on — the routed-dispatch einsums and
    capacity bookkeeping are in the measured step, so this is the
    chip-level cost of the MoE machinery."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import build_lm_step

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1, 1),
                ("data", "seq", "model"))
    dim = int(os.environ.get("BENCH_MOE_DIM", "1024"))
    depth = int(os.environ.get("BENCH_MOE_DEPTH", "8"))
    experts = int(os.environ.get("BENCH_MOE_EXPERTS", "8"))
    lm = transformer_lm(vocab=32768, dim=dim, depth=depth, heads=dim // 64,
                        max_len=seq, compute_dtype=jnp.bfloat16,
                        moe_experts=experts, moe_every=2)
    params, _ = lm.init(random.PRNGKey(0))
    step = build_lm_step(lm, mesh, params, lr=1e-2,
                         moe_balance_weight=0.01)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32768, (batch, seq))
        .astype(np.int32),
        NamedSharding(mesh, P("data", "seq")))

    flops = step_flops(step, params, tokens)
    state = {"p": params}

    def run(n):
        p = state["p"]
        for _ in range(n):
            p, loss = step(p, tokens)
        state["p"] = p
        state["loss"] = float(jax.device_get(loss))

    med, times = timed_windows(lambda: run(iters), lambda: run(5), windows)
    sps = iters / med
    mfu = check_mfu("moe_lm", flops, sps, peak)
    return {
        "batch": batch, "seq_len": seq, "dim": dim, "depth": depth,
        "experts": experts, "top_k": 1, "steps_per_sec": sps,
        "tokens_per_sec": sps * batch * seq, "flops_per_step": flops,
        "mfu": mfu, "window_times": times, "final_loss": state["loss"],
    }


def bench_pp_lm(batch, seq, iters, windows, peak):
    """GPipe machinery cost on the real chip: the pipeline-parallel LM step
    (train.lm.build_lm_pp_step) at S=1 (one stage — the only pipe size one
    chip can host) with M microbatches, vs the plain fused step on the
    SAME model, measured back to back.  At S=1 there is no bubble, so any
    deficit is pure schedule machinery: the tick scan (unrolled here —
    measured ~1.6x over the rolled scan), per-microbatch head, and
    activation slicing.  The bubble on a real pod adds the known
    (S-1)/(M+S-1) on top — this row bounds the REST of the PP overhead.
    MFU uses the plain step's cost_analysis flops for both (the scanned
    PP program under-reports: XLA counts one loop iteration).  Config is
    dim 512 x depth 8: the attached tunnel's remote-compile helper cannot
    compile the dim-1024 PP program (HTTP 500 at ~30KB MLIR)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.train.lm import (build_lm_pp_step, build_lm_step,
                                        stack_blocks)

    devs = jax.devices()
    dim = int(os.environ.get("BENCH_PP_DIM", "512"))
    depth = int(os.environ.get("BENCH_PP_DEPTH", "8"))
    M = int(os.environ.get("BENCH_PP_MICROBATCHES", "4"))
    lm = transformer_lm(vocab=32768, dim=dim, depth=depth, heads=dim // 64,
                        max_len=seq, compute_dtype=jnp.bfloat16)
    params, _ = lm.init(random.PRNGKey(0))

    # plain fused step on the same model: the machinery-free reference
    mesh3 = Mesh(np.asarray(devs[:1]).reshape(1, 1, 1),
                 ("data", "seq", "model"))
    step_ref = build_lm_step(lm, mesh3, params, lr=1e-2, donate=False)
    toks3 = jax.device_put(
        np.random.RandomState(0).randint(0, 32768, (batch, seq))
        .astype(np.int32), NamedSharding(mesh3, P("data", "seq")))
    flops = step_flops(step_ref, params, toks3)
    pstate = {"p": params}

    def run_ref(n):
        p = pstate["p"]
        for _ in range(n):
            p, loss = step_ref(p, toks3)
        pstate["p"] = p
        pstate["loss"] = float(jax.device_get(loss))

    med_ref, _ = timed_windows(lambda: run_ref(iters), lambda: run_ref(3),
                               windows)
    ref_sps = iters / med_ref

    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1), ("data", "pipe"))
    shared, stacked = stack_blocks(params, depth)
    shared = jax.device_put(shared, NamedSharding(mesh, P()))
    stacked = jax.device_put(stacked, NamedSharding(mesh, P("pipe")))
    step = build_lm_pp_step(mesh, shared, stacked, lr=1e-2,
                            num_microbatches=M,
                            compute_dtype=jnp.bfloat16, unroll=True)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 32768, (batch, seq))
        .astype(np.int32), NamedSharding(mesh, P("data")))

    state = {"s": shared, "k": stacked}

    def run(n):
        sh, stk = state["s"], state["k"]
        for _ in range(n):
            sh, stk, loss = step(sh, stk, tokens)
        state["s"], state["k"] = sh, stk
        state["loss"] = float(jax.device_get(loss))

    med, times = timed_windows(lambda: run(iters), lambda: run(5), windows)
    sps = iters / med
    mfu = check_mfu("pp_lm", flops, sps, peak)
    return {
        "batch": batch, "seq_len": seq, "dim": dim, "depth": depth,
        "stages": 1, "microbatches": M, "steps_per_sec": sps,
        "tokens_per_sec": sps * batch * seq, "mfu": mfu,
        "plain_steps_per_sec": ref_sps,
        "machinery_efficiency_vs_plain": sps / ref_sps,
        "window_times": times, "final_loss": state["loss"],
    }


def serve_bench(concurrencies=(1, 2, 4, 8), prompt_len: int = 16,
                max_new: int = 32, dim: int = 256, depth: int = 4,
                heads: int = 8, vocab: int = 512):
    """Continuous-batched serving throughput vs the repo's sequential
    decode path (docs/SERVING.md).

    For each concurrency ``c``: ``c`` requests arrive at once, the
    ``serve.engine`` admits them all and ticks until done — aggregate
    tok/s plus TTFT (arrival to first token: queue-position cost made
    visible, requests prefill one at a time) and TPOT (per-token
    latency = tick wall time, one sample per request per tick) p50/p99.
    The baseline is ``c`` back-to-back ``greedy_generate`` calls — the
    pre-serve inference path (``examples/lm.py --generate``), which
    dispatches eagerly per request; the engine's jitted tick amortizes
    weight reads over every active slot, so the gap widens with ``c``.
    """
    import jax
    import numpy as np
    from distlearn_tpu.models.transformer import (greedy_generate,
                                                  transformer_lm)
    from distlearn_tpu.serve.engine import DecodeEngine
    max_len = 1
    while max_len < prompt_len + max_new:
        max_len *= 2
    model = transformer_lm(vocab=vocab, dim=dim, depth=depth, heads=heads,
                           max_len=max_len)
    params, _ = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    def prompts(c, batched):
        shape = (prompt_len,) if batched else (1, prompt_len)
        return [rng.integers(1, vocab, size=shape).astype(np.int32)
                for _ in range(c)]

    # warm both paths out of the timed region (compile once per shape)
    np.asarray(greedy_generate(params, prompts(1, False)[0], max_new))
    eng = DecodeEngine(params, num_slots=max(concurrencies),
                       max_len=max_len, page=16)
    s, _ = eng.admit(prompts(1, True)[0], max_new)
    eng.tick()
    eng.finish(s)

    def pct(xs, q):
        xs = sorted(xs)
        return xs[max(0, min(len(xs) - 1,
                             int(round(q / 100.0 * (len(xs) - 1)))))]

    rows = []
    for c in concurrencies:
        ps = prompts(c, False)
        t0 = time.perf_counter()
        for p in ps:
            np.asarray(greedy_generate(params, p, max_new))
        seq_tok_s = c * max_new / (time.perf_counter() - t0)

        ps = prompts(c, True)
        ttft, tpot = [], []
        t0 = time.perf_counter()
        emitted = {}
        for p in ps:
            slot, _ = eng.admit(p, max_new)
            ttft.append(time.perf_counter() - t0)
            emitted[slot] = 1
        done = 0
        while done < c:
            tt = time.perf_counter()
            ticked = eng.tick()
            dt = time.perf_counter() - tt
            for slot in ticked:
                tpot.append(dt)
                emitted[slot] += 1
                if emitted[slot] >= max_new:
                    eng.finish(slot)
                    done += 1
        tok_s = c * max_new / (time.perf_counter() - t0)
        row = {"concurrency": c, "tokens_per_sec": tok_s,
               "sequential_tokens_per_sec": seq_tok_s,
               "speedup_vs_sequential": tok_s / seq_tok_s,
               "ttft_p50": pct(ttft, 50), "ttft_p99": pct(ttft, 99),
               "tpot_p50": pct(tpot, 50), "tpot_p99": pct(tpot, 99)}
        rows.append(row)
        print(f"[bench] serve c={c}: {tok_s:.1f} tok/s "
              f"(sequential {seq_tok_s:.1f}, "
              f"{tok_s / seq_tok_s:.2f}x), TTFT p50={row['ttft_p50'] * 1e3:.1f}ms "
              f"p99={row['ttft_p99'] * 1e3:.1f}ms, "
              f"TPOT p50={row['tpot_p50'] * 1e3:.1f}ms", file=sys.stderr)
    # Raw-speed features (docs/SERVING.md): radix prefix cache and
    # speculative decode, measured on the same model.
    from distlearn_tpu.serve.prefix_cache import RadixPrefixCache
    from distlearn_tpu.serve.speculate import NGramDrafter

    # Cache-hit TTFT: two prompts sharing 90% of their tokens.  The
    # second request's radix match covers the shared whole pages so its
    # prefill runs only the suffix — the cut is exact in positions and
    # also measured in wall time (best-of to strip scheduler noise).
    cpage = 8
    cplen = 5 * cpage
    overlap = int(cplen * 0.9)
    ceng = DecodeEngine(params, num_slots=2, max_len=max_len, page=cpage)
    cache = RadixPrefixCache(ceng.cache)
    base = rng.integers(1, vocab, size=cplen).astype(np.int32)
    variant = base.copy()
    variant[overlap:] = (variant[overlap:] % (vocab - 1)) + 1
    job = ceng.begin(base, 4)
    while ceng.prefill_step(job) is None:
        pass
    cache.insert(base, ceng.cache.block_table[job.slot])
    ceng.finish(job.slot)

    def run_prefill(hit, reps=5):
        best, clen = float("inf"), 0
        for _ in range(reps):
            clen, pages = cache.match(variant) if hit else (0, [])
            t0 = time.perf_counter()
            j = ceng.begin(variant, 4, shared=pages)
            while ceng.prefill_step(j) is None:
                pass
            best = min(best, time.perf_counter() - t0)
            ceng.finish(j.slot)
        return best, clen

    run_prefill(False, reps=1)          # warm both prefill programs
    run_prefill(True, reps=1)
    t_full, _ = run_prefill(False)
    t_hit, cached_len = run_prefill(True)
    pc = {"page": cpage, "prompt_len": cplen, "overlap_tokens": overlap,
          "overlap_frac": overlap / cplen, "cached_tokens": cached_len,
          "prefill_positions_full": cplen,
          "prefill_positions_cached": cplen - cached_len,
          "prefill_cut": cplen / (cplen - cached_len),
          "ttft_full_ms": t_full * 1e3, "ttft_cached_ms": t_hit * 1e3,
          "ttft_speedup": t_full / t_hit}
    print(f"[bench] serve prefix cache: {cached_len}/{cplen} tokens "
          f"cached at {overlap / cplen:.0%} overlap -> prefill cut "
          f"{pc['prefill_cut']:.1f}x positions, "
          f"{pc['ttft_speedup']:.2f}x wall "
          f"({t_full * 1e3:.1f}ms -> {t_hit * 1e3:.1f}ms)",
          file=sys.stderr)

    # Speculative decode: accepted tokens per verify dispatch with the
    # n-gram prompt-lookup drafter (no second model) on a self-similar
    # stream, exact greedy equivalence asserted against the reference.
    s0, f0 = eng.admit(prompts(1, True)[0], 4)
    eng.verify({s0: [f0]})              # warm the verify program
    eng.finish(s0)
    srng = np.random.default_rng(100)   # decoupled from the row prompts
    pattern = srng.integers(1, vocab, size=4).astype(np.int32)
    sprompt = np.tile(pattern, prompt_len // 4 + 1)[:prompt_len]
    spec_new = max_len - prompt_len     # long enough to amortize ramp-up
    ref = np.asarray(greedy_generate(
        params, sprompt[None], spec_new))[0].tolist()
    drafter = NGramDrafter(k=4)
    slot, first = eng.admit(sprompt, spec_new)
    toks = [first]
    dispatches = 0
    t0 = time.perf_counter()
    while len(toks) < spec_new:
        budget = min(drafter.k, spec_new - len(toks) - 1,
                     int(eng.cache.limit[slot])
                     - int(eng.cache.lengths[slot]) - 1)
        d = drafter.propose([int(t) for t in sprompt] + toks,
                            k=budget) if budget > 0 else []
        if d:
            toks.extend(eng.verify({slot: d})[slot])
        else:
            toks.append(eng.tick()[slot])
        dispatches += 1
    spec_s = time.perf_counter() - t0
    eng.finish(slot)
    sp = {"drafter": "ngram", "k": drafter.k, "max_new": spec_new,
          "decode_tokens": len(toks) - 1, "dispatches": dispatches,
          "accepted_tokens_per_tick": (len(toks) - 1) / dispatches,
          "plain_dispatches": spec_new - 1,
          "greedy_equal": toks == ref,
          "decode_seconds": spec_s}
    print(f"[bench] serve speculation: {len(toks) - 1} tokens in "
          f"{dispatches} dispatches = "
          f"{sp['accepted_tokens_per_tick']:.2f} tok/tick "
          f"(plain = 1.00), greedy_equal={sp['greedy_equal']}",
          file=sys.stderr)

    return {"model": {"dim": dim, "depth": depth, "heads": heads,
                      "vocab": vocab, "max_len": max_len},
            "prompt_len": prompt_len, "max_new": max_new, "rows": rows,
            "prefix_cache": pc, "speculation": sp}


def chip_health_probe():
    """Chained bf16 4096^3 matmuls ended by a REAL device_get (the
    platform's completion signaling is optimistic — r1 lesson).  Healthy
    v5e measures ~100-143 TFLOP/s here; the attached chip/tunnel has been
    observed degraded 25x (5.8 TFLOP/s) for extended windows.  Recorded
    with every run so a depressed benchmark row is attributable to the
    environment, not mistaken for a framework regression."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np
    x = jnp.ones((4096, 4096), jnp.bfloat16)
    f = jax.jit(lambda a: a @ a / 64.0)
    _ = np.asarray(jax.device_get(f(x)))
    # short probe first: on a badly degraded chip the full 30-matmul
    # chain has itself been observed to take minutes — extrapolate from
    # 3 instead of risking the whole bench run on the canary
    t0 = _t.perf_counter()
    r = x
    for _ in range(3):
        r = f(r)
    _ = np.asarray(jax.device_get(r))
    dt3 = _t.perf_counter() - t0
    if dt3 > 3.0:
        return 2 * 4096**3 * 3 / dt3 / 1e12
    t0 = _t.perf_counter()
    N = 27
    for _ in range(N):
        r = f(r)
    _ = np.asarray(jax.device_get(r))
    return 2 * 4096**3 * N / (_t.perf_counter() - t0) / 1e12


def _device_liveness_gate(attempts: int = 2, timeout_s: float = 90.0):
    """The attached tunnel has been observed to HANG outright — even
    ``jax.devices()`` blocking forever — for extended windows.  Probing
    it in a SUBPROCESS (the only thing a hung PJRT call can't take down)
    before the first in-process device touch turns an unbounded hang
    into an honest, attributable failure record.  Retries because the
    tunnel also blips back."""
    for i in range(attempts):
        # Popen + bounded reap, NOT subprocess.run: run()'s timeout path
        # kills the child then waits UNBOUNDEDLY for it to be reaped, and
        # a child hung in uninterruptible tunnel I/O never is.  An
        # unkillable child gets abandoned instead of hanging the gate.
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        try:
            proc.communicate(timeout=timeout_s)
            if proc.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        print(f"[bench] device liveness probe {i + 1}/{attempts} failed "
              "(tunnel hung?) — retrying", file=sys.stderr)
        time.sleep(15.0)
    return False


_LAST_GOOD_BASENAME = "BENCH_LAST_GOOD.json"


def _last_good_headline(root=None):
    """The most recent REAL headline this repo has recorded, or None.

    Prefers the bench's own committed ``BENCH_LAST_GOOD.json`` (written on
    every successful run); falls back to scanning the driver's
    ``BENCH_r*.json`` artifacts for the newest round whose parsed value is
    a real measurement."""
    def _real_value(rec):
        v = rec.get("value")
        return isinstance(v, (int, float)) and not isinstance(v, bool) \
            and v > 0

    root = root or os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(root, _LAST_GOOD_BASENAME)
    try:
        with open(path) as fh:
            rec = json.load(fh)
        if _real_value(rec):
            return rec
    except (OSError, ValueError):
        pass
    import glob
    best = None
    for p in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(p) as fh:
                art = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = art.get("parsed") or {}
        # a prior outage round's artifact is itself a carried-forward
        # record — laundering it through this scan would restamp the
        # measurement with the wrong round's provenance.  Degraded-chip
        # and CPU rounds are real runs but not representative TPU
        # measurements (the write path refuses them for
        # BENCH_LAST_GOOD.json; this scan must match).
        if parsed.get("stale") or parsed.get("degraded"):
            continue
        if " tpu chip(s)" not in str(parsed.get("unit", "")):
            continue
        if _real_value(parsed):
            rec = dict(parsed)
            rec.setdefault("recorded_at", f"round {art.get('n', '?')} "
                           f"driver artifact {os.path.basename(p)}")
            if best is None or art.get("n", 0) >= best[0]:
                best = (art.get("n", 0), rec)
    return best[1] if best else None


def _outage_headline():
    """The record to emit when the tunnel is dead: the last good
    measurement carried forward and marked stale, NOT value 0.0 — a zero
    reads as a 100% regression to any cross-round consumer, while the
    outage is an environment fact that says nothing about the framework."""
    last = _last_good_headline()
    outage = ("the attached TPU tunnel is unresponsive (jax.devices() "
              "hangs in a subprocess after repeated attempts) — an "
              "environment outage, not a framework result; rerun when "
              "the tunnel recovers")
    if last is None:
        return {
            "metric": "cifar10_convnet_allreduce_sgd_steps_per_sec",
            "value": 0.0,
            "unit": "NO MEASUREMENT: " + outage,
            "vs_baseline": 0.0,
        }
    return {
        "metric": last.get(
            "metric", "cifar10_convnet_allreduce_sgd_steps_per_sec"),
        "value": last["value"],
        "unit": (f"STALE (carried forward from "
                 f"{last.get('recorded_at', 'an earlier run')}): "
                 + last.get("unit", "") + " | NO NEW MEASUREMENT: "
                 + outage),
        "vs_baseline": last.get("vs_baseline", 0.0),
        "stale": True,
        "stale_source": last.get("recorded_at"),
    }


def main():
    _enable_compile_cache()
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "100"))
    windows = int(os.environ.get("BENCH_WINDOWS", "5"))
    warmup = int(os.environ.get("BENCH_WARMUP", "10"))

    if os.environ.get("BENCH_SKIP_LIVENESS_GATE") != "1" \
            and not _device_liveness_gate():
        # Emit the one-line contract with an explicit explanation instead
        # of hanging forever at the first jax.devices() call — an absent
        # record looks like a framework failure; this is attributable.
        print(json.dumps(_outage_headline()))
        return

    platform, kind, peak = detect_peak_flops()
    details: dict = {"protocol": PROTOCOL, "platform": platform,
                     "device_kind": kind, "peak_bf16_flops": peak}
    if platform == "tpu":
        probe = run_bench_section("chip_health", chip_health_probe)
        if probe is not None:
            details["chip_health_tflops"] = probe
            print(f"[bench] chip health probe: {probe:.1f} TFLOP/s "
                  "(chained bf16 matmul; healthy ~100-143, degraded "
                  "windows observed at ~1-6)", file=sys.stderr)
        if probe is not None and probe < 15.0:
            # The chip runs 10-100x under spec for hours at a time
            # (observed).  A full-length run on a sick chip times out and
            # records NOTHING; shrunk windows record honest (labeled)
            # numbers plus the probe that explains them.  Only defaults
            # shrink — explicit env settings are respected.
            details["degraded_chip_mode"] = True
            print("[bench] DEGRADED CHIP: shrinking default iteration "
                  "counts so the run completes; rows reflect the sick "
                  "chip, see chip_health_tflops", file=sys.stderr)
            for var, small in (("BENCH_ITERS", "20"),
                               ("BENCH_WINDOWS", "2"),
                               ("BENCH_SCAN_K", "10"),
                               ("BENCH_RESNET_ITERS", "4"),
                               ("BENCH_LM_LONG_ITERS", "3"),
                               ("BENCH_LM_LONG_CFGS", "1x4096"),
                               ("BENCH_LM_ITERS", "5"),
                               ("BENCH_EA_TAU", "5")):
                os.environ.setdefault(var, small)
            batch = int(os.environ.get("BENCH_BATCH", "256"))
            iters = int(os.environ["BENCH_ITERS"])
            windows = int(os.environ["BENCH_WINDOWS"])
            warmup = int(os.environ.get("BENCH_WARMUP", "5"))

    # --- headline: CIFAR-10 convnet fused AllReduceSGD ---------------------
    # Measured on the SCANNED step (train.build_sgd_scan_step: K chained
    # full steps — fwd+bwd+psum+update on K distinct batches — per host
    # dispatch).  The scan measures the CHIP; the per-call rate (diagnostic
    # below) additionally measures the host→device dispatch tunnel, whose
    # latency on this remote-attached chip varies hour to hour.  Per-step
    # flops come from the per-call program's cost_analysis (XLA reports one
    # loop iteration's flops for a While program, so the scanned program's
    # own figure would undercount by K).
    scan_k = max(1, int(os.environ.get("BENCH_SCAN_K", "20")))
    step_1, ts_1, bx_1, by_1, n_dev = _build_cifar(batch)
    flops = step_flops(step_1, ts_1, bx_1, by_1)
    step_s, ts_s, bxs, bys, _ = _build_cifar(batch, scan_k=scan_k)
    sps, times, loss = bench_step_fn(step_s, ts_s, bxs, bys, iters, windows,
                                     warmup, steps_per_call=scan_k)
    mfu = check_mfu("cifar10", flops, sps, peak)
    details["cifar10"] = {
        "batch": batch, "iters": iters, "windows": windows,
        "steps_per_call": scan_k,
        "steps_per_sec": sps, "images_per_sec": sps * batch,
        "steps_per_sec_per_chip": sps / max(1, n_dev),
        "flops_per_step": flops, "mfu": mfu,
        "window_times": times, "final_loss": loss, "devices": n_dev,
    }
    print(f"[bench] cifar10 {platform}x{n_dev} batch={batch} "
          f"(scan x{scan_k}): {sps:.1f} steps/s ({sps * batch:.0f} img/s)"
          + (f", MFU={mfu:.4f}" if mfu is not None else ""),
          file=sys.stderr)

    # Per-call diagnostic: one host round trip per step.  Well below the
    # scanned rate = the dispatch tunnel, not the chip, is the bottleneck.
    if os.environ.get("BENCH_SKIP_PERCALL") != "1":
        sps_1, _, _ = bench_step_fn(step_1, ts_1, bx_1, by_1,
                                    max(20, iters // 2), 3, warmup=5)
        details["cifar10_per_dispatch"] = {"steps_per_sec": sps_1,
                                           "scan_vs_per_call": sps / sps_1}
        print(f"[bench] per-dispatch: {sps_1:.1f} steps/s "
              f"(scan {sps / sps_1:.2f}x — dispatch "
              f"{'bound' if sps / sps_1 > 1.1 else 'fully pipelined'})",
              file=sys.stderr)

    # --- fused vs unfused update delta (Pallas kernels on/off) -------------
    from distlearn_tpu.ops.fused_update import fused_enabled
    if os.environ.get("BENCH_SKIP_UNFUSED") != "1" and fused_enabled(None):
        step_u, ts_u, bxu, byu, _ = _build_cifar(batch, fused=False,
                                                 scan_k=scan_k)
        sps_u, _, _ = bench_step_fn(step_u, ts_u, bxu, byu,
                                    max(iters // 2, scan_k), 3, warmup=5,
                                    steps_per_call=scan_k)
        details["cifar10_unfused_steps_per_sec"] = sps_u
        details["fused_speedup"] = sps / sps_u
        print(f"[bench] unfused: {sps_u:.1f} steps/s "
              f"(fused speedup {sps / sps_u:.3f}x)", file=sys.stderr)

    # --- EASGD τ-cycle throughput (the reference's 2nd core algorithm) ------
    if os.environ.get("BENCH_SKIP_EA") != "1" and platform == "tpu":
        ea = run_bench_section("easgd_cycle", lambda: bench_easgd_cycle(
            batch, int(os.environ.get("BENCH_EA_TAU", "10")),
            iters, 3))
        if ea:
            details["easgd_cycle"] = ea
            print(f"[bench] easgd tau={ea['tau']} batch={batch}: "
                  f"{ea['steps_per_sec']:.1f} local steps/s "
                  f"({ea['images_per_sec']:.0f} img/s, "
                  f"{ea['cycles_per_sec']:.1f} elastic rounds/s)",
                  file=sys.stderr)

    # --- gradient allreduce bandwidth --------------------------------------
    # (when the multichip suite runs below it produces this same
    # measurement as its first row — reuse it instead of paying the
    # 20-iter collective twice)
    ar_mb = int(os.environ.get("BENCH_AR_MB", "64"))
    mc_will_run = os.environ.get("BENCH_SKIP_MULTICHIP") != "1"
    if mc_will_run:
        details["allreduce"] = None       # filled from the multichip row
    elif n_dev > 1:
        details["allreduce"] = allreduce_bench(ar_mb)
    else:
        details["allreduce"] = allreduce_proxy_cpu8(ar_mb)
    if details["allreduce"]:
        ar = details["allreduce"]
        print(f"[bench] allreduce {ar['payload_mb']}MB x{ar['devices']} "
              f"({ar.get('proxy', 'device mesh')}): "
              f"busbw {ar['busbw_gb_s']:.2f} GB/s", file=sys.stderr)

    # --- multichip suite (real mesh when available; labeled CPU proxy) ------
    if mc_will_run:
        if n_dev > 1:
            details["multichip"] = run_bench_section(
                "multichip", lambda: multichip_suite(ar_mb))
        else:
            details["multichip"] = multichip_proxy_cpu(
                int(os.environ.get("BENCH_MC_DEVICES", "8")))
        mc = details.get("multichip")
        if mc:
            details["allreduce"] = dict(mc["allreduce"])
            if "proxy" in mc:
                details["allreduce"]["proxy"] = \
                    f"cpu{mc['devices']}_virtual_mesh"
            a2 = details["allreduce"]
            print(f"[bench] allreduce {a2['payload_mb']}MB x"
                  f"{a2['devices']} ({a2.get('proxy', 'device mesh')}): "
                  f"busbw {a2['busbw_gb_s']:.2f} GB/s", file=sys.stderr)
        if mc:
            tag = mc.get("proxy", "real mesh")
            ar_mc = mc["allreduce"]
            eff = (f", ICI eff {ar_mc['ici_efficiency']:.0%}"
                   if "ici_efficiency" in ar_mc else "")
            print(f"[bench] multichip ({tag}, {mc['devices']} dev): "
                  f"allreduce busbw {ar_mc['busbw_gb_s']:.2f} GB/s{eff}; "
                  f"dp weak-scaling "
                  f"{mc['dp_scaling']['weak_scaling_efficiency']:.2f}; "
                  f"easgd {mc['easgd_round']['cycles_per_sec']:.2f} "
                  "cycles/s"
                  + (f"; pp S={mc['pp_lm']['stages']} "
                     f"{mc['pp_lm']['tokens_per_sec']:.0f} tok/s"
                     if "pp_lm" in mc else ""), file=sys.stderr)

    # --- host (DCN/TCP) backend: tree vs ring --------------------------------
    if os.environ.get("BENCH_SKIP_HOST") != "1":
        try:
            details["host_allreduce"] = host_allreduce_bench(
                int(os.environ.get("BENCH_HOST_MB", "16")),
                int(os.environ.get("BENCH_HOST_NODES", "4")))
            h = details["host_allreduce"]
            print(f"[bench] host allreduce {h['payload_mb']}MB x"
                  f"{h['devices']} (localhost TCP): tree "
                  f"{h['tree_busbw_gb_s']:.2f} GB/s, ring "
                  f"{h['ring_busbw_gb_s']:.2f} GB/s "
                  f"({h['ring_speedup']:.2f}x shared-CPU; "
                  f"{h['ring_speedup_emulated']:.2f}x on emulated "
                  f"{h['emulated_link_mb_s']:.0f} MB/s links; busiest NIC "
                  f"{h['ring_max_nic_bytes']/1e6:.1f} vs "
                  f"{h['tree_max_nic_bytes']/1e6:.1f} MB)",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] host allreduce bench failed: {e}",
                  file=sys.stderr)
        try:
            details["host_sync"] = host_sync_bench(
                int(os.environ.get("BENCH_SYNC_MB", "2")),
                int(os.environ.get("BENCH_SYNC_HOSTS", "2")),
                int(os.environ.get("BENCH_SYNC_LOCAL", "8")))
            s = details["host_sync"]
            hb, yb = s["host_backend"], s["hybrid_backend"]
            print(f"[bench] host sync {s['payload_mb']}MB x"
                  f"{s['hosts']}hx{s['local_devices']}d: flat "
                  f"{hb['host_leg_bytes_per_host']/1e6:.1f} MB/host -> "
                  f"hybrid {yb['host_leg_bytes_per_host']/1e6:.1f} MB/host "
                  f"({s['host_leg_byte_reduction']:.1f}x fewer); emulated "
                  f"{s['emulated_link_mb_s']:.0f} MB/s link: "
                  f"{hb['syncs_per_sec_emulated']:.2f} -> "
                  f"{yb['syncs_per_sec_emulated']:.2f} syncs/s "
                  f"({s['hybrid_sync_speedup_emulated']:.1f}x)",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] host sync bench failed: {e}", file=sys.stderr)

    # --- host wire path: per-leaf vs packed/quantized frames -----------------
    if os.environ.get("BENCH_SKIP_WIRE") != "1":
        try:
            details["host_wire"] = host_wire_bench(
                int(os.environ.get("BENCH_WIRE_ITERS", "20")))
            for set_name, w in details["host_wire"].items():
                print(f"[bench] wire {set_name} ({w['leaves']} leaves): "
                      f"perleaf {w['perleaf']['syncs_per_sec']:.1f} -> "
                      f"packed {w['raw']['syncs_per_sec']:.1f} syncs/s "
                      f"({w['packed_raw_speedup']:.2f}x); int8 "
                      f"{w['int8']['bytes_per_sync']/1e6:.2f} MB/sync "
                      f"({w['int8_byte_reduction']:.2f}x fewer bytes)",
                      file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] host wire bench failed: {e}", file=sys.stderr)
        try:
            details["wire_cpu_cost"] = wire_cpu_bench()
            w = details["wire_cpu_cost"]
            print(f"[bench] wire cpu ({w['logical_mb']:.1f}MB int8): "
                  f"encode {w['int8_encode_ref_ns_per_byte']:.2f} -> "
                  f"{w['int8_encode_fused_ns_per_byte']:.2f} ns/B "
                  f"({w['int8_encode_speedup']:.2f}x fused); apply "
                  f"{w['int8_apply_ref_ns_per_byte']:.2f} -> "
                  f"{w['int8_apply_fused_ns_per_byte']:.2f} ns/B "
                  f"({w['int8_apply_speedup']:.2f}x); sync-loop CPU "
                  f"{w['sync_loop_cpu_reduction']:.2f}x lower",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] wire cpu bench failed: {e}", file=sys.stderr)

    # --- AsyncEA parameter-server protocol throughput ------------------------
    if os.environ.get("BENCH_SKIP_ASYNC") != "1":
        try:
            details["async_ea"] = async_ea_bench(
                int(os.environ.get("BENCH_ASYNC_MB", "8")),
                int(os.environ.get("BENCH_ASYNC_CLIENTS", "2")))
            a = details["async_ea"]
            print(f"[bench] asyncEA {a['param_mb']}MB params x"
                  f"{a['clients']} clients: {a['syncs_per_sec']:.1f} "
                  f"syncs/s ({a['payload_gb_s']:.2f} GB/s through the "
                  "server)", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] asyncEA bench failed: {e}", file=sys.stderr)
        # ResNet-scale center through the CONCURRENT server (overlapped
        # per-client handshakes — the north-star structure)
        try:
            details["async_ea_resnet_scale"] = async_ea_bench(
                int(os.environ.get("BENCH_ASYNC_BIG_MB", "100")),
                int(os.environ.get("BENCH_ASYNC_BIG_CLIENTS", "2")),
                syncs_per_client=int(
                    os.environ.get("BENCH_ASYNC_BIG_SYNCS", "4")),
                server_impl="concurrent")
            a = details["async_ea_resnet_scale"]
            print(f"[bench] asyncEA concurrent {a['param_mb']}MB params x"
                  f"{a['clients']} clients: {a['syncs_per_sec']:.2f} "
                  f"syncs/s ({a['payload_gb_s']:.2f} GB/s through the "
                  "server)", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] asyncEA concurrent bench failed: {e}",
                  file=sys.stderr)

    # --- sharded center: striped parameter-server scaling --------------------
    if os.environ.get("BENCH_SKIP_SHARD") != "1":
        try:
            details["host_shard"] = host_shard_bench(
                int(os.environ.get("BENCH_SHARD_CLIENTS", "4")),
                int(os.environ.get("BENCH_SHARD_SYNCS", "4")))
            for set_name, w in details["host_shard"].items():
                print(f"[bench] shard {set_name} ({w['param_mb']:.1f}MB x"
                      f"{w['clients']} clients): emulated "
                      f"{w['emulated']['s1']['syncs_per_sec']:.2f} -> "
                      f"{w['emulated']['s4']['syncs_per_sec']:.2f} syncs/s "
                      f"S=1->4 ({w['emulated_shard_speedup']:.2f}x on "
                      f"{w['emulated_link_mb_s']:.0f} MB/s links; loopback "
                      f"{w['loopback_shard_speedup']:.2f}x; S=1 at "
                      f"{w['emulated_s1_vs_baseline']:.2f}x of unsharded "
                      "baseline)", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] shard bench failed: {e}", file=sys.stderr)

    # --- ResNet-50 utilization bench ---------------------------------------
    if os.environ.get("BENCH_SKIP_RESNET") != "1" and platform == "tpu":
        rb = int(os.environ.get("BENCH_RESNET_BATCH", "256"))
        ri = int(os.environ.get("BENCH_RESNET_ITERS", "30"))
        r = run_bench_section("resnet50",
                              lambda: bench_resnet50(rb, ri, 3, peak))
        if r:
            details["resnet50"] = r
            print(f"[bench] resnet50 batch={rb}: "
                  f"{r['images_per_sec']:.0f} img/s"
                  + (f", MFU={r['mfu']:.4f}" if r["mfu"] is not None
                     else ""), file=sys.stderr)
        # norm-free (SkipInit) variant: the delta vs the row above is the
        # measured BN channel-reduction cost (~50% of step time per the
        # r3 profile)
        r2 = run_bench_section(
            "resnet50_skipinit",
            lambda: bench_resnet50(rb, ri, 3, peak, norm="none"))
        if r2:
            details["resnet50_skipinit"] = r2
            sp = (f" ({r2['steps_per_sec'] / r['steps_per_sec']:.2f}x vs "
                  "BN)" if r else "")
            print(f"[bench] resnet50 skipinit batch={rb}: "
                  f"{r2['images_per_sec']:.0f} img/s"
                  + (f", MFU={r2['mfu']:.4f}" if r2["mfu"] is not None
                     else "") + sp, file=sys.stderr)

    # --- transformer LM (long-context) utilization bench --------------------
    if os.environ.get("BENCH_SKIP_LM") != "1" and platform == "tpu":
        lb = int(os.environ.get("BENCH_LM_BATCH", "8"))
        ls = int(os.environ.get("BENCH_LM_SEQ", "1024"))
        li = int(os.environ.get("BENCH_LM_ITERS", "30"))
        t = run_bench_section(
            "transformer_lm", lambda: bench_transformer_lm(lb, ls, li, 3,
                                                           peak))
        if t:
            details["transformer_lm"] = t
            print(f"[bench] transformer_lm batch={lb} seq={ls}: "
                  f"{t['tokens_per_sec']:.0f} tok/s"
                  + (f", MFU={t['mfu']:.4f}" if t["mfu"] is not None else ""),
                  file=sys.stderr)

    # --- mixed-precision LM step: before/after at three widths --------------
    if os.environ.get("BENCH_SKIP_LM_MIXED") != "1" and platform == "tpu":
        md = [int(v) for v in os.environ.get(
            "BENCH_LM_MIXED_DIMS", "1024,2048,4096").split(",")]
        mr = run_bench_section(
            "lm_mixed", lambda: bench_lm_mixed_sweep(
                md, int(os.environ.get("BENCH_LM_BATCH", "8")),
                int(os.environ.get("BENCH_LM_SEQ", "1024")),
                int(os.environ.get("BENCH_LM_MIXED_ITERS", "15")), 3,
                peak))
        if mr:
            details["lm_mixed"] = mr

    # --- routed-MoE LM utilization ------------------------------------------
    if os.environ.get("BENCH_SKIP_MOE") != "1" and platform == "tpu":
        mo = run_bench_section("moe_lm", lambda: bench_moe_lm(
            int(os.environ.get("BENCH_LM_BATCH", "8")),
            int(os.environ.get("BENCH_LM_SEQ", "1024")),
            int(os.environ.get("BENCH_LM_ITERS", "30")), 3, peak))
        if mo:
            details["moe_lm"] = mo
            print(f"[bench] moe_lm ({mo['experts']} experts, top-1) "
                  f"batch={mo['batch']} seq={mo['seq_len']}: "
                  f"{mo['tokens_per_sec']:.0f} tok/s"
                  + (f", MFU={mo['mfu']:.4f}" if mo["mfu"] is not None
                     else ""), file=sys.stderr)

    # --- pipeline-parallel machinery overhead (S=1 on one chip) -------------
    if os.environ.get("BENCH_SKIP_PP") != "1" and platform == "tpu":
        pr = run_bench_section("pp_lm", lambda: bench_pp_lm(
            int(os.environ.get("BENCH_LM_BATCH", "8")),
            int(os.environ.get("BENCH_LM_SEQ", "1024")),
            int(os.environ.get("BENCH_LM_ITERS", "30")), 3, peak))
        if pr:
            details["pp_lm"] = pr
            print(f"[bench] pp_lm (S=1, M={pr['microbatches']}): "
                  f"{pr['tokens_per_sec']:.0f} tok/s — GPipe machinery "
                  f"{pr['machinery_efficiency_vs_plain']:.3f}x of plain "
                  "step (bubble excluded; real pods add (S-1)/(M+S-1))",
                  file=sys.stderr)

    # --- long-context LM (chunked causal attention + selective remat) -------
    if os.environ.get("BENCH_SKIP_LM_LONG") != "1" and platform == "tpu":
        # 1x16384 runs the scanned-depth layout ("s" suffix): the
        # unrolled program at that length is what the attached tunnel's
        # remote-compile helper rejects (HTTP 500).
        if ("BENCH_LM_LONG_BATCH" in os.environ
                or "BENCH_LM_LONG_SEQ" in os.environ):
            # round-2 interface: honor the old single-config vars
            cfgs = (os.environ.get("BENCH_LM_LONG_BATCH", "1") + "x"
                    + os.environ.get("BENCH_LM_LONG_SEQ", "4096"))
        else:
            # trailing "s" = scanned-depth layout (1x16384 only compiles
            # scanned — the unrolled program exceeds the compile helper)
            cfgs = os.environ.get("BENCH_LM_LONG_CFGS",
                                  "1x4096,1x8192,4x4096,1x16384s")
        lci = int(os.environ.get("BENCH_LM_LONG_ITERS", "15"))
        lm_dim, lm_depth = _lm_dim_depth()
        rows = []
        for cfg in cfgs.split(","):
            cfg = cfg.strip()
            scanned = cfg.endswith("s")
            lcb, lcs = (int(v) for v in cfg.rstrip("s").split("x"))
            # Long-context recipe (r4): CHUNKED causal attention (masked
            # half of the scores never computed, softmax weights saved so
            # backward re-runs no exp — measured faster than both the
            # naive path and the Pallas flash kernel on v5e, which is
            # exp/VPU-bound at this shape) + selective remat where the
            # saved f32 weights fit HBM, full remat otherwise.  MFU uses
            # model flops (no-remat program); HFU counts the recompute.
            w_bytes = lcb * (lm_dim // 64) * lcs * lcs // 2 * 4 * lm_depth
            remat_mode = "mlp" if w_bytes < 9e9 else "full"
            row = run_bench_section(
                f"lm_long {cfg}",
                lambda lcb=lcb, lcs=lcs, rm=remat_mode, sc=scanned:
                    bench_transformer_lm(lcb, lcs, lci, 3, peak,
                                         attn="chunked", remat=rm,
                                         scan_blocks=sc))
            if row:
                rows.append(row)
        # Configs whose no-remat program the compile helper rejects have
        # mfu=None; extrapolate model flops analytically, calibrated on a
        # row where cost_analysis worked (same dim/depth, so the
        # non-matmul overhead fraction transfers).
        cal = [r for r in rows if r["mfu"] is not None and peak]
        if cal:
            c = cal[0]
            ratio = c["flops_per_step"] / _analytic_lm_train_flops(
                c["batch"], c["seq_len"], c["dim"], c["depth"])
            for r in rows:
                if r["mfu"] is None and peak:
                    est = ratio * _analytic_lm_train_flops(
                        r["batch"], r["seq_len"], r["dim"], r["depth"])
                    r["flops_per_step"] = est
                    r["mfu"] = check_mfu("lm_long(analytic)", est,
                                         r["steps_per_sec"], peak)
                    r["mfu_basis"] = "analytic_calibrated"
        for r in rows:
            print(f"[bench] lm_long ({r['attn']}+remat={r['remat']}) "
                  f"batch={r['batch']} "
                  f"seq={r['seq_len']}: {r['tokens_per_sec']:.0f} tok/s"
                  + (f", MFU={r['mfu']:.4f}" if r["mfu"] is not None else "")
                  + ("(analytic)" if r.get("mfu_basis") else "")
                  + (f", HFU={r['hfu']:.4f}" if r["hfu"] is not None
                     else ""), file=sys.stderr)
        if rows:
            details["transformer_lm_long"] = rows

    # --- serving: continuous batching vs sequential decode ------------------
    if os.environ.get("BENCH_SKIP_SERVE") != "1":
        sv = run_bench_section("serve_bench", serve_bench)
        if sv:
            details["serve_bench"] = sv

    # --- modeled baseline ---------------------------------------------------
    baseline = (sps if platform == "cpu"
                else cpu_baseline(batch))
    if platform == "cpu":
        cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cpu_baseline.json")
        with open(cache, "w") as fh:
            json.dump({"steps_per_sec": sps, "batch": batch,
                       "protocol": PROTOCOL}, fh)
    details["cpu_baseline_steps_per_sec"] = baseline
    vs = (sps / baseline) if baseline else 1.0

    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAILS.json"), "w") as fh:
            json.dump(details, fh, indent=2)
    except OSError as e:
        print(f"[bench] could not write BENCH_DETAILS.json: {e}",
              file=sys.stderr)

    headline = {
        "metric": "cifar10_convnet_allreduce_sgd_steps_per_sec",
        "value": round(sps, 4),
        "unit": (f"steps/s (global batch {batch}, {n_dev} {platform} "
                 f"chip(s), median of {windows}x{iters}-step windows, "
                 f"{scan_k} steps/dispatch"
                 + (f", MFU {mfu:.4f}" if mfu is not None else "")
                 + "; vs_baseline = ratio to the SAME step on this host's "
                 "single CPU core — a modeled stand-in for the reference's "
                 "CPU path, NOT a framework-vs-framework claim)"),
        "vs_baseline": round(vs, 4),
    }
    if details.get("degraded_chip_mode"):
        # machine-readable marker so no cross-round consumer (incl. the
        # outage fallback scan above) mistakes a sick-chip number for a
        # representative measurement
        headline["degraded"] = True
    # Persist the last REAL TPU measurement so a future tunnel outage can
    # carry it forward (stale-marked) instead of reporting a fake zero.
    # CPU/degraded runs don't overwrite a healthy record.
    if platform == "tpu" and not details.get("degraded_chip_mode"):
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    _LAST_GOOD_BASENAME), "w") as fh:
                json.dump(dict(headline, recorded_at=time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime())), fh, indent=2)
        except OSError as e:
            print(f"[bench] could not write {_LAST_GOOD_BASENAME}: {e}",
                  file=sys.stderr)
    print(json.dumps(headline))


if __name__ == "__main__":
    if "--cpu-probe" in sys.argv:
        _pin_cpu()
        _enable_compile_cache()
        batch = int(os.environ.get("BENCH_BATCH", "256"))
        step, ts, bx, by, _ = _build_cifar(batch)
        sps, _, _ = bench_step_fn(
            step, ts, bx, by,
            int(os.environ.get("BENCH_ITERS", "10")),
            int(os.environ.get("BENCH_WINDOWS", "3")),
            int(os.environ.get("BENCH_WARMUP", "2")))
        print(json.dumps({"value": sps}))
    elif "--allreduce-probe" in sys.argv:
        _pin_cpu(int(os.environ.get("BENCH_AR_DEVICES", "8")))
        _enable_compile_cache()
        print(json.dumps(allreduce_bench(
            int(os.environ.get("BENCH_AR_MB", "64")))))
    elif "--serve-probe" in sys.argv:
        # Standalone serving probe: runs serve_bench alone and MERGES the
        # result into BENCH_DETAILS.json (read-modify-write) so a serving
        # re-measure doesn't discard the training rows from a full run.
        _pin_cpu(1)
        _enable_compile_cache()
        sv = serve_bench()
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_DETAILS.json")
        try:
            with open(path) as fh:
                details = json.load(fh)
        except (OSError, ValueError):
            details = {}
        details["serve_bench"] = sv
        with open(path, "w") as fh:
            json.dump(details, fh, indent=2)
        print(json.dumps(sv["rows"]))
    elif "--wire-cpu-probe" in sys.argv:
        # Standalone fused-codec probe: runs wire_cpu_bench alone and
        # MERGES the row into BENCH_DETAILS.json (read-modify-write) so
        # a codec re-measure doesn't discard the training rows.  Chip-
        # and jax-free; also the distlint wirek budget refresh source.
        _pin_cpu(1)
        w = wire_cpu_bench(
            int(os.environ.get("BENCH_WIRE_CPU_REPS", "9")),
            int(os.environ.get("BENCH_WIRE_CPU_SYNCS", "30")))
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_DETAILS.json")
        try:
            with open(path) as fh:
                details = json.load(fh)
        except (OSError, ValueError):
            details = {}
        details["wire_cpu_cost"] = w
        with open(path, "w") as fh:
            json.dump(details, fh, indent=2)
        print(json.dumps(w))
    elif "--host-sync-probe" in sys.argv:
        # Standalone collective-backend probe: runs host_sync_bench
        # alone and MERGES the row into BENCH_DETAILS.json (read-
        # modify-write) so a backend re-measure doesn't discard the
        # training rows.  TPU-free: the hybrid children force the
        # 8-device CPU platform themselves.
        hs = host_sync_bench(
            int(os.environ.get("BENCH_SYNC_MB", "2")),
            int(os.environ.get("BENCH_SYNC_HOSTS", "2")),
            int(os.environ.get("BENCH_SYNC_LOCAL", "8")))
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_DETAILS.json")
        try:
            with open(path) as fh:
                details = json.load(fh)
        except (OSError, ValueError):
            details = {}
        details["host_sync"] = hs
        with open(path, "w") as fh:
            json.dump(details, fh, indent=2)
        print(json.dumps(hs))
    elif "--multichip-probe" in sys.argv:
        _pin_cpu(int(os.environ.get("BENCH_MC_DEVICES", "8")))
        _enable_compile_cache()
        print(json.dumps(multichip_suite(
            int(os.environ.get("BENCH_AR_MB", "64")))))
    else:
        main()
