"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Absent from the reference (single-process forward/backward per node —
SURVEY.md §2c), provided here as a first-class mesh dimension alongside
data/sequence/tensor parallelism: stage parameters are sharded over a
``pipe`` axis (one stage per device), microbatches stream through the
stages, and the inter-stage hop is a neighbor ``ppermute`` riding one ICI
link.  The whole pipeline — all ticks, all stages — is ONE ``lax.scan``
inside one jitted shard_map program, so XLA overlaps each tick's compute
with the neighbor transfer, and ``jax.grad`` through the scan yields the
standard GPipe backward schedule for free (functional autodiff replaces the
hand-written backward pipelines of imperative frameworks).

Schedule: ``M`` microbatches over ``S`` stages take ``M + S - 1`` ticks;
bubble fraction ``(S-1)/(M+S-1)`` — choose ``M >> S`` to amortize.

SPMD shape: every device runs the same program; at tick ``t`` stage 0
ingests microbatch ``t`` (or zeros once input is exhausted) while stages
``1..S-1`` consume the activation ppermuted from their predecessor.  The
last stage's valid outputs are broadcast back to all stages (psum-masked,
like :func:`distlearn_tpu.parallel.mesh.broadcast_from`), keeping the
caller's output replicated over the pipe axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def pipeline_apply(stage_fn: Callable, stage_params: PyTree, x: jax.Array,
                   num_microbatches: int, axis_name: str = "pipe",
                   consume_fn: Callable | None = None,
                   unroll: bool | int = False) -> jax.Array:
    """Run ``x`` through ``S`` pipelined stages (``S`` = size of
    ``axis_name``).

    Args:
      stage_fn: ``(params, h) -> h`` — ONE stage's transform.  Must map a
        microbatch ``[mb, ...]`` to the same shape (inter-stage activations
        are homogeneous, the usual pipeline restriction).
      stage_params: THIS device's stage parameters (caller shards a stacked
        ``[S, ...]`` pytree over the pipe axis and squeezes, exactly like
        the per-node state in distlearn_tpu.train).
      x: the full local batch ``[B, ...]`` (replicated over the pipe axis);
        ``B`` must divide into ``num_microbatches`` equal microbatches.
      num_microbatches: GPipe ``M``; bubble = (S-1)/(M+S-1).
      consume_fn: optional ``(out_mb, mb_index) -> scalar`` folding each
        microbatch's LAST-stage output (e.g. its loss share) as it emerges
        from the pipeline.  SPMD caveat: it executes every tick on every
        rank (same program everywhere); only the last rank's valid ticks
        are accumulated — the rest are masked to zero, so no gradient
        flows from them.
      unroll: forwarded to the tick ``lax.scan``.  ``True`` inlines all
        ``T = M+S-1`` ticks so XLA fuses and overlaps across tick
        boundaries — measured ~1.6x on the one-chip GPipe bench
        (docs/PERF.md) — at the cost of a ~T-times-larger program (long
        compiles; this host's remote-compile helper rejects very large
        programs, so it is off by default and recommended for small M).

    Returns:
      Without ``consume_fn``: ``[B, ...]`` outputs of the LAST stage,
      replicated over the pipe axis (differentiable end to end).
      With ``consume_fn``: the LOCAL share of ``Σ_mb consume_fn(out_mb,
      mb)`` — nonzero only on the last rank; ``lax.psum`` it over
      ``axis_name`` *outside* the differentiated region (psum transposes
      to psum under shard_map).  This path never materializes the
      ``[T, mb, ...]`` output stack and skips the output broadcast — the
      scalar psum replaces a full [B, ...] collective.
    """
    S = lax.psum(1, axis_name)          # static under shard_map
    idx = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M
    mbs = x.reshape((M, mb) + x.shape[1:])
    T = M + S - 1

    # Probe the stage output type (abstract — no FLOPs run): the scan carry
    # must be well-typed, and pipelining requires homogeneous activations.
    out_aval = jax.eval_shape(stage_fn, stage_params, mbs[0])
    if out_aval.shape != mbs[0].shape:
        raise ValueError(
            f"stage_fn must preserve activation shape (got {mbs[0].shape} "
            f"-> {out_aval.shape}); wrap in/out projections around the "
            "pipeline, not inside it")
    zeros_state = jnp.zeros(out_aval.shape, out_aval.dtype)

    fwd_perm = [(j, j + 1) for j in range(S - 1)]   # no wraparound

    def ingest(state, t):
        # stage 0 ingests microbatch t (zeros once exhausted); others take
        # the activation their predecessor ppermuted last tick
        feed = lax.dynamic_index_in_dim(mbs, jnp.minimum(t, M - 1), 0,
                                        keepdims=False)
        feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
        return jnp.where(idx == 0, feed.astype(zeros_state.dtype), state)

    if consume_fn is not None:
        def tick(carry, t):
            state, acc = carry
            out = stage_fn(stage_params, ingest(state, t))
            m = t - (S - 1)          # microbatch index emerging this tick
            val = consume_fn(out, jnp.maximum(m, 0))
            acc = acc + jnp.where((idx == S - 1) & (m >= 0), val,
                                  jnp.zeros_like(val))
            return (lax.ppermute(out, axis_name, fwd_perm), acc), None

        (_, acc), _ = lax.scan(tick, (zeros_state,
                                      jnp.zeros((), jnp.float32)),
                               jnp.arange(T), unroll=unroll)
        return acc

    def tick(state, t):
        out = stage_fn(stage_params, ingest(state, t))
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        return nxt, out

    _, outs = lax.scan(tick, zeros_state, jnp.arange(T),
                       unroll=unroll)                      # [T, mb, ...]

    # The last stage's outputs at ticks S-1 .. T-1 are microbatches 0..M-1.
    valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
    y = valid.reshape((B,) + valid.shape[2:])
    # broadcast from the last stage so every device returns the result
    from distlearn_tpu.parallel.mesh import broadcast_from
    return broadcast_from(y, S - 1, axis_name)
