"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Absent from the reference (single-process forward/backward per node —
SURVEY.md §2c), provided here as a first-class mesh dimension alongside
data/sequence/tensor parallelism: stage parameters are sharded over a
``pipe`` axis (one stage per device), microbatches stream through the
stages, and the inter-stage hop is a neighbor ``ppermute`` riding one ICI
link.  The whole pipeline — all ticks, all stages — is ONE ``lax.scan``
inside one jitted shard_map program, so XLA overlaps each tick's compute
with the neighbor transfer, and ``jax.grad`` through the scan yields the
standard GPipe backward schedule for free (functional autodiff replaces the
hand-written backward pipelines of imperative frameworks).

Schedule: ``M`` microbatches over ``S`` stages take ``M + S - 1`` ticks;
bubble fraction ``(S-1)/(M+S-1)`` — choose ``M >> S`` to amortize.

SPMD shape: every device runs the same program; at tick ``t`` stage 0
ingests microbatch ``t`` (or zeros once input is exhausted) while stages
``1..S-1`` consume the activation ppermuted from their predecessor.  The
last stage's valid outputs are broadcast back to all stages (psum-masked,
like :func:`distlearn_tpu.parallel.mesh.broadcast_from`), keeping the
caller's output replicated over the pipe axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from distlearn_tpu.utils import compat

PyTree = Any


def pipeline_apply(stage_fn: Callable, stage_params: PyTree, x: jax.Array,
                   num_microbatches: int, axis_name: str = "pipe",
                   consume_fn: Callable | None = None,
                   unroll: bool | int = False) -> jax.Array:
    """Run ``x`` through ``S`` pipelined stages (``S`` = size of
    ``axis_name``).

    Args:
      stage_fn: ``(params, h) -> h`` — ONE stage's transform.  Must map a
        microbatch ``[mb, ...]`` to the same shape (inter-stage activations
        are homogeneous, the usual pipeline restriction).
      stage_params: THIS device's stage parameters (caller shards a stacked
        ``[S, ...]`` pytree over the pipe axis and squeezes, exactly like
        the per-node state in distlearn_tpu.train).
      x: the full local batch ``[B, ...]`` (replicated over the pipe axis);
        ``B`` must divide into ``num_microbatches`` equal microbatches.
      num_microbatches: GPipe ``M``; bubble = (S-1)/(M+S-1).
      consume_fn: optional ``(out_mb, mb_index) -> scalar`` folding each
        microbatch's LAST-stage output (e.g. its loss share) as it emerges
        from the pipeline.  SPMD caveat: it executes every tick on every
        rank (same program everywhere); only the last rank's valid ticks
        are accumulated — the rest are masked to zero, so no gradient
        flows from them.
      unroll: forwarded to the tick ``lax.scan``.  ``True`` inlines all
        ``T = M+S-1`` ticks so XLA fuses and overlaps across tick
        boundaries — measured ~1.6x on the one-chip GPipe bench
        (docs/PERF.md) — at the cost of a ~T-times-larger program (long
        compiles; this host's remote-compile helper rejects very large
        programs, so it is off by default and recommended for small M).

    Returns:
      Without ``consume_fn``: ``[B, ...]`` outputs of the LAST stage,
      replicated over the pipe axis (differentiable end to end).
      With ``consume_fn``: the LOCAL share of ``Σ_mb consume_fn(out_mb,
      mb)`` — nonzero only on the last rank; ``lax.psum`` it over
      ``axis_name`` *outside* the differentiated region (psum transposes
      to psum under shard_map).  This path never materializes the
      ``[T, mb, ...]`` output stack and skips the output broadcast — the
      scalar psum replaces a full [B, ...] collective.
    """
    S = lax.psum(1, axis_name)          # static under shard_map
    idx = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M
    mbs = x.reshape((M, mb) + x.shape[1:])
    T = M + S - 1

    # Probe the stage output type (abstract — no FLOPs run): the scan carry
    # must be well-typed, and pipelining requires homogeneous activations.
    out_aval = jax.eval_shape(stage_fn, stage_params, mbs[0])
    if out_aval.shape != mbs[0].shape:
        raise ValueError(
            f"stage_fn must preserve activation shape (got {mbs[0].shape} "
            f"-> {out_aval.shape}); wrap in/out projections around the "
            "pipeline, not inside it")
    zeros_state = jnp.zeros(out_aval.shape, out_aval.dtype)

    fwd_perm = [(j, j + 1) for j in range(S - 1)]   # no wraparound

    def ingest(state, t):
        # stage 0 ingests microbatch t (zeros once exhausted); others take
        # the activation their predecessor ppermuted last tick
        feed = lax.dynamic_index_in_dim(mbs, jnp.minimum(t, M - 1), 0,
                                        keepdims=False)
        feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
        return jnp.where(idx == 0, feed.astype(zeros_state.dtype), state)

    if consume_fn is not None:
        def tick(carry, t):
            state, acc = carry
            out = stage_fn(stage_params, ingest(state, t))
            m = t - (S - 1)          # microbatch index emerging this tick
            val = consume_fn(out, jnp.maximum(m, 0))
            acc = acc + jnp.where((idx == S - 1) & (m >= 0), val,
                                  jnp.zeros_like(val))
            return (lax.ppermute(out, axis_name, fwd_perm), acc), None

        (_, acc), _ = lax.scan(tick, (zeros_state,
                                      jnp.zeros((), jnp.float32)),
                               jnp.arange(T), unroll=unroll)
        return acc

    def tick(state, t):
        out = stage_fn(stage_params, ingest(state, t))
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        return nxt, out

    _, outs = lax.scan(tick, zeros_state, jnp.arange(T),
                       unroll=unroll)                      # [T, mb, ...]

    # The last stage's outputs at ticks S-1 .. T-1 are microbatches 0..M-1.
    valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
    y = valid.reshape((B,) + valid.shape[2:])
    # broadcast from the last stage so every device returns the result
    from distlearn_tpu.parallel.mesh import broadcast_from
    return broadcast_from(y, S - 1, axis_name)


def pipeline_1f1b(stage_fn: Callable, stage_params: PyTree,
                  consume_fn: Callable, consume_params: PyTree,
                  x: jax.Array, num_microbatches: int,
                  axis_name: str = "pipe"):
    """One-forward-one-backward pipeline schedule, gradients included.

    :func:`pipeline_apply` + ``jax.grad`` IS GPipe: all M forwards run
    before any backward, so the autodiff residuals of every in-flight
    microbatch stay live — activation memory O(M).  This function runs
    the 1F1B schedule instead: each microbatch's backward starts as soon
    as it leaves the last stage, so at most ``2(S-1)+1`` microbatch
    INPUTS are ever held per stage — activation memory O(S), the reason
    1F1B is the production schedule when M >> S.  The price: gradients
    are computed manually (``jax.vjp`` per tick) rather than by
    differentiating through the forward scan, so this function RETURNS
    gradients and cannot itself sit under ``jax.grad``.

    Schedule (SPMD — every rank runs the same T-tick scan, masked by its
    ``axis_name`` index): tick ``t`` runs the GPipe forward for
    microbatch ``t - idx`` AND the backward for microbatch
    ``t - 2(S-1) + idx``; the last stage seeds its own cotangent from
    ``consume_fn``'s vjp in the same tick its forward emerges, and
    cotangents ride a backward neighbor ppermute.  Total ticks
    ``T = M + 2S - 2`` (vs GPipe's ``M + S - 1`` forward ticks plus the
    reversed backward scan — same compute, same bubble fraction).  The
    per-tick backward re-runs the stage forward inside ``jax.vjp``
    (recompute-from-stage-input), matching the memory/FLOP trade of
    ``remat=True`` GPipe.

    Args:
      stage_fn: ``(stage_params, h) -> h`` — shape-preserving, as in
        :func:`pipeline_apply`.
      consume_fn: ``(consume_params, out_mb, mb_index) -> scalar`` — the
        last-stage loss share (e.g. this microbatch's share of the
        global-mean NLL).  Unlike :func:`pipeline_apply`'s ``consume_fn``
        it takes its parameters EXPLICITLY, because their gradient must
        be returned (a closure would silently drop it).
      consume_params: pytree of parameters consumed by ``consume_fn``.
      x: ``[B, ...]`` input ACTIVATIONS (already embedded), replicated
        over the pipe axis.
      num_microbatches: M; ``B`` must divide evenly.

    Returns ``(local_share, g_stage_params, g_consume_params, g_x)``:
    the loss share (nonzero only on the last rank — psum it), this
    stage's parameter gradients, ``consume_fn``'s parameter gradients
    (nonzero only on the last rank — psum over pipe reassembles), and
    the gradient w.r.t. ``x`` (nonzero only on rank 0; backprop it
    through the embedding outside).
    """
    S = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M
    mbs = x.reshape((M, mb) + x.shape[1:])
    T = M + 2 * S - 2
    BUF = 2 * S - 1            # max in-flight saved inputs per stage

    out_aval = jax.eval_shape(stage_fn, stage_params, mbs[0])
    if out_aval.shape != mbs[0].shape:
        raise ValueError(
            f"stage_fn must preserve activation shape (got {mbs[0].shape} "
            f"-> {out_aval.shape})")
    act_dtype = out_aval.dtype
    zeros_act = jnp.zeros(out_aval.shape, act_dtype)

    fwd_perm = [(j, j + 1) for j in range(S - 1)]
    bwd_perm = [(j, j - 1) for j in range(1, S)]
    zf32 = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        fwd_in, buf, cot_in, g_stage, g_cons, gx, share = carry

        # ---- forward half: GPipe ingest + stage forward -------------------
        m_f = t - idx                      # this stage's fwd microbatch
        fwd_valid = (m_f >= 0) & (m_f < M)
        feed = lax.dynamic_index_in_dim(mbs, jnp.clip(m_f, 0, M - 1), 0,
                                        keepdims=False)
        a_in = jnp.where(idx == 0, feed.astype(act_dtype), fwd_in)
        out = stage_fn(stage_params, a_in)
        buf = lax.dynamic_update_index_in_dim(buf, a_in, t % BUF, 0)

        # last stage: fold the loss share and seed the cotangent for this
        # SAME microbatch's backward, which runs this very tick.  The head
        # vjp (vocab-sized logits matmul + log-softmax + backward) is S
        # times the necessary compute if every stage runs it only to mask
        # the result — consume_fn contains no collectives, so lax.cond
        # genuinely skips it on all ranks but the live last stage.
        def cons(cp, o):
            return consume_fn(cp, o, jnp.clip(m_f, 0, M - 1))

        last_live = (idx == S - 1) & fwd_valid

        def head_live(cp, o):
            val, cvjp = jax.vjp(cons, cp, o)
            g_cp_t, seed = cvjp(jnp.ones((), val.dtype))
            return val.astype(jnp.float32), g_cp_t, seed.astype(act_dtype)

        def head_skip(cp, o):
            return (zf32, jax.tree_util.tree_map(jnp.zeros_like, cp),
                    jnp.zeros(o.shape, act_dtype))

        val, g_cp_t, seed = lax.cond(last_live, head_live, head_skip,
                                     consume_params, out)
        share = share + val
        g_cons = jax.tree_util.tree_map(lambda a, g: a + g, g_cons, g_cp_t)

        # ---- backward half: 1F1B interleave -------------------------------
        m_b = t - (2 * S - 2) + idx        # this stage's bwd microbatch
        bwd_valid = (m_b >= 0) & (m_b < M)
        cot = jnp.where(idx == S - 1, seed.astype(act_dtype),
                        cot_in.astype(act_dtype))
        # its input was saved at tick m_b + idx
        slot = jnp.clip(m_b + idx, 0, T - 1) % BUF
        a_saved = lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
        _, svjp = jax.vjp(stage_fn, stage_params, a_saved)
        g_p_t, g_in = svjp(cot)
        g_stage = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(bwd_valid, g, jnp.zeros_like(g)),
            g_stage, g_p_t)
        # rank 0's input-gradient is the embedding cotangent for mb m_b
        gx_upd = lax.dynamic_update_index_in_dim(
            gx, g_in.astype(gx.dtype), jnp.clip(m_b, 0, M - 1), 0)
        gx = jnp.where((idx == 0) & bwd_valid, gx_upd, gx)

        # ---- neighbor exchanges for the next tick -------------------------
        fwd_nxt = lax.ppermute(out, axis_name, fwd_perm)
        cot_nxt = lax.ppermute(g_in, axis_name, bwd_perm)
        return (fwd_nxt, buf, cot_nxt, g_stage, g_cons, gx, share), None

    init = (zeros_act,
            jnp.zeros((BUF,) + out_aval.shape, act_dtype),
            zeros_act,
            jax.tree_util.tree_map(jnp.zeros_like, stage_params),
            jax.tree_util.tree_map(jnp.zeros_like, consume_params),
            jnp.zeros(mbs.shape, x.dtype),
            zf32)
    (_, _, _, g_stage, g_cons, gx, share), _ = lax.scan(
        tick, init, jnp.arange(T))
    return share, g_stage, g_cons, gx.reshape((B,) + x.shape[1:])
