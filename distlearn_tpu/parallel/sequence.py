"""Sequence/context parallelism: ring attention over a mesh axis.

The reference is CNN-only (SURVEY.md §2c: SP/CP explicitly absent), but this
framework treats long-context as first-class: attention over sequences longer
than one chip's memory runs blockwise with K/V rotating around the ICI ring
(Ring Attention; blockwise online-softmax accumulation as in
FlashAttention), so sequence length scales linearly with the number of chips
while every hop rides a neighbor ICI link (``lax.ppermute``).

Usage: shard the sequence axis of q/k/v over a mesh axis inside
``shard_map`` and call :func:`ring_attention` with that axis name.  Each
device holds ``L_local = L / axis_size`` positions; communication is
``axis_size - 1`` neighbor exchanges of the local K/V block, fully
overlappable with the per-block attention compute by XLA's latency-hiding
scheduler.

All accumulation is f32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distlearn_tpu.utils import compat


def _block_attn(q, k, v, scale, mask):
    """Scores + masked online-softmax partials for one K/V block.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; mask: [Lq, Lk] bool or None.
    Returns (m_blk [B,H,Lq], s_exp [B,H,Lq,Lk], o_blk [B,H,Lq,D]) partials.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)                      # [B,H,Lq]
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0) would be wrong,
    # so replace -inf row-max with 0 (the row's s_exp is all zeros anyway)
    m_safe = jnp.where(jnp.isneginf(m_blk), 0.0, m_blk)
    s_exp = jnp.exp(scores - m_safe[..., None])           # [B,H,Lq,Lk]
    s_exp = jnp.where(jnp.isneginf(scores), 0.0, s_exp)
    # AV in the value dtype with f32 accumulation (bf16 MXU path on bf16
    # configs; identical math for f32) — softmax stats stay f32 throughout
    o_blk = jnp.einsum("bhqk,bkhd->bhqd", s_exp.astype(v.dtype), v,
                       preferred_element_type=jnp.float32)
    return m_safe, s_exp.sum(-1), o_blk


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   impl: str | None = None,
                   layout: str = "contig",
                   unroll: bool | int = False) -> jax.Array:
    """Blockwise ring attention.

    Args:
      q, k, v: local shards ``[B, L_local, H, D]`` — the global sequence is
        the concatenation over the mesh axis in rank order (``layout=
        "contig"``), or the :func:`zigzag_indices` permutation of it
        (``layout="zigzag"``).
      axis_name: mesh axis carrying the sequence shards.
      causal: apply a causal mask over GLOBAL positions.
      impl: single-device kernel choice, honored ONLY in the degenerate
        n == 1 case (forwarded to :func:`local_attention`).  For n > 1
        the inner kernel is always the portable blockwise
        :func:`_block_attn` — the Pallas flash kernel in this jax
        version returns no softmax residuals, so its per-block outputs
        cannot be merged across ring hops; use the zigzag layout to
        halve the causal block work, and note its per-block score
        buffer is [B, H, L_loc/2, L_loc/2] (a quarter of the contiguous
        ring's per-block buffer).
      unroll: forwarded to the ring ``fori_loop`` — inlining the n-1
        hops lets XLA overlap each hop's ppermute with the next block's
        compute across iteration boundaries (the r3 GPipe lesson; use
        for small n).
      layout: ``"zigzag"`` + ``causal`` runs the balanced schedule that
        never computes fully-masked blocks (~2x FLOP cut at large n, and
        identical load on every rank — the contiguous causal ring makes
        every rank wait for rank n-1's n-blocks-of-work).  Non-causal
        attention is permutation-equivariant, so zigzag data needs no
        special handling there (the standard ring is already correct).

    Returns: local attention output ``[B, L_local, H, D]`` (q's dtype),
    in the same layout as the inputs.
    """
    if layout not in ("contig", "zigzag"):
        raise ValueError(f"layout must be 'contig' or 'zigzag', "
                         f"got {layout!r}")
    n = compat.axis_size(axis_name)
    if layout == "zigzag" and causal and n > 1:
        if q.shape[1] % 2:
            raise ValueError(
                f"zigzag layout needs an even local length (two stripes "
                f"per rank), got {q.shape[1]}")
        return _zigzag_ring_causal(q, k, v, axis_name, n,
                                   lax.axis_index(axis_name), unroll=unroll)
    if n == 1:
        # Degenerate ring: the whole sequence is local.  Delegate to the
        # single-device kernel so the flash/chunked paths (no O(L^2)
        # score buffer / causal FLOP skip) stay available — the blockwise
        # fallback below would materialize the full [B,H,L,L] s_exp for
        # its one block.
        return local_attention(q, k, v, causal=causal, impl=impl)
    my = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    scale = 1.0 / (D ** 0.5)

    q_pos = my * Lq + jnp.arange(Lq)                      # global q positions

    def body(i, carry):
        k_cur, v_cur, m, l, o = carry
        src = (my - i) % n                                # owner of this block
        if causal:
            k_pos = src * Lq + jnp.arange(Lq)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        m_blk, l_blk, o_blk = _block_attn(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)                        # rescale old acc
        beta = jnp.exp(m_blk - m_new)
        l = l * alpha + l_blk * beta
        o = o * alpha[..., None] + o_blk * beta[..., None]
        # rotate K/V to the next neighbor (ring step over ICI)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l, o

    m0 = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m0, l0, o0),
                                  unroll=unroll)
    out = o / jnp.maximum(l, 1e-30)[..., None]            # [B,H,Lq,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def zigzag_indices(n: int, L: int):
    """Global-position permutation for the zigzag sequence layout.

    With ``n`` ranks the global sequence splits into ``2n`` equal stripes;
    rank ``r`` holds stripes ``r`` and ``2n-1-r`` concatenated.  Returns an
    int array ``idx`` of length ``L`` such that ``x_zigzag = x[..., idx]``
    produces the layout whose rank-order contiguous shards are the zigzag
    shards (i.e. shard it with the same ``P(..., seq_axis)`` spec as the
    contiguous layout).  Invert with ``jnp.argsort(idx)``.

    Why: under a CAUSAL mask the contiguous layout is pathologically
    imbalanced — rank 0's queries see almost no keys while rank n-1's see
    all of them, and every rank pays the worst rank's wall clock.  Pairing
    an early stripe with its mirror-image late stripe gives every rank an
    identical two-full-blocks-per-hop schedule (see
    :func:`ring_attention` ``layout="zigzag"``).
    """
    import numpy as np
    if L % (2 * n):
        raise ValueError(f"sequence length {L} must divide into 2*n={2*n} "
                         "equal zigzag stripes")
    s = L // (2 * n)
    idx = []
    for r in range(n):
        idx.extend(range(r * s, (r + 1) * s))
        idx.extend(range((2 * n - 1 - r) * s, (2 * n - r) * s))
    return np.asarray(idx, np.int32)


def _merge_blocks(acc, blk):
    """Online-softmax merge of two blockwise partial results
    ``(m [B,H,Lq], l [B,H,Lq], o [B,H,Lq,D])``."""
    m, l, o = acc
    mb, lb, ob = blk
    m_new = jnp.maximum(m, mb)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(mb - m_new)
    return (m_new, l * alpha + lb * beta,
            o * alpha[..., None] + ob * beta[..., None])


def _zigzag_ring_causal(q, k, v, axis_name, n, my, unroll=False):
    """Causal ring attention on the zigzag layout (local shard = early
    stripe ``a=my`` ++ late stripe ``b=2n-1-my``).

    Per ring hop the work is exactly two UNMASKED stripe blocks on every
    rank: ``qb×k_early(src)`` always (the late stripe sees every early
    stripe), plus ``qa×k_early(src)`` when ``src < my`` or
    ``qb×k_late(src)`` when ``src > my`` — one of the two, never both, so
    the load is identical on all ranks and the fully-masked blocks the
    contiguous layout wastes ~half its FLOPs computing are never
    launched.  Hop 0 handles the two in-stripe causal diagonals plus the
    local ``qb×ka`` block."""
    B, L2, H, D = q.shape
    s = L2 // 2
    scale = 1.0 / (D ** 0.5)
    tri = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]

    qa, qb = q[:, :s], q[:, s:]
    ka, kb = k[:, :s], k[:, s:]
    va, vb = v[:, :s], v[:, s:]

    # hop 0: local blocks
    acc_a = _block_attn(qa, ka, va, scale, tri)              # diagonal of a
    acc_b = _merge_blocks(_block_attn(qb, ka, va, scale, None),   # full
                          _block_attn(qb, kb, vb, scale, tri))    # diagonal

    def body(i, carry):
        kc, vc, kd, vd, acc_a, acc_b = carry
        src = (my - i) % n
        # unconditional: late queries attend src's early stripe
        acc_b = _merge_blocks(acc_b, _block_attn(qb, kc, vc, scale, None))
        # one conditional full block — same shape either way, so select
        # the operands and then select which accumulator takes the result
        pred = src < my
        q_sel = jnp.where(pred, qa, qb)
        k_sel = jnp.where(pred, kc, kd)
        v_sel = jnp.where(pred, vc, vd)
        blk = _block_attn(q_sel, k_sel, v_sel, scale, None)
        new_a = _merge_blocks(acc_a, blk)
        new_b = _merge_blocks(acc_b, blk)
        acc_a = jax.tree_util.tree_map(
            lambda nw, old: jnp.where(pred, nw, old), new_a, acc_a)
        acc_b = jax.tree_util.tree_map(
            lambda old, nw: jnp.where(pred, old, nw), acc_b, new_b)
        perm = [(j, (j + 1) % n) for j in range(n)]
        rot = lambda t: lax.ppermute(t, axis_name, perm)   # noqa: E731
        return rot(kc), rot(vc), rot(kd), rot(vd), acc_a, acc_b

    init = (*(lax.ppermute(t, axis_name, [(j, (j + 1) % n) for j in range(n)])
              for t in (ka, va, kb, vb)), acc_a, acc_b)
    *_, acc_a, acc_b = lax.fori_loop(1, n, body, init, unroll=unroll)

    def finish(acc):
        m, l, o = acc
        return o / jnp.maximum(l, 1e-30)[..., None]        # [B,H,s,D]

    out = jnp.concatenate([finish(acc_a), finish(acc_b)], axis=2)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def alltoall_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       axis_name: str, causal: bool = False,
                       impl: str | None = None) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Two ``all_to_all`` collectives swap the SEQUENCE sharding for a HEAD
    sharding: each device then holds the FULL sequence for ``H/n`` of the
    heads, runs ordinary full-attention locally, and swaps back.  Compared
    to :func:`ring_attention` (n-1 neighbor hops, never materializes the
    full sequence): total bytes moved are lower (two all-to-alls of the
    activations vs rotating K/V n-1 times), but the full ``L x L`` score
    block must fit in memory and the head count must be divisible by the
    axis size — the standard trade; both variants are first-class.

    q/k/v: local shards ``[B, L_local, H, D]`` (global sequence = rank-order
    concatenation over the axis).  Returns ``[B, L_local, H, D]``.
    """
    n = compat.axis_size(axis_name)
    if n == 1:
        return local_attention(q, k, v, causal=causal, impl=impl)
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"alltoall_attention needs head count divisible by the "
            f"sequence-axis size, got {H} heads over {n} devices; use "
            "ring_attention for this configuration")

    def seq_to_heads(x):
        # [B, L_loc, H, D] -> [B, L, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    out = local_attention(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                          causal=causal, impl=impl)  # full-seq, local heads
    # [B, L, H/n, D] -> [B, L_loc, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def resolve_chunk(L: int) -> int:
    """Effective chunked-attention chunk for local length ``L``:
    ``DISTLEARN_TPU_CHUNK`` when set (must be a positive int — a
    malformed override raises rather than silently benchmarking a config
    the user did not ask for), else the measured default
    ``max(128, L // 32)`` (see :func:`chunked_causal_attention`).
    The ONE place the resolution rule lives — the example's advisory note
    and the attention dispatch both call it, so they cannot drift."""
    import os
    env = os.environ.get("DISTLEARN_TPU_CHUNK")
    if env:
        try:
            c = int(env)
        except ValueError:
            raise ValueError(
                f"DISTLEARN_TPU_CHUNK={env!r} is not an integer") from None
        if c <= 0:
            raise ValueError(
                f"DISTLEARN_TPU_CHUNK={env!r} must be positive")
        return c
    return max(128, L // 32)


def chunked_engages(L: int, chunk: int | None = None) -> bool:
    """Whether the chunked causal path actually runs at local length
    ``L`` (it needs ``L > chunk`` and ``L % chunk == 0``; otherwise the
    dispatch falls back to plain XLA attention)."""
    c = chunk if chunk else resolve_chunk(L)
    return L > c and L % c == 0


def chunked_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                             chunk: int | None = None) -> jax.Array:
    """Causal attention with the masked half of the score matrix never
    computed — a portable (pure-XLA) counterpart to flash attention tuned
    for the opposite end of the memory/compute trade.

    The query axis is split into static chunks; chunk ``i`` attends only
    to keys ``[0, (i+1)*chunk)``, so the matmul and exp work is the causal
    ~L^2/2 rather than the full L^2 the naive path computes-then-masks.
    Unlike flash, the per-chunk softmax weights are left for XLA to save
    as backward residuals: the backward pass re-runs NO exp.  On v5e the
    lm_long config is exp/VPU-bound, where flash pays ~3x the exp count
    (forward + two backward recomputes) of this path's 1x — measured
    (docs/PERF.md): chunked beats both flash and the naive path at
    seq 4096 while using O(L^2/2) f32 residual memory, which fits at the
    batch sizes a 16 GB chip trains at this length anyway.  For long
    sequences at larger batch, flash remains the memory-bound choice.

    Only the diagonal sub-block gets a mask; the strict-past prefix is
    computed unmasked — no [L, L] predicate materialization.

    ``chunk=None`` resolves via :func:`resolve_chunk` (``DISTLEARN_TPU_
    CHUNK`` override, else ``max(128, L // 32)``): the measured v5e sweep
    at L=4096 improves monotonically down to 128 (5.6 -> 11.3 steps/s
    on the full train step across 2048/1024/512/256/128), while capping
    the chunk count at 32 keeps the unrolled per-block program bounded
    for very long sequences (the compile-size failure mode the scanned
    depth layout exists for).  Chunks must stay multiples of the
    128-lane tile — 384 measured catastrophically (6.1 steps/s).
    """
    B, L, H, D = q.shape
    if chunk is None:
        chunk = resolve_chunk(L)
    if not chunked_engages(L, chunk):
        return local_attention(q, k, v, causal=True, impl="xla")
    scale = 1.0 / (D ** 0.5)
    pos = jnp.arange(chunk)
    diag_mask = pos[:, None] >= pos[None, :]          # [chunk, chunk]
    outs = []
    for i in range(L // chunk):
        qs = q[:, i * chunk:(i + 1) * chunk]
        parts = []
        if i:  # strictly-past keys: fully visible, no mask at all
            s_pre = jnp.einsum("bqhd,bkhd->bhqk", qs, k[:, :i * chunk],
                               preferred_element_type=jnp.float32) * scale
            parts.append(s_pre)
        s_diag = jnp.einsum("bqhd,bkhd->bhqk", qs,
                            k[:, i * chunk:(i + 1) * chunk],
                            preferred_element_type=jnp.float32) * scale
        parts.append(jnp.where(diag_mask[None, None], s_diag, -jnp.inf))
        s = jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]
        w = jax.nn.softmax(s, axis=-1)                # f32, saved for bwd
        outs.append(jnp.einsum("bhqk,bkhd->bqhd", w.astype(q.dtype),
                               v[:, :(i + 1) * chunk],
                               preferred_element_type=jnp.float32))
    return jnp.concatenate(outs, axis=1).astype(q.dtype)


def _flash_enabled(override: bool | None) -> bool:
    """Opt-in Pallas flash-attention (TPU only).  Priority: explicit arg >
    ``DISTLEARN_TPU_FLASH`` env > off.  Off by default because at moderate
    lengths XLA's own fused attention is on par (measured on v5e: flash
    wins ~10-12% at L >= 4096 and removes the O(L^2) score buffer — turn
    it on for long-context configs)."""
    if override is not None:
        return bool(override)
    from distlearn_tpu.utils.flags import env_truthy
    return bool(env_truthy("DISTLEARN_TPU_FLASH"))


def local_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    flash: bool | None = None,
                    impl: str | None = None) -> jax.Array:
    """Single-device attention (same layout as the sharded variants), for
    non-sharded runs and as the per-shard kernel of
    :func:`alltoall_attention`.  q/k/v: [B, L, H, D].

    ``impl`` picks the kernel: ``"xla"`` (naive fused, full [B,H,L,L]
    scores), ``"flash"`` (Pallas blockwise online softmax, no score
    materialization), or ``"chunked"`` (:func:`chunked_causal_attention`
    — causal FLOP skip with saved softmax weights; falls back to xla for
    non-causal or short/ragged L).  Default resolution: the ``flash``
    arg (back-compat), then the ``DISTLEARN_TPU_ATTN`` env var, then
    ``DISTLEARN_TPU_FLASH``, then xla."""
    B, L, H, D = q.shape
    explicit_flash = flash is True or impl == "flash"
    if impl is None:
        if flash is not None:
            impl = "flash" if flash else "xla"
        else:
            import os
            impl = os.environ.get("DISTLEARN_TPU_ATTN") \
                or ("flash" if _flash_enabled(None) else "xla")
    if impl not in ("xla", "flash", "chunked"):
        raise ValueError(f"attention impl must be 'xla', 'flash', or "
                         f"'chunked', got {impl!r}")
    if impl == "chunked":
        chunk = resolve_chunk(L)
        if causal and chunked_engages(L, chunk):
            return chunked_causal_attention(q, k, v, chunk=chunk)
        impl = "xla"     # chunking only pays off via the causal FLOP skip
    if impl == "flash":
        # the Pallas kernel's default blocking needs L to be a multiple of
        # its 128-wide blocks
        supported = jax.default_backend() == "tpu" and L >= 128 and L % 128 == 0
        if supported:
            from jax.experimental.pallas.ops.tpu.flash_attention import \
                flash_attention
            out = flash_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=causal,
                sm_scale=1.0 / (D ** 0.5))
            return out.transpose(0, 2, 1, 3).astype(q.dtype)
        if explicit_flash:
            # explicitly requested (flash=True or impl="flash" argument) —
            # refusing loudly beats silently materializing the O(L^2)
            # buffer the caller asked to avoid; env-driven requests fall
            # back quietly so one flag can cover mixed configs
            raise ValueError(
                "flash attention needs the TPU backend and seq len a "
                f"multiple of 128; got backend={jax.default_backend()}, "
                f"L={L}. Drop the explicit flash request to use the "
                "portable path.")
        # env-enabled but unsupported here: portable fallback
    scale = 1.0 / (D ** 0.5)
    # native-dtype inputs + f32 ACCUMULATION: on bf16 configs the MXU runs
    # bf16 matmuls accumulating in f32 (upcasting the operands instead
    # would force f32 matmuls — 8x slower on the systolic array — and f32
    # score traffic; for f32 models this is identical math)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        pos = jnp.arange(L)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)   # stays f32 (stable softmax)
    out = jnp.einsum("bhqk,bkhd->bhqd", w.astype(q.dtype), v,
                     preferred_element_type=jnp.float32)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
