"""Multi-host bootstrap — pod-scale mesh construction over DCN.

The reference runs across machines by hand-wiring sockets: node 1 calls
``ipc.server``, every other host dials it, and each passes the resulting
server/client into ``ipc.Tree`` (examples/client_remote.lua:34-41,
examples/AsyncEASGD.sh ssh'd remote clients).  The TPU-native equivalent is
``jax.distributed.initialize``: every process dials one coordinator, after
which ``jax.devices()`` spans ALL hosts' chips and one SPMD program runs
over a global :class:`~distlearn_tpu.parallel.mesh.MeshTree` — collectives
ride ICI within a slice and DCN across slices, scheduled by XLA rather than
a hand-rolled socket tree.

Two deployment shapes, mirroring the reference's two:

* **Global-mesh SPMD** (this module): all hosts join one mesh; the fused
  train steps (distlearn_tpu.train) need no changes — the mesh just has
  more devices.  Per-host input shards become one global batch via
  :func:`host_local_batch`.
* **Process-per-node over the TCP tree** (examples/client_remote.py): each
  host trains independently and syncs through
  distlearn_tpu.parallel.host_algorithms — the reference's own topology,
  for clusters without a shared XLA runtime.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

PyTree = object


@dataclass(frozen=True)
class ProcessInfo:
    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int

    @property
    def is_root(self) -> bool:
        return self.process_id == 0


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_count: int | None = None) -> ProcessInfo:
    """Join (or create) the multi-process JAX runtime.

    Args mirror ``jax.distributed.initialize``; each falls back to the
    ``DISTLEARN_COORDINATOR`` / ``DISTLEARN_NUM_PROCESSES`` /
    ``DISTLEARN_PROCESS_ID`` env vars, and to JAX's own auto-detection
    (cloud TPU metadata) when ``None`` everywhere — so on a real TPU pod
    slice ``initialize()`` with no arguments does the right thing.

    ``local_device_count`` forces that many *virtual CPU devices* on this
    process — the single-machine stand-in for per-host chips (tests /
    examples; same trick as SURVEY.md §4's ipc.map analogue).  Call BEFORE
    any other jax device query.
    """
    coordinator = coordinator or os.environ.get("DISTLEARN_COORDINATOR")
    if num_processes is None and "DISTLEARN_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DISTLEARN_NUM_PROCESSES"])
    if process_id is None and "DISTLEARN_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DISTLEARN_PROCESS_ID"])

    import jax
    if local_device_count:
        from distlearn_tpu.utils.platform import force_cpu
        force_cpu(local_device_count)
    if local_device_count or \
            os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # Cross-process collectives on the CPU backend need gloo (the
        # single-machine / CI stand-in for ICI+DCN).  Checked via env, NOT
        # jax.default_backend(): querying the backend here would initialize
        # it before jax.distributed.initialize and break the pod path.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return ProcessInfo(process_id=jax.process_index(),
                       num_processes=jax.process_count(),
                       local_devices=jax.local_device_count(),
                       global_devices=jax.device_count())


def global_mesh_tree(axis_name: str = "data"):
    """A :class:`MeshTree` spanning every device of every joined process —
    the pod-scale ``tree`` handle.  num_nodes == global device count; the
    fused train steps work unchanged on it."""
    import jax

    from distlearn_tpu.parallel.mesh import MeshTree
    return MeshTree(devices=jax.devices(), axis_name=axis_name)


def host_local_batch(tree, array) -> object:
    """Assemble a GLOBAL batch from this process's host-local shard.

    Every process passes its local slice (leading axis = per-host batch);
    the result is one global jax.Array sharded over ``tree``'s axis with
    global leading size ``num_processes * per_host``.  This is the
    multi-host replacement for ``device_put(x, sharding)``, which only
    works when one process addresses all devices.
    """
    import jax

    return jax.make_array_from_process_local_data(tree.node_sharding, array)
