"""Parallelism algorithms and the device-mesh communication layer."""

from distlearn_tpu.parallel.mesh import MeshTree, all_reduce, broadcast_from, node_index
from distlearn_tpu.parallel.allreduce_sgd import AllReduceSGD
from distlearn_tpu.parallel.allreduce_ea import AllReduceEA
from distlearn_tpu.parallel.async_ea import (AsyncEAClient, AsyncEAServer,
                                             AsyncEAServerConcurrent,
                                             AsyncEATester, StaleCenterError,
                                             adaptive_tau_bounds)
from distlearn_tpu.parallel.ha import (StandbyCenter, install_signal_flush,
                                       promote, restore_center)
from distlearn_tpu.parallel.sequence import (ring_attention, local_attention,
                                             alltoall_attention)
from distlearn_tpu.parallel.pp import pipeline_apply
from distlearn_tpu.parallel.ep import moe_ffn, route_top1, route_topk
from distlearn_tpu.parallel.host_algorithms import (TreeAllReduceSGD,
                                                    TreeAllReduceEA)

__all__ = [
    "MeshTree",
    "all_reduce",
    "broadcast_from",
    "node_index",
    "AllReduceSGD",
    "AllReduceEA",
    "AsyncEAServer",
    "AsyncEAServerConcurrent",
    "AsyncEAClient",
    "AsyncEATester",
    "StaleCenterError",
    "adaptive_tau_bounds",
    "StandbyCenter",
    "install_signal_flush",
    "promote",
    "restore_center",
    "ring_attention",
    "local_attention",
    "alltoall_attention",
    "pipeline_apply",
    "moe_ffn",
    "route_top1",
    "route_topk",
    "TreeAllReduceSGD",
    "TreeAllReduceEA",
]
