"""Tensor-parallel region markers — the Megatron f/g pair as custom-VJP
collectives.

Inside ``shard_map`` without replication tracking (``check_vma=False``),
``lax.psum`` transposes to ``psum``, which double-counts when the cotangent
is already replicated across the TP axis.  The correct TP semantics are the
classic pair:

* :func:`tp_enter` ("f"): identity forward, **psum backward** — placed where
  a replicated activation enters the column-parallel region, so gradients of
  upstream replicated params get reduced over the TP axis.
* :func:`tp_reduce` ("g"): **psum forward**, identity backward — placed after
  the row-parallel matmul, so TP-sharded weight slices see exactly their own
  gradient (no tp-fold scaling).

With one f/g pair per TP block the residual stream stays replicated in
forward AND backward, so replicated-leaf gradients are identical on every TP
rank and sharded-leaf gradients are exact per slice.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_enter(x, axis_name: str):
    return x


def _enter_fwd(x, axis_name):
    return x, None


def _enter_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


tp_enter.defvjp(_enter_fwd, _enter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis_name: str):
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


tp_reduce.defvjp(_reduce_fwd, _reduce_bwd)
