"""Synchronous data-parallel gradient averaging (reference: lua/AllReduceSGD.lua).

The reference exposes three closures over a ``tree`` handle:

* ``sumGradients(grads)``            — allreduce-sum gradients (lua :10-15)
* ``sumAndNormalizeGradients(grads)``— same, then scale by ``1/n`` where ``n``
  is the number of nodes that contributed this step (lua :18-30; not all nodes
  contribute every step under uneven data partitioning)
* ``synchronizeParameters(params)``  — end-of-epoch sync: the node with the
  most steps wins and its params are broadcast to everyone (lua :33-54)

TPU-native design: per-node state is carried explicitly (functional), nodes are
mesh devices, and each operation is a pure function usable *inside* a
``shard_map``-ped step so XLA fuses the psum with the surrounding compute.
The reference's flush-allreduce dance (lua :37 — nodes that stopped stepping
contribute zeros to keep the socket tree alive) is unnecessary on a
gang-scheduled mesh; its *observable* semantics — contributor-count
normalization and winner-takes-all sync — are reproduced with a participation
mask (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distlearn_tpu.parallel import mesh as mesh_lib
from distlearn_tpu.parallel.mesh import DEFAULT_AXIS, MeshTree

PyTree = Any


class SGDSyncState(NamedTuple):
    """Per-node sync state (ref: ``stepsPerNode`` LongTensor, lua :7).

    ``my_steps`` is *this node's* step count this epoch — the reference only
    ever increments its own slot and allreduces the vector lazily at sync time
    (lua :13-14, :39), so a per-node scalar carries the same information.
    """
    my_steps: jax.Array  # i32 scalar (per-node, sharded)


def init_state() -> SGDSyncState:
    return SGDSyncState(my_steps=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# In-step pure functions (compose inside shard_map-ed train steps)
# ---------------------------------------------------------------------------

def sum_gradients(grads: PyTree, state: SGDSyncState,
                  contrib: jax.Array | None = None,
                  axis_name: str = DEFAULT_AXIS
                  ) -> tuple[PyTree, SGDSyncState, jax.Array]:
    """Allreduce-sum gradients across nodes (ref lua :10-15).

    Returns ``(summed_grads, new_state, n_contributors)``.  ``contrib`` is this
    node's participation flag (defaults to contributing).
    """
    c = jnp.ones((), jnp.int32) if contrib is None else jnp.asarray(contrib, jnp.int32)
    summed, n = mesh_lib.all_reduce(grads, axis_name, contrib=c)
    new_state = SGDSyncState(my_steps=state.my_steps + c)
    return summed, new_state, n


def sum_and_normalize_gradients(grads: PyTree, state: SGDSyncState,
                                contrib: jax.Array | None = None,
                                axis_name: str = DEFAULT_AXIS
                                ) -> tuple[PyTree, SGDSyncState, jax.Array]:
    """Allreduce-sum then scale by ``1/n`` contributors (ref lua :18-30)."""
    summed, new_state, n = sum_gradients(grads, state, contrib, axis_name)
    scale = jnp.where(n > 0, 1.0 / jnp.maximum(n, 1), 0.0)
    normed = jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype), summed)
    return normed, new_state, n


def synchronize_parameters(params: PyTree, state: SGDSyncState,
                           axis_name: str = DEFAULT_AXIS
                           ) -> tuple[PyTree, SGDSyncState]:
    """Winner-takes-all end-of-epoch sync (ref lua :33-54).

    Reference semantics: allreduce the per-node step counts, the node with the
    greatest count wins (ties → highest index, matching ``stepsPerNode:sort()``
    taking the last element, lua :41), every other node zeros its params, and
    one final allreduce leaves the winner's params on all nodes — bitwise
    identical, which is the reference's own test oracle
    (test/test_AllReduceSGD.lua:38).  Here: all_gather the counts, argmax with
    last-wins tie-break, masked psum.  The reference's separate
    ``steps == 0 → plain scatter from root`` branch (lua :52) is the
    degenerate case where every count is 0 and the winner is the last node;
    we keep the exact reference behavior by scattering from node 0 when no
    node stepped.
    """
    steps = lax.all_gather(state.my_steps, axis_name)  # [num_nodes]
    num_nodes = steps.shape[0]
    # Last-max tie-break: argmax of reversed vector.
    rev = steps[::-1]
    winner = num_nodes - 1 - jnp.argmax(rev)
    # No steps anywhere -> scatter from root (node 0), ref lua :52.
    winner = jnp.where(jnp.max(steps) > 0, winner, 0)
    me = lax.axis_index(axis_name)
    mask = (me == winner)
    synced = jax.tree_util.tree_map(
        lambda p: lax.psum(jnp.where(mask, p, jnp.zeros_like(p)), axis_name),
        params)
    return synced, SGDSyncState(my_steps=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# Host-level factory mirroring the reference closure API
# ---------------------------------------------------------------------------

class AllReduceSGD:
    """Factory over any :class:`~distlearn_tpu.comm.backend.
    CollectiveBackend`, mirroring ``AllReduceSGD(tree)`` (lua :4).

    ``tree`` may be a :class:`MeshTree`/``MeshBackend`` (whole-view:
    stacked node arrays, this handle sees every node), a ``HostBackend``
    (one node per process, plain pytrees), or a ``HybridBackend`` (this
    host's ``stacked_nodes``-row slice of the global node set).  The
    value convention follows the handle (module docstring of
    distlearn_tpu.comm.backend); ``contrib`` follows it too — a
    per-node vector for whole-view handles, a bool or per-local-row
    mask otherwise.  Training loops that care about throughput should
    instead compose the in-step functions above into one jitted train
    step — see :mod:`distlearn_tpu.train.trainer`.
    """

    def __init__(self, tree: MeshTree):
        self.tree = tree
        self._axis = getattr(tree, "axis_name", None)
        stacked = getattr(tree, "stacked_nodes", tree.num_nodes)
        self._local = 1 if stacked is None else int(stacked)
        self._offset = int(getattr(tree, "node_offset", 0))
        self._whole = self._local == tree.num_nodes
        # steps per node, host-tracked (ref keeps a LongTensor, lua :7);
        # partial-view handles fill only their own slots and allreduce the
        # vector at sync time — exactly the reference's lazy
        # ``stepsPerNode`` (lua :13-14,:39).
        self._steps = np.zeros(tree.num_nodes, dtype=np.int64)

    def sum_gradients(self, grads: PyTree, contrib=None) -> tuple[PyTree, int]:
        """Ref lua :10-15. ``grads`` follow the handle's value convention.
        Returns (summed, n)."""
        out, n = self.tree.all_reduce(grads, contrib=contrib)
        self._bump(contrib)
        return out, n

    def sum_and_normalize_gradients(self, grads: PyTree, contrib=None
                                    ) -> tuple[PyTree, int]:
        """Ref lua :18-30."""
        out, n = self.tree.all_reduce(grads, contrib=contrib)
        if n > 1:
            out = jax.tree_util.tree_map(lambda g: g / n, out)
        self._bump(contrib)
        return out, n

    def _bump(self, contrib):
        lo, hi = self._offset, self._offset + self._local
        if contrib is None or contrib is True:
            self._steps[lo:hi] += 1
        elif contrib is False:
            pass
        else:
            self._steps[lo:hi] += np.asarray(contrib, dtype=np.int64)

    def _global_steps(self) -> np.ndarray:
        """Every handle's view of the full per-node step vector.  Whole-view
        handles already hold it; partial-view handles allreduce a vector
        carrying only their own slots (slots are disjoint, so the sum IS
        the global vector — the reference's sync-time allreduce of
        ``stepsPerNode``, lua :39)."""
        if self._whole:
            return self._steps
        mine = np.zeros(self.tree.num_nodes, np.int64)
        lo, hi = self._offset, self._offset + self._local
        mine[lo:hi] = self._steps[lo:hi]
        if getattr(self.tree, "stacked_nodes", None) is None:
            red, _ = self.tree.all_reduce(mine)
            return np.asarray(red)
        stacked = np.zeros((self._local, self.tree.num_nodes), np.int64)
        for r in range(self._local):
            stacked[r, lo + r] = self._steps[lo + r]
        red, _ = self.tree.all_reduce(stacked)
        return np.asarray(self.tree.node_slice(red, 0))

    def synchronize_parameters(self, params: PyTree) -> PyTree:
        """Ref lua :33-54: winner-takes-all (most steps, ties → highest index),
        or plain scatter from root when no node stepped this epoch."""
        steps = self._global_steps()
        if steps.max() > 0:
            winner = int(len(steps) - 1 - np.argmax(steps[::-1]))
            synced = self.tree.scatter(params, src=winner)
        else:
            synced = self.tree.scatter(params, src=0)
        self._steps[:] = 0
        return synced
