"""Center HA: restore/promote helpers around the AsyncEA checkpoint layer.

The server side of failover (docs/HA.md).  ``AsyncEAServer`` writes
``ckpt_{step}.npz`` files whose tree is ``{"center": {"<i>": leaf}}`` plus
HA metadata (epoch, per-client applied-seq ledger, negotiated codecs);
this module turns a directory of those files back into a SERVING center:

* :func:`restore_center` — load the newest (or a specific) checkpoint into
  the leaf structure of a template pytree.
* :func:`promote` — restore + ``init_server`` + ``adopt_ha_meta`` on a
  standby server, bumping the center epoch so the dead primary is fenced.
* :class:`StandbyCenter` — the warm-standby loop: tail the checkpoint
  directory, optionally probe the primary, promote on demand.
* :func:`install_signal_flush` — SIGTERM hook for the final checkpoint
  flush before the process dies.

Clients need none of this: their half is ``AsyncEAClient.failover`` (walk
the dial list, rejoin, replay the pending delta).
"""

from __future__ import annotations

import os
import signal
import socket
import time
from typing import Any, Callable

from distlearn_tpu import obs
from distlearn_tpu.utils.checkpoint import latest_step, restore_checkpoint

from .async_ea import _leaves, _rebuild
from distlearn_tpu.utils.logging import print_server

PyTree = Any


def _template(like: PyTree) -> dict:
    """The npz-side tree shape ``_checkpoint_locked`` writes: center
    leaves keyed by flat index under "center"."""
    return {"center": {str(i): leaf
                       for i, leaf in enumerate(_leaves(like))}}


def restore_center(directory: str, like: PyTree,
                   step: int | None = None) -> tuple[PyTree, dict]:
    """Restore a center checkpoint into the structure of ``like``
    (shape/dtype validated leaf by leaf; ``step=None`` -> newest).
    Returns ``(center_pytree, metadata)`` — metadata carries the HA keys
    ``epoch`` / ``applied_seq`` / ``wire`` for :meth:`adopt_ha_meta`."""
    tree, meta = restore_checkpoint(directory, _template(like), step=step)
    got = [tree["center"][str(i)] for i in range(len(_leaves(like)))]
    return _rebuild(like, got), meta


def promote(srv, directory: str, like: PyTree,
            step: int | None = None) -> PyTree:
    """Promote ``srv`` (a standby ``AsyncEAServer``/``Concurrent``) to
    primary: restore the newest center checkpoint, seed the server with
    it, and adopt the HA metadata — which bumps the epoch past the dead
    primary's, so the fence refuses anything it might still serve.
    Returns the restored center pytree (the promoted trajectory's state,
    e.g. for a tester)."""
    with obs.span("async_ea.promote", directory=directory):
        center, meta = restore_center(directory, like, step=step)
        srv.init_server(center)
        srv.adopt_ha_meta(meta)
    obs.counter("async_ea_failover_promotions_total",
                "standby centers promoted to primary").inc()
    print_server(f"promoted from {directory} "
                 f"(step {meta.get('step')}, epoch {srv.epoch})")
    return center


def tcp_probe(host: str, port: int, timeout: float = 1.0) -> bool:
    """True when something is accepting on (host, port) — the minimal
    is-the-primary-alive probe for :meth:`StandbyCenter.watch`."""
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


class StandbyCenter:
    """The warm-standby loop around a server constructed with
    ``standby=True`` (listeners bound, no clients awaited): tail the
    checkpoint directory the primary writes, and :meth:`promote` when told
    — or when :meth:`watch`'s probe of the primary goes dark.

    The server is NOT serving until promotion; after :meth:`promote` the
    caller runs the normal serve loop (``sync_server`` / ``start``).
    """

    def __init__(self, server, directory: str, like: PyTree):
        self.server = server
        self.directory = directory
        self.like = like
        self.promoted = False

    def poll_step(self) -> int | None:
        """Newest checkpoint step visible right now (None: none yet)."""
        return latest_step(self.directory)

    def wait_for_checkpoint(self, timeout: float | None = None,
                            poll: float = 0.25) -> int:
        """Block until at least one checkpoint exists; returns its step.
        Raises ``TimeoutError`` after ``timeout`` seconds."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = self.poll_step()
            if step is not None:
                return step
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no checkpoint appeared in {self.directory} "
                    f"within {timeout}s")
            time.sleep(poll)

    def promote(self, step: int | None = None) -> PyTree:
        """Restore + take over as the next epoch (see :func:`promote`)."""
        center = promote(self.server, self.directory, self.like, step=step)
        self.promoted = True
        return center

    def watch(self, primary_probe: Callable[[], bool],
              poll: float = 0.5, ckpt_grace: float = 30.0) -> PyTree:
        """The standby main loop: re-probe the primary every ``poll``
        seconds and promote the moment it stops answering (two misses —
        one could be a restart blip).  Returns the restored center.

        The FIRST probe is deferred until a checkpoint exists: a tcp
        probe of the primary's protocol port during its startup accept
        would be counted toward the expected client dials; a visible
        checkpoint proves startup completed.  (Post-startup probes are
        safe — a server with nobody evicted leaves unknown dials in the
        listen backlog, and rejoin-window accepts carry a speak-by
        deadline.)  ``ckpt_grace`` bounds the wait for a final
        checkpoint racing in after the primary went dark."""
        self.wait_for_checkpoint()
        misses = 0
        while True:
            if primary_probe():
                misses = 0
            else:
                misses += 1
                if misses >= 2:
                    self.wait_for_checkpoint(timeout=ckpt_grace)
                    return self.promote()
            time.sleep(poll)


def install_signal_flush(srv, signums=(signal.SIGTERM,)) -> None:
    """Install a final-flush handler: on each of ``signums``, write one
    last checkpoint (blocking until durable) then deliver the signal's
    prior disposition.  A previously installed Python handler is chained;
    the default disposition is re-delivered via re-raise so exit codes
    stay honest.  Call from the main thread (signal module rule)."""
    for signum in signums:
        prev = signal.getsignal(signum)

        def _flush(num, frame, _prev=prev):
            try:
                srv.checkpoint_now(wait=True)
            except Exception as e:  # noqa: BLE001 — dying anyway
                print_server(f"final checkpoint flush failed: {e!r}")
            if callable(_prev):
                _prev(num, frame)
            elif _prev is not signal.SIG_IGN:
                signal.signal(num, signal.SIG_DFL)
                os.kill(os.getpid(), num)

        signal.signal(signum, _flush)
