"""Host-side AllReduceSGD / AllReduceEA over the TCP tree backend.

These are the literal reference semantics (lua/AllReduceSGD.lua,
lua/AllReduceEA.lua) for deployments where nodes are OS processes/hosts on
DCN rather than devices on an ICI mesh — the process-per-node shape of the
original framework (examples/mnist.sh spawning N ``th`` processes).
On-mesh training should use the fused builders in distlearn_tpu.train; these
adapters exist for (a) parity with the reference's multi-process mode,
(b) the multi-host control plane, and (c) running the reference's own
randomized invariant tests against the tree backend
(test/test_AllReduceSGD.lua, test/test_AllReduceEA.lua).

**Uneven-step protocol.**  Tree reductions are blocking and pair by ordinal:
node A's k-th allreduce completes against every other node's k-th allreduce.
Nodes run different step counts per epoch, so a node that finished early must
keep *serving* stragglers' rounds from inside its sync call — the reference
does this with torch-ipc's flush mode (``tree.allReduce(nil, add, zeroFn)``,
lua/AllReduceSGD.lua:37; inline EA callback, lua/AllReduceEA.lua:58-68).
Here every round carries a ``flush`` rider counting how many participants are
in their sync call; the sync loop serves rounds (zero-contribution for SGD,
real elastic contributions for EA — matching the reference's two flush
flavors) until a round reports all nodes flushing, which is the terminal
round.
"""

from __future__ import annotations

from typing import Any

import numpy as np

try:
    import jax.tree_util as _jtu
except Exception:  # pragma: no cover
    _jtu = None

from distlearn_tpu.comm.tree import Tree

PyTree = Any


class TreeAllReduceSGD:
    """Reference lua/AllReduceSGD.lua over a TCP tree (API: sumGradients /
    sumAndNormalizeGradients / synchronizeParameters, lua :56-60)."""

    def __init__(self, tree: Tree):
        self.tree = tree
        self.my_steps = 0   # own slot of stepsPerNode (ref lua :7)

    def _round(self, grads: PyTree, contrib: bool, flushing: bool
               ) -> tuple[PyTree, int, int]:
        """One ordinal-paired reduction round: gradient sum + contributor
        count + flush count (rider is summed across all ranks regardless of
        ``contrib``)."""
        summed, n, n_flush = self.tree.all_reduce_ex(
            grads, contrib=contrib, rider=1 if flushing else 0)
        return summed, n, n_flush

    def sum_gradients(self, grads: PyTree, contrib: bool = True
                      ) -> tuple[PyTree, int]:
        """Ref lua :10-15: allreduce-sum grads, bump own step count."""
        out, n, _ = self._round(grads, contrib, flushing=False)
        if contrib:
            self.my_steps += 1
        return out, n

    def sum_and_normalize_gradients(self, grads: PyTree, contrib: bool = True
                                    ) -> tuple[PyTree, int]:
        """Ref lua :18-30: sum then scale by 1/n contributors."""
        out, n = self.sum_gradients(grads, contrib)
        if n > 1:
            out = _jtu.tree_map(lambda g: g / np.asarray(n, g.dtype), out)
        return out, n

    def synchronize_parameters(self, params: PyTree) -> PyTree:
        """Ref lua :33-54.  Serve stragglers' gradient rounds with zero
        contributions (the ``zeroFn`` flush, lua :37) until every node is
        here; then allreduce the step counts, winner = max steps (ties →
        highest rank, matching ``stepsPerNode:sort()`` taking the last
        element, lua :41); non-winners zero their params; one final allreduce
        leaves the winner's params everywhere — bitwise (the reference's own
        oracle, test/test_AllReduceSGD.lua:38).  If NO node stepped: scatter
        from root (lua :52)."""
        zeros = _jtu.tree_map(np.zeros_like, params)
        while True:
            _, _, n_flush = self._round(zeros, contrib=False, flushing=True)
            if n_flush == self.tree.num_nodes:
                break
        steps_vec = np.zeros(self.tree.num_nodes, np.int64)
        steps_vec[self.tree.rank] = self.my_steps
        all_steps, _ = self.tree.all_reduce(steps_vec)
        if int(all_steps.max()) > 0:
            rev = all_steps[::-1]
            winner = len(all_steps) - 1 - int(np.argmax(rev))
            mine = params if self.tree.rank == winner else zeros
            synced, _ = self.tree.all_reduce(mine)
        else:
            synced = self.tree.scatter(params)
        self.my_steps = 0
        return synced


class TreeAllReduceEA:
    """Reference lua/AllReduceEA.lua over a TCP tree (API: averageParameters /
    synchronizeCenter / synchronizeParameters, lua :102-106)."""

    def __init__(self, tree: Tree, tau: int, alpha: float):
        self.tree = tree
        self.tau = int(tau)
        self.alpha = float(alpha)
        self.step = 0
        self.center: PyTree | None = None

    def _one_time_init(self, params: PyTree):
        """Ref lua :11-22: lazily clone params as the center replica."""
        if self.center is None:
            self.center = _jtu.tree_map(
                lambda p: np.array(p, dtype=np.asarray(p).dtype, copy=True),
                params)

    def _round(self, params: PyTree, flushing: bool) -> tuple[PyTree, int]:
        """One elastic round (ref lua :35-45): delta=(p-c)*alpha, p-=delta,
        allreduce deltas, center+=Σdelta.  Flush rounds contribute REAL
        deltas (the reference's inline callback, lua :58-68)."""
        delta = _jtu.tree_map(
            lambda p, c: (np.asarray(p) - c)
            * np.asarray(self.alpha, np.asarray(p).dtype),
            params, self.center)
        new_params = _jtu.tree_map(lambda p, d: np.asarray(p) - d,
                                   params, delta)
        summed, _, n_flush = self.tree.all_reduce_ex(
            delta, rider=1 if flushing else 0)
        self.center = _jtu.tree_map(lambda c, d: c + d, self.center, summed)
        return new_params, n_flush

    def average_parameters(self, params: PyTree) -> PyTree:
        """Ref lua :25-47: every tau-th local step runs one elastic round;
        other steps are communication-free (lua :31)."""
        self._one_time_init(params)
        self.step += 1
        if self.step % self.tau != 0:
            return params
        new_params, _ = self._round(params, flushing=False)
        return new_params

    def _drain(self, params: PyTree) -> PyTree:
        """Serve stragglers' rounds with real elastic contributions until all
        nodes are draining (ref handleUnevenSteps, lua :50-72)."""
        self._one_time_init(params)
        while True:
            params, n_flush = self._round(params, flushing=True)
            if n_flush == self.tree.num_nodes:
                return params

    def synchronize_center(self, params: PyTree) -> PyTree:
        """Ref lua :77-84: drain uneven rounds, then scatter the root's
        center (fp-drift repair), reset the step counter."""
        params = self._drain(params)
        self.center = self.tree.scatter(self.center)
        self.step = 0
        return params

    def synchronize_parameters(self, params: PyTree) -> PyTree:
        """Ref lua :87-100: drain, scatter params from root, center :=
        params."""
        if self.center is not None:
            params = self._drain(params)
        else:
            self._one_time_init(params)
        params = self.tree.scatter(params)
        self.center = _jtu.tree_map(
            lambda p: np.array(p, dtype=np.asarray(p).dtype, copy=True),
            params)
        self.step = 0
        return params
