"""The device-mesh communication layer: TPU-native replacement for torch-ipc's ``tree``.

The reference framework's entire communication backend is the external torch-ipc
C++ library: a base-b tree of TCP sockets with ``tree.allReduce`` /
``tree.scatter`` / ``tree.walkTable`` / ``tree.nodeIndex`` / ``tree.numNodes``
(reference call sites: lua/AllReduceSGD.lua:12-52, lua/AllReduceEA.lua:41-96,
examples/mnist.lua:16).  On TPU the idiomatic equivalent is *not* a socket tree:
"nodes" are devices in a :class:`jax.sharding.Mesh`, per-node values are arrays
with a leading node axis sharded over that mesh, and every collective lowers to
an XLA ICI collective (``lax.psum``) inside a jitted function.

Two API levels:

* **In-step collectives** (:func:`all_reduce`, :func:`broadcast_from`,
  :func:`node_index`): pure functions referencing a mesh axis name, for
  composing *inside* ``shard_map``-ped train steps — the hot path, where the
  collective fuses with the surrounding compute in one XLA program.

* **Host-level ops** (:class:`MeshTree`): mirrors the reference ``tree``
  surface (``all_reduce``, ``scatter``, ``walk``, ``node_index``,
  ``num_nodes``) operating on *stacked node arrays* — pytrees whose leaves have
  a leading ``num_nodes`` axis, sharded one-slice-per-device.  Each call is a
  jitted ``shard_map``.  This is the 1:1 translation surface for porting
  reference-style scripts; real training loops should prefer the fused
  builders in :mod:`distlearn_tpu.train`.

``walkTable`` needs no replacement: JAX pytrees + ``jax.tree_util.tree_map``
are the first-class equivalent; :meth:`MeshTree.walk` is provided for parity.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distlearn_tpu.utils.compat import shard_map
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any

DEFAULT_AXIS = "data"


# ---------------------------------------------------------------------------
# In-step collectives (use inside shard_map / pjit-ed step functions)
# ---------------------------------------------------------------------------

def node_index(axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """This node's 0-based index along the mesh axis (ref: ``tree.nodeIndex``,
    which is 1-based; here 0-based, matching JAX convention)."""
    return lax.axis_index(axis_name)


def all_reduce(tree: PyTree, axis_name: str = DEFAULT_AXIS,
               contrib: jax.Array | None = None) -> tuple[PyTree, jax.Array]:
    """Sum a pytree across the mesh axis; returns ``(reduced_tree, n)``.

    Mirrors ``tree.allReduce(value, add) -> _, n`` (lua/AllReduceSGD.lua:12):
    ``n`` is the number of *contributing* nodes.  The reference's tree lets
    non-stepping nodes keep the reduction alive by contributing zeros via a
    ``zeroFn``; on a gang-scheduled mesh every device always participates, so
    the same observable semantics are expressed with a participation mask:
    non-contributors' values are zeroed before the psum and ``n`` counts the
    mask (SURVEY.md §7 "hard parts").

    Args:
      tree: pytree of per-node arrays (local shard view, no node axis).
      axis_name: mesh axis to reduce over.
      contrib: optional boolean/0-1 scalar — whether *this* node contributes.
        ``None`` means all nodes contribute.
    """
    if contrib is None:
        n = jnp.asarray(lax.psum(1, axis_name))
        return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree), n
    c = jnp.asarray(contrib)
    n = lax.psum(c.astype(jnp.int32), axis_name)
    masked = jax.tree_util.tree_map(lambda x: x * c.astype(x.dtype), tree)
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), masked), n


def broadcast_from(tree: PyTree, src, axis_name: str = DEFAULT_AXIS) -> PyTree:
    """Broadcast ``src``'s values to every node along the axis.

    Replaces ``tree.scatter`` (root broadcast — lua/AllReduceSGD.lua:52,
    lua/AllReduceEA.lua:83,93): implemented as a psum of masked values, which
    XLA lowers to an ICI all-reduce (or all-gather+select) — deterministic and
    bitwise identical on every replica.
    """
    idx = lax.axis_index(axis_name)
    mask = (idx == src)

    def _sel(x):
        return lax.psum(jnp.where(mask, x, jnp.zeros_like(x)), axis_name)

    return jax.tree_util.tree_map(_sel, tree)


def all_gather_scalar(x: jax.Array, axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Gather a per-node scalar into a ``[num_nodes]`` vector on every node."""
    return lax.all_gather(x, axis_name)


def squeeze_node(tree: PyTree) -> PyTree:
    """Drop the local size-1 node axis inside a shard_map over stacked node
    arrays (each device sees its [1, ...] slice of the stack)."""
    return jax.tree_util.tree_map(lambda x: jnp.squeeze(x, 0), tree)


def expand_node(tree: PyTree) -> PyTree:
    """Re-add the local node axis before returning from a shard_map."""
    return jax.tree_util.tree_map(lambda x: x[None], tree)


# ---------------------------------------------------------------------------
# Host-level MeshTree
# ---------------------------------------------------------------------------

class MeshTree:
    """Host-side handle over a device mesh, mirroring the reference ``tree``.

    Per-node values are **stacked node arrays**: every leaf has a leading
    ``num_nodes`` axis, sharded one-row-per-device along ``axis_name``.  This
    is the TPU analogue of "each process holds its own tensor": one global
    jax.Array whose shards live device-side, collectives run over ICI.

    Construction mirrors ``ipc.LocalhostTree(nodeIndex, numNodes)``
    (examples/mnist.lua:16) — except a single SPMD program drives all nodes,
    so there is no per-process handshake; multi-host pods join via
    ``jax.distributed.initialize`` before constructing the mesh.
    """

    def __init__(self, num_nodes: int | None = None,
                 devices: Sequence[jax.Device] | None = None,
                 axis_name: str = DEFAULT_AXIS):
        if devices is None:
            devices = jax.devices()
        if num_nodes is not None:
            if num_nodes > len(devices):
                raise ValueError(
                    f"num_nodes={num_nodes} exceeds available devices ({len(devices)})")
            devices = devices[:num_nodes]
        self.axis_name = axis_name
        self.mesh = Mesh(np.asarray(devices), (axis_name,))
        self.num_nodes = len(devices)
        self._jit_cache: dict = {}

    # -- shardings ---------------------------------------------------------
    @property
    def node_sharding(self) -> NamedSharding:
        """Sharding for stacked node arrays: leading axis split over nodes."""
        return NamedSharding(self.mesh, P(self.axis_name))

    @property
    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def node_spec(self) -> P:
        return P(self.axis_name)

    # -- data movement -----------------------------------------------------
    def _put_global(self, x, sharding: NamedSharding):
        """Host value -> global jax.Array under ``sharding``.  Built with
        ``make_array_from_callback`` so it also works when the mesh spans
        multiple processes (jax.distributed) and this process addresses only
        some devices — ``device_put`` would reject that."""
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    def put_per_node(self, tree: PyTree) -> PyTree:
        """Place a stacked pytree (leading axis == num_nodes) onto the mesh."""
        def _put(x):
            x = np.asarray(x)
            if x.shape[0] != self.num_nodes:
                raise ValueError(
                    f"leading axis {x.shape[0]} != num_nodes {self.num_nodes}")
            return self._put_global(x, self.node_sharding)
        return jax.tree_util.tree_map(_put, tree)

    def replicate(self, tree: PyTree) -> PyTree:
        """Stack one value to all nodes: v -> [num_nodes, *v.shape], sharded."""
        def _rep(x):
            x = np.asarray(x)
            stacked = np.broadcast_to(x[None], (self.num_nodes,) + x.shape)
            return self._put_global(stacked, self.node_sharding)
        return jax.tree_util.tree_map(_rep, tree)

    # -- collectives on stacked node arrays --------------------------------
    def _shard_fn(self, key: str, fn: Callable, n_node_args: int,
                  out_replicated: bool = False):
        """jit(shard_map(fn)) with per-node in-specs; cached by key."""
        cache_key = (key, n_node_args, out_replicated)
        if cache_key not in self._jit_cache:
            in_specs = tuple(P(self.axis_name) for _ in range(n_node_args))
            out_specs = P() if out_replicated else P(self.axis_name)
            mapped = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=False)
            self._jit_cache[cache_key] = jax.jit(mapped)
        return self._jit_cache[cache_key]

    def all_reduce_program(self, masked: bool = False):
        """The cached jitted shard_map behind :meth:`all_reduce` — exposed
        so distlint's ``sync`` family can lower and budget the collective
        program itself without executing it.  ``masked=True`` returns the
        contrib-vector variant (``(tree, contrib[num_nodes]) -> (tree,
        n[num_nodes])``)."""
        axis = self.axis_name
        if not masked:
            def _ar(t):
                red, _ = all_reduce(squeeze_node(t), axis)
                return expand_node(red)
            return self._shard_fn("all_reduce", _ar, 1)

        def _arm(t, c):
            c = jnp.squeeze(c, 0)
            red, n = all_reduce(squeeze_node(t), axis, contrib=c)
            return expand_node(red), n[None]
        return self._shard_fn("all_reduce_masked", _arm, 2)

    def all_reduce(self, tree: PyTree, contrib: jax.Array | None = None
                   ) -> tuple[PyTree, int]:
        """Sum per-node values; every node's row ends up holding the sum.

        Mirrors ``tree.allReduce(value, function(a,b) return a:add(b) end)``
        (lua/AllReduceSGD.lua:12,20): returns ``(reduced, n_contributors)``;
        the reduced stacked array has identical rows (each node's buffer now
        holds the reduction, like the in-place torch semantics).
        """
        if contrib is None:
            out = self.all_reduce_program(False)(tree)
            return out, self.num_nodes
        contrib = jnp.asarray(contrib)
        out, n = self.all_reduce_program(True)(tree, contrib)
        return out, int(n[0])

    def scatter(self, tree: PyTree, src: int = 0) -> PyTree:
        """Broadcast node ``src``'s row to every node (ref: ``tree.scatter``)."""
        if not 0 <= src < self.num_nodes:
            raise ValueError(f"src={src} out of range for {self.num_nodes} nodes")
        axis = self.axis_name

        def _sc(t):
            out = broadcast_from(squeeze_node(t), src, axis)
            return expand_node(out)
        return self._shard_fn(f"scatter_{src}", _sc, 1)(tree)

    def spmd(self, fn: Callable, in_specs, out_specs, static_argnums=()):
        """shard_map + jit a step function over this mesh (the hot path)."""
        mapped = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
        return jax.jit(mapped, static_argnums=static_argnums)

    # -- parity helpers ----------------------------------------------------
    @staticmethod
    def walk(tree: PyTree, fn: Callable) -> PyTree:
        """``tree.walkTable`` parity: map ``fn`` over every leaf."""
        return jax.tree_util.tree_map(fn, tree)

    def node_slice(self, tree: PyTree, i: int) -> PyTree:
        """Pull node ``i``'s row back to host (for tests / debugging)."""
        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x[i])), tree)
