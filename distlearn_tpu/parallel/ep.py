"""Expert parallelism: a routed mixture-of-experts layer over a mesh axis.

Absent from the reference (SURVEY.md §2c lists EP as explicitly out of its
scope), provided as the last of the framework's first-class mesh
dimensions (data / sequence / tensor / pipeline / expert).  The design is
the GShard/Switch pattern expressed TPU-natively:

* **Routing** (per device, local tokens): a linear router picks each
  token's top-1 expert; tokens beyond an expert's capacity are dropped
  (their combine weight is zero — output falls back to the residual
  stream, the standard Switch behavior).
* **Dispatch/combine as einsums**: boolean dispatch mask ``[N, E, C]`` and
  float combine weights ``[N, E, C]`` turn gather/scatter into two MXU
  einsums — no dynamic shapes, no sorting, XLA-friendly.
* **All-to-all over the expert axis**: each device owns ONE expert; the
  dispatched buckets ``[E, C, D]`` are exchanged so device ``e`` receives
  every peer's bucket for expert ``e``, applies its expert FFN to
  ``E*C`` tokens in one batched matmul, and the reverse all-to-all routes
  results home.  Both hops ride ICI.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def route_topk(router_logits: jax.Array, capacity: int, k: int = 1
               ) -> tuple[jax.Array, jax.Array, dict]:
    """Top-k routing with capacity (k=1: Switch; k=2: GShard).

    Args:
      router_logits: ``[N, E]`` raw router scores for local tokens.
      capacity: per-expert bucket size ``C``.
      k: experts per token.  Combine weights are the chosen gates
        renormalized over the k picks (GShard); with k=1 this is the raw
        top-1 gate (Switch).  Bucket slots are claimed in rank order —
        every token's 1st choice before any token's 2nd — so congestion
        drops low-rank assignments first.

    Returns ``(dispatch, combine, aux)``: dispatch ``[N, E, C]`` bool —
    token n occupies slot c of expert e; combine ``[N, E, C]`` float32 —
    gate weight at the same coordinates (zero for dropped assignments);
    aux — routing health terms:

    * ``balance_loss``: the Switch load-balancing loss ``E · Σ_e f_e·P_e``
      (arXiv:2101.03961 eq. 4-6): ``f_e`` = fraction of tokens whose TOP
      choice is expert e, ``P_e`` = mean router probability on e.  Equals
      1.0 at perfect balance; grows as the router collapses.  Both factors
      see the pre-capacity assignment, so the gradient pushes the router
      itself toward balance (differentiable through ``P_e``).
    * ``dropped_frac``: fraction of the ``N*k`` assignments dropped by
      capacity (combine weight zero — tokens fall back to the residual).
    """
    N, E = router_logits.shape
    if not 1 <= k <= E:
        raise ValueError(f"top-k routing needs 1 <= k <= num_experts, "
                         f"got k={k} with {E} experts")
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(gates, k)                        # [N, k]
    if k == 1:
        weights = topv          # Switch: the RAW top-1 gate scales the
        # output, so router gradients flow through the kept path
    else:
        # GShard: renormalize the chosen gates over the k picks
        weights = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    dispatch3 = jnp.zeros((N, E, capacity), jnp.bool_)
    combine = jnp.zeros((N, E, capacity), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)     # slots claimed by higher ranks
    for j in range(k):
        onehot = jax.nn.one_hot(topi[:, j], E, dtype=jnp.int32)  # [N, E]
        # position within the expert bucket, after rank<j claims.  If a
        # higher rank overflowed the bucket, counts pushes pos past
        # capacity — full buckets drop lower ranks either way.
        pos = (jnp.cumsum(onehot, axis=0) + counts[None, :]) * onehot - 1
        disp = (onehot > 0) & (pos < capacity)              # [N, E] kept?
        slot = jax.nn.one_hot(jnp.where(disp, pos, -1), capacity,
                              dtype=jnp.bool_)              # [N, E, C]
        d3 = slot & disp[..., None]
        dispatch3 = dispatch3 | d3
        combine = combine + d3.astype(jnp.float32) \
            * weights[:, j][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
        if j == 0:
            frac_tokens = jnp.mean(onehot.astype(jnp.float32), axis=0)
    frac_probs = jnp.mean(gates, axis=0)                    # P_e
    aux = {
        "balance_loss": E * jnp.sum(frac_tokens * frac_probs),
        "dropped_frac": 1.0 - jnp.sum(dispatch3.astype(jnp.float32))
        / (N * k),
    }
    return dispatch3, combine, aux


def route_top1(router_logits: jax.Array, capacity: int
               ) -> tuple[jax.Array, jax.Array]:
    """Top-1 routing with capacity (``route_topk`` with k=1, aux dropped).

    Returns ``(dispatch, combine)``: dispatch ``[N, E, C]`` bool — token n
    goes to slot c of expert e; combine ``[N, E, C]`` float32 — softmax
    gate weight at the same coordinates (zero for dropped tokens).
    """
    dispatch, combine, _ = route_topk(router_logits, capacity, k=1)
    return dispatch, combine


def _route_and_bucket(router_w: jax.Array, x: jax.Array,
                      capacity_factor: float, E: int, top_k: int = 1):
    """Shared routing prologue: capacity, top-k dispatch/combine masks, the
    per-expert token buckets, and the routing-health aux terms.  ONE
    implementation so the local oracle and the distributed path cannot
    silently diverge."""
    N, _ = x.shape
    capacity = max(1, int(-(-N * capacity_factor * top_k // E)))
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)   # [N, E]
    dispatch, combine, aux = route_topk(logits, capacity, top_k)
    buckets = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    return combine, buckets, capacity, aux


def _combine(combine_w: jax.Array, expert_out: jax.Array) -> jax.Array:
    return jnp.einsum("nec,ecd->nd", combine_w.astype(expert_out.dtype),
                      expert_out)


def moe_ffn_local(expert_fn: Callable, stacked_params: PyTree,
                  router_w: jax.Array, x: jax.Array,
                  capacity_factor: float = 1.25, top_k: int = 1,
                  return_aux: bool = False):
    """Single-device mixture-of-experts (all experts resident): the same
    routing/dispatch/combine math as :func:`moe_ffn` with the all-to-all
    hops removed and the experts applied under ``vmap``.  This is both the
    no-expert-axis fallback for MoE models and the reference oracle the
    distributed path is tested against.

    ``stacked_params``: pytree whose leaves carry a leading expert axis
    ``[E, ...]``; ``expert_fn(params_e, tokens)`` applies ONE expert.
    ``return_aux=True`` additionally returns the :func:`route_topk` aux
    dict (balance loss + dropped fraction).
    """
    E = router_w.shape[1]
    combine, buckets, _, aux = _route_and_bucket(router_w, x,
                                                 capacity_factor, E, top_k)
    out = jax.vmap(expert_fn)(stacked_params, buckets)      # [E, C, D]
    y = _combine(combine, out)
    return (y, aux) if return_aux else y


def moe_ffn(expert_fn: Callable, expert_params: PyTree, router_w: jax.Array,
            x: jax.Array, capacity_factor: float = 1.25,
            axis_name: str = "expert", top_k: int = 1,
            return_aux: bool = False):
    """Expert-parallel mixture-of-experts FFN (one expert per device).

    Args:
      expert_fn: ``(params, tokens) -> tokens`` — THIS device's expert,
        applied to a ``[E*C, D]`` batch of dispatched tokens.
      expert_params: this device's expert parameters (caller shards a
        stacked ``[E, ...]`` pytree over ``axis_name`` and squeezes).
      router_w: ``[D, E]`` router weights (replicated — every device must
        route identically).
      x: local tokens ``[N, D]`` (flatten batch/sequence first).
      capacity_factor: bucket size ``C = ceil(N * top_k / E * factor)``.
      top_k: experts per token (1 = Switch, 2 = GShard).
      return_aux: also return the :func:`route_topk` aux dict (balance
        loss + dropped fraction) for this device's local tokens.

    Returns ``[N, D]``: gate-weighted expert outputs; capacity-dropped
    tokens contribute zeros (add the residual stream outside).
    """
    E = lax.psum(1, axis_name)
    N, D = x.shape
    if router_w.shape != (D, E):
        raise ValueError(
            f"router_w must be [{D}, {E}] (token dim x expert-axis size, "
            f"one expert per device), got {router_w.shape}")
    combine, buckets, capacity, aux = _route_and_bucket(
        router_w, x, capacity_factor, E, top_k)
    # all-to-all: device e receives every peer's bucket for expert e,
    # stacked along a peer axis -> [E_peers, C, D] -> one batched FFN call
    recv = lax.all_to_all(buckets, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                       # [E*C, ...] rows
    out = expert_fn(expert_params, recv.reshape(E * capacity, D))
    out = out.reshape(E, capacity, D)
    # reverse hop: peers get their tokens back at the same coordinates
    home = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                       # [E, C, D]
    y = _combine(combine, home)
    return (y, aux) if return_aux else y
