"""Expert parallelism: a routed mixture-of-experts layer over a mesh axis.

Absent from the reference (SURVEY.md §2c lists EP as explicitly out of its
scope), provided as the last of the framework's first-class mesh
dimensions (data / sequence / tensor / pipeline / expert).  The design is
the GShard/Switch pattern expressed TPU-natively:

* **Routing** (per device, local tokens): a linear router picks each
  token's top-1 expert; tokens beyond an expert's capacity are dropped
  (their combine weight is zero — output falls back to the residual
  stream, the standard Switch behavior).
* **Dispatch/combine as einsums**: boolean dispatch mask ``[N, E, C]`` and
  float combine weights ``[N, E, C]`` turn gather/scatter into two MXU
  einsums — no dynamic shapes, no sorting, XLA-friendly.
* **All-to-all over the expert axis**: each device owns ONE expert; the
  dispatched buckets ``[E, C, D]`` are exchanged so device ``e`` receives
  every peer's bucket for expert ``e``, applies its expert FFN to
  ``E*C`` tokens in one batched matmul, and the reverse all-to-all routes
  results home.  Both hops ride ICI.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def route_top1(router_logits: jax.Array, capacity: int
               ) -> tuple[jax.Array, jax.Array]:
    """Top-1 routing with capacity.

    Args:
      router_logits: ``[N, E]`` raw router scores for local tokens.
      capacity: per-expert bucket size ``C``.

    Returns ``(dispatch, combine)``: dispatch ``[N, E, C]`` bool — token n
    goes to slot c of expert e; combine ``[N, E, C]`` float32 — softmax
    gate weight at the same coordinates (zero for dropped tokens).
    """
    N, E = router_logits.shape
    gates = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gates, axis=-1)                     # [N]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)     # [N, E]
    # position of each token within its expert's bucket (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1           # [N, E]
    dispatch = (onehot > 0) & (pos < capacity)              # [N, E] kept?
    slot = jax.nn.one_hot(jnp.where(dispatch, pos, -1), capacity,
                          dtype=jnp.bool_)                  # [N, E, C]
    dispatch3 = slot & dispatch[..., None]
    gate = jnp.max(gates * onehot, axis=-1)                 # [N] top-1 weight
    combine = dispatch3.astype(jnp.float32) * gate[:, None, None]
    return dispatch3, combine


def _route_and_bucket(router_w: jax.Array, x: jax.Array,
                      capacity_factor: float, E: int):
    """Shared routing prologue: capacity, top-1 dispatch/combine masks, and
    the per-expert token buckets.  ONE implementation so the local oracle
    and the distributed path cannot silently diverge."""
    N, _ = x.shape
    capacity = max(1, int(-(-N * capacity_factor // E)))
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)   # [N, E]
    dispatch, combine = route_top1(logits, capacity)
    buckets = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    return combine, buckets, capacity


def _combine(combine_w: jax.Array, expert_out: jax.Array) -> jax.Array:
    return jnp.einsum("nec,ecd->nd", combine_w.astype(expert_out.dtype),
                      expert_out)


def moe_ffn_local(expert_fn: Callable, stacked_params: PyTree,
                  router_w: jax.Array, x: jax.Array,
                  capacity_factor: float = 1.25) -> jax.Array:
    """Single-device mixture-of-experts (all experts resident): the same
    routing/dispatch/combine math as :func:`moe_ffn` with the all-to-all
    hops removed and the experts applied under ``vmap``.  This is both the
    no-expert-axis fallback for MoE models and the reference oracle the
    distributed path is tested against.

    ``stacked_params``: pytree whose leaves carry a leading expert axis
    ``[E, ...]``; ``expert_fn(params_e, tokens)`` applies ONE expert.
    """
    E = router_w.shape[1]
    combine, buckets, _ = _route_and_bucket(router_w, x, capacity_factor, E)
    out = jax.vmap(expert_fn)(stacked_params, buckets)      # [E, C, D]
    return _combine(combine, out)


def moe_ffn(expert_fn: Callable, expert_params: PyTree, router_w: jax.Array,
            x: jax.Array, capacity_factor: float = 1.25,
            axis_name: str = "expert") -> jax.Array:
    """Expert-parallel mixture-of-experts FFN (one expert per device).

    Args:
      expert_fn: ``(params, tokens) -> tokens`` — THIS device's expert,
        applied to a ``[E*C, D]`` batch of dispatched tokens.
      expert_params: this device's expert parameters (caller shards a
        stacked ``[E, ...]`` pytree over ``axis_name`` and squeezes).
      router_w: ``[D, E]`` router weights (replicated — every device must
        route identically).
      x: local tokens ``[N, D]`` (flatten batch/sequence first).
      capacity_factor: bucket size ``C = ceil(N / E * factor)``.

    Returns ``[N, D]``: gate-weighted expert outputs; capacity-dropped
    tokens contribute zeros (add the residual stream outside).
    """
    E = lax.psum(1, axis_name)
    N, D = x.shape
    if router_w.shape != (D, E):
        raise ValueError(
            f"router_w must be [{D}, {E}] (token dim x expert-axis size, "
            f"one expert per device), got {router_w.shape}")
    combine, buckets, capacity = _route_and_bucket(router_w, x,
                                                   capacity_factor, E)
    # all-to-all: device e receives every peer's bucket for expert e,
    # stacked along a peer axis -> [E_peers, C, D] -> one batched FFN call
    recv = lax.all_to_all(buckets, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                       # [E*C, ...] rows
    out = expert_fn(expert_params, recv.reshape(E * capacity, D))
    out = out.reshape(E, capacity, D)
    # reverse hop: peers get their tokens back at the same coordinates
    home = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                       # [E, C, D]
    return _combine(combine, home)
