"""Asynchronous EASGD over a hub-and-spoke parameter server — the TPU-native
rebuild of lua/AsyncEA.lua.

Three roles (reference export surface lua/AsyncEA.lua:294-303):

* **server** — holds the authoritative center variable pinned host-side, does
  no training; admits ONE client at a time through the ``Enter?``/``Enter``
  critical section (lua :163-177), streams the center, receives the elastic
  delta, applies ``center += delta`` (lua :198-228).
* **client** — trains locally; every ``tau``-th step runs the sync handshake:
  ``Enter?`` → fetch center → local elastic move ``delta=(p-c)*alpha;
  p-=delta`` (lua :109-119) → push delta.
* **tester** — a dedicated evaluation process the server pushes the center to
  every ``testTime`` syncs (lua :239-292).

Socket topology (examples/EASGD_server.lua:67-77): broadcast channel on
``port`` (all clients), one dedicated per-client channel on ``port + i``,
test channel on ``port + numNodes + 1``.

TPU-native stance: genuinely asynchronous point-to-point against a live
center does not fit the SPMD/XLA model, so this is the one subsystem built on
the host-side transport (C++ framing hot path, distlearn_tpu.comm) rather
than ICI collectives — exactly mirroring where the reference was native
(SURVEY.md §7 "hard parts").  Device↔host staging happens only at the
``tau``-spaced sync points, so the hot local-step loop stays on-device.

Params cross this API as pytrees; leaves are converted with ``np.asarray`` /
left as numpy — callers using jax arrays get numpy back and re-place onto
device (see examples/easgd_client.py).
"""

from __future__ import annotations

import select
import time
from typing import Any

import jax
import numpy as np

from distlearn_tpu import obs
from distlearn_tpu.comm import Conn, ProtocolError, Server, connect, wire
from distlearn_tpu.obs import trace as obs_trace
from distlearn_tpu.ops import wire_kernels
from distlearn_tpu.utils.logging import print_client, print_server, print_tester

PyTree = Any

ENTER_Q = "Enter?"
ENTER = "Enter"
REJOIN_Q = "Rejoin?"
REJOIN = "Rejoin"
CENTER_Q = "Center?"
DELTA_Q = "delta?"
DELTA = "delta"
TEST_Q = "Test?"
ACK = "Ack"
SHARD_Q = "Shard?"
REPLAY_Q = "Replay"
JOIN_Q = "Join?"
JOIN = "Join"
LEAVE_Q = "Leave?"
LEAVE = "Leave"

#: Shard-negotiation schema version (the "shard" key in Enter?/Rejoin?).
SHARD_V = 1

#: applied-seq sentinel meaning "assume everything was applied" — adopted
#: when a restored checkpoint's per-stripe seq table cannot be matched to
#: the current stripe plan (replay degrades to at-most-once, never twice).
_SEQ_INF = 2 ** 62

#: α·τ stability product ceiling the straggler-adaptive τ respects
#: (docs/EA_CONVERGENCE.md: the measured guidance is α = 0.9/τ, i.e. the
#: elastic fixed point destabilizes as α·τ walks past ~1).
ALPHA_TAU_PRODUCT = 0.9


def adaptive_tau_bounds(tau: int, alpha: float) -> tuple[int, int]:
    """``[lo, hi]`` bounds for the straggler-adaptive sync period: never
    below the configured τ (a straggler syncs LESS often, not more) and
    never past ``ALPHA_TAU_PRODUCT / α`` — stretching τ without shrinking
    α walks the α·τ stability product toward divergence, so the stretch
    is capped where the product the fleet was tuned for still holds."""
    lo = max(1, int(tau))
    hi = max(lo, int(ALPHA_TAU_PRODUCT / alpha)) if alpha > 0 else lo
    return lo, hi


class StaleCenterError(ProtocolError):
    """A center answered an admission request with an OLDER epoch than the
    client has already synced against — the zombie-primary fence
    (docs/HA.md).  A pre-failover primary coming back from a stall must
    never serve (or take deltas from) a client that moved on to the
    promoted standby; the client drops the refusing address from its
    failover dial list and re-dials."""

# ---------------------------------------------------------------------------
# Wire negotiation (packed 'P' frames + codecs, comm/wire.py).
#
# A new client advertises {"wire": {"v": 1, "codec": ...}} inside its
# Enter?/Rejoin? request; extra keys are invisible to an old server (it only
# reads "q"/"clientID" and replies the plain "Enter" string), so the client
# detects a legacy peer from the STRING reply and falls back to per-leaf
# 'T' frames.  A new server replies {"a": "Enter", "wire": {...}} — a dict
# — ONLY to clients that advertised, so old clients keep getting the plain
# string they expect.  Both directions of a negotiated handshake (center
# down, delta up) then use ONE packed frame with the agreed codec.  An
# unsupported codec is answered with a wire error and an eviction — mixed
# fleets fail loudly (ProtocolError at the client) instead of silently
# corrupting tensors.


def _parse_wire_request(msg) -> tuple[str | None, str | None]:
    """(codec, error) from an admission-family message's "wire" key.
    ``(None, None)`` = legacy peer; ``(codec, None)`` = negotiated;
    ``(codec, error)`` = advertised but unusable (answer loudly)."""
    spec = msg.get("wire") if isinstance(msg, dict) else None
    if spec is None:
        return None, None
    if not isinstance(spec, dict):
        return None, f"malformed wire spec {spec!r}"
    codec = spec.get("codec")
    if codec not in wire.CODECS:
        return codec, (f"unsupported wire codec {codec!r} "
                       f"(supported: {', '.join(wire.CODECS)})")
    return codec, None


def _check_wire_reply(reply, want: str, codec: str) -> bool:
    """Client-side half of the negotiation: True when the server agreed to
    the packed wire, False when it answered with the legacy plain string
    (fall back to per-leaf frames), ProtocolError on desync or rejection."""
    if reply == want:
        return False                      # legacy server: per-leaf 'T' wire
    if isinstance(reply, dict) and reply.get("a") == want:
        w = reply.get("wire")
        if isinstance(w, dict) and w.get("error"):
            raise ProtocolError(
                f"server rejected wire codec {codec!r}: {w['error']}")
        if not isinstance(w, dict) or w.get("codec") != codec:
            raise ProtocolError(
                f"wire negotiation desync: requested codec {codec!r}, "
                f"server answered {w!r}")
        return True
    raise ProtocolError(f"protocol desync: expected {want!r}, got {reply!r}")


# ---------------------------------------------------------------------------
# Sharded center (Dean et al. 2012 applied to the EASGD hub).
#
# The server may stripe its leaf list into S contiguous byte-balanced
# ranges (wire.plan_stripes).  Stripe 0 always rides the existing
# dedicated channel — an unsharded sync IS the one-stripe special case —
# and stripes 1..S-1 get their own listener ports and per-stripe locks,
# so different clients' syncs on different stripes proceed concurrently
# and one client's stripes pipeline (stripe i's apply/reply overlaps
# stripe i+1's recv).  Negotiation piggybacks the wire handshake: a
# client adds {"shard": {"v": 1}} to its Enter?/Rejoin? advertisement
# (packed wire only), and the server's dict reply carries the explicit
# stripe plan {"shard": {"v", "n", "ports", "stripes"}} — old peers on
# either side never see the extra key and keep the S=1 legacy behavior.
# The client then dials each shard port once, introduces itself with a
# {"q": "Shard?", "clientID", "shard"} hello, and reuses those
# connections for every subsequent sync (rejoin re-dials them).


def _fanout(fns):
    """Run thunks concurrently — leg 0 on the calling thread, the rest on
    transient threads — and re-raise the first failure only after EVERY
    leg has settled, so a caller's eviction/cleanup never races a
    still-running leg."""
    if len(fns) == 1:
        fns[0]()
        return
    import threading
    errs: list = [None] * len(fns)

    def run(i):
        try:
            fns[i]()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs[i] = e

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(1, len(fns))]
    for t in threads:
        t.start()
    run(0)
    for t in threads:
        t.join()
    for e in errs:
        if e is not None:
            raise e


class _ShardEndpoint:
    """One shard channel: a listener on its own port plus the per-client
    conns registered by ``Shard?`` hellos.  Clients dial lazily after the
    Enter reply advertises the stripe plan; a registered conn persists
    across syncs and a re-hello for the same cid (rejoin) supersedes it.
    """

    def __init__(self, host: str, port: int, shard: int, num_nodes: int,
                 throttle_bps: float | None = None, is_member=None):
        import threading
        self.shard = shard
        self.num_nodes = num_nodes
        self.throttle_bps = throttle_bps
        # membership predicate for hello validation: elastic servers pass
        # their live roster (joined cids run past num_nodes); the default
        # keeps the historical fixed-fleet range check
        self._is_member = is_member or (lambda c: 1 <= c <= num_nodes)
        self.server = Server(host, port)
        # Several stripe workers poll this listener concurrently;
        # Server.accept's settimeout dance is not thread-safe (one
        # thread's finally-reset flips a racing thread's in-flight accept
        # to fully blocking).  A non-blocking listener makes the race
        # benign: the losing accept gets BlockingIOError and moves on.
        self.server.sock.setblocking(False)
        self.port = self.server.port
        self.conns: dict[int, Conn] = {}
        self._reg_lock = threading.Lock()   # guards the conns dict only

    def _poll_accept(self, wait: float) -> bool:
        """Accept at most one pending dial and register it by its hello.
        Runs lock-free (multiple stripe workers may poll concurrently;
        each services a different accepted socket) — only the dict
        update takes the registration lock.  Returns True when the
        listener had a dial pending (even if another worker won it or
        the hello was bad), so callers can drain the backlog."""
        r, _, _ = select.select([self.server.sock], [], [], wait)
        if not r:
            return False
        try:
            raw, _ = self.server.sock.accept()
        except (BlockingIOError, OSError):
            return True             # another stripe worker won this dial
        raw.setblocking(True)       # BSD inherits O_NONBLOCK from listener
        c = Conn(raw)
        try:
            c.set_timeout(2.0)
            hello = c.recv_msg()
            c.set_timeout(None)
            cid = int(hello.get("clientID", -1)) \
                if isinstance(hello, dict) else -1
            if (not isinstance(hello, dict) or hello.get("q") != SHARD_Q
                    or hello.get("shard") != self.shard
                    or cid < 1 or not self._is_member(cid)):
                raise ProtocolError(f"bad shard hello {hello!r}")
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError):
            c.close()
            return True
        if self.throttle_bps:
            c.throttle_bps = self.throttle_bps
        with self._reg_lock:
            old = self.conns.get(cid)
            self.conns[cid] = c
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        return True

    def get_conn(self, cid: int, timeout: float) -> Conn:
        """The cid's registered shard conn, accepting pending dials until
        it shows up or the timeout passes (the client dials every shard
        channel right after its first sharded Enter reply, so the dial
        is normally already in the listen backlog)."""
        deadline = time.monotonic() + timeout
        while True:
            # drain EVERY pending dial before trusting the registry: a
            # rejoin's fresh socket may be queued behind the previous
            # admission's dead one (TCP backlog is FIFO), and returning
            # the stale registration would serve — and then evict on —
            # a conn the client already replaced.
            while self._poll_accept(0.0):
                pass
            with self._reg_lock:
                c = self.conns.get(cid)
            if c is not None and c.sock.fileno() >= 0:
                return c
            wait = deadline - time.monotonic()
            if wait <= 0:
                raise TimeoutError(
                    f"client #{cid} never dialed shard {self.shard}")
            self._poll_accept(min(wait, 0.1))

    def drop(self, cid: int):
        with self._reg_lock:
            c = self.conns.pop(cid, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def drop_if(self, cid: int, conn: Conn) -> bool:
        """Drop the cid's registration only if it is still ``conn`` —
        a registration superseded in the meantime belongs to a newer
        admission and must survive.  True when dropped."""
        with self._reg_lock:
            if self.conns.get(cid) is not conn:
                return False
            del self.conns[cid]
        try:
            conn.close()
        except OSError:
            pass
        return True

    def drop_if_dead(self, cid: int, conn: Conn) -> bool:
        """``drop_if``, but only when conn's peer is already gone (EOF
        pending).  MSG_PEEK keeps any real payload intact, so a live
        conn with a request in flight is never judged dead.  One-shot:
        a FIN still in flight makes this return False — callers that
        must not leak a dying socket have to poll."""
        import socket as _socket
        try:
            r, _, _ = select.select([conn.sock], [], [], 0)
            if r and conn.sock.recv(1, _socket.MSG_PEEK) == b"":
                return self.drop_if(cid, conn)
        except OSError:
            return self.drop_if(cid, conn)
        return False

    def close(self):
        with self._reg_lock:
            conns, self.conns = list(self.conns.values()), {}
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self.server.close()


def _leaves(tree: PyTree) -> list[np.ndarray]:
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _rebuild(tree: PyTree, leaves: list[np.ndarray]) -> PyTree:
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _expect(conn: Conn, want: str):
    """Protocol step check — explicit (never stripped under ``python -O``,
    unlike the reference's asserts) and diagnostic on desync."""
    got = conn.recv_msg()
    if got != want:
        raise ProtocolError(f"protocol desync: expected {want!r}, got {got!r}")


class AsyncEAServer:
    """Parameter-server role (ref initServer/syncServer/testNet)."""

    def __init__(self, host: str, port: int, num_nodes: int,
                 with_tester: bool = False, accept_timeout: float = 120.0,
                 handshake_timeout: float | None = 30.0, shards: int = 1,
                 throttle_bps: float | None = None, standby: bool = False,
                 elastic: bool = False,
                 centers: list[tuple[str, int]] | None = None):
        import threading
        self.num_nodes = num_nodes
        self._host = host
        # Elastic membership (ROADMAP item 4): when on, the server keeps
        # accepting broadcast dials and admits NEW clients through the
        # Join? handshake (cids past num_nodes, ephemeral dedicated
        # ports) and retires them through Leave? — the fleet is a live
        # roster, not a construction-time constant.
        self.elastic = bool(elastic)
        # HA dial list advertised to joiners in the Join reply (the same
        # ``--centers`` roster founding clients get on the command line),
        # so a Join?-admitted client can failover() like everyone else
        # instead of dying with its center (docs/ELASTIC.md).
        self.advertised_centers: list[tuple[str, int]] = [
            (h, int(p)) for h, p in (centers or [])]
        # Live roster: every admitted cid (initial fleet + joiners, minus
        # leavers).  Ids are NEVER reused — the exactly-once ledger and
        # the concurrent server's generation counters stay unambiguous.
        self.members: set[int] = set(range(1, num_nodes + 1))
        self._next_cid = num_nodes + 1
        # per-client capacity weight advertised at Join?/Enter? (default
        # 1.0) — folded into every delta apply as
        # ``w_i = cap_i * num_nodes / Σ_live cap_j`` so a grown fleet
        # does not multiply the effective α (docs/ELASTIC.md)
        self._capacity: dict[int, float] = {}
        self.shards = max(1, int(shards))
        # emulated-link pacing applied to every conn this server accepts
        # (bench/chip-free harnesses; None = full loopback speed)
        self.throttle_bps = throttle_bps
        # Per-handshake IO timeout on the dedicated channels: a client that
        # dies or hangs mid-sync (after Enter?) must not wedge the serve loop
        # — it gets EVICTED and the server keeps serving the others.  The
        # reference wedges here (lua/AsyncEA.lua:163-228 has no timeouts);
        # "match the reference's fragility" is not the bar (VERDICT r1).
        self.handshake_timeout = handshake_timeout
        self.evicted: set[int] = set()
        self._cid_to_broadcast: dict[int, int] = {}
        # negotiated wire codec per client id (None = legacy per-leaf 'T'
        # frames), refreshed on every Enter?/Rejoin? — see _admit
        self._wire_cid: dict[int, str | None] = {}
        # broadcast conns accepted for a possible rejoin that have not yet
        # spoken, with a speak-by deadline — a dialed-but-silent socket
        # must not keep the serve/dispatch loop alive forever
        self._rejoin_pending: list = []
        # Broadcast channel: all clients connect here (EASGD_server.lua:67-68).
        self.broadcast = Server(host, port)
        # Dedicated per-client channels, keyed by cid: the initial fleet
        # on the reference's fixed ports port+i (EASGD_server.lua:71-77);
        # joiners get ephemeral listeners advertised in the Join reply.
        self.dedicated_servers: dict[int, Server] = {
            i + 1: Server(host, port + i + 1) for i in range(num_nodes)}
        # Test channel on port+numNodes+1 (EASGD_server.lua:69-70).
        self.test_server = Server(host, port + num_nodes + 1) \
            if with_tester else None
        # Shard channels (stripes 1..S-1; stripe 0 rides the dedicated
        # conns) listen above the test channel: port+numNodes+2+(s-1).
        # Effective stripe count waits for init_server (it depends on the
        # leaf list); extra endpoints just never get advertised.
        self.shard_endpoints = [
            _ShardEndpoint(host, port + num_nodes + 2 + i, i + 1, num_nodes,
                           throttle_bps=throttle_bps,
                           is_member=self.members.__contains__)
            for i in range(self.shards - 1)]
        self.stripes: list[tuple[int, int]] | None = None
        # per-leaf split counts + the VIRTUAL leaf list (oversized leaves
        # cut into flat chunk views) the stripe ranges index — see
        # wire.plan_splits; real-leaf (shape, dtype) kept for validation
        # and for stitching snapshots back together
        self.splits: list[int] | None = None
        self._vcenter: list[np.ndarray] | None = None
        self._leaf_meta: list[tuple[tuple, Any]] | None = None
        self._shard_spec: dict | None = None
        # whether each client negotiated the sharded sync this admission
        self._shard_cid: dict[int, bool] = {}
        # -- HA state (docs/HA.md) -------------------------------------------
        # Center epoch: bumped on promotion (adopt_ha_meta) and carried in
        # every dict admission reply; a client that has seen a NEWER epoch
        # refuses this center (zombie fence) and vice versa.
        self.epoch = 0
        # per-client sync sequence claimed in the latest Enter? (None =
        # legacy/pre-HA client) and, per stripe, the highest seq whose
        # delta has been APPLIED — the exactly-once ledger the rejoin
        # replay consults.  Recorded in the same critical section as the
        # center publish (see _apply_stripe/_apply_delta overrides).
        self._sync_seq: dict[int, int | None] = {}
        self._applied_seq: dict[int, list[int]] = {}
        # trace context claimed in the latest Enter? (None = peer not
        # propagating) — server-side spans of that client's sync re-enter
        # it so the whole cross-process sync shares one trace id.  Read
        # under the same lock hold as codec/seq in the concurrent server:
        # same-admission consistency.
        self._trace_cid: dict[int, dict | None] = {}
        # checkpoint plumbing (enable_checkpoint); _ckpt_lock serializes
        # snapshot+save and is only ever OUTER of the concurrent server's
        # _lock (DL102: acyclic)
        self._ckpt = None
        self._ckpt_every = 1
        self._ckpt_count = 0
        self._ckpt_lock = threading.Lock()
        self._sync_total = 0
        self._closed = False
        self._standby = bool(standby)
        if standby:
            # Warm standby: no fleet to accept — every cid starts evicted,
            # so admission happens exclusively through the rejoin path
            # once this process is promoted (ha.promote / --standby).
            self.dedicated: dict[int, Conn | None] = \
                dict.fromkeys(range(1, num_nodes + 1))
            self.test_conn = None
            self.evicted = set(range(1, num_nodes + 1))
        else:
            self.broadcast.accept(num_nodes, timeout=accept_timeout)
            self.dedicated = {}
            for cid in range(1, num_nodes + 1):
                self.dedicated[cid] = self.dedicated_servers[cid].accept(
                    1, timeout=accept_timeout)[0]
            self.test_conn = \
                self.test_server.accept(1, timeout=accept_timeout)[0] \
                if with_tester else None
            if throttle_bps:
                for c in (self.broadcast.conns + list(self.dedicated.values())
                          + ([self.test_conn] if self.test_conn else [])):
                    c.throttle_bps = throttle_bps
        self.center: list[np.ndarray] | None = None
        self.current_client: int | None = None
        # Telemetry handles (obs.NULL when DISTLEARN_OBS=0) resolve once
        # per server; ``_obs_on`` gates only work the null sink cannot
        # absorb (perf_counter pairs).
        self._obs_on = obs.enabled()
        self._c_syncs = obs.counter(
            "async_ea_syncs_total", "deltas applied to the center")
        self._c_evict = obs.counter(
            "async_ea_evictions_total", "clients evicted mid-handshake")
        self._c_rejoin = obs.counter(
            "async_ea_rejoins_total", "evicted clients re-admitted")
        self._c_joins = obs.counter(
            "async_ea_membership_joins_total",
            "new clients admitted through the Join? handshake")
        self._c_join_fail = obs.counter(
            "async_ea_membership_join_failures_total",
            "Join? handshakes refused or failed mid-adoption")
        self._c_leaves = obs.counter(
            "async_ea_membership_leaves_total",
            "graceful Leave? departures, by pending-delta outcome",
            labels=("outcome",))
        self._g_members = obs.gauge(
            "async_ea_membership_size",
            "live fleet size (admitted members minus evicted)")
        self._g_members.set(len(self.members - self.evicted))
        self._c_stale = obs.counter(
            "async_ea_failover_stale_refusals_total",
            "admissions refused on the epoch fence (stale/zombie center)")
        self._h_handshake = obs.histogram(
            "async_ea_handshake_seconds",
            "full sync handshake (Enter sent to delta validated)")
        self._h_apply = obs.histogram(
            "async_ea_center_apply_seconds",
            "center += delta apply time (host or device path)")
        self._c_shard_syncs = obs.counter(
            "async_ea_shard_syncs_total",
            "stripe legs completed (sharded syncs only), by shard",
            labels=("shard",))
        self._c_shard_bytes = obs.counter(
            "async_ea_shard_wire_bytes_total",
            "wire bytes a stripe leg moved (center down + delta up), "
            "by shard", labels=("shard",))
        self._h_shard_apply = obs.histogram(
            "async_ea_shard_apply_seconds",
            "per-stripe center apply time, by shard", labels=("shard",))
        # fused wire path (ops/wire_kernels): resolved once per instance so
        # in-process tests can toggle DISTLEARN_TPU_WIREK per server
        self._wirek = wire_kernels.wirek_enabled()
        self._h_center_apply = obs.histogram(
            "center_apply_seconds",
            "fused dequantize+apply of one received wire payload onto the "
            "center (no decoded f32 copy), by stripe ('all' = whole-tree)",
            labels=("shard",))

    def init_server(self, params: PyTree):
        """Clone params as center, broadcast it to every client
        (ref lua :150-160)."""
        self.center = [x.copy() for x in _leaves(params)]
        self._leaf_meta = [(tuple(t.shape), t.dtype) for t in self.center]
        self.splits = wire.plan_splits([t.nbytes for t in self.center],
                                       [t.size for t in self.center],
                                       self.shards)
        self._vcenter = wire.split_views(self.center, self.splits)
        self.stripes = wire.plan_stripes([v.nbytes for v in self._vcenter],
                                         self.shards)
        if len(self.stripes) > 1:
            self._shard_spec = {
                "v": SHARD_V, "n": len(self.stripes),
                "ports": [ep.port for ep in
                          self.shard_endpoints[:len(self.stripes) - 1]],
                "stripes": [[lo, hi] for lo, hi in self.stripes],
                "splits": [[i, p] for i, p in enumerate(self.splits)
                           if p > 1]}
        for conn in self.broadcast.conns:
            try:
                # per-leaf 'T' frames: the initial broadcast happens BEFORE
                # any client has spoken, so there is no capability
                # advertisement to negotiate against — old-wire clients
                # must be able to read it (new clients auto-detect either)
                conn.send_tensors(self.center, packed=False)
            except (TimeoutError, ConnectionError, OSError) as e:
                # Dead before the first broadcast: drop it; it is evicted for
                # real when it never completes a handshake.
                print_server(f"initial broadcast to a client failed: {e!r}")
                conn.close()

    def _check_delta(self, deltas: list[np.ndarray],
                     center: list[np.ndarray] | None = None):
        """Reject a structurally wrong delta BEFORE any leaf is applied, so
        the center never takes a torn update (a mismatched client config
        becomes an eviction, not a corrupted center).  Dtype skew is config
        skew too: an int or f64 delta of the right shape must not be
        silently cast into the center (ADVICE r3).  ``center`` narrows the
        check to one stripe's (virtual) slice; the default checks a
        whole-tree delta against the REAL leaf layout recorded at init —
        the published center list may be the virtual chunk view.  A
        :class:`wire.PackedPayload` (the fused-apply path receives wire
        bytes undecoded) is checked against its manifest's LOGICAL
        shapes/dtypes — same skew, same eviction."""
        meta = ([(tuple(t.shape), t.dtype) for t in center]
                if center is not None else self._leaf_meta)
        if isinstance(deltas, wire.PackedPayload):
            got = [(tuple(e["shape"]), np.dtype(e["dtype"]))
                   for e in deltas.manifest["leaves"]]
        else:
            got = [(tuple(d.shape), d.dtype) for d in deltas]
        for (shape, dtype), (dshape, ddtype) in zip(meta, got):
            if dshape != shape:
                raise ProtocolError(
                    f"delta leaf shape {dshape} != center "
                    f"{shape} — client/server model config skew")
            if ddtype != dtype:
                raise ProtocolError(
                    f"delta leaf dtype {ddtype} != center {dtype} — "
                    "client/server model config skew")

    # -- capacity-weighted elastic averaging (docs/ELASTIC.md) ---------------
    def _delta_weight(self, cid: int) -> float:
        """The scale folded into client ``cid``'s delta applies:
        ``cap_cid * num_nodes / Σ_live cap_j``.  The elastic move's
        effective pull on the center is ``α · Σ_i w_i`` per round of
        fleet syncs — normalizing the weights to sum to ``num_nodes``
        keeps that product at the value the fleet was tuned for while
        the roster grows or shrinks (a 2× fleet would otherwise double
        the effective α — docs/EA_CONVERGENCE.md's stability product).
        Exactly 1.0 for the initial equal-capacity fleet, so fixed-fleet
        runs stay bitwise identical (the scale multiply is skipped)."""
        if not self.elastic:
            return 1.0
        live = self.members - self.evicted
        if not live:
            return 1.0
        total = sum(self._capacity.get(c, 1.0) for c in live)
        if total <= 0.0:
            return 1.0
        return self._capacity.get(cid, 1.0) * self.num_nodes / total

    def _scale_delta(self, deltas, w: float):
        """Scale a validated delta by its capacity weight, in place where
        the buffers allow.  ``w == 1.0`` returns the delta untouched
        (bitwise fixed-fleet compatibility — and the fused undecoded
        payload path survives); any other weight decodes a packed
        payload first, since the wire bytes cannot be rescaled."""
        if w == 1.0:
            return deltas
        if isinstance(deltas, wire.PackedPayload):
            deltas = deltas.decoded()
        out = []
        for d in deltas:
            d = np.asarray(d)
            if not d.flags.writeable:
                d = d.copy()
            d *= np.asarray(w, d.dtype)
            out.append(d)
        return out

    def _record_applied(self, cid: int, idx: int, seq: int):
        """Mark stripe ``idx`` of client ``cid``'s sync ``seq`` as applied
        (monotonic per stripe).  Callers invoke this in the same critical
        section that publishes the center slice, so a checkpoint snapshot
        (center + this ledger, one hold) is mutually consistent and the
        rejoin replay is exactly-once."""
        seqs = self._applied_seq.get(cid)
        if seqs is None:
            seqs = self._applied_seq[cid] = [0] * len(self.stripes)
        if seq > seqs[idx]:
            seqs[idx] = seq

    def _apply_payload_into(self, targets: list[np.ndarray],
                            payload: "wire.PackedPayload"):
        """Fold one undecoded wire payload into ``targets`` IN PLACE via
        the fused dequantize+apply kernels — the decoded f32 copy the
        numpy path materializes per leaf never exists.  Bitwise-identical
        to ``decode_into`` + ``t += d`` (same elementwise multiply-then-
        add, no FMA contraction — see ops/wire_kernels.py)."""
        for t, entry, buf in zip(targets, payload.manifest["leaves"],
                                 payload.bufs):
            enc = entry["enc"]
            if enc == "raw":
                t += buf        # dtypes equal (checked) — no astype copy
            elif enc == "int8":
                wire_kernels.dequant_add(t, buf, entry["scale"], out=t)
            else:               # fp16
                wire_kernels.dequant_add(t, buf, None, out=t)

    def _apply_delta(self, deltas: list[np.ndarray],
                     ha: tuple[int, int] | None = None):
        """Fold a fully-received, validated delta into the center.  The
        serial server mutates in place; the concurrent subclass overrides
        this with its immutable-publish version (so the serial
        ``sync_server`` API keeps working on a concurrent server, whose
        center leaves are frozen).  ``deltas`` may be an undecoded
        :class:`wire.PackedPayload` (the fused wire path).  ``ha=(cid,
        seq)`` records the apply in the exactly-once ledger (a whole-tree
        delta covers every stripe)."""
        t0 = time.perf_counter() if self._obs_on else 0.0
        if isinstance(deltas, wire.PackedPayload):
            self._apply_payload_into(self.center, deltas)
            if self._obs_on:
                self._h_center_apply.labels(shard="all").observe(
                    time.perf_counter() - t0)
        else:
            for t, d in zip(self.center, deltas):
                t += d          # dtypes equal (checked) — no astype copy
        if ha is not None:
            for idx in range(len(self.stripes)):
                self._record_applied(ha[0], idx, ha[1])
        self._sync_total += 1
        self._c_syncs.inc()
        if self._obs_on:
            self._h_apply.observe(time.perf_counter() - t0)

    # -- sharded serving -----------------------------------------------------
    def _enter_reply(self, cid: int, want: str):
        """The admission reply for one client: the legacy plain string, or
        the dict form carrying the wire agreement plus — for clients that
        negotiated sharding — the explicit stripe plan."""
        codec = self._wire_cid.get(cid)
        if codec is None:
            return want
        reply: dict[str, Any] = {"a": want,
                                 "wire": {"v": wire.WIRE_V, "codec": codec},
                                 "epoch": self.epoch}
        if self._shard_cid.get(cid):
            reply["shard"] = self._shard_spec
        return reply

    def _stripe_center(self, lo: int, hi: int) -> list[np.ndarray]:
        """VIRTUAL center leaves [lo, hi) to stream for one stripe leg
        (concurrent server overrides with its atomic snapshot's slice)."""
        return self._vcenter[lo:hi]

    def _serve_stripe_leg(self, conn: Conn, idx: int,
                          codec: str) -> list[np.ndarray]:
        """One stripe's half of a sharded sync on an admitted client's
        channel: ``Center?`` -> center slice down, ``delta?`` -> delta
        slice up, validated.  Returns the received delta slice (the
        caller applies it — serial and concurrent appliers differ)."""
        lo, hi = self.stripes[idx]
        b0 = conn.bytes_sent + conn.bytes_received
        center = self._stripe_center(lo, hi)
        with obs.span("async_ea.stripe_leg", shard=idx):
            _expect(conn, CENTER_Q)
            conn.send_tensors(center, codec=codec, packed=True)
            _expect(conn, DELTA_Q)
            conn.send_msg(DELTA)
            dl = (None if self.handshake_timeout is None
                  else time.monotonic() + self.handshake_timeout)
            if self._wirek and codec not in (None, "raw"):
                # fused wire path: keep the delta in wire dtype (int8 is
                # 4x fewer bytes to hold) and dequantize inside the apply
                deltas = conn.recv_payload(n=hi - lo, deadline=dl)
            else:
                deltas = conn.recv_tensors(n=hi - lo, deadline=dl)
            self._check_delta(deltas, center=center)
        self._c_shard_syncs.labels(shard=idx).inc()
        self._c_shard_bytes.labels(shard=idx).inc(
            conn.bytes_sent + conn.bytes_received - b0)
        return deltas

    def _apply_stripe(self, idx: int, deltas: list[np.ndarray],
                      ha: tuple[int, int] | None = None):
        """Fold one validated stripe's delta into its center slice.
        Atomicity is per stripe: a client dying mid-sync may land a
        subset of stripes, each complete-or-nothing — the stale-update
        asynchrony EASGD already tolerates (arXiv:1412.6651 §4).  The
        exactly-once ledger tracks exactly that per-stripe granularity:
        ``ha=(cid, seq)`` marks THIS stripe of THAT sync applied."""
        lo, hi = self.stripes[idx]
        t0 = time.perf_counter() if self._obs_on else 0.0
        if isinstance(deltas, wire.PackedPayload):
            # fused path: wire bytes dequantize straight into the slice
            self._apply_payload_into(self._vcenter[lo:hi], deltas)
            if self._obs_on:
                self._h_center_apply.labels(shard=idx).observe(
                    time.perf_counter() - t0)
        else:
            for t, d in zip(self._vcenter[lo:hi], deltas):
                t += d      # disjoint element ranges (chunk views of a
                #             split leaf included): threads never collide
        if ha is not None:
            self._record_applied(ha[0], idx, ha[1])
        if self._obs_on:
            self._h_shard_apply.labels(shard=idx).observe(
                time.perf_counter() - t0)

    def _count_sync(self):
        """One full client sync completed on the sharded path (counted
        once per sync, not per stripe leg)."""
        self._sync_total += 1
        self._c_syncs.inc()

    @property
    def syncs_completed(self) -> int:
        """Deltas applied since construction (the concurrent server
        overrides with its lock-guarded count) — also the checkpoint
        step counter."""
        return self._sync_total

    def _serve_striped(self, cid: int, conn: Conn):
        """Serve every stripe of one sharded sync.  Stripe 0 rides the
        dedicated channel on the calling thread; stripes 1.. run on
        transient threads against their shard endpoints, so one client's
        legs pipeline.  Any leg failure re-raises (after all legs settle)
        into the caller's eviction handling; completed stripes stay
        applied (see ``_apply_stripe``)."""
        codec = self._wire_cid[cid]
        seq = self._sync_seq.get(cid)
        tc = self._trace_cid.get(cid)
        ha = (cid, seq) if seq is not None else None
        w = self._delta_weight(cid)

        def leg(idx):
            if idx == 0:
                c = conn
            else:
                ep = self.shard_endpoints[idx - 1]
                c = ep.get_conn(cid,
                                timeout=self.handshake_timeout or 30.0)
                c.set_timeout(self.handshake_timeout)
            # legs run on transient _fanout threads, which do not inherit
            # the admission thread's context stack — re-enter explicitly
            with obs_trace.use_context(tc):
                self._apply_stripe(
                    idx, self._scale_delta(
                        self._serve_stripe_leg(c, idx, codec), w), ha=ha)

        _fanout([lambda i=i: leg(i) for i in range(len(self.stripes))])
        self._count_sync()

    def _evict(self, cid: int, why: Exception):
        """Drop a dead/hung client: close all its channels (broadcast,
        dedicated, every shard) so recv_any stops selecting it and stripe
        legs fail fast; remaining clients keep syncing."""
        self.evicted.add(cid)
        self._c_evict.inc()
        self._g_members.set(len(self.members - self.evicted))
        print_server(f"evicting client #{cid}: {why!r}")
        conn = self.dedicated.get(cid)      # None on a never-admitted
        if conn is not None:                # standby slot
            try:
                conn.close()
            except OSError:
                pass
        for ep in self.shard_endpoints:
            ep.drop(cid)
        idx = self._cid_to_broadcast.get(cid)
        if idx is not None:
            try:
                self.broadcast.conns[idx].close()
            except OSError:
                pass

    @property
    def live_clients(self) -> int:
        return len(self.members - self.evicted)

    # -- re-admission --------------------------------------------------------
    #
    # The reference has no recovery at all (lua/AsyncEA.lua wedges on a dead
    # peer); eviction alone made failure survivable but terminal — a
    # transiently-hung worker was dead forever (VERDICT r4 next #8).  Rejoin
    # completes the elastic story: an evicted client re-dials BOTH channels
    # (its old sockets are closed server-side), announces itself with
    # ``Rejoin?`` on the fresh broadcast conn, receives the CURRENT center
    # over the fresh dedicated conn (its own copy is stale by definition),
    # acks, and is a full participant again.
    def _accept_rejoiners(self):
        """Accept pending broadcast re-connections (non-blocking poll of the
        listening socket).  Only meaningful while somebody is evicted — the
        fast path is one set-emptiness check.  Accepted conns get a
        speak-by deadline: a rejoiner that dials in but never sends its
        ``Rejoin?`` (the same hang that got it evicted) is closed when the
        deadline passes, so a silent socket cannot keep the dispatcher
        alive past its rejoin grace or wedge ``drained`` forever."""
        self._prune_broadcast()
        now = time.monotonic()
        kept = []
        for c, dl in self._rejoin_pending:
            if c.sock.fileno() < 0:
                continue                      # spoke (or died) — tracked out
            if now > dl:
                try:
                    c.close()
                except OSError:
                    pass
                continue
            kept.append((c, dl))
        self._rejoin_pending = kept
        if not self.evicted and not self.elastic:
            return
        while True:
            r, _, _ = select.select([self.broadcast.sock], [], [], 0.0)
            if not r:
                return
            try:
                new = self.broadcast.accept(
                    1, timeout=self.handshake_timeout or 30.0)
            except (TimeoutError, OSError):
                return
            if self.throttle_bps:
                new[0].throttle_bps = self.throttle_bps
            # speak-by measured from the accept's RETURN — a deadline off
            # the pre-accept poll timestamp silently shortened the grace
            # by however long the accept itself took
            self._rejoin_pending.append(
                (new[0], time.monotonic()
                 + (self.handshake_timeout or 30.0)))

    def _prune_broadcast(self):
        """Closed broadcast conns accumulate forever once rejoin dials
        re-open the listener (``Server.accept`` only appends): drop them
        and remap the cid -> index table.  The concurrent server overrides
        to run under its dispatcher lock (workers read the map during
        eviction)."""
        if all(c.sock.fileno() >= 0 for c in self.broadcast.conns):
            return
        mapping = self.broadcast.prune_closed()
        self._cid_to_broadcast = {
            cid: mapping[i] for cid, i in self._cid_to_broadcast.items()
            if i in mapping}

    def _note_spoke(self, idx: int):
        """A broadcast conn delivered a message: it is no longer a silent
        rejoin candidate — drop it from the speak-by watch list (its fate
        now follows the normal admit/readmit paths)."""
        conn = self.broadcast.conns[idx]
        self._rejoin_pending = [(c, dl) for c, dl in self._rejoin_pending
                                if c is not conn]

    def _evict_dropped(self, idx: int, why: Exception):
        """``recv_any``'s frame-timeout drop closed a broadcast conn at
        transport level.  If that conn belonged to an admitted client,
        record a REAL eviction (closing its dedicated channel too) so the
        bookkeeping stays true and the client can later ``rejoin()`` —
        a transport-level close with no eviction record was permanently
        unrecoverable (r5 review)."""
        for cid, i in self._cid_to_broadcast.items():
            if i == idx and cid not in self.evicted:
                self._evict(cid, why)
                return

    def _rejoin_center(self) -> list[np.ndarray]:
        """Center leaves to stream to a rejoiner (concurrent server
        overrides with its atomic snapshot)."""
        return self.center

    def _finish_readmit(self, cid: int, idx: int, conn: Conn):
        """Swap in the fresh channels and clear the evicted bit (concurrent
        server overrides to also respawn the client's worker)."""
        self.evicted.discard(cid)
        self._cid_to_broadcast[cid] = idx
        self.dedicated[cid] = conn
        self._c_rejoin.inc()
        self._g_members.set(len(self.members - self.evicted))

    def _readmit(self, idx: int, msg) -> None:
        """Complete one ``Rejoin?`` handshake: validate the claimed id is
        actually evicted, accept the client's fresh dedicated connection,
        stream the current center down it, and re-admit on the client's
        ``Ack``.  Any failure leaves the client evicted (it can try again);
        the center is never touched."""
        cid = self._parse_cid(msg)
        conn_b = self.broadcast.conns[idx]
        if cid < 0 or cid not in self.evicted:
            self._drop_peer(idx, f"dropping rejoin with bad clientID "
                                 f"{msg.get('clientID')!r}")
            return
        codec, wire_err = _parse_wire_request(msg)
        srv = self.dedicated_servers.get(cid)
        if srv is None:
            # a joiner whose ephemeral listener is gone (e.g. after a
            # promotion to a center that never saw it) cannot rejoin by
            # port — it has to Join? afresh (docs/ELASTIC.md)
            self._drop_peer(idx, f"dropping rejoin of client #{cid}: "
                                 "no dedicated listener for that cid")
            return
        try:
            # SHORT bound: the rejoin protocol dials the dedicated channel
            # BEFORE announcing Rejoin?, so a legit dial is already in the
            # listen backlog — a long wait here would let one half-rejoin
            # (announce without dial) stall serving for every live client
            # by handshake_timeout per attempt.
            new = srv.accept(
                1, timeout=min(self.handshake_timeout or 2.0, 2.0))[0]
        except (TimeoutError, OSError) as e:
            print_server(f"rejoin of client #{cid} failed at dedicated "
                         f"accept: {e!r}")
            try:
                conn_b.close()
            except OSError:
                pass
            return
        if self.throttle_bps:
            new.throttle_bps = self.throttle_bps
        try:
            with obs.span("async_ea.rejoin", cid=cid):
                new.set_timeout(self.handshake_timeout)
                claimed_epoch = msg.get("epoch")
                if isinstance(claimed_epoch, int) \
                        and claimed_epoch > self.epoch:
                    # zombie fence on the rejoin leg (see _refuse_stale)
                    self._c_stale.inc()
                    new.send_msg({"a": REJOIN, "stale": True,
                                  "epoch": self.epoch})
                    raise ProtocolError(
                        f"center epoch {self.epoch} is stale: client "
                        f"#{cid} has synced with epoch {claimed_epoch}")
                if wire_err is not None:
                    # same loud rejection as _reject_wire, on the rejoin leg
                    new.send_msg({"a": REJOIN, "wire": {"error": wire_err}})
                    raise ProtocolError(wire_err)
                self._wire_cid[cid] = codec
                self._shard_cid[cid] = (isinstance(msg.get("shard"), dict)
                                        and codec is not None
                                        and self._shard_spec is not None)
                reply = self._enter_reply(cid, REJOIN)
                # Exactly-once replay negotiation (docs/HA.md): the client
                # claims the sequence of its newest un-acked delta; we
                # answer with the stripes whose ledger entry is older —
                # the ones the dying center (or this freshly restored one)
                # never applied.  Lock-free ledger read is safe: the cid
                # is evicted, so none of its legs are in flight.
                claimed_seq = msg.get("replay")
                need: list[int] = []
                if (isinstance(reply, dict) and isinstance(claimed_seq, int)
                        and claimed_seq > 0 and self.stripes is not None):
                    seqs = (self._applied_seq.get(cid)
                            or [0] * len(self.stripes))
                    need = [i for i, s in enumerate(seqs)
                            if s < claimed_seq]
                    reply["replay"] = {"seq": claimed_seq, "need": need}
                new.send_msg(reply)
                # rejoin streams the FULL center over the fresh dedicated
                # conn regardless of sharding (rejoins are rare; the
                # client re-dials its shard channels afterwards, so every
                # stripe is resynced by construction)
                new.send_tensors(self._rejoin_center(),
                                 codec=codec or "raw", packed=codec is not None)
                _expect(new, ACK)
                if need:
                    self._recv_replay(cid, new, claimed_seq, need)
                new.set_timeout(None)
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError) as e:
            print_server(f"rejoin of client #{cid} failed mid-handshake: "
                         f"{e!r}")
            for c in (new, conn_b):
                try:
                    c.close()
                except OSError:
                    pass
            return
        self._finish_readmit(cid, idx, new)
        print_server(f"client #{cid} re-admitted")

    def _recv_replay(self, cid: int, conn: Conn, seq: int,
                     need: list[int]):
        """Receive and apply the replayed stripes of the client's claimed
        sync ``seq`` (the rejoin reply told it which ones this center's
        ledger is missing).  The client resends the EXACT encoded payload
        bytes it stored at encode time, so a restored/promoted center
        lands bitwise on the same trajectory as an unkilled one; a client
        that cannot replay (stripe plan changed, payloads gone) sends an
        abort header and the delta is dropped — the lost stale update
        EASGD already tolerates (docs/EA_CONVERGENCE.md)."""
        hdr = conn.recv_msg()
        if not (isinstance(hdr, dict) and hdr.get("q") == REPLAY_Q):
            raise ProtocolError(
                f"protocol desync: expected {REPLAY_Q!r} header, "
                f"got {hdr!r}")
        if not hdr.get("abort"):
            dl = (None if self.handshake_timeout is None
                  else time.monotonic() + self.handshake_timeout)
            w = self._delta_weight(cid)
            for i in need:
                lo, hi = self.stripes[i]
                deltas = conn.recv_tensors(n=hi - lo, deadline=dl)
                self._check_delta(deltas,
                                  center=self._stripe_center(lo, hi))
                self._apply_stripe(i, self._scale_delta(deltas, w),
                                   ha=(cid, seq))
            self._count_sync()
        conn.send_msg(ACK)

    def _parse_cid(self, msg) -> int:
        """The clientID an admission-family message claims, or -1 when
        absent/unparseable/out of range — shared by ``_admit`` and
        ``_readmit`` so the id rules cannot drift between the two paths."""
        try:
            cid = int(msg.get("clientID", -1))
        except (TypeError, ValueError):
            return -1
        return cid if cid >= 1 and cid in self.members else -1

    def _drop_peer(self, idx: int, why: str):
        """Close one broadcast conn and log why (bad request/id)."""
        try:
            self.broadcast.conns[idx].close()
        except OSError:
            pass
        print_server(why)

    def _admit(self, idx: int, msg) -> int | None:
        """Validate one broadcast-channel request (``Enter?`` + a sane,
        non-evicted clientID).  Returns the client id, or ``None`` after
        dropping the broken peer — shared by the serial serve loop and the
        concurrent dispatcher so admission rules cannot drift."""
        if not isinstance(msg, dict) or msg.get("q") != ENTER_Q:
            self._drop_peer(idx, f"dropping peer with bad request {msg!r}")
            return None
        cid = self._parse_cid(msg)
        if cid < 0 or cid in self.evicted:
            self._drop_peer(idx, f"dropping peer with bad clientID "
                                 f"{msg.get('clientID')!r}")
            return None
        self._cid_to_broadcast[cid] = idx
        claimed = msg.get("epoch")
        if isinstance(claimed, int) and claimed > self.epoch:
            self._refuse_stale(cid, claimed)
            return None
        codec, wire_err = _parse_wire_request(msg)
        if wire_err is not None:
            self._reject_wire(cid, wire_err)
            return None
        self._wire_cid[cid] = codec
        # capacity refresh: a client may (re-)advertise its weight on any
        # admission; absent means "keep whatever the roster has" (1.0)
        cap = msg.get("capacity")
        if isinstance(cap, (int, float)) and cap > 0:
            self._capacity[cid] = float(cap)
        # sharding requires the packed wire AND a multi-stripe plan; a
        # client that advertised against an unsharded server (or without
        # a codec) just gets no "shard" key back and stays single-stripe
        self._shard_cid[cid] = (isinstance(msg.get("shard"), dict)
                                and codec is not None
                                and self._shard_spec is not None)
        # the sync sequence this admission claims (None = pre-HA client):
        # recorded into the exactly-once ledger when the delta applies
        seq = msg.get("seq")
        self._sync_seq[cid] = seq if isinstance(seq, int) else None
        # optional trace context: absent or malformed degrades to "no
        # trace" — a legacy or adversarial peer must never break admission
        tc = msg.get(obs_trace.TRACE_KEY)
        self._trace_cid[cid] = tc if obs_trace.valid_context(tc) else None
        return cid

    def _reject_wire(self, cid: int, err: str):
        """A client advertised a wire codec this server cannot speak:
        answer LOUDLY on the dedicated channel (where the client blocks
        waiting for Enter — it raises ProtocolError on the error reply)
        and evict.  Silently falling back would ship fp32 to a client
        that asked for compression; silently proceeding would corrupt."""
        conn = self.dedicated.get(cid)
        if conn is not None:
            try:
                conn.set_timeout(self.handshake_timeout)
                conn.send_msg({"a": ENTER, "wire": {"error": err}})
            except (TimeoutError, ConnectionError, OSError):
                pass
        self._evict(cid, ProtocolError(err))

    def _refuse_stale(self, cid: int, claimed: int):
        """The client has synced against a NEWER center epoch than ours:
        this process is a zombie (pre-failover) primary.  Answer loudly on
        the dedicated channel — the client raises ``StaleCenterError`` and
        drops this address from its dial list — and evict; this center
        must never stream a center or take a delta from that client."""
        self._c_stale.inc()
        err = (f"center epoch {self.epoch} is stale: client #{cid} has "
               f"synced with epoch {claimed}")
        conn = self.dedicated.get(cid)
        if conn is not None:
            try:
                conn.set_timeout(self.handshake_timeout)
                conn.send_msg({"a": ENTER, "stale": True,
                               "epoch": self.epoch})
            except (TimeoutError, ConnectionError, OSError):
                pass
        self._evict(cid, ProtocolError(err))

    # -- elastic membership (Join?/Leave?, docs/ELASTIC.md) ------------------
    def _handle_join(self, idx: int, msg) -> None:
        """Admit a NEW client (``Join?``).  The joiner has no cid and no
        dedicated channel yet: assign the next monotonic cid (never
        reused), open an ephemeral dedicated listener and advertise its
        port in the reply, then run the rejoin-shaped center adoption
        (center down, Ack up).  Registration happens only AFTER the Ack
        lands — the join fence: a cid that never adopted the current
        center can never be admitted to push a delta (the membership
        model in lint/model.py checks exactly this, DL302)."""
        conn_b = self.broadcast.conns[idx]
        if not self.elastic or self.center is None:
            self._c_join_fail.inc()
            self._drop_peer(idx, "dropping Join?: server is "
                            + ("not serving yet" if self.elastic
                               else "not elastic"))
            return
        codec, wire_err = _parse_wire_request(msg)
        if wire_err is not None:
            self._c_join_fail.inc()
            try:
                conn_b.set_timeout(self.handshake_timeout)
                conn_b.send_msg({"a": JOIN, "wire": {"error": wire_err}})
            except (TimeoutError, ConnectionError, OSError):
                pass
            self._drop_peer(idx, f"dropping joiner: {wire_err}")
            return
        cap = msg.get("capacity")
        cap = float(cap) if isinstance(cap, (int, float)) and cap > 0 else 1.0
        cid = self._next_cid
        ded = Server(self._host, 0)     # ephemeral port, advertised below
        try:
            with obs.span("async_ea.join", cid=cid):
                reply: dict[str, Any] = {"a": JOIN, "clientID": cid,
                                         "port": ded.port,
                                         "epoch": self.epoch}
                if self.advertised_centers:
                    # the joiner's failover dial list — without it a
                    # joiner only ever knows the center admitting it
                    reply["centers"] = [[h, p] for h, p
                                        in self.advertised_centers]
                if codec is not None:
                    reply["wire"] = {"v": wire.WIRE_V, "codec": codec}
                conn_b.set_timeout(self.handshake_timeout)
                conn_b.send_msg(reply)
                conn_b.set_timeout(None)
                new = ded.accept(1, timeout=self.handshake_timeout or 30.0)[0]
                if self.throttle_bps:
                    new.throttle_bps = self.throttle_bps
                new.set_timeout(self.handshake_timeout)
                new.send_tensors(self._rejoin_center(), codec=codec or "raw",
                                 packed=codec is not None)
                _expect(new, ACK)
                new.set_timeout(None)
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError) as e:
            self._c_join_fail.inc()
            ded.close()
            print_server(f"join of client #{cid} failed mid-handshake: "
                         f"{e!r}")
            try:
                conn_b.close()
            except OSError:
                pass
            return
        self._next_cid = cid + 1
        sharded = (isinstance(msg.get("shard"), dict) and codec is not None
                   and self._shard_spec is not None)
        self._register_member(cid, idx, new, ded, capacity=cap,
                              codec=codec, sharded=sharded)
        print_server(f"client #{cid} joined (capacity {cap:g}, fleet "
                     f"size {self.live_clients})")

    def _register_member(self, cid: int, idx: int, conn: Conn,
                         ded: Server, *, capacity: float,
                         codec: str | None, sharded: bool) -> None:
        """Install a joiner into the roster — the concurrent server
        overrides to also create its token queue and spawn its workers
        under the dispatcher lock."""
        self.members.add(cid)
        self._capacity[cid] = capacity
        self.dedicated_servers[cid] = ded
        self.dedicated[cid] = conn
        self._cid_to_broadcast[cid] = idx
        self._wire_cid[cid] = codec
        self._shard_cid[cid] = sharded
        self._c_joins.inc()
        self._g_members.set(len(self.members - self.evicted))

    def _handle_leave(self, idx: int, msg) -> None:
        """Graceful departure (``Leave?``): flush the leaver's newest
        delta through the exactly-once ledger — the reply names the
        stripes whose applied-seq is behind the claimed seq and the
        client replays exactly those encoded bytes — then retire the
        cid: channels and listener closed, roster entry and capacity
        dropped.  The weight renormalization is implicit: weights derive
        from the live roster (``_delta_weight``), so the survivors'
        shares grow the moment the leaver is gone."""
        cid = self._parse_cid(msg)
        if cid < 0:
            self._drop_peer(idx, f"dropping leave with bad clientID "
                                 f"{msg.get('clientID')!r}")
            return
        if cid in self.evicted:
            # nothing can be in flight and the dedicated channel is gone:
            # the pending delta (if any) is unreachable — dropped, the
            # stale-update loss EASGD already tolerates
            self._c_leaves.labels(outcome="dropped").inc()
            self._remove_member(cid)
            print_server(f"client #{cid} left (was evicted; "
                         "pending delta dropped)")
            return
        # let any in-flight legs of the leaver's LAST sync settle before
        # reading the ledger — replaying a stripe a worker is still
        # applying would double-apply it (concurrent server override)
        self._wait_cid_idle(cid, self.handshake_timeout or 30.0)
        conn = self.dedicated.get(cid)
        claimed = msg.get("seq")
        need: list[int] = []
        if (isinstance(claimed, int) and claimed > 0
                and self.stripes is not None):
            seqs = self._applied_seq.get(cid) or [0] * len(self.stripes)
            need = [i for i, s in enumerate(seqs) if s < claimed]
        outcome = "flushed" if need else "clean"
        if conn is None:
            outcome = "dropped"
        else:
            try:
                with obs.span("async_ea.leave", cid=cid):
                    conn.set_timeout(self.handshake_timeout)
                    conn.send_msg({"a": LEAVE,
                                   "replay": {"seq": claimed, "need": need}})
                    if need and isinstance(claimed, int):
                        self._recv_replay(cid, conn, claimed, need)
                    conn.set_timeout(None)
            except (TimeoutError, ConnectionError, ProtocolError, OSError,
                    ValueError) as e:
                outcome = "dropped"
                print_server(f"leave flush of client #{cid} failed: {e!r} "
                             "(pending delta dropped)")
        self._c_leaves.labels(outcome=outcome).inc()
        self._remove_member(cid)
        print_server(f"client #{cid} left ({outcome}; fleet size "
                     f"{self.live_clients})")

    def _wait_cid_idle(self, cid: int, timeout: float) -> bool:
        """Block until none of ``cid``'s sync legs are in flight.  The
        serial server IS the only serving thread, so nothing can be in
        flight while it sits here."""
        return True

    def _remove_member(self, cid: int) -> None:
        """Retire a cid for good: close every channel AND its dedicated
        listener, then drop the roster entry.  Unlike an eviction the
        cid cannot come back — ids are never reused, a departed client
        re-enters through a fresh Join?."""
        conn = self.dedicated.pop(cid, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        for ep in self.shard_endpoints:
            ep.drop(cid)
        idx = self._cid_to_broadcast.pop(cid, None)
        if idx is not None:
            try:
                self.broadcast.conns[idx].close()
            except OSError:
                pass
        srv = self.dedicated_servers.pop(cid, None)
        if srv is not None:
            srv.close()
        self.members.discard(cid)
        self.evicted.discard(cid)
        for table in (self._capacity, self._wire_cid, self._shard_cid,
                      self._sync_seq, self._applied_seq):
            table.pop(cid, None)
        self._g_members.set(len(self.members - self.evicted))

    def sync_server(self, params: PyTree,
                    timeout: float | None = None) -> PyTree:
        """One full server-side sync round (ref ``syncServer``, lua :230-237):
        admit one client, send center, receive delta, apply it, and copy the
        center into the server-local params (returned).

        A client that fails mid-handshake (EOF, hang past
        ``handshake_timeout``, protocol desync) is evicted and the round
        retries with the next requester — the center never takes a partial
        delta (updates apply leaf-by-leaf only after every leaf arrived).

        ``timeout`` bounds the wait for ANY sync request (``None`` = wait
        forever, the reference's behavior).

        While any client is evicted the wait is sliced so pending
        ``Rejoin?`` re-connections get accepted (see :meth:`_readmit`); a
        rejoin round admits no sync — the loop continues to the next
        request.  If ALL clients are evicted/closed this still raises
        ``RuntimeError`` (no open connections); a caller that wants to
        wait out a full outage catches it and calls ``sync_server`` again.
        """
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            self._accept_rejoiners()
            if deadline is None:
                slice_t = 0.5 if (self.evicted or self.elastic) else None
            else:
                slice_t = max(0.0, deadline - time.monotonic())
                if self.evicted or self.elastic:
                    slice_t = min(slice_t, 0.5)
            # serverEnterSync (lua :163-177): critical section — one client.
            try:
                idx, msg = self.broadcast.recv_any(
                    timeout=slice_t, frame_timeout=self.handshake_timeout,
                    on_drop=self._evict_dropped)
            except TimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                continue
            except RuntimeError:
                # recv_any with zero open conns.  For a normal server that
                # is the documented "fleet finished" stop condition —
                # re-raise.  A (promoted) standby STARTS with zero conns
                # and every cid evicted: its whole fleet arrives through
                # Rejoin? dials, so keep polling _accept_rejoiners.  An
                # ELASTIC server's next client may likewise arrive on the
                # listening socket (Join?) at any time — keep polling.
                if not ((self._standby and self.evicted) or self.elastic):
                    raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(
                        "no sync request within the timeout (standby "
                        "still waiting for its fleet to re-dial)")
                time.sleep(0.05)
                continue
            self._note_spoke(idx)
            if isinstance(msg, dict) and msg.get("q") == REJOIN_Q:
                self._readmit(idx, msg)
                continue
            if isinstance(msg, dict) and msg.get("q") == JOIN_Q:
                self._handle_join(idx, msg)
                continue
            if isinstance(msg, dict) and msg.get("q") == LEAVE_Q:
                self._handle_leave(idx, msg)
                continue
            cid = self._admit(idx, msg)
            if cid is None:
                continue
            self.current_client = cid
            conn = self.dedicated[cid]      # 1-based ids (ref)
            t0 = time.perf_counter() if self._obs_on else 0.0
            codec = self._wire_cid.get(cid)
            deltas = None
            try:
                with obs_trace.use_context(self._trace_cid.get(cid)), \
                        obs.span("async_ea.handshake", cid=cid):
                    conn.set_timeout(self.handshake_timeout)
                    conn.send_msg(self._enter_reply(cid, ENTER))
                    print_server(f"current client is #{self.current_client}")

                    if self._shard_cid.get(cid):
                        # striped sync: every leg validates and applies its
                        # own slice inside (per-stripe atomicity)
                        self._serve_striped(cid, conn)
                        conn.set_timeout(None)
                    else:
                        # serverSendCenter (lua :180-196): ONE packed frame
                        # on a negotiated wire, per-leaf 'T' frames for
                        # legacy
                        _expect(conn, CENTER_Q)
                        conn.send_tensors(self.center, codec=codec or "raw",
                                          packed=codec is not None)

                        # serverGetUpdateDiff (lua :198-228): receive the
                        # FULL delta before applying any of it, so an
                        # eviction mid-stream leaves the center untouched.
                        # The monotonic deadline covers the WHOLE delta
                        # stream: a client trickling payload bytes re-arms
                        # the kernel timeout forever, the exact wedge the
                        # frame deadline closes for control frames.
                        _expect(conn, DELTA_Q)
                        conn.send_msg(DELTA)
                        dl = (None if self.handshake_timeout is None
                              else time.monotonic() + self.handshake_timeout)
                        # auto-detects packed vs per-leaf, so a legacy
                        # client needs no branch here.  Fused wire path:
                        # receive UNDECODED and dequantize inside the
                        # apply; else quantized deltas decode into fresh
                        # center-dtype arrays
                        if self._wirek and codec not in (None, "raw"):
                            deltas = conn.recv_payload(
                                n=len(self.center), deadline=dl)
                        else:
                            deltas = conn.recv_tensors(n=len(self.center),
                                                       deadline=dl)
                        self._check_delta(deltas)
                        conn.set_timeout(None)
            except (TimeoutError, ConnectionError, ProtocolError, OSError,
                    ValueError) as e:   # ValueError: undecodable JSON frame
                self._evict(cid, e)
                continue
            if self._obs_on:
                self._h_handshake.observe(time.perf_counter() - t0)
            if deltas is not None:
                seq = self._sync_seq.get(cid)
                deltas = self._scale_delta(deltas, self._delta_weight(cid))
                self._apply_delta(
                    deltas, ha=(cid, seq) if seq is not None else None)
            print_server(f"received delta from client #{self.current_client}")
            self._maybe_checkpoint()
            return _rebuild(params, [t.copy() for t in self.center])

    def test_net(self, tensors: list[np.ndarray] | None = None) -> bool:
        """Push the center to the tester (ref ``testNet``, lua :239-258).

        A dead/hung tester must not stall training: the handshake runs
        under ``handshake_timeout`` and a failed tester is dropped (later
        calls no-op, returning False).  ``tensors`` overrides the pushed
        leaves (the concurrent server passes an atomic snapshot)."""
        conn = self.test_conn
        if conn is None:
            return False
        try:
            conn.set_timeout(self.handshake_timeout)
            conn.send_msg(TEST_Q)
            # the tester's Center? may carry a wire advertisement (a dict,
            # like Enter?) — negotiate the packed frame the same way
            msg = conn.recv_msg()
            codec = None
            if isinstance(msg, dict) and msg.get("q") == CENTER_Q:
                codec, wire_err = _parse_wire_request(msg)
                if wire_err is not None:
                    conn.send_msg({"a": TEST_Q, "wire": {"error": wire_err}})
                    raise ProtocolError(wire_err)
            elif msg != CENTER_Q:
                raise ProtocolError(
                    f"protocol desync: expected {CENTER_Q!r}, got {msg!r}")
            conn.send_tensors(tensors if tensors is not None else self.center,
                              codec=codec or "raw", packed=codec is not None)
            _expect(conn, ACK)
            conn.set_timeout(None)
            return True
        except (TimeoutError, ConnectionError, ProtocolError, OSError,
                ValueError) as e:
            print_server(f"dropping tester: {e!r}")
            conn.close()
            self.test_conn = None
            return False

    # -- HA: periodic checkpointing + promotion (docs/HA.md) -----------------
    def enable_checkpoint(self, directory: str, every: int = 1,
                          keep: int = 3):
        """Checkpoint the center (plus the HA ledger) to ``directory``
        every ``every`` applied syncs, keeping the newest ``keep`` files.
        Uses the bf16-safe ``AsyncCheckpointer`` — the snapshot is taken
        synchronously (consistent by construction, see ``_ha_state``) and
        the atomic ``ckpt_{step}.npz`` write happens off-thread.  Returns
        self so construction chains."""
        from distlearn_tpu.utils.checkpoint import AsyncCheckpointer
        self._ckpt = AsyncCheckpointer(directory, keep=keep)
        self._ckpt_every = max(1, int(every))
        self._ckpt_count = self.syncs_completed
        self._c_ckpt_saves = obs.counter(
            "center_ckpt_saves_total", "center checkpoints written")
        self._g_ckpt_step = obs.gauge(
            "center_ckpt_last_step", "sync count of the newest checkpoint")
        self._h_ckpt_save = obs.histogram(
            "center_ckpt_save_seconds",
            "snapshot + save-submit time per center checkpoint")
        return self

    def _ha_state(self) -> tuple[int, list[np.ndarray], dict]:
        """(step, REAL center leaves, HA metadata) — one mutually
        consistent snapshot.  The serial server is single-threaded, so
        plain reads ARE consistent; the concurrent override grabs the
        center pointer, ledger, and epoch under one lock hold."""
        leaves = self._rejoin_center()
        meta = {"epoch": self.epoch,
                "applied_seq": {str(c): list(s)
                                for c, s in self._applied_seq.items()},
                "wire": {str(c): v for c, v in self._wire_cid.items()},
                "shards": self.shards,
                "num_nodes": self.num_nodes,
                "members": sorted(self.members),
                "capacity": {str(c): v for c, v in self._capacity.items()}}
        return self.syncs_completed, leaves, meta

    def _checkpoint_locked(self):
        """Snapshot + save; caller holds ``_ckpt_lock``.  Leaves are keyed
        ``center/<i>`` in the npz (flat index order — the restore template
        in ``parallel/ha.py`` mirrors it)."""
        t0 = time.perf_counter()
        step, leaves, meta = self._ha_state()
        self._ckpt.save(step,
                        {"center": {str(i): t for i, t in enumerate(leaves)}},
                        metadata=meta)
        self._ckpt_count = step
        self._c_ckpt_saves.inc()
        self._g_ckpt_step.set(step)
        self._h_ckpt_save.observe(time.perf_counter() - t0)

    def _maybe_checkpoint(self):
        """Cadence check on the sync path.  Non-blocking: if another
        thread is mid-checkpoint, skip — the next sync re-checks (the
        cadence is a floor, not a schedule)."""
        if self._ckpt is None \
                or self.syncs_completed - self._ckpt_count < self._ckpt_every:
            return
        if not self._ckpt_lock.acquire(blocking=False):
            return
        try:
            if self.syncs_completed - self._ckpt_count >= self._ckpt_every:
                self._checkpoint_locked()
        finally:
            self._ckpt_lock.release()

    def checkpoint_now(self, wait: bool = False):
        """Unconditional checkpoint (the SIGTERM final flush —
        ``ha.install_signal_flush``).  ``wait=True`` blocks until the file
        is durably on disk."""
        if self._ckpt is None:
            return
        with self._ckpt_lock:
            self._checkpoint_locked()
        if wait:
            self._ckpt.wait()

    def adopt_ha_meta(self, meta: dict | None):
        """Adopt a restored checkpoint's HA metadata and take over as the
        NEXT center epoch (promotion).  Call after ``init_server`` with
        the restored center — the stripe plan must exist so the per-cid
        applied-seq ledgers can be validated against it; a ledger cut for
        a different plan degrades to the at-most-once sentinel (the
        replay is skipped, never double-applied)."""
        meta = meta or {}
        try:
            self.epoch = int(meta.get("epoch", 0)) + 1
        except (TypeError, ValueError):
            self.epoch = 1
        # resume the restored sync count: checkpoint filenames are keyed
        # by it, and a promoted center restarting at 0 would leave the
        # dead primary's higher-numbered files winning latest_step —
        # the NEXT promotion would then restore pre-failover state
        try:
            self._sync_total = max(self._sync_total,
                                   int(meta.get("step", 0)))
        except (TypeError, ValueError):
            pass
        self._ckpt_count = self.syncs_completed
        n = len(self.stripes) if self.stripes else 1
        for key, val in (meta.get("applied_seq") or {}).items():
            try:
                cid = int(key)
            except (TypeError, ValueError):
                continue
            if cid not in self.members:
                # a joiner cid from the dead center: its ephemeral
                # dedicated listener is gone, so it cannot rejoin here —
                # it re-enters through a fresh Join? (docs/ELASTIC.md)
                continue
            if (isinstance(val, list) and len(val) == n
                    and all(isinstance(v, int) for v in val)):
                self._applied_seq[cid] = list(val)
            else:
                self._applied_seq[cid] = [_SEQ_INF] * n
        obs.counter("center_ckpt_restores_total",
                    "center checkpoints restored (promotions)").inc()
        return self

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._ckpt is not None:
            try:
                self._ckpt.wait()   # surface (don't lose) a failed write
            except Exception as e:  # noqa: BLE001 — close never raises
                print_server(f"final checkpoint flush failed: {e!r}")
        self.broadcast.close()
        for s in self.dedicated_servers.values():
            s.close()
        for ep in self.shard_endpoints:
            ep.close()
        if self.test_server:
            self.test_server.close()


class AsyncEAServerConcurrent(AsyncEAServer):
    """Concurrent parameter-server: same wire protocol (clients and testers
    connect unchanged), but handshakes for different clients OVERLAP — the
    north-star scaling the reference's one-at-a-time critical section
    (lua/AsyncEA.lua:163-177) rules out.

    Structure: a dispatcher thread drains ``Enter?`` requests from the
    broadcast channel and routes a token to the requesting client's worker
    thread; each worker owns that client's dedicated channel exclusively
    (the framed transport separates channels, so streams never interleave)
    and runs the full center-down/delta-up handshake concurrently with the
    other workers.  The center itself stays atomic: workers SNAPSHOT it
    under a lock (then stream without blocking appliers) and APPLY deltas
    under the same lock — a client never receives a torn center, and
    ``center += delta`` remains serialized.  Relaxation vs the serial
    server: two overlapping clients may both fetch the pre-update center
    and push deltas computed against it — the standard stale-gradient
    asynchrony EASGD is built to tolerate (arXiv:1412.6651 §4), traded for
    N-way IO overlap.

    ``pin_device`` pins the center on a jax device with a jitted donated
    ``center += delta`` apply (the BASELINE.json north-star "one-sided
    update against a pinned center replica"); host numpy otherwise.
    Note: worth it when the accelerator is locally attached — on a
    remote-tunneled chip the per-sync device round trip dominates.
    """

    def __init__(self, host: str, port: int, num_nodes: int,
                 with_tester: bool = False, accept_timeout: float = 120.0,
                 handshake_timeout: float | None = 30.0,
                 pin_device=None, rejoin_grace: float = 10.0,
                 shards: int = 1, throttle_bps: float | None = None,
                 standby: bool = False, elastic: bool = False,
                 centers: list[tuple[str, int]] | None = None):
        super().__init__(host, port, num_nodes, with_tester=with_tester,
                         accept_timeout=accept_timeout,
                         handshake_timeout=handshake_timeout,
                         shards=shards, throttle_bps=throttle_bps,
                         standby=standby, elastic=elastic,
                         centers=centers)
        # How long the dispatcher keeps polling for a Rejoin? after every
        # broadcast conn has closed WHILE somebody is evicted — bounded so
        # a permanently-dead evictee cannot hold up shutdown/drained.
        self.rejoin_grace = float(rejoin_grace)
        import queue
        import threading
        self._lock = threading.Lock()
        # serializes APPLIERS (the center += delta semantics stay ordered)
        # separately from the pointer lock, so snapshot readers never wait
        # behind an O(P) apply — they grab the current immutable center
        # list under self._lock in O(1)
        self._apply_lock = threading.Lock()
        # per-cid token queues (growable: a Join? adds an entry under
        # self._lock, a Leave? pops it after sentinelling the worker out)
        self._queues: dict[int, Any] = {
            cid: queue.Queue() for cid in range(1, num_nodes + 1)}
        # (cid, stripe) -> token queue for the stripe workers (stripes
        # 1..S-1; stripe 0 rides the main worker), filled in start()
        self._shard_queues: dict[tuple[int, int], Any] = {}
        # per-stripe applier locks (host path): slice updates on different
        # stripes must not serialize behind one _apply_lock.  Kept in a
        # list so each stripe's lock is its own node; sized in init_server
        # once the stripe plan exists.
        self._stripe_locks: list = []
        # per-client connection generation (ADVICE r5 stale-token race):
        # bumped on every eviction AND every readmit under self._lock;
        # queue tokens carry the generation they were issued against and
        # workers discard mismatches — a token from before an evict/rejoin
        # cycle must never drive a handshake on the fresh connection
        self._conn_gen: dict[int, int] = {
            cid: 0 for cid in range(1, num_nodes + 1)}
        self._threads: list = []
        self._workers: dict[int, Any] = {}
        self._stop = threading.Event()
        self._dispatch_closed = threading.Event()
        self._inflight = 0
        # per-cid slice of _inflight (same lock holds): the Leave? flush
        # must wait out the leaver's in-flight legs before reading the
        # ledger, or the replay would double-apply a stripe a worker is
        # still applying
        self._inflight_cid: dict[int, int] = {}
        self._sync_count = 0
        self._device = pin_device
        self._dev_center = None
        self._dev_apply = None
        # fused device applies for undecoded wire payloads, cached by the
        # frame's per-leaf encoding signature (shapes retrace within one
        # jit as usual) — int8 deltas cross H2D at wire width (4x fewer
        # bytes than the decoded f32 the numpy path would ship)
        self._dev_wire_fns: dict[tuple, Any] = {}
        # mirrors _inflight (same lock holds) so /metrics and /healthz see
        # the dispatcher's view without taking the dispatcher lock
        self._g_inflight = obs.gauge(
            "async_ea_inflight", "sync handshakes currently in flight")
        # set by start()/stop(); the chaos soak asserts it returns to 0 so
        # repeated restart cycles provably don't accumulate threads
        self._g_threads = obs.gauge(
            "async_ea_server_threads",
            "live dispatcher/worker threads of this server")

    # -- center storage ------------------------------------------------------
    #
    # Host path: the center is an IMMUTABLE published version — every apply
    # builds fresh leaves (one fused ``t + d`` pass, no astype copy) and
    # swaps the list pointer under the lock.  Snapshots are therefore a
    # pointer grab, not the O(P) memcpy-under-lock the r3 profile showed
    # dominating 100 MB-scale syncs; workers stream straight from the
    # frozen arrays.  Published leaves are marked read-only so a caller
    # mutating ``current_center``'s result fails loudly instead of
    # corrupting what concurrent workers are streaming.
    def init_server(self, params: PyTree):
        import threading
        super().init_server(params)
        self._stripe_locks = [threading.Lock() for _ in self.stripes]
        if self._device is not None:
            self._pin()
        else:
            if len(self.stripes) > 1:
                # striped: the PUBLISHED list is the virtual chunk view —
                # two stripes may own chunks of the same real leaf, and
                # publishing whole real leaves would let their rebuilds
                # race (last writer drops the other's chunk).  Real
                # leaves are stitched back on demand in _snapshot.
                self.center = self._vcenter
            for t in self.center:
                t.flags.writeable = False

    def _pin(self):
        """Move the center to the device; build the donated fused apply.
        Device leaves mirror the published layout: the VIRTUAL list when
        striped (chunk slices update independently), real otherwise."""
        self._dev_center = [jax.device_put(t, self._device)
                            for t in self._vcenter]

        def _apply(center, deltas):
            return [c + d.astype(c.dtype) for c, d in zip(center, deltas)]

        self._dev_apply = jax.jit(_apply, donate_argnums=(0,))

    def _dev_wire_apply(self, center: list, payload: "wire.PackedPayload"
                        ) -> list:
        """Donated fused apply of an UNDECODED payload onto device leaves:
        wire-dtype buffers go H2D as-is and dequantize on device, so the
        host never materializes (or ships) the decoded f32 copy.  The jit
        is cached per encoding signature; scales ride as scalar args (no
        retrace per sync)."""
        entries = payload.manifest["leaves"]
        key = tuple(e["enc"] for e in entries)
        fn = self._dev_wire_fns.get(key)
        if fn is None:
            def _apply(cs, bs, ss, _encs=key):
                out = []
                for c, b, s, enc in zip(cs, bs, ss, _encs):
                    d = b.astype(c.dtype)
                    if enc == "int8":
                        d = d * s.astype(c.dtype)
                    out.append(c + d)
                return out
            fn = self._dev_wire_fns[key] = jax.jit(_apply,
                                                   donate_argnums=(0,))
        put = [jax.device_put(b, self._device) for b in payload.bufs]
        scales = [np.asarray(e.get("scale", 1.0)) for e in entries]
        return fn(center, put, scales)

    def _snapshot_v(self) -> list[np.ndarray]:
        """The published (possibly virtual) leaf list — what stripe legs
        stream from."""
        with self._lock:
            if self._dev_center is not None:
                return [np.asarray(jax.device_get(t))
                        for t in self._dev_center]
            return self.center      # immutable published version: no copy

    def _snapshot(self) -> list[np.ndarray]:
        """REAL-leaf snapshot (tester pushes, rejoin center,
        ``current_center``): split leaves stitch their chunks back."""
        leaves = self._snapshot_v()
        if self.splits is not None and any(p > 1 for p in self.splits):
            leaves = wire.merge_views(
                leaves, self.splits,
                [shape for shape, _ in self._leaf_meta])
        return leaves

    def _apply_delta(self, deltas: list[np.ndarray],
                     ha: tuple[int, int] | None = None):
        t0 = time.perf_counter() if self._obs_on else 0.0
        payload = deltas if isinstance(deltas, wire.PackedPayload) else None
        if self._dev_center is not None:
            if payload is not None and len(self._stripe_locks) <= 1:
                # fused device apply straight from wire bytes
                with self._lock:
                    self._dev_center = self._dev_wire_apply(
                        self._dev_center, payload)
                    self._sync_count += 1
                    if ha is not None:
                        for idx in range(len(self.stripes)):
                            self._record_applied(ha[0], idx, ha[1])
                if self._obs_on:
                    self._h_center_apply.labels(shard="all").observe(
                        time.perf_counter() - t0)
                self._c_syncs.inc()
                if self._obs_on:
                    self._h_apply.observe(time.perf_counter() - t0)
                return
            if payload is not None:
                # striped device center wants the VIRTUAL re-cut of real
                # leaves — decode once (rare: unsharded client against a
                # striped pinned server) and fall through
                deltas = payload.decoded()
            if len(self._stripe_locks) > 1:
                # device leaves follow the virtual layout when striped
                deltas = wire.split_views(deltas, self.splits)
            with self._lock:
                self._dev_center = self._dev_apply(
                    self._dev_center,
                    [jax.device_put(d, self._device) for d in deltas])
                self._sync_count += 1
                if ha is not None:      # whole tree = every stripe applied
                    for idx in range(len(self.stripes)):
                        self._record_applied(ha[0], idx, ha[1])
        elif len(self._stripe_locks) > 1:
            # striped center: route the whole-list delta (legacy clients /
            # the serial API) through the per-stripe appliers — a
            # whole-list rebuild-and-swap here would lose a concurrent
            # sharded client's slice publish.  The wire carried REAL
            # leaves; re-cut them to the virtual layout the stripes index
            # (an undecoded payload decodes first — rare path: unsharded
            # client against a striped concurrent server).
            if payload is not None:
                deltas = payload.decoded()
            vdeltas = wire.split_views(deltas, self.splits)
            with self._apply_lock:   # whole-list appliers stay ordered
                for idx, (lo, hi) in enumerate(self.stripes):
                    self._apply_stripe(idx, vdeltas[lo:hi], ha=ha)
            with self._lock:
                self._sync_count += 1
        else:
            with self._apply_lock:  # appliers serialize; readers do not wait
                if payload is not None:
                    # fused immutable publish: fresh leaf = t + dequant(b)
                    # in one pass, never a decoded intermediate
                    new = []
                    for t, entry, buf in zip(self.center,
                                             payload.manifest["leaves"],
                                             payload.bufs):
                        if entry["enc"] == "raw":
                            new.append(t + buf)
                        else:
                            new.append(wire_kernels.dequant_add(
                                t, buf, entry.get("scale")))
                    if self._obs_on:
                        self._h_center_apply.labels(shard="all").observe(
                            time.perf_counter() - t0)
                else:
                    new = [t + d for t, d in zip(self.center, deltas)]
                for t in new:
                    t.flags.writeable = False
                with self._lock:
                    self.center = new
                    self._sync_count += 1
                    if ha is not None:
                        self._record_applied(ha[0], 0, ha[1])
        self._c_syncs.inc()
        if self._obs_on:
            self._h_apply.observe(time.perf_counter() - t0)

    def _stripe_center(self, lo: int, hi: int) -> list[np.ndarray]:
        return self._snapshot_v()[lo:hi]

    def _apply_stripe(self, idx: int, deltas: list[np.ndarray],
                      ha: tuple[int, int] | None = None):
        """Slice apply with immutable publish: build fresh read-only
        leaves for the stripe under ITS lock (appliers on different
        stripes run concurrently — the tentpole's point), then swap them
        into a copy of the published list under the pointer lock, so
        snapshot readers stay O(1) and never see a torn slice.  The
        exactly-once ledger entry rides the SAME pointer-lock hold as the
        publish — a checkpoint snapshot can never see a published slice
        without its ledger entry or vice versa."""
        lo, hi = self.stripes[idx]
        t0 = time.perf_counter() if self._obs_on else 0.0
        payload = deltas if isinstance(deltas, wire.PackedPayload) else None
        if self._dev_center is not None:
            if payload is not None:
                with self._lock:
                    self._dev_center[lo:hi] = self._dev_wire_apply(
                        self._dev_center[lo:hi], payload)
                    if ha is not None:
                        self._record_applied(ha[0], idx, ha[1])
                if self._obs_on:
                    self._h_center_apply.labels(shard=idx).observe(
                        time.perf_counter() - t0)
                    self._h_shard_apply.labels(shard=idx).observe(
                        time.perf_counter() - t0)
                return
            put = [jax.device_put(d, self._device) for d in deltas]
            with self._lock:
                self._dev_center[lo:hi] = self._dev_apply(
                    self._dev_center[lo:hi], put)
                if ha is not None:
                    self._record_applied(ha[0], idx, ha[1])
        else:
            stripe_locks = self._stripe_locks
            with stripe_locks[idx]:
                # entries [lo, hi) only change under this stripe's lock,
                # so reading them outside the pointer lock is stable
                if payload is not None:
                    # fused immutable publish, straight from wire bytes
                    new = []
                    for t, entry, buf in zip(self.center[lo:hi],
                                             payload.manifest["leaves"],
                                             payload.bufs):
                        if entry["enc"] == "raw":
                            new.append(t + buf)
                        else:
                            new.append(wire_kernels.dequant_add(
                                t, buf, entry.get("scale")))
                    if self._obs_on:
                        self._h_center_apply.labels(shard=idx).observe(
                            time.perf_counter() - t0)
                else:
                    new = [t + d
                           for t, d in zip(self.center[lo:hi], deltas)]
                for t in new:
                    t.flags.writeable = False
                with self._lock:
                    pub = list(self.center)
                    pub[lo:hi] = new
                    self.center = pub
                    if ha is not None:
                        self._record_applied(ha[0], idx, ha[1])
        if self._obs_on:
            self._h_shard_apply.labels(shard=idx).observe(
                time.perf_counter() - t0)

    def _count_sync(self):
        with self._lock:
            self._sync_count += 1
        self._c_syncs.inc()

    @property
    def syncs_completed(self) -> int:
        with self._lock:
            return self._sync_count

    def adopt_ha_meta(self, meta: dict | None):
        out = super().adopt_ha_meta(meta)
        with self._lock:
            self._sync_count = max(self._sync_count, self._sync_total)
        self._ckpt_count = self.syncs_completed
        return out

    def _ha_state(self) -> tuple[int, list[np.ndarray], dict]:
        """Consistent HA snapshot: center pointer, applied-seq ledger,
        epoch, and step all under ONE ``_lock`` hold (each apply publishes
        its slice and its ledger entry in that same hold, so the tuple is
        mutually consistent by construction — a torn checkpoint taken
        mid-sync restores and replays only the genuinely missing
        stripes).  The stitch of split leaves runs outside the lock: the
        grabbed leaves are immutable published versions."""
        with self._lock:
            if self._dev_center is not None:
                leaves = [np.asarray(jax.device_get(t))
                          for t in self._dev_center]
            else:
                leaves = self.center
            seqs = {str(c): list(s) for c, s in self._applied_seq.items()}
            epoch = self.epoch
            step = self._sync_count
        if self.splits is not None and any(p > 1 for p in self.splits):
            leaves = wire.merge_views(
                leaves, self.splits,
                [shape for shape, _ in self._leaf_meta])
        meta = {"epoch": epoch, "applied_seq": seqs,
                "wire": {str(c): v for c, v in self._wire_cid.items()},
                "shards": self.shards, "num_nodes": self.num_nodes}
        return step, leaves, meta

    @property
    def drained(self) -> bool:
        """True once no further syncs can arrive: every broadcast channel
        has closed (the dispatcher exited) and no handshake is in flight —
        the concurrent counterpart of the serial loop's
        RuntimeError-from-recv_any stop condition (a serve loop polling
        ``syncs_completed`` must also stop on this, or finished clients
        would leave it spinning forever)."""
        if not self._dispatch_closed.is_set():
            return False
        with self._lock:
            inflight = self._inflight
        return (inflight == 0
                and all(q.empty() for q in self._queues.values())
                and all(q.empty() for q in self._shard_queues.values()))

    def current_center(self, params: PyTree) -> PyTree:
        """Snapshot of the center as a pytree shaped like ``params``."""
        return _rebuild(params, self._snapshot())

    def test_net(self, tensors: list[np.ndarray] | None = None) -> bool:
        """Tester push from an atomic snapshot (the live host list may be
        mid-apply on a worker thread; the device copy is authoritative when
        pinned).  The snapshot is passed down explicitly — NEVER by
        swapping ``self.center``, which a concurrent ``_apply_delta``
        iterates."""
        if self.test_conn is None:
            return False
        return super().test_net(tensors if tensors is not None
                                else self._snapshot())

    def _evict(self, cid: int, why: Exception):
        """Concurrent eviction: mark + drain the client's token queue under
        the SAME lock the dispatcher enqueues under, so no token can land
        after the drain — otherwise a token issued in the
        admit-then-enqueue window would never be consumed, ``_inflight``
        would leak, and ``drained`` could never become true (ADVICE r3
        TOCTOU)."""
        with self._lock:
            self._evict_locked(cid, why)

    def _evict_locked(self, cid: int, why: Exception):
        """Eviction body; caller holds ``self._lock`` (the worker's
        stale-conn check needs check+evict ATOMIC against a concurrent
        rejoin's state flip — two separate acquisitions let a rejoin land
        in between and get its fresh conn closed by a stale decision).
        Idempotent per eviction cycle: a sharded sync fails on every leg
        at once (the first leg's eviction closes the other legs' conns),
        and only the FIRST decision may bump the generation, count, and
        drain — the dispatcher cannot enqueue for an evicted cid, so
        there is nothing new to drain on re-entry."""
        if cid in self.evicted:
            return
        import queue as _q
        self._conn_gen[cid] = self._conn_gen.get(cid, 0) + 1
        #                               ^ stale tokens die at the worker
        super()._evict(cid, why)
        for q in ([q for q in (self._queues.get(cid),) if q is not None]
                  + [sq for (qcid, _), sq in self._shard_queues.items()
                     if qcid == cid]):
            while True:
                try:
                    token = q.get_nowait()
                except _q.Empty:
                    break
                if token is not None:     # the None stop sentinel never
                    self._dec_inflight_locked(cid)  # incremented _inflight

    def _dec_inflight_locked(self, cid: int, n: int = 1):
        """Settle ``n`` of ``cid``'s in-flight leg slots; caller holds
        ``self._lock`` (the per-cid table and the global count must move
        together — ``_wait_cid_idle`` reads both)."""
        self._inflight -= n
        self._g_inflight.dec(n)
        left = self._inflight_cid.get(cid, 0) - n
        if left > 0:
            self._inflight_cid[cid] = left
        else:
            self._inflight_cid.pop(cid, None)

    def _delta_weight(self, cid: int) -> float:
        # workers read the membership set concurrently with dispatcher
        # join/leave mutations — snapshot under the lock (no recursion:
        # every caller applies deltas unlocked)
        with self._lock:
            return super()._delta_weight(cid)

    # -- threads -------------------------------------------------------------
    def _health(self) -> dict:
        """The ``/healthz`` payload (obs.export): liveness an external
        prober needs to tell serving from draining from dead.  Reads are
        lock-free — telemetry tolerates a torn view."""
        return {"live_clients": self.live_clients,
                "inflight": self._inflight,
                "drained": self.drained}

    def start(self):
        """Spawn the dispatcher, one main worker per client, and — when
        the center is striped — one stripe worker per (client, stripe>0).
        Returns self."""
        import queue
        import threading
        if self.shards > 1 and self.stripes is None:
            raise RuntimeError(
                "init_server must run before start on a sharded server: "
                "the stripe plan sizes the stripe workers")
        obs.set_health_source(self._health)
        self._threads = [threading.Thread(target=self._dispatch, daemon=True)]
        self._workers = {
            cid: threading.Thread(target=self._worker, args=(cid,),
                                  daemon=True)
            for cid in sorted(self.members)}
        self._threads += list(self._workers.values())
        if self.stripes is not None and len(self.stripes) > 1:
            for cid in sorted(self.members):
                for idx in range(1, len(self.stripes)):
                    self._shard_queues[(cid, idx)] = queue.Queue()
                    self._threads.append(threading.Thread(
                        target=self._shard_worker, args=(cid, idx),
                        daemon=True))
        for t in self._threads:
            t.start()
        self._g_threads.set(len(self._threads))
        return self

    def stop(self, deadline: float = 10.0):
        """Stop the dispatcher and every worker: sentinel all queues, join
        with a SHARED deadline across the whole thread set, and — if any
        thread is still alive (blocked in socket IO past its own timeout)
        — close the server's sockets so the blocked call fails fast, then
        join once more.  Repeated start/stop cycles (the chaos soak's
        kill/promote loop) must not accumulate threads or fds; the
        surviving count is published on ``async_ea_server_threads`` so the
        soak can assert it returns to zero."""
        self._stop.set()
        for q in list(self._queues.values()):
            q.put(None)
        for q in self._shard_queues.values():
            q.put(None)
        end = time.monotonic() + deadline
        for t in self._threads:
            t.join(timeout=max(0.0, end - time.monotonic()))
        if any(t.is_alive() for t in self._threads):
            # escalation: a thread wedged in recv/accept holds its socket;
            # closing every listener/conn surfaces an error in the blocked
            # call and the thread exits through its normal handler
            self.close()
            end = time.monotonic() + deadline
            for t in self._threads:
                if t.is_alive():
                    t.join(timeout=max(0.0, end - time.monotonic()))
        self._threads = [t for t in self._threads if t.is_alive()]
        self._workers = {cid: t for cid, t in self._workers.items()
                         if t.is_alive()}
        if not self._threads:
            # legs dispatched but never settled die with their workers;
            # release this server's contribution to the (shared) gauge
            # or a killed-mid-sync center leaves it stranded nonzero
            with self._lock:
                if self._inflight:
                    self._g_inflight.dec(self._inflight)
                    self._inflight = 0
                self._inflight_cid.clear()
        self._g_threads.set(len(self._threads))
        obs.set_health_source(None)

    def _rejoin_grace_poll(self) -> bool:
        """True once a re-connection landed (a fresh broadcast conn is
        open); False when the grace expires or the server is stopping."""
        deadline = time.monotonic() + self.rejoin_grace
        while time.monotonic() < deadline and not self._stop.is_set():
            self._accept_rejoiners()
            if any(c.sock.fileno() >= 0 for c in self.broadcast.conns):
                return True
            time.sleep(0.05)
        return False

    def _dispatch(self):
        try:
            self._dispatch_loop()
        finally:
            self._dispatch_closed.set()

    def _prune_broadcast(self):
        with self._lock:        # workers read the cid map during eviction
            super()._prune_broadcast()

    def _rejoin_center(self) -> list[np.ndarray]:
        return self._snapshot()

    def _finish_readmit(self, cid: int, idx: int, conn: Conn):
        """Re-admit and make sure the client has a live worker.  A worker
        that evicted its OWN client has returned and needs a respawn; a
        worker whose client was evicted by the DISPATCHER (frame-timeout /
        reset on the broadcast conn) is still parked on the queue — it
        re-reads ``self.dedicated[cid-1]`` per token, so it serves the
        fresh channel as-is and spawning a second worker on the same
        queue would race it.  State flips under the dispatcher lock —
        _admit's evicted re-check and the queue-drain in _evict both run
        under it."""
        import threading
        with self._lock:
            # fresh connection, fresh generation: tokens issued against
            # the pre-eviction conn still in flight anywhere must not
            # drive a handshake on this one
            self._conn_gen[cid] = self._conn_gen.get(cid, 0) + 1
            super()._finish_readmit(cid, idx, conn)
            # a worker that self-evicted DEREGISTERED itself in the same
            # lock hold as its eviction, so presence here means parked
            # and serviceable (is_alive() alone races the exiting thread)
            need = self._workers.get(cid) is None
            if need:
                t = threading.Thread(target=self._worker, args=(cid,),
                                     daemon=True)
                self._workers[cid] = t
                # drop exited threads while appending: a flaky client
                # cycling evict->rejoin must not grow this list forever
                self._threads = [th for th in self._threads
                                 if th.is_alive()] + [t]
        if need:
            t.start()

    # -- elastic membership (concurrent overrides) ---------------------------
    def _register_member(self, cid: int, idx: int, conn: Conn,
                         ded: Server, *, capacity: float,
                         codec: str | None, sharded: bool) -> None:
        """Roster install + the joiner's serving threads: token queue,
        generation slot, main worker, and (striped) one shard queue +
        worker per stripe — all created under the dispatcher lock so an
        Enter? racing the join either sees the whole kit or none of it."""
        import queue
        import threading
        with self._lock:
            super()._register_member(cid, idx, conn, ded,
                                     capacity=capacity, codec=codec,
                                     sharded=sharded)
            self._conn_gen.setdefault(cid, 0)
            self._queues[cid] = queue.Queue()
            t = threading.Thread(target=self._worker, args=(cid,),
                                 daemon=True)
            self._workers[cid] = t
            spawn = [t]
            if self.stripes is not None and len(self.stripes) > 1:
                for s in range(1, len(self.stripes)):
                    self._shard_queues[(cid, s)] = queue.Queue()
                    spawn.append(threading.Thread(
                        target=self._shard_worker, args=(cid, s),
                        daemon=True))
            # drop exited threads while appending (same hygiene as the
            # rejoin respawn): churn must not grow this list forever
            self._threads = [th for th in self._threads
                             if th.is_alive()] + spawn
        for t in spawn:
            t.start()
        self._g_threads.set(len(self._threads))

    def _remove_member(self, cid: int) -> None:
        """Retire the cid AND its serving threads: bump the generation
        (stale tokens die), drain + sentinel its queues so the parked
        workers exit, and pop the per-cid state — all under the
        dispatcher lock, so nothing can enqueue into a dying queue."""
        import queue as _q
        with self._lock:
            self._conn_gen[cid] = self._conn_gen.get(cid, 0) + 1
            qs = [q for q in (self._queues.pop(cid, None),)
                  if q is not None]
            for key in [k for k in self._shard_queues if k[0] == cid]:
                qs.append(self._shard_queues.pop(key))
            for q in qs:
                while True:
                    try:
                        token = q.get_nowait()
                    except _q.Empty:
                        break
                    if token is not None:
                        self._dec_inflight_locked(cid)
                q.put(None)         # unpark + retire the worker
            self._workers.pop(cid, None)
            self._conn_gen.pop(cid, None)
            super()._remove_member(cid)

    def _wait_cid_idle(self, cid: int, timeout: float) -> bool:
        """Wait out the cid's in-flight legs (bounded).  New tokens for
        this cid cannot land meanwhile — the dispatcher is the only
        enqueuer and it is the thread sitting here."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                q = self._queues.get(cid)
                idle = (self._inflight_cid.get(cid, 0) == 0
                        and (q is None or q.empty()))
            if idle:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def _dispatch_loop(self):
        while not self._stop.is_set():
            self._accept_rejoiners()
            try:
                idx, msg = self.broadcast.recv_any(
                    timeout=0.5, frame_timeout=self.handshake_timeout,
                    on_drop=self._evict_dropped)
            except TimeoutError:
                continue
            except RuntimeError:
                # every broadcast conn closed.  With nobody evicted that
                # is terminal (all clients finished) — dispatch is done.
                # With an evicted client a Rejoin? can still arrive on
                # the listening socket: poll for one for a bounded grace
                # before giving up.  But judge evictions only AFTER any
                # in-flight handshake settles: a client crashing with a
                # clean FIN closes its broadcast conn (seen here first)
                # while its worker is still mid-handshake on the other
                # channels — returning on the instantaneous empty
                # ``evicted`` would kill dispatch moments before that
                # worker's eviction lands, making rejoin impossible.
                if self.elastic:
                    # an elastic fleet legitimately drains to zero (all
                    # left) and grows again: keep polling the listener
                    # for the next Join?/Rejoin? until stopped
                    self._accept_rejoiners()
                    time.sleep(0.05)
                    continue
                deadline = time.monotonic() + (self.handshake_timeout
                                               or 30.0)
                while time.monotonic() < deadline and not self.evicted:
                    with self._lock:
                        if self._inflight == 0:
                            break
                    time.sleep(0.01)
                if not self.evicted or not self._rejoin_grace_poll():
                    return
                continue
            except (ConnectionError, OSError, ValueError):
                # a worker EVICTING its client closes that client's
                # broadcast conn while this thread is blocked in select on
                # it — EBADF/negative-fd surfaces here.  That is one dead
                # conn, not the end of dispatch: keep serving the others
                # (exiting here orphaned the live clients' Enter? requests
                # — observed as a full-suite wedge)
                continue
            self._note_spoke(idx)
            if isinstance(msg, dict) and msg.get("q") == REJOIN_Q:
                # rejoin handshakes are rare; blocking dispatch for one
                # bounded (handshake_timeout) center push is acceptable
                self._readmit(idx, msg)
                continue
            if isinstance(msg, dict) and msg.get("q") == JOIN_Q:
                # same rarity argument as rejoin: the join adoption is
                # one bounded center push on the dispatcher thread
                self._handle_join(idx, msg)
                continue
            if isinstance(msg, dict) and msg.get("q") == LEAVE_Q:
                self._handle_leave(idx, msg)
                continue
            cid = self._admit(idx, msg)
            if cid is None:
                continue
            with self._lock:
                # re-check under the lock: the client's worker may have
                # evicted it (and drained its queue) since _admit's
                # unlocked check — enqueueing now would leak the token
                if cid in self.evicted:
                    continue
                q = self._queues.get(cid)
                if q is None:
                    continue            # left between _admit and here
                # tokens carry the connection generation they were issued
                # against; every leg settles its own _inflight slot
                gen = self._conn_gen.get(cid, 0)
                sharded = (self._shard_cid.get(cid, False)
                           and bool(self._shard_queues))
                n_legs = len(self.stripes) if sharded else 1
                self._inflight += n_legs
                self._g_inflight.inc(n_legs)
                self._inflight_cid[cid] = \
                    self._inflight_cid.get(cid, 0) + n_legs
                q.put(gen)
                if sharded:
                    for idx in range(1, len(self.stripes)):
                        self._shard_queues[(cid, idx)].put(gen)

    def _worker(self, cid: int):
        bufs = None     # reusable delta recv buffers (host path): no 100 MB
        #                 allocation + page-fault pass per sync
        # the queue is captured once: a graceful leave pops the dict entry
        # and sentinels THIS queue, so the parked thread still drains it
        q = self._queues.get(cid)
        if q is None:
            return
        while not self._stop.is_set():
            token = q.get()
            if token is None:
                return
            # re-read per token: a rejoin swaps the dedicated conn while
            # this thread is parked on the queue (dispatcher-side
            # evictions never unpark it).  The generation check rides the
            # same lock hold so conn/codec/sharded are all from the same
            # connection epoch as the token.
            with self._lock:
                stale = token != self._conn_gen.get(cid, 0)
                conn = self.dedicated.get(cid)
                codec = self._wire_cid.get(cid)
                sharded = self._shard_cid.get(cid, False)
                # the claimed seq rides the same hold as conn/codec, so it
                # is from the same admission as the token — a faster next
                # admission overwriting _sync_seq cannot skew this sync's
                # ledger entry
                seq = self._sync_seq.get(cid)
                tc = self._trace_cid.get(cid)   # same-admission context
                if conn is None:
                    stale = True
                if stale:
                    self._dec_inflight_locked(cid)
            if stale:
                continue
            t0 = time.perf_counter() if self._obs_on else 0.0
            try:
                try:
                    with obs_trace.use_context(tc), \
                            obs.span("async_ea.handshake", cid=cid):
                        conn.set_timeout(self.handshake_timeout)
                        conn.send_msg(self._enter_reply(cid, ENTER))
                        if sharded:
                            # stripe 0 only — stripes 1.. run on their own
                            # workers against the shard endpoints,
                            # concurrently with this leg
                            deltas = self._serve_stripe_leg(conn, 0, codec)
                            conn.set_timeout(None)
                        else:
                            _expect(conn, CENTER_Q)
                            # stream OUTSIDE the lock; one packed frame on
                            # a negotiated wire
                            conn.send_tensors(self._snapshot(),
                                              codec=codec or "raw",
                                              packed=codec is not None)
                            _expect(conn, DELTA_Q)
                            conn.send_msg(DELTA)
                            # whole-delta-stream deadline: see sync_server
                            dl = (None if self.handshake_timeout is None
                                  else time.monotonic()
                                  + self.handshake_timeout)
                            if (self._wirek
                                    and codec not in (None, "raw")):
                                # fused wire path: the delta stays in
                                # wire dtype until the apply dequantizes
                                # it (device path: H2D at wire width)
                                deltas = conn.recv_payload(
                                    n=len(self._leaf_meta), deadline=dl)
                            elif self._dev_center is None:
                                if bufs is None:
                                    # REAL leaf layout: a legacy client's
                                    # delta is per-leaf whatever the
                                    # published (virtual) center looks like
                                    bufs = [np.empty(shape, dtype)
                                            for shape, dtype
                                            in self._leaf_meta]
                                # recv_tensors(out=...) itself rejects
                                # shape/dtype skew (ProtocolError ->
                                # eviction below) and auto-detects packed
                                # vs per-leaf frames
                                deltas = conn.recv_tensors(out=bufs,
                                                           deadline=dl)
                            else:
                                deltas = conn.recv_tensors(
                                    n=len(self._leaf_meta), deadline=dl)
                            self._check_delta(deltas)   # before ANY apply:
                            # a config-skewed client is an eviction, never
                            # a torn or silently-dead worker (the serve
                            # loop polls drained)
                            conn.set_timeout(None)
                except (TimeoutError, ConnectionError, ProtocolError,
                        OSError, ValueError) as e:
                    # only evict if OUR conn is still the client's current
                    # channel — failing on a conn a rejoin already
                    # replaced must not evict the re-admitted client.
                    # Check + evict + deregister under ONE lock hold: a
                    # rejoin flipping the conn between them would get its
                    # fresh channel closed by the stale decision, and a
                    # rejoin landing between the evict and this thread's
                    # exit would see is_alive()==True and skip the
                    # respawn, stranding the client's tokens forever.
                    with self._lock:
                        current = self.dedicated.get(cid) is conn
                        if current:
                            self._evict_locked(cid, e)  # drains queue too
                            self._workers.pop(cid, None)
                    if current:
                        return
                    continue                   # stale-conn failure: park
                if self._obs_on:
                    self._h_handshake.observe(time.perf_counter() - t0)
                ha = (cid, seq) if seq is not None else None
                deltas = self._scale_delta(deltas, self._delta_weight(cid))
                if sharded:
                    self._apply_stripe(0, deltas, ha=ha)
                    self._count_sync()
                else:
                    self._apply_delta(deltas, ha=ha)  # full delta, atomic
                self._maybe_checkpoint()
            finally:
                with self._lock:
                    self._dec_inflight_locked(cid)

    def _shard_worker(self, cid: int, idx: int):
        """Serve stripe ``idx`` (>= 1) of one client's syncs, forever.

        Unlike the main worker this thread never exits on eviction: tokens
        are generation-stamped, so anything enqueued before an eviction or
        rejoin is discarded here by a cheap integer compare, and the
        thread simply parks for the client's next admission.  That keeps
        the rejoin path free of (num_shards - 1) respawn bookkeeping."""
        ep = self.shard_endpoints[idx - 1]
        # captured once, like _worker: a graceful leave pops the dict entry
        # and sentinels this queue so the parked thread retires itself
        q0 = self._shard_queues.get((cid, idx))
        if q0 is None:
            return
        while not self._stop.is_set():
            token = q0.get()
            if token is None:
                return
            with self._lock:
                stale = token != self._conn_gen.get(cid, 0)
                codec = self._wire_cid.get(cid)
                seq = self._sync_seq.get(cid)   # same hold: same admission
                tc = self._trace_cid.get(cid)
            try:
                if stale:
                    continue
                conn = None
                try:
                    conn = ep.get_conn(cid,
                                       timeout=self.handshake_timeout or 30.0)
                    with self._lock:
                        superseded = token != self._conn_gen.get(cid, 0)
                    if superseded:
                        # superseded while we waited for the dial (an
                        # eviction raced past us): don't serve or judge
                        # the registered conn on a stale token.  If it is
                        # the DEAD admission's socket resurrected from
                        # the listen backlog after the eviction sweep,
                        # reap it — and since its FIN may still be in
                        # flight (the dying client closes its channels
                        # one by one), park as a reaper, polling until
                        # it dies, is superseded by a fresh dial, or the
                        # next admission's token takes over.
                        while (not self._stop.is_set() and q0.empty()
                               and ep.conns.get(cid) is conn):
                            if ep.drop_if_dead(cid, conn):
                                break
                            time.sleep(0.05)
                        continue
                    conn.set_timeout(self.handshake_timeout)
                    with obs_trace.use_context(tc):
                        deltas = self._serve_stripe_leg(conn, idx, codec)
                    conn.set_timeout(None)
                except (TimeoutError, ConnectionError, ProtocolError,
                        OSError, ValueError) as e:
                    # the conn we just failed on is dead: if it is still
                    # the registered channel, drop it NO MATTER the
                    # generation — a leg that registered it after the
                    # first leg's eviction swept the endpoints would
                    # otherwise leak it (the identity check keeps a conn
                    # a rejoin already superseded safe).  Evict only on a
                    # current-generation token: a stale leg tripping over
                    # a socket from a superseded admission must never
                    # evict the re-admitted client.  _evict_locked is
                    # idempotent, so every stripe leg of a dead client
                    # reporting at once is fine.
                    with self._lock:
                        registered = (conn is not None
                                      and ep.conns.get(cid) is conn)
                        if registered:
                            ep.drop(cid)
                        if (token == self._conn_gen.get(cid, 0)
                                and (conn is None or registered)):
                            self._evict_locked(cid, e)
                    continue
                self._apply_stripe(idx,
                                   self._scale_delta(deltas,
                                                     self._delta_weight(cid)),
                                   ha=(cid, seq) if seq is not None else None)
            finally:
                with self._lock:
                    self._dec_inflight_locked(cid)


class _DeltaSender:
    """Depth-1 background sender for the compute/communication overlap
    path: ``submit(job)`` hands the previous round's delta transmit to a
    worker thread and returns immediately, so the next round's τ local
    steps overlap the delta's wire round-trip.  The bounded queue (at most
    ONE in-flight job — ``submit`` flushes the previous one first)
    preserves the EASGD staleness bound: a client can never be more than
    one un-acknowledged delta ahead of the center it last fetched.

    A background failure is stored and re-raised at the next ``flush``
    (the top of the next sync), where the caller's eviction/rejoin
    handling already lives; ``drain`` discards it (the rejoin path is
    about to replace the connection the error came from)."""

    def __init__(self):
        import queue
        import threading
        self._q: Any = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._err: BaseException | None = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            job = self._q.get()
            if job is None:
                self._idle.set()
                return
            try:
                job()
            except BaseException as e:  # noqa: BLE001 — surfaced at flush
                self._err = e
            finally:
                self._idle.set()

    def flush(self):
        """Wait out the in-flight job; re-raise its failure, if any."""
        self._idle.wait()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, job):
        self.flush()            # depth 1: at most one delta in flight
        self._idle.clear()
        self._q.put(job)

    def drain(self):
        """Wait for idle and DISCARD any stored failure (eviction/rejoin
        cleanup — the conn the failure came from is being replaced)."""
        self._idle.wait()
        self._err = None

    def close(self):
        self._idle.wait()
        self._q.put(None)
        self._t.join(timeout=5.0)
        self._err = None


class AsyncEAClient:
    """Worker role (ref initClient/syncClient).

    ``codec`` selects the wire format for the sync handshake: ``"raw"``
    (default) coalesces each direction into one packed frame, ``"fp16"``/
    ``"int8"`` additionally quantize (deltas carry client-side
    error-feedback residuals so the quantization error is re-injected
    into later rounds, 1-bit-SGD style); ``None`` speaks the legacy
    per-leaf wire unconditionally.  The codec is negotiated per handshake
    — against an old server the client silently falls back to the legacy
    frames (the server never sees the advertisement's extra keys).

    ``overlap=True`` pushes each round's delta from a background sender
    (depth-1 queue) so local training overlaps the transmit round-trip;
    failures surface at the NEXT sync, where eviction handling already
    lives.
    """

    def __init__(self, host: str, port: int, node: int, tau: int,
                 alpha: float, codec: str | None = "raw",
                 overlap: bool = False, sharded: bool = True,
                 throttle_bps: float | None = None,
                 centers: list[tuple[str, int]] | None = None,
                 capacity: float = 1.0, adaptive_tau: bool = False,
                 slice_backend=None,
                 _broadcast: Conn | None = None,
                 _dedicated_port: int | None = None):
        if node < 1:
            raise ValueError("node is 1-based (reference convention)")
        if codec is not None and codec not in wire.CODECS:
            raise ValueError(f"unknown wire codec {codec!r} "
                             f"(supported: {', '.join(wire.CODECS)})")
        if capacity <= 0:
            raise ValueError("capacity must be > 0")
        self.node = node
        self.tau = int(tau)
        self.alpha = float(alpha)
        self.codec = codec
        self.capacity = float(capacity)
        # straggler-adaptive τ (docs/ELASTIC.md): stretch the sync period
        # from the observed sync-latency EWMA, never past the α·τ
        # stability product (docs/EA_CONVERGENCE.md) — a slow client syncs
        # less often instead of queueing behind the fleet
        self.adaptive_tau = bool(adaptive_tau)
        self._tau_lo, self._tau_hi = adaptive_tau_bounds(tau, alpha)
        self.tau_effective = self._tau_lo
        self._next_sync = self._tau_lo
        self._lat_ewma: float | None = None
        self._lat_floor: float | None = None
        # sharded=True merely ADVERTISES the capability (alongside the wire
        # codec); the server decides whether to stripe.  False pins the
        # single-channel sync even against a sharded server.
        self.sharded = bool(sharded) and codec is not None
        self.throttle_bps = throttle_bps
        self.step = 0
        self.host, self.port = host, port
        # clientBroadcast -> port; dedicated client -> port+node
        # (EASGD_client.lua:58-61).  A joiner's dedicated channel lives on
        # the ephemeral port the Join reply advertised instead (join()
        # also hands over the already-dialed broadcast conn).
        self._ded_port = _dedicated_port
        self.broadcast = (_broadcast if _broadcast is not None
                          else connect(host, port))
        self.conn = connect(host, port + node if _dedicated_port is None
                            else _dedicated_port)
        if throttle_bps:
            self.conn.throttle_bps = throttle_bps
        self.center: list[np.ndarray] | None = None
        # the "client is a whole pod slice" deployment (ROADMAP item 1):
        # a stacked-value backend (MeshBackend / single-host HybridBackend)
        # reducing this client's L device rows; params carry a leading
        # [L] axis, the center stays wire-shape, and ONE TCP leg pushes
        # the slice-sum delta — equivalent to L plain clients syncing
        # against the same center snapshot, at 1/L the host-leg bytes
        self._slice = slice_backend
        self._slice_rows = 0
        if slice_backend is not None:
            rows = getattr(slice_backend, "stacked_nodes", None)
            if not rows:
                raise ValueError(
                    "slice_backend must be a stacked-value backend "
                    "(stacked_nodes set) — MeshBackend or HybridBackend")
            self._slice_rows = int(rows)
        # None until the first handshake; False pins legacy once a plain-
        # string reply proves the server predates the packed wire
        self._packed: bool | None = None
        self._residuals: list[np.ndarray] | None = None
        self._sender = _DeltaSender() if overlap else None
        # stripe plan pinned from the first sharded Enter reply; conns to
        # shard endpoints (stripes 1..S-1 — stripe 0 rides self.conn).
        # _splits is the per-leaf chunk table the stripe ranges index
        # (sub-leaf striping: wire.plan_splits / wire.split_views).
        self._shard_spec: dict | None = None
        self._stripes: list[tuple[int, int]] | None = None
        self._splits: list[int] | None = None
        self._shard_conns: list[Conn] = []
        # -- HA state (docs/HA.md) -------------------------------------------
        # failover dial list: the primary plus any standby addresses; a
        # center refusing us on the epoch fence is removed permanently
        self._centers: list[tuple[str, int]] = [(host, port)] + [
            (h, int(p)) for h, p in (centers or [])
            if (h, int(p)) != (host, port)]
        self._center_i = 0
        # newest center epoch any reply carried; announced back so a
        # zombie primary refuses us instead of serving stale state
        self._seen_epoch: int | None = None
        # per-sync sequence stamped into Enter?; (_seq, payloads, bounds)
        # of the newest encoded delta is kept until the next sync so a
        # failover rejoin can replay the exact bytes (exactly-once)
        self._seq = 0
        self._pending: tuple[int, list, list] | None = None
        self._last_reply: dict | None = None
        # fused wire path (ops/wire_kernels): resolved once per instance
        # so in-process tests can toggle DISTLEARN_TPU_WIREK per client
        self._wirek = wire_kernels.wirek_enabled()
        # per-stripe reusable staging: frame buffers the fused kernels
        # write wire bytes into (one iovec per send, no per-sync alloc)
        # and decode scratch for the numpy fallback's residual
        self._framebufs: list[wire.FrameBuffer] = []
        self._dec_scratch: dict[int, list[np.ndarray]] = {}
        self._obs_on = obs.enabled()
        self._h_encode = obs.histogram(
            "wire_encode_seconds",
            "one stripe's delta encode (quantize + error-feedback "
            "residual), by stripe", labels=("shard",))
        self._c_redials = obs.counter(
            "async_ea_failover_redials_total",
            "failover re-dial attempts (per candidate center tried)")
        self._c_replays = obs.counter(
            "async_ea_failover_replays_total",
            "rejoin replay outcomes of the pending delta, by outcome",
            labels=("outcome",))
        self._c_stale = obs.counter(
            "async_ea_failover_stale_refusals_total",
            "admissions refused on the epoch fence (stale/zombie center)")
        self._g_tau = obs.gauge(
            "async_ea_adaptive_tau",
            "effective sync period after straggler adaptation, by client",
            labels=("cid",))

    def _announce(self, q: str, want: str) -> bool:
        """Send an admission request (with the wire advertisement unless a
        previous reply proved the server legacy) and parse the reply.
        Returns True when this handshake uses the packed wire."""
        adv = self.codec is not None and self._packed is not False
        msg: dict[str, Any] = {"q": q, "clientID": self.node}
        if adv:
            msg["wire"] = {"v": wire.WIRE_V, "codec": self.codec}
            if self.sharded:
                msg["shard"] = {"v": SHARD_V}
            if self.capacity != 1.0:
                # capacity-weighted EA (docs/ELASTIC.md): an extra key a
                # legacy server never looks at; an elastic one folds it
                # into the delta weight on every admission
                msg["capacity"] = self.capacity
            # epoch fence (docs/HA.md): announce the newest epoch we've
            # synced against so a demoted/zombie center refuses us loudly
            # instead of serving state the fleet has moved past
            if self._seen_epoch is not None:
                msg["epoch"] = self._seen_epoch
            if q == ENTER_Q:
                self._seq += 1
                msg["seq"] = self._seq
            elif q == REJOIN_Q and self._pending is not None:
                # offer the pending delta's seq: the server answers with
                # which stripes it never applied (exactly-once replay)
                msg["replay"] = self._pending[0]
        # optional trace context (None unless DISTLEARN_TRACE_PROP is on
        # AND a trace is active): a key a legacy server never looks at;
        # with propagation off the message is bitwise identical to a
        # pre-trace client's
        tc = obs_trace.wire_context()
        if tc is not None:
            msg[obs_trace.TRACE_KEY] = tc
        self.broadcast.send_msg(msg)
        reply = self.conn.recv_msg()
        if not adv:
            if reply != want:
                raise ProtocolError(
                    f"protocol desync: expected {want!r}, got {reply!r}")
            return False
        if isinstance(reply, dict) and reply.get("stale"):
            raise StaleCenterError(
                f"center at {self.host}:{self.port} refused us as stale: "
                f"its epoch {reply.get('epoch')!r} is behind ours "
                f"({self._seen_epoch!r})")
        self._packed = _check_wire_reply(reply, want, self.codec)
        self._last_reply = reply if isinstance(reply, dict) else None
        if isinstance(reply, dict):
            ep = reply.get("epoch")
            if isinstance(ep, int):
                if self._seen_epoch is not None and ep < self._seen_epoch:
                    # a center claiming an OLDER epoch than one we've
                    # synced with is a zombie predating the fence keys
                    raise StaleCenterError(
                        f"center at {self.host}:{self.port} serves epoch "
                        f"{ep}, but we have synced with epoch "
                        f"{self._seen_epoch}")
                self._seen_epoch = ep
        if self.sharded and self._packed:
            self._apply_shard_spec(reply.get("shard"))
        return self._packed

    def _apply_shard_spec(self, spec) -> None:
        """Adopt (first sight) or re-verify the server's stripe plan from a
        sharded Enter/Rejoin reply.  On first sight, validate the plan and
        dial + hello every shard endpoint; the plan is then PINNED — a
        server that changes or drops it mid-stream is a protocol error,
        not something to silently re-stripe against (the error-feedback
        residuals are laid out per-stripe)."""
        if self._shard_spec is not None:
            if spec != self._shard_spec:
                raise ProtocolError(
                    f"shard plan changed mid-stream: pinned "
                    f"{self._shard_spec!r}, server now says {spec!r}")
            return
        if spec is None:
            return                          # unsharded (or legacy) server
        ok = (isinstance(spec, dict) and spec.get("v") == SHARD_V
              and isinstance(spec.get("ports"), list)
              and isinstance(spec.get("stripes"), list)
              and isinstance(spec.get("splits", []), list))
        splits = [1] * len(self.center or [])
        if ok:
            stripes = [tuple(s) for s in spec["stripes"]]
            n = spec.get("n")
            ok = (n == len(stripes) and n == len(spec["ports"]) + 1
                  and n >= 2 and stripes[0][0] == 0
                  and all(len(s) == 2 and s[0] < s[1] for s in stripes)
                  and all(stripes[i][1] == stripes[i + 1][0]
                          for i in range(n - 1)))
        if ok:
            # the split table: sparse [leaf_index, parts] rows cutting
            # oversized leaves into flat chunks — stripe ranges index the
            # resulting virtual list, so the cover check is against it
            last = -1
            for row in spec.get("splits", []):
                ok = (ok and isinstance(row, (list, tuple))
                      and len(row) == 2
                      and all(isinstance(v, int) for v in row)
                      and last < row[0] < len(splits) and row[1] >= 2
                      and row[1] <= int(self.center[row[0]].size or 0))
                if not ok:
                    break
                splits[row[0]] = row[1]
                last = row[0]
            ok = ok and stripes[-1][1] == len(splits) + sum(
                p - 1 for p in splits)
        if not ok:
            raise ProtocolError(f"malformed shard plan {spec!r}")
        conns = []
        try:
            for s, port in enumerate(spec["ports"], start=1):
                c = connect(self.host, port)
                if self.throttle_bps:
                    c.throttle_bps = self.throttle_bps
                c.send_msg({"q": SHARD_Q, "clientID": self.node, "shard": s})
                conns.append(c)
        except (ConnectionError, OSError):
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
            raise
        self._shard_spec = spec
        self._stripes = stripes
        self._splits = splits
        self._shard_conns = conns

    def init_client(self, params: PyTree) -> PyTree:
        """Receive the initial center from the server's broadcast; params :=
        center (ref lua :64-78).  The initial broadcast is always per-leaf
        (nothing has been negotiated yet) but ``recv_tensors`` auto-detects
        either framing."""
        leaves = _leaves(params)
        self.center = self.broadcast.recv_tensors(n=len(leaves))
        if self._slice is not None:
            # every device row of the slice starts at the center
            L = self._slice_rows
            return _rebuild(params, [
                np.ascontiguousarray(
                    np.broadcast_to(c[None], (L,) + c.shape))
                for c in self.center])
        return _rebuild(params, [c.copy() for c in self.center])

    def sync_client(self, params: PyTree) -> tuple[PyTree, bool]:
        """Every ``tau``-th call: full sync handshake (ref ``syncClient``,
        lua :134-146).  Returns ``(new_params, synced)``."""
        self.step += 1
        if self.adaptive_tau:
            # due-step counter instead of exact modulus: tau_effective
            # may change between syncs, so "every τ-th step" becomes
            # "τ_eff steps after the last sync"
            if self.step < self._next_sync:
                return params, False
        elif self.step % self.tau != 0:     # isSyncNeeded (lua :47-57)
            return params, False
        if not obs_trace.propagate_enabled():
            return self._sync_once(params)
        # one trace per sync: the root span below is the parent every
        # wire-context hop (center handshake, each stripe leg, the fetch
        # and push legs here) stitches to in tools/tracecat.py
        with obs_trace.use_context(obs_trace.new_trace()), \
                obs.span("async_ea.sync", cid=self.node):
            return self._sync_once(params)

    def _sync_once(self, params: PyTree) -> tuple[PyTree, bool]:
        t_sync = time.perf_counter() if self.adaptive_tau else 0.0

        if self._sender is not None:
            # previous round's delta must be fully on the wire before the
            # next Enter? — also where a background failure surfaces
            self._sender.flush()
        # clientEnterSync (lua :82-92)
        print_client(self.node, "waiting to sync")
        packed = self._announce(ENTER_Q, ENTER)
        striped = packed and self._stripes is not None
        vcenter = None
        if striped:
            # the virtual (sub-leaf split) list the stripe ranges index —
            # views into the same center buffers, rebuilt per sync so a
            # rejoin's fresh buffers are always the ones written into
            vcenter = wire.split_views(self.center, self._splits)
            if self._stripes[-1][1] != len(vcenter):
                raise ProtocolError(
                    f"shard plan covers {self._stripes[-1][1]} virtual "
                    f"leaves, center splits to {len(vcenter)}")
        # clientGetCenter (lua :95-106): one packed frame (negotiated) or
        # per-leaf, auto-detected — either way into the preallocated
        # center buffers.  Striped: one Center? leg per stripe, fanned out
        # so stripe i's decode overlaps stripe i+1's receive (stripe 0 on
        # the dedicated conn — identical to the unsharded fetch).
        if striped:
            conns = [self.conn] + self._shard_conns
            tc0 = obs_trace.current()   # fanout threads don't inherit it

            def _fetch(i):
                lo, hi = self._stripes[i]
                with obs_trace.use_context(tc0), \
                        obs.span("async_ea.fetch_center", shard=i):
                    conns[i].send_msg(CENTER_Q)
                    # chunk views write through into the real center
                    # leaves
                    conns[i].recv_tensors(out=vcenter[lo:hi])

            _fanout([lambda i=i: _fetch(i)
                     for i in range(len(self._stripes))])
        else:
            self.conn.send_msg(CENTER_Q)
            self.center = self.conn.recv_tensors(out=self.center)
        # calculateUpdateDiff (lua :109-119): local EA math.  The scale is
        # folded in-place into the one (p - c) temporary — at 100 MB-leaf
        # scale a second full-size allocation per leaf is measurable on the
        # sync path.
        leaves = _leaves(params)
        if self._slice is not None:
            # slice client: params are stacked [L, ...] rows; each row takes
            # its own elastic pull against the shared center, and the wire
            # delta is the ROW-SUM over the slice (one in-mesh reduction,
            # then the single TCP push below) — what L plain clients would
            # have pushed against the same center snapshot, in 1/L sends
            row_deltas = []
            for p, c in zip(leaves, self.center):
                d = np.asarray(p - c[None], dtype=c.dtype)
                d *= np.asarray(self.alpha, d.dtype)
                row_deltas.append(d)
            new_leaves = [p - d for p, d in zip(leaves, row_deltas)]
            red, _ = self._slice.all_reduce(row_deltas)
            deltas = [np.ascontiguousarray(x)
                      for x in self._slice.node_slice(red, 0)]
        else:
            deltas = []
            for p, c in zip(leaves, self.center):
                # deltas go over the wire in the CENTER's dtype: the server
                # rejects dtype skew as config skew, and a client whose
                # local params drifted wider (e.g. f64 promotion) still
                # interops — its delta is representable either way
                d = np.asarray(p - c, dtype=c.dtype)
                d *= np.asarray(self.alpha, d.dtype)
                deltas.append(d)
            new_leaves = [p - d for p, d in zip(leaves, deltas)]
        payloads = None
        if packed:
            if (self.codec != "raw"
                    and (self._residuals is None
                         or len(self._residuals) != len(deltas))):
                # full-length residual list allocated BEFORE striping so a
                # stripe's slice aliases the same per-leaf arrays whatever
                # the plan — see _encode_stripe
                self._residuals = [np.zeros_like(d) for d in deltas]
            # striped: encode over the VIRTUAL lists (chunk views of the
            # same delta/residual arrays), matching the server's layout
            enc_deltas, enc_res = deltas, self._residuals
            if striped:
                enc_deltas = wire.split_views(deltas, self._splits)
                if self._residuals is not None:
                    enc_res = wire.split_views(self._residuals,
                                               self._splits)
            bounds = self._stripes if striped else [(0, len(enc_deltas))]
            payloads = [self._encode_stripe(enc_deltas, enc_res, lo, hi, i)
                        for i, (lo, hi) in enumerate(bounds)]
            # keep the encoded bytes until the next sync: if the center
            # dies with this delta partially applied, the failover rejoin
            # replays exactly the stripes the server never saw
            self._pending = (self._seq, payloads, [tuple(b) for b in bounds])
        else:
            self._pending = None
        # clientSendDiff (lua :122-132)
        conn = self.conn
        # captured HERE: the push may run later on the background sender
        # thread, which has no context stack of its own
        tc1 = obs_trace.current()

        def _push_delta():
            if striped:
                conns = [conn] + self._shard_conns

                def _push(i):
                    with obs_trace.use_context(tc1), \
                            obs.span("async_ea.push_delta", shard=i):
                        conns[i].send_msg(DELTA_Q)
                        _expect(conns[i], DELTA)
                        conns[i].send_packed(payloads[i])

                _fanout([lambda i=i: _push(i) for i in range(len(payloads))])
                return
            with obs_trace.use_context(tc1), \
                    obs.span("async_ea.push_delta", shard=0):
                conn.send_msg(DELTA_Q)
                _expect(conn, DELTA)
                if payloads is not None:
                    conn.send_packed(payloads[0])
                else:
                    for d in deltas:
                        conn.send_tensor(d)

        if self._sender is not None:
            # overlap: the transmit/apply round-trip runs behind the next
            # τ local steps; params for those steps are already computed
            self._sender.submit(_push_delta)
        else:
            _push_delta()
        if self.adaptive_tau:
            self._note_sync_latency(time.perf_counter() - t_sync)
            self._next_sync = self.step + self.tau_effective
        print_client(self.node, "synced")
        return _rebuild(params, new_leaves), True

    def _note_sync_latency(self, dt: float) -> None:
        """Fold one sync's wall time into the latency EWMA and re-derive
        ``tau_effective``: the stretch factor is the EWMA over the best
        latency ever observed (the un-contended floor), so a straggling
        client syncs proportionally less often — bounded above by the
        α·τ stability product (``adaptive_tau_bounds``)."""
        self._lat_ewma = (dt if self._lat_ewma is None
                          else 0.7 * self._lat_ewma + 0.3 * dt)
        self._lat_floor = (self._lat_ewma if self._lat_floor is None
                           else min(self._lat_floor, self._lat_ewma))
        ratio = (self._lat_ewma / self._lat_floor
                 if self._lat_floor and self._lat_floor > 0 else 1.0)
        self.tau_effective = min(self._tau_hi,
                                 max(self._tau_lo,
                                     int(round(self._tau_lo * ratio))))
        if self._obs_on:
            self._g_tau.labels(cid=self.node).set(self.tau_effective)

    def _encode_stripe(self, deltas: list[np.ndarray],
                       residuals: list[np.ndarray] | None,
                       lo: int, hi: int, idx: int = 0):
        """Encode one stripe's delta slice for the packed wire.  Error
        feedback (Seide et al. 2014) for lossy codecs: quantize delta +
        carried residual, keep the quantization error for the next round —
        without it the bias accumulates and quantized-EA walks away from
        the fp32 fixed point.  ``deltas``/``residuals`` are the lists the
        stripe plan indexes (the virtual chunk views when striped) —
        residual chunks view the full-length per-leaf arrays, so
        per-stripe state stays exact under any plan.

        Fused path (``DISTLEARN_TPU_WIREK``, default on): ONE kernel pass
        per leaf produces q, scale, and ``r = d - dequant(q)`` straight
        into stripe ``idx``'s reusable frame buffer — no encode-then-
        decode double walk, no per-sync allocation, one iovec on the
        wire.  Bitwise-identical to the numpy path (ops/wire_kernels.py
        carries the proof), which the fallback keeps."""
        sl = deltas[lo:hi]
        if self.codec == "raw":
            return wire.encode_leaves(sl, "raw")
        t0 = time.perf_counter() if self._obs_on else 0.0
        res = residuals[lo:hi]
        for d, r in zip(sl, res):
            d += r
        if self._wirek:
            while len(self._framebufs) <= idx:
                self._framebufs.append(wire.FrameBuffer())
            payload = wire_kernels.encode_ef_into(
                sl, res, self.codec, out=self._framebufs[idx])
        else:
            payload = wire.encode_leaves(sl, self.codec)
            # decode into per-stripe reusable scratch (not fresh arrays):
            # the residual walk allocates nothing in steady state
            sc = self._dec_scratch.get(idx)
            if (sc is None or len(sc) != len(sl)
                    or any(s.shape != d.shape or s.dtype != d.dtype
                           for s, d in zip(sc, sl))):
                sc = self._dec_scratch[idx] = [np.empty_like(d)
                                               for d in sl]
            for r, d, dec in zip(res, sl, payload.decoded_into(sc)):
                np.subtract(d, dec, out=r)
        if self._obs_on:
            self._h_encode.labels(shard=idx).observe(
                time.perf_counter() - t0)
        return payload

    def _rejoin_handshake(self, n_leaves: int, retries: int,
                          retry_interval: float,
                          handshake_timeout: float | None,
                          host: str | None = None,
                          port: int | None = None) -> None:
        """The shared Rejoin? machinery behind :meth:`rejoin` and
        :meth:`failover`: tear down every connection, re-dial (optionally
        a DIFFERENT center), announce ``Rejoin?``, adopt the center, and
        run the replay exchange for a pending delta."""
        if host is not None:
            # _apply_shard_spec dials shard endpoints against self.host,
            # so the target must be adopted before the announce
            self.host, self.port = host, port if port is not None else self.port
        if self._sender is not None:
            # wait out (and discard the failure of) any in-flight delta —
            # it was riding the connection being replaced
            self._sender.drain()
        for c in (self.broadcast, self.conn, *self._shard_conns):
            try:
                c.close()
            except OSError:
                pass
        # unpin the stripe plan: the Rejoin reply re-advertises it and
        # _apply_shard_spec re-dials every shard endpoint (the server
        # dropped our old shard conns at eviction), so every stripe is
        # freshly resynced by construction
        self._shard_spec = None
        self._stripes = None
        self._splits = None
        self._shard_conns = []
        # dedicated BEFORE the Rejoin? announce: the server completes the
        # handshake by accepting on port+node and must find us dialed in
        self.broadcast = connect(self.host, self.port, retries=retries,
                                 retry_interval=retry_interval)
        # a joiner's dedicated channel is the ephemeral listener the Join
        # reply advertised — it survives evictions (only _remove_member
        # closes it), so rejoin works against the SAME center; a promoted
        # standby never heard of it, so failover() routes joiners through
        # _join_handshake (a fresh Join? under a new cid) instead of here
        self.conn = connect(self.host,
                            self.port + self.node if self._ded_port is None
                            else self._ded_port,
                            retries=retries, retry_interval=retry_interval)
        if self.throttle_bps:
            self.conn.throttle_bps = self.throttle_bps
        # bounded: a server that never re-admits (e.g. this client was
        # transport-dropped without an eviction record) must surface a
        # TimeoutError here, not wedge the worker forever
        self.conn.set_timeout(handshake_timeout)
        self._announce(REJOIN_Q, REJOIN)
        # deadline over the WHOLE center stream: a server stalling
        # mid-tensor must surface here too, not only on control frames
        dl = (None if handshake_timeout is None
              else time.monotonic() + handshake_timeout)
        self.center = self.conn.recv_tensors(n=n_leaves, deadline=dl)
        self.conn.send_msg(ACK)
        self._replay_exchange()
        self.conn.set_timeout(None)

    def _replay_exchange(self) -> None:
        """After a Rejoin handshake: if the server asked for replay (its
        Rejoin reply carries ``{"replay": {"seq", "need"}}``), resend the
        pending stripes it never applied — the exactly-once half of
        failover.  The pending delta is consumed either way: whatever the
        outcome, the next sync starts from the adopted center."""
        info = (self._last_reply or {}).get("replay") \
            if isinstance(self._last_reply, dict) else None
        pending, self._pending = self._pending, None
        if not isinstance(info, dict):
            if pending is not None:
                # promoted-from-checkpoint path with no seq record for us,
                # or a legacy-style reply: the delta is simply lost — EA
                # absorbs a dropped delta, it must NOT be double-applied
                self._c_replays.labels(outcome="dropped").inc()
            return
        need = info.get("need") or []
        if not need:
            self._c_replays.labels(outcome="clean").inc()
            return
        seq, payloads, bounds = (pending if pending is not None
                                 else (None, [], []))
        # the server's plan for THIS handshake must match the plan the
        # pending payloads were encoded under, else the bytes land on the
        # wrong stripe ranges — abort the replay rather than corrupt
        plan_ok = (pending is not None and info.get("seq") == seq
                   and all(isinstance(i, int) and 0 <= i < len(payloads)
                           for i in need))
        if plan_ok:
            if self._stripes is not None:
                plan_ok = bounds == [tuple(s) for s in self._stripes]
            else:
                plan_ok = len(bounds) == 1
        if not plan_ok:
            self.conn.send_msg({"q": REPLAY_Q, "abort": True})
            _expect(self.conn, ACK)
            self._c_replays.labels(outcome="dropped").inc()
            return
        self.conn.send_msg({"q": REPLAY_Q, "n": len(need)})
        for i in need:
            self.conn.send_packed(payloads[i])
        _expect(self.conn, ACK)
        self._c_replays.labels(outcome="replayed").inc()

    def _join_handshake(self, n_leaves: int, retries: int,
                        retry_interval: float,
                        handshake_timeout: float | None,
                        host: str, port: int) -> None:
        """Failover re-entry for a ``Join?``-admitted client: its
        dedicated channel is an ephemeral listener that only ever
        existed on the dead center, so a promoted standby cannot
        complete a ``Rejoin?`` handshake for it.  Instead re-enter
        through a FRESH ``Join?`` — new cid, new ephemeral dedicated
        port — keeping local params and residuals exactly as
        :meth:`failover` does for founding clients.  Epoch-fenced
        client-side: a center whose epoch is behind the newest we have
        seen is a zombie and raises :class:`StaleCenterError` so the
        failover walk removes it permanently.

        The new cid has no applied-seq ledger entry, so a pending delta
        cannot be replayed exactly-once — it is dropped (EA absorbs a
        lost delta; double-applying one is the bug), mirroring the
        promoted-without-seq path in :meth:`_replay_exchange`."""
        if self._sender is not None:
            self._sender.drain()
        for c in (self.broadcast, self.conn, *self._shard_conns):
            try:
                c.close()
            except OSError:
                pass
        self._shard_spec = None
        self._stripes = None
        self._splits = None
        self._shard_conns = []
        self.host, self.port = host, port
        b = connect(host, port, retries=retries,
                    retry_interval=retry_interval)
        try:
            b.set_timeout(handshake_timeout)
            msg: dict[str, Any] = {"q": JOIN_Q, "capacity": self.capacity}
            if self.codec is not None:
                msg["wire"] = {"v": wire.WIRE_V, "codec": self.codec}
                if self.sharded:
                    msg["shard"] = {"v": SHARD_V}
            b.send_msg(msg)
            reply = b.recv_msg()
            if not (isinstance(reply, dict) and reply.get("a") == JOIN):
                raise ProtocolError(
                    f"protocol desync: expected {JOIN!r} reply, "
                    f"got {reply!r}")
            ep = reply.get("epoch")
            if isinstance(ep, int):
                if (self._seen_epoch is not None
                        and ep < self._seen_epoch):
                    raise StaleCenterError(
                        f"join admitted by a stale center: epoch {ep} "
                        f"< seen {self._seen_epoch}")
                self._seen_epoch = ep
            w = reply.get("wire")
            if isinstance(w, dict) and w.get("error"):
                raise ProtocolError(str(w["error"]))
            cid, dport = reply.get("clientID"), reply.get("port")
            if not (isinstance(cid, int) and isinstance(dport, int)):
                raise ProtocolError(f"malformed {JOIN!r} reply {reply!r}")
            b.set_timeout(None)
        except BaseException:
            b.close()
            raise
        self.broadcast = b
        was = self.node
        self.node = cid
        self._ded_port = dport
        self.conn = connect(host, dport, retries=retries,
                            retry_interval=retry_interval)
        if self.throttle_bps:
            self.conn.throttle_bps = self.throttle_bps
        self.conn.set_timeout(handshake_timeout)
        dl = (None if handshake_timeout is None
              else time.monotonic() + handshake_timeout)
        self.center = self.conn.recv_tensors(n=n_leaves, deadline=dl)
        self.conn.send_msg(ACK)
        self.conn.set_timeout(None)
        self._packed = isinstance(w, dict)
        hint = reply.get("centers")
        if isinstance(hint, list):
            self._adopt_centers_hint(hint)
        if self._pending is not None:
            self._pending = None
            self._c_replays.labels(outcome="dropped").inc()
        print_client(self.node, f"re-joined the fleet as #{cid} "
                     f"(was #{was})")

    def _adopt_centers_hint(self, hint) -> None:
        """Fold a Join-reply ``centers`` roster into the failover dial
        list (dedup, current center kept first)."""
        for item in hint:
            try:
                h, p = item
                addr = (str(h), int(p))
            except (TypeError, ValueError):
                continue
            if addr not in self._centers:
                self._centers.append(addr)

    def rejoin(self, params: PyTree, retries: int = 60,
               retry_interval: float = 0.25,
               handshake_timeout: float | None = 60.0) -> PyTree:
        """Recover from an eviction: re-dial both channels, announce
        ``Rejoin?``, and take the server's CURRENT center as params (the
        local copy is stale by definition — rejoining with drifted params
        would push a delta against a center the client never saw).

        The server must be serving (its serve loop accepts rejoiners
        whenever any client is evicted).  Raises the underlying transport
        error if the server is gone; safe to call again.  Local state
        (``step``, ``tau``) is preserved so the sync cadence continues.
        """
        # the center we quantized against is gone; carrying a residual
        # across an eviction would re-inject error from a stale round.
        # (failover() deliberately KEEPS both — see docs/HA.md.)
        self._residuals = None
        self._pending = None
        self._rejoin_handshake(len(_leaves(params)), retries,
                               retry_interval, handshake_timeout)
        print_client(self.node, "re-admitted")
        return _rebuild(params, [c.copy() for c in self.center])

    def failover(self, params: PyTree, retries: int = 60,
                 retry_interval: float = 0.25,
                 handshake_timeout: float | None = 60.0) -> PyTree:
        """Survive a center death: walk the dial list (primary + standbys)
        until some center — possibly a freshly promoted standby — admits
        us through the Rejoin path, replaying the pending delta if asked.

        Unlike :meth:`rejoin`, the LOCAL params and error-feedback
        residuals are preserved: the promoted center restored from a
        checkpoint of the same trajectory, so the EASGD staleness bound
        and the residual error-feedback stream both remain valid
        (docs/HA.md, docs/EA_CONVERGENCE.md).  A center that refuses us on
        the epoch fence is removed from the dial list permanently.
        Returns ``params`` unchanged; raises ``ConnectionError`` when the
        dial list is exhausted.

        A ``Join?``-admitted client (ephemeral dedicated port) re-enters
        through a fresh ``Join?`` under a new cid instead of ``Rejoin?``
        — see :meth:`_join_handshake`; its dial list comes from the
        ``centers`` roster its join reply carried.
        """
        n = len(_leaves(params))
        with obs.span("async_ea.failover", cid=self.node):
            for _ in range(max(1, int(retries))):
                if not self._centers:
                    break
                host, port = self._centers[self._center_i
                                           % len(self._centers)]
                self._c_redials.inc()
                enter = (self._join_handshake if self._ded_port is not None
                         else self._rejoin_handshake)
                try:
                    enter(n, retries=3, retry_interval=retry_interval,
                          handshake_timeout=handshake_timeout,
                          host=host, port=port)
                except StaleCenterError:
                    # MUST come before ProtocolError (its base class):
                    # a fenced-off center can never become valid again
                    self._c_stale.inc()
                    try:
                        self._centers.remove((host, port))
                    except ValueError:
                        pass
                    continue
                except (TimeoutError, ConnectionError, ProtocolError,
                        OSError):
                    self._center_i += 1
                    continue
                print_client(self.node, "failed over to "
                             f"{self.host}:{self.port}")
                return params
        raise ConnectionError(
            f"client {self.node}: no center admitted us "
            f"(dial list: {self._centers!r})")

    @classmethod
    def join(cls, host: str, port: int, params: PyTree, tau: int,
             alpha: float, *, capacity: float = 1.0,
             codec: str | None = "raw", overlap: bool = False,
             sharded: bool = True, adaptive_tau: bool = False,
             throttle_bps: float | None = None,
             centers: list[tuple[str, int]] | None = None,
             timeout: float | None = 60.0
             ) -> tuple["AsyncEAClient", PyTree]:
        """Enter a RUNNING elastic fleet: announce ``Join?`` on the
        broadcast port (no cid — the server assigns the next monotonic
        one and opens an ephemeral dedicated listener for us), dial the
        advertised port, adopt the current center, and Ack — only then
        does the server count us a member (the join fence).  Returns
        ``(client, params)`` with params := center, ready for
        :meth:`sync_client`."""
        b = connect(host, port)
        try:
            b.set_timeout(timeout)
            msg: dict[str, Any] = {"q": JOIN_Q, "capacity": float(capacity)}
            if codec is not None:
                msg["wire"] = {"v": wire.WIRE_V, "codec": codec}
                if sharded:
                    msg["shard"] = {"v": SHARD_V}
            b.send_msg(msg)
            reply = b.recv_msg()
            if not (isinstance(reply, dict) and reply.get("a") == JOIN):
                raise ProtocolError(
                    f"protocol desync: expected {JOIN!r} reply, "
                    f"got {reply!r}")
            w = reply.get("wire")
            if isinstance(w, dict) and w.get("error"):
                raise ProtocolError(str(w["error"]))
            cid, dport = reply.get("clientID"), reply.get("port")
            if not (isinstance(cid, int) and isinstance(dport, int)):
                raise ProtocolError(f"malformed {JOIN!r} reply {reply!r}")
            b.set_timeout(None)
        except BaseException:
            b.close()
            raise
        cl = cls(host, port, cid, tau, alpha, codec=codec, overlap=overlap,
                 sharded=sharded, throttle_bps=throttle_bps,
                 centers=centers, capacity=capacity,
                 adaptive_tau=adaptive_tau, _broadcast=b,
                 _dedicated_port=dport)
        try:
            ep = reply.get("epoch")
            if isinstance(ep, int):
                cl._seen_epoch = ep
            # the join ACK's ``centers`` roster is the joiner's failover
            # dial list — with it a joiner survives a center kill through
            # failover() exactly like a founding client (docs/ELASTIC.md)
            hint = reply.get("centers")
            if isinstance(hint, list):
                cl._adopt_centers_hint(hint)
            # the join reply echoing the wire advertisement plays the role
            # of the Enter reply in _announce: packed wire is negotiated
            cl._packed = isinstance(w, dict)
            leaves = _leaves(params)
            cl.conn.set_timeout(timeout)
            cl.center = cl.conn.recv_tensors(n=len(leaves))
            cl.conn.send_msg(ACK)
            cl.conn.set_timeout(None)
        except BaseException:
            cl.close()
            raise
        print_client(cid, "joined the fleet")
        return cl, _rebuild(params, [c.copy() for c in cl.center])

    def leave(self, timeout: float | None = 30.0) -> None:
        """Depart gracefully: flush any overlapped send, announce
        ``Leave?`` with the seq of our newest delta, and run the replay
        exchange for whatever stripes the center's ledger is missing —
        the leaver's last contribution lands exactly once instead of
        being dropped.  Closes every channel on the way out (even when
        the flush fails — the lost delta is the staleness EASGD already
        tolerates)."""
        try:
            if self._sender is not None:
                try:
                    self._sender.flush()
                except (TimeoutError, ConnectionError, ProtocolError,
                        OSError, ValueError):
                    pass        # conn may be dead; Leave? will say so too
            with obs.span("async_ea.leave", cid=self.node):
                self.broadcast.set_timeout(timeout)
                self.conn.set_timeout(timeout)
                self.broadcast.send_msg({"q": LEAVE_Q,
                                         "clientID": self.node,
                                         "seq": self._seq})
                reply = self.conn.recv_msg()
                if not (isinstance(reply, dict)
                        and reply.get("a") == LEAVE):
                    raise ProtocolError(
                        f"protocol desync: expected {LEAVE!r} reply, "
                        f"got {reply!r}")
                self._last_reply = reply
                self._replay_exchange()
            print_client(self.node, "left the fleet")
        finally:
            self.close()

    def close(self):
        if self._sender is not None:
            self._sender.close()
        self.broadcast.close()
        self.conn.close()
        for c in self._shard_conns:
            try:
                c.close()
            except OSError:
                pass


class AsyncEATester:
    """Evaluation role (ref initTester/startTest/finishTest).

    ``codec`` opts into the packed wire for center fetches.  Unlike the
    client, the tester's advertisement rides its OWN ``Center?`` request
    (there is no prior Enter? leg), so an advertising tester against an
    old server desyncs — leave ``codec=None`` in mixed fleets.
    """

    def __init__(self, host: str, port: int, num_nodes: int,
                 codec: str | None = None):
        if codec is not None and codec not in wire.CODECS:
            raise ValueError(f"unknown wire codec {codec!r} "
                             f"(supported: {', '.join(wire.CODECS)})")
        self.codec = codec
        # test channel on port+numNodes+1 (EASGD_tester.lua:64)
        self.conn = connect(host, port + num_nodes + 1)

    def start_test(self, params: PyTree) -> PyTree:
        """Block until the server pushes ``Test?``; fetch center into params
        (ref lua :268-285)."""
        _expect(self.conn, TEST_Q)
        if self.codec is not None:
            self.conn.send_msg({"q": CENTER_Q,
                                "wire": {"v": wire.WIRE_V,
                                         "codec": self.codec}})
        else:
            self.conn.send_msg(CENTER_Q)
        leaves = _leaves(params)
        new = self.conn.recv_tensors(n=len(leaves))
        print_tester("received center for evaluation")
        return _rebuild(params, new)

    def finish_test(self):
        """Ack the round so the server resumes (ref lua :287-292)."""
        self.conn.send_msg(ACK)

    def close(self):
        self.conn.close()
